"""StatusManager + node health derivation.

Reference: src/main/StatusManager.{h,cpp} — one current status string per
category (newest wins), removed on recovery, surfaced as the ``status``
lines in ``/info``.  On top of the status lines this module derives a
machine-readable health verdict (``/health`` + the ``node.health``
gauge) suitable for load-balancer probes: ledger age vs. the close
target, herder state, tx-queue depth, overlay peer count and the bucket
GC backlog, each with an explicit reason string when degraded.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional

from ..util.clock import monotonic_now

# Reference StatusManager categories, extended with the subsystems this
# node actually reports on.
STATUS_CATEGORIES = (
    "history-catchup",
    "history-publish",
    "scp",
    "overlay",
    "bucket",
    "ledger",
    "requires-upgrades",
)


class StatusManager:
    """Per-category current-status strings (reference semantics: the
    NEWEST status per category is the only one kept; a recovered
    subsystem clears its category)."""

    def __init__(self) -> None:
        self._statuses: Dict[str, str] = {}

    def set_status(self, category: str, msg: str) -> None:
        if category not in STATUS_CATEGORIES:
            raise ValueError(f"unknown status category {category!r}")
        self._statuses[category] = msg

    def clear_status(self, category: str) -> None:
        self._statuses.pop(category, None)

    def get_status(self, category: str) -> Optional[str]:
        return self._statuses.get(category)

    def statuses(self) -> Dict[str, str]:
        return dict(self._statuses)

    def status_lines(self) -> List[str]:
        """The /info ``status`` array (reference: the strings
        StatusManager contributes to the info response)."""
        return [f"[{cat}] {msg}" for cat, msg in
                sorted(self._statuses.items())]

    def __len__(self) -> int:
        return len(self._statuses)


# ---------------------------------------------------------------------------
# health derivation
# ---------------------------------------------------------------------------

# Degraded when the LCL is older than this many close targets — one
# missed round is jitter, two means the node is not keeping consensus
# pace (reference shape: the /info "age" an operator watches).
HEALTH_LEDGER_AGE_FACTOR = 2.0
# tx-queue depth beyond this many maximum tx sets signals backpressure
# the node cannot drain
HEALTH_TX_QUEUE_FACTOR = 4
# unreclaimed bucket files beyond the referenced+pinned set tolerated
# before GC is considered backlogged
HEALTH_BUCKET_GC_BACKLOG = 512
# backlog probe cadence: the directory listing is re-taken at most this
# often; probes in between serve the cached count
GC_BACKLOG_TTL_S = 5.0


def _bucket_gc_backlog(app) -> int:
    """Bucket files on disk that neither the live list references nor any
    snapshot pins — what the next GC pass would delete.  0 when the node
    runs in-memory.

    Deliberately LOCK-FREE and cached: /health must keep answering while
    the main loop is stalled (possibly INSIDE the bucket store lock — a
    wedged merge adopt is a realistic stall), so this never acquires the
    store lock; the pin set is read as a GIL-atomic dict snapshot
    (approximate by design — a probe tolerates a torn read), and the
    directory listing is taken at most once per GC_BACKLOG_TTL_S so
    Prometheus scrapes of node.health don't re-list a thousands-of-files
    bucket dir each time."""
    store = getattr(app, "bucket_store", None)
    if store is None:
        return 0
    cached = getattr(app, "_gc_backlog_cache", None)
    now = monotonic_now()
    if cached is not None and now - cached[0] < GC_BACKLOG_TTL_S:
        return cached[1]
    try:
        keep = set(app.lm.bucket_list.referenced_hashes())
        keep.update(list(store._pins))
        backlog = 0
        for name in os.listdir(store.path):
            if name.startswith("bucket-") and name.endswith(".xdr") \
                    and name[len("bucket-"):-len(".xdr")] not in keep:
                backlog += 1
    except RuntimeError:
        # pins/levels mutated mid-iteration (lock-free by design): keep
        # the previous reading rather than block or fail the probe
        return cached[1] if cached is not None else 0
    app._gc_backlog_cache = (now, backlog)
    return backlog


def evaluate_health(app) -> dict:
    """The /health document: ``status`` is "ok" or "degraded" with one
    reason string per failing check; ``checks`` carries the raw numbers
    either way so a probe's logs explain themselves."""
    from ..herder.herder import HerderState

    reasons: List[str] = []
    close_target = float(app.herder.ledger_timespan)
    age = max(0.0, app.clock.system_now()
              - app.lm.lcl_header.scpValue.closeTime)
    max_age = HEALTH_LEDGER_AGE_FACTOR * close_target
    if age > max_age:
        reasons.append(f"ledger age {age:.1f}s exceeds "
                       f"{max_age:.1f}s ({HEALTH_LEDGER_AGE_FACTOR:g}x "
                       f"close target)")

    state = app.herder.get_state_human()
    if state != HerderState.TRACKING:
        reasons.append(f"herder state is {state!r}, not tracking")

    depth = app.herder.tx_queue.size
    max_depth = HEALTH_TX_QUEUE_FACTOR * max(
        1, app.lm.lcl_header.maxTxSetSize)
    if depth > max_depth:
        reasons.append(f"tx queue depth {depth} exceeds {max_depth}")

    # sustained admission backlog: the pipeline's hysteresis valve is the
    # "sustained" filter (engages at the high watermark, clears only at
    # the low one) — while engaged, this node is shedding/throttling
    # intake and a load balancer should route around it
    adm = getattr(app.herder, "admission", None)
    adm_depth = adm.depth if adm is not None else 0
    if adm is not None and adm.backpressured:
        reasons.append(f"admission backlog {adm_depth} "
                       f"(back-pressure engaged at "
                       f"{adm.backpressure_high})")

    peers = app.overlay.num_authenticated()
    # an app without a config (e.g. a simulated in-process node) is by
    # definition part of a network and expects peers
    cfg = getattr(app, "config", None)
    standalone = cfg is not None and (cfg.RUN_STANDALONE
                                      or not cfg.KNOWN_PEERS)
    if peers == 0 and not standalone:
        reasons.append("no authenticated peers")

    backlog = _bucket_gc_backlog(app)
    if backlog > HEALTH_BUCKET_GC_BACKLOG:
        reasons.append(f"bucket GC backlog {backlog} files")

    # archive recovery in flight: a distinct degraded status ("the node
    # is resyncing from a history archive and will be back") vs plain
    # out-of-sync ("the node is stuck and needs attention").  Both answer
    # non-"ok", so probes and load balancers route around it either way.
    catchup_msg = app.status.get_status("history-catchup")
    if catchup_msg is not None:
        reasons.append(f"catching up from archive: {catchup_msg}")

    status = "ok" if not reasons \
        else ("catching-up" if catchup_msg is not None else "degraded")
    return {
        "status": status,
        "reasons": reasons,
        "checks": {
            "ledger_age_s": round(age, 1),
            "close_target_s": close_target,
            "herder_state": state,
            "tx_queue_depth": depth,
            "admission_backlog": adm_depth,
            "authenticated_peers": peers,
            "bucket_gc_backlog": backlog,
            "catching_up": catchup_msg is not None,
        },
        "statuses": app.status.statuses(),
    }


def health_gauge_value(app) -> float:
    """node.health: 1.0 healthy, 0.0 degraded (the gauge form of
    /health, for alerting off the Prometheus exposition)."""
    return 1.0 if evaluate_health(app)["status"] == "ok" else 0.0
