"""HTTP admin endpoints (reference: src/main/CommandHandler.{h,cpp}).

Full surface: /info /health /dumpflight /metrics /trace /tracespans
/profile /slo /quorum /peers /tx /scp /ll /logrotate /manualclose /bans
/unban /connect /droppeer /maintenance /clearmetrics /self-check
/upgrades /surveytopologytimesliced /getsurveyresult /getledgerentry.

/health answers 200 ("ok") or 503 ("degraded", with reasons) — the
load-balancer probe surface; /dumpflight serves the live post-mortem
bundle (flight events, span stack, metrics — util/eventlog).  Malformed
query parameters answer 400 (_BadRequest), never 500.

The admin server runs on its own threads and marshals work onto the main
thread: a ThreadingHTTPServer serves reads directly (GIL-atomic snapshots
of plain dicts) and marshals mutations (/tx, /manualclose, /connect, ...)
onto the clock's action queue, waiting for the main crank loop.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..util import logging as slog

log = slog.get("CommandHandler")


class _BadRequest(Exception):
    """Malformed query parameter — surfaces as HTTP 400, never 500."""


def _hex_param(qs: dict, name: str, required: bool = True) -> bytes:
    raw = qs.get(name, [""])[0]
    if not raw:
        if required:
            raise _BadRequest(f"missing required hex param {name!r}")
        return b""
    try:
        return bytes.fromhex(raw)
    except ValueError:
        raise _BadRequest(f"param {name!r} must be hex") from None


def _int_param(qs: dict, name: str, default=None) -> int:
    raw = qs.get(name, [None])[0]
    if raw is None:
        if default is None:
            raise _BadRequest(f"missing required integer param {name!r}")
        return default
    try:
        return int(raw)
    except ValueError:
        raise _BadRequest(f"param {name!r} must be an integer") from None


class CommandHandler:
    def __init__(self, app, port: int, host: str = "127.0.0.1"):
        self.app = app
        self.host = host
        self.port = port
        self.server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        handler = self._make_handler()
        self.server = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="http-admin", daemon=True)
        self._thread.start()
        log.info("admin endpoint on http://%s:%d", self.host, self.port)

    def stop(self) -> None:
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
            self.server = None

    # ------------------------------------------------------------------
    def _on_main(self, fn, name: str = "http-cmd") -> dict:
        """Marshal a mutation onto the clock loop and wait (reference:
        CommandHandler routes through the app's main thread)."""
        done = threading.Event()
        result: dict = {}

        def work() -> None:
            try:
                out = fn()
                if isinstance(out, dict):
                    result.update(out)
            except Exception as e:
                log.debug("admin action %s failed: %s", name, e)
                result.update({"status": "ERROR", "detail": str(e)})
            done.set()

        self.app.clock.post_action(work, name=name)
        if not done.wait(timeout=10.0):
            return {"status": "ERROR", "detail": "timed out"}
        return result

    def _submit_tx_on_main(self, blob: bytes) -> dict:
        return self._on_main(lambda: self.app.submit_tx(blob),
                             name="http-tx")

    def _make_handler(self):
        handler_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            @staticmethod
            def _snap(fn):
                """Read main-thread state with retry: dict iteration can
                race a concurrent mutation (RuntimeError) — retry instead
                of surfacing a 500."""
                for _ in range(5):
                    try:
                        return fn()
                    except RuntimeError:
                        continue
                return fn()

            def _reply(self, obj, code=200):
                body = json.dumps(obj, indent=1).encode()
                self._reply_raw(body, "application/json", code)

            def _reply_raw(self, body: bytes, content_type: str, code=200):
                self.send_response(code)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                app = handler_self.app
                url = urlparse(self.path)
                try:
                    if url.path == "/info":
                        self._reply({"info": self._snap(app.info)})
                    elif url.path == "/health":
                        # NOT marshalled: a load-balancer probe must keep
                        # answering while the main loop is stalled — that
                        # stall is exactly what it exists to detect (the
                        # reads are GIL-atomic snapshots)
                        doc = self._snap(app.health)
                        self._reply(doc,
                                    200 if doc["status"] == "ok" else 503)
                    elif url.path == "/dumpflight":
                        # the live post-mortem: same bundle a crash writes
                        from ..util import eventlog
                        self._reply(self._snap(lambda: eventlog.flight_bundle(
                            "live dump via /dumpflight")))
                    elif url.path == "/metrics":
                        fmt = parse_qs(url.query).get("format", ["json"])[0]
                        if fmt == "prometheus":
                            from ..util.metrics import (registry,
                                                        render_prometheus)
                            text = self._snap(lambda: render_prometheus(
                                registry().snapshot()))
                            self._reply_raw(
                                text.encode(),
                                "text/plain; version=0.0.4; charset=utf-8")
                        else:
                            self._reply({"metrics": self._snap(app.metrics)})
                    elif url.path == "/trace":
                        from ..util import tracing
                        qs = parse_qs(url.query)
                        slot = _int_param(qs, "slot", default=-1) \
                            if "slot" in qs else None
                        doc = self._snap(
                            lambda: tracing.to_chrome_trace(slot=slot))
                        self._reply_raw(json.dumps(doc).encode(),
                                        "application/json")
                    elif url.path == "/tracespans":
                        # incremental cross-node export: marks + finished
                        # root spans past the caller's watermark, plus a
                        # fresh clock anchor (util/fleettrace collector)
                        from ..util import tracing
                        qs = parse_qs(url.query)
                        since = _int_param(qs, "since", default=0)
                        slot = _int_param(qs, "slot", default=-1) \
                            if "slot" in qs else None
                        doc = self._snap(
                            lambda: tracing.tracespans_doc(since,
                                                           slot=slot))
                        self._reply_raw(json.dumps(doc).encode(),
                                        "application/json")
                    elif url.path == "/profile":
                        # always-on sampling profiler (util/sampleprof)
                        from ..util import sampleprof
                        qs = parse_qs(url.query)
                        fmt = qs.get("format", ["json"])[0]
                        prof = sampleprof.profiler()
                        if fmt == "folded":
                            self._reply_raw(
                                (prof.folded() + "\n").encode(),
                                "text/plain; charset=utf-8")
                        else:
                            self._reply(self._snap(prof.snapshot))
                    elif url.path == "/slo":
                        tracker = getattr(app, "slo_tracker", None)
                        if tracker is None:
                            self._reply({"error": "no SLO tracker "
                                         "configured"}, 404)
                        else:
                            self._reply(self._snap(tracker.report))
                    elif url.path == "/timeseries":
                        # retrospective telemetry (util/timeseries):
                        # watermark-incremental history of the metric
                        # registry, same since/next_since contract as
                        # /tracespans
                        store = getattr(app, "timeseries", None)
                        if store is None:
                            self._reply({"error": "no time-series store "
                                         "configured"}, 404)
                        else:
                            from ..util.metrics import METRIC_NAME_RE
                            qs = parse_qs(url.query)
                            since = _int_param(qs, "since", default=0)
                            metric = qs.get("metric", [""])[0]
                            if metric and not METRIC_NAME_RE.match(metric):
                                raise _BadRequest(
                                    f"malformed metric name {metric!r}")
                            doc = self._snap(lambda: store.doc(
                                since, metric=metric or None))
                            self._reply_raw(json.dumps(doc).encode(),
                                            "application/json")
                    elif url.path == "/closecosts":
                        # per-close cost ledger (ledger/costs): one row
                        # per sealed ledger past the caller's watermark
                        ring = getattr(getattr(app, "lm", None),
                                       "close_costs", None)
                        if ring is None:
                            self._reply({"error": "no close-cost ledger "
                                         "configured"}, 404)
                        else:
                            qs = parse_qs(url.query)
                            since = _int_param(qs, "since", default=0)
                            doc = self._snap(lambda: ring.doc(since))
                            self._reply_raw(json.dumps(doc).encode(),
                                            "application/json")
                    elif url.path == "/quorum":
                        transitive = parse_qs(url.query).get(
                            "transitive", ["false"])[0] == "true"
                        self._reply(self._snap(
                            lambda: app.quorum_info(transitive)))
                    elif url.path == "/peers":
                        self._reply({"authenticated": self._snap(
                            lambda: [p.hex() for p in
                                     app.overlay.authenticated_peers])})
                    elif url.path == "/scp":
                        self._reply({
                            "state": app.herder.get_state_human(),
                            "tracking": app.herder
                            .tracking_consensus_ledger_index()})
                    elif url.path == "/tx":
                        qs = parse_qs(url.query)
                        blob = qs.get("blob", [""])[0]
                        try:
                            raw = bytes.fromhex(blob)
                        except ValueError:
                            self._reply({"status": "ERROR",
                                         "detail": "blob must be hex"}, 400)
                            return
                        self._reply(handler_self._submit_tx_on_main(raw))
                    elif url.path == "/ll":
                        self._log_level(parse_qs(url.query))
                    elif url.path == "/logrotate":
                        from ..util import logging as slog2
                        self._reply(handler_self._on_main(
                            slog2.rotate, name="logrotate")
                            or {"status": "rotated"})
                    elif url.path == "/manualclose":
                        self._reply(handler_self._on_main(
                            lambda: app.manual_close(), name="manualclose"))
                    elif url.path == "/bans":
                        # _snap: sorted() iterates the ban set while the
                        # main thread may ban/unban — retry the GIL-atomic
                        # snapshot instead of surfacing a transient 500
                        # (found by the thread-safety audit, ISSUE 9)
                        self._reply({"bans": self._snap(
                            lambda: [n.hex() for n in
                                     app.overlay.ban_manager
                                     .banned_nodes()])})
                    elif url.path == "/unban":
                        # marshalled: the ban table lives in the main
                        # thread's sqlite connection
                        nid = _hex_param(parse_qs(url.query), "node")
                        out = handler_self._on_main(
                            lambda: app.overlay.ban_manager.unban_node(nid),
                            name="unban")
                        self._reply(out or {"status": "unbanned"})
                    elif url.path == "/ban":
                        nid = _hex_param(parse_qs(url.query), "node")
                        out = handler_self._on_main(
                            lambda: app.overlay.ban_manager.ban_node(nid),
                            name="ban")
                        self._reply(out or {"status": "banned"})
                    elif url.path == "/connect":
                        qs = parse_qs(url.query)
                        host = qs.get("peer", [""])[0]
                        port = _int_param(qs, "port", default=11625)
                        self._reply(handler_self._on_main(
                            lambda: app.connect_to(host, port),
                            name="connect"))
                    elif url.path == "/droppeer":
                        nid = _hex_param(parse_qs(url.query), "node")
                        self._reply(handler_self._on_main(
                            lambda: app.drop_peer(nid),
                            name="droppeer"))
                    elif url.path == "/maintenance":
                        self._reply(handler_self._on_main(
                            app.maintainer.perform_maintenance,
                            name="maintenance"))
                    elif url.path == "/clearmetrics":
                        # marshalled: the registry is mutated by main-thread
                        # metric insertion; clearing from the HTTP thread
                        # would race snapshot/insert iteration
                        from ..util.metrics import registry
                        self._reply(handler_self._on_main(
                            lambda: registry().clear(), name="clearmetrics")
                            or {"status": "cleared"})
                    elif url.path == "/self-check":
                        self._reply(handler_self._on_main(
                            app.self_check, name="self-check"))
                    elif url.path == "/upgrades":
                        self._upgrades(parse_qs(url.query))
                    elif url.path == "/surveytopologytimesliced":
                        node = _hex_param(parse_qs(url.query), "node",
                                          required=False)
                        self._reply(handler_self._on_main(
                            lambda: app.survey_node(node or None),
                            name="survey"))
                    elif url.path == "/stopsurvey":
                        self._reply(handler_self._on_main(
                            lambda: app.stop_survey(), name="stopsurvey"))
                    elif url.path == "/getsurveyresult":
                        self._reply(self._snap(app.overlay.survey.results))
                    elif url.path == "/getledgerentry":
                        # marshalled: snapshot construction must not race
                        # add_batch's spill window on the main thread
                        key = _hex_param(parse_qs(url.query), "key")
                        self._reply(handler_self._on_main(
                            lambda: app.get_ledger_entry(key),
                            name="getledgerentry"))
                    else:
                        self._reply({"error": "unknown endpoint",
                                     "endpoints": sorted(_ENDPOINTS)}, 404)
                except _BadRequest as e:
                    self._reply({"error": str(e)}, 400)
                except Exception as e:  # admin surface must never crash
                    log.warning("admin request failed: %s", e)
                    self._reply({"error": str(e)}, 500)

            def _log_level(self, qs) -> None:
                from ..util import logging as slog2
                level = qs.get("level", [None])[0]
                partition = qs.get("partition", [None])[0]
                fmt = qs.get("format", [None])[0]
                # validate EVERY param before applying ANY of them: a
                # request that answers 400 must be side-effect free
                if fmt is not None and fmt not in slog2.LOG_FORMATS:
                    raise _BadRequest(
                        f"format must be one of {slog2.LOG_FORMATS}")
                if level is not None and level.upper() not in (
                        "DEBUG", "INFO", "WARNING", "ERROR", "CRITICAL",
                        "FATAL"):
                    raise _BadRequest(f"unknown log level {level!r}")
                if partition is not None \
                        and partition not in slog2.PARTITIONS:
                    raise _BadRequest(f"unknown partition {partition!r}")
                if fmt is not None:
                    # runtime structured-logging switch (reference: the
                    # spdlog pattern swap behind /ll)
                    slog2.set_format(fmt)
                    if level is None:
                        self._reply({"status": "ok", "format": fmt})
                        return
                if level is None:
                    self._reply({"levels": slog2.current_levels(),
                                 "format": slog2.current_format()})
                    return
                # direct call, deliberately NOT marshalled: setLevel is a
                # thread-safe single attribute store, and /ll must keep
                # working while the main loop is stalled — that is exactly
                # when an operator reaches for it
                slog2.set_level(level.upper(), partition)
                self._reply({"status": "ok", "partition": partition or "all",
                             "level": level.upper(),
                             "format": slog2.current_format()})

            def _upgrades(self, qs) -> None:
                app = handler_self.app
                mode = qs.get("mode", ["get"])[0]
                if mode == "get":
                    self._reply(self._snap(
                        lambda: app.herder.upgrades.pending_json()))
                elif mode == "clear":
                    out = handler_self._on_main(
                        lambda: app.herder.upgrades.set_parameters(None),
                        name="upgrades-clear")
                    self._reply(out or {"status": "cleared"})
                elif mode == "set":
                    from ..herder.upgrades import UpgradeParameters

                    def opt(name):
                        return _int_param(qs, name, default=0) \
                            if name in qs else None

                    params = UpgradeParameters(
                        upgrade_time=_int_param(qs, "upgradetime", default=0),
                        protocol_version=opt("protocolversion"),
                        base_fee=opt("basefee"),
                        max_tx_set_size=opt("maxtxsetsize"),
                        base_reserve=opt("basereserve"))
                    out = handler_self._on_main(
                        lambda: app.herder.upgrades.set_parameters(params),
                        name="upgrades-set")
                    self._reply(out or {"status": "set"})
                else:
                    self._reply({"error": f"bad mode {mode}"}, 400)

        return Handler


_ENDPOINTS = [
    "/info", "/health", "/dumpflight", "/metrics", "/trace",
    "/tracespans", "/profile", "/slo", "/timeseries", "/closecosts",
    "/quorum",
    "/peers", "/scp", "/tx", "/ll",
    "/logrotate", "/manualclose", "/bans", "/ban", "/unban", "/connect",
    "/droppeer", "/maintenance", "/clearmetrics", "/self-check",
    "/upgrades", "/surveytopologytimesliced", "/stopsurvey",
    "/getsurveyresult", "/getledgerentry",
]
