"""HTTP admin endpoint: /info /metrics /quorum /peers /tx /scp.

Reference: src/main/CommandHandler.{h,cpp} over lib/httpthreaded — the
admin server runs on its own threads and marshals work onto the main
thread.  Here a ThreadingHTTPServer serves reads directly (GIL-atomic
snapshots of plain dicts) and marshals /tx submission onto the clock's
action queue, waiting for the main crank loop to process it.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlparse

from ..util import logging as slog

log = slog.get("CommandHandler")


class CommandHandler:
    def __init__(self, app, port: int, host: str = "127.0.0.1"):
        self.app = app
        self.host = host
        self.port = port
        self.server: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        handler = self._make_handler()
        self.server = ThreadingHTTPServer((self.host, self.port), handler)
        self.port = self.server.server_address[1]
        self._thread = threading.Thread(target=self.server.serve_forever,
                                        name="http-admin", daemon=True)
        self._thread.start()
        log.info("admin endpoint on http://%s:%d", self.host, self.port)

    def stop(self) -> None:
        if self.server is not None:
            self.server.shutdown()
            self.server.server_close()
            self.server = None

    # ------------------------------------------------------------------
    def _submit_tx_on_main(self, blob: bytes) -> dict:
        """Marshal tx submission onto the clock loop and wait (reference:
        CommandHandler routes through the app's main thread)."""
        done = threading.Event()
        result: dict = {}

        def work() -> None:
            result.update(self.app.submit_tx(blob))
            done.set()

        self.app.clock.post_action(work, name="http-tx")
        if not done.wait(timeout=10.0):
            return {"status": "ERROR", "detail": "timed out"}
        return result

    def _make_handler(self):
        handler_self = self

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                pass

            @staticmethod
            def _snap(fn):
                """Read main-thread state with retry: dict iteration can
                race a concurrent mutation (RuntimeError) — retry instead
                of surfacing a 500."""
                for _ in range(5):
                    try:
                        return fn()
                    except RuntimeError:
                        continue
                return fn()

            def _reply(self, obj, code=200):
                body = json.dumps(obj, indent=1).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):
                app = handler_self.app
                url = urlparse(self.path)
                try:
                    if url.path == "/info":
                        self._reply({"info": self._snap(app.info)})
                    elif url.path == "/metrics":
                        self._reply({"metrics": self._snap(app.metrics)})
                    elif url.path == "/quorum":
                        self._reply(self._snap(app.quorum_info))
                    elif url.path == "/peers":
                        self._reply({"authenticated": self._snap(
                            lambda: [p.hex() for p in
                                     app.overlay.authenticated_peers])})
                    elif url.path == "/scp":
                        self._reply({
                            "state": app.herder.get_state_human(),
                            "tracking": app.herder
                            .tracking_consensus_ledger_index()})
                    elif url.path == "/tx":
                        qs = parse_qs(url.query)
                        blob = qs.get("blob", [""])[0]
                        try:
                            raw = bytes.fromhex(blob)
                        except ValueError:
                            self._reply({"status": "ERROR",
                                         "detail": "blob must be hex"}, 400)
                            return
                        self._reply(handler_self._submit_tx_on_main(raw))
                    else:
                        self._reply({"error": "unknown endpoint",
                                     "endpoints": ["/info", "/metrics",
                                                   "/quorum", "/peers",
                                                   "/scp", "/tx"]}, 404)
                except Exception as e:  # admin surface must never crash
                    self._reply({"error": str(e)}, 500)

        return Handler
