"""Self-check: cross-verify the node's durable state.

Reference: src/main/ApplicationUtils — selfCheck + the `self-check` CLI /
`/self-check` endpoint: re-hash the stored LCL header, check the bucket
list against it, re-hash every referenced bucket file, and probe archive
reachability.  All checks are read-only; the result is a pass/fail report
(the reference logs and returns an exit code — fail-stop is left to the
caller).
"""

from __future__ import annotations

from typing import List, Optional

from ..crypto.sha import sha256
from ..util import logging as slog

log = slog.get("Main")


def self_check(lm, database=None, bucket_dir=None,
               archives=()) -> dict:
    """Run every applicable check; returns {"ok": bool, "checks": [...]}."""
    checks: List[dict] = []

    def check(name: str, ok: bool, detail: str = "") -> None:
        checks.append({"name": name, "ok": bool(ok), "detail": detail})
        (log.info if ok else log.error)("self-check %s: %s %s",
                                        name, "OK" if ok else "FAIL", detail)

    # 1. LCL header self-consistency
    header_hash = sha256(lm.lcl_header.to_xdr())
    check("lcl-header-hash", header_hash == lm.lcl_hash,
          f"stored {lm.lcl_hash.hex()[:16]} recomputed "
          f"{header_hash.hex()[:16]}")

    # 2. live bucket list matches the header.  With native live close
    # the authoritative buckets live in the C engine between checkpoint
    # boundaries — rebuild the Python view first (hash-verified inside)
    if lm.native_closer is not None and lm.native_closer.bridge.active:
        lm.native_closer.bridge.sync_buckets_to(lm)
    check("bucket-list-hash",
          lm.bucket_list.hash() == lm.lcl_header.bucketListHash)

    # 3. DB round-trip of the header
    if database is not None:
        stored = database.load_header_by_hash(lm.lcl_hash)
        check("db-header", stored is not None
              and sha256(stored.to_xdr()) == lm.lcl_hash)

    # 4. on-disk bucket files re-hash to their names
    if bucket_dir is not None:
        bad = []
        for hex_hash in lm.bucket_list.referenced_hashes():
            if hex_hash == "0" * 64:
                continue
            bucket = bucket_dir.load(hex_hash)   # load() re-hashes
            if bucket is None or bucket.hash().hex() != hex_hash:
                bad.append(hex_hash[:16])
        check("bucket-files", not bad, ",".join(bad))

    # 5. archives are reachable and their HAS parses; before the first
    # checkpoint publish an empty archive is the expected state
    from ..history.archive import checkpoint_frequency
    for i, archive in enumerate(archives):
        try:
            has = archive.get_state()
            if has is None:
                not_yet = lm.lcl_header.ledgerSeq < checkpoint_frequency()
                check(f"archive-{i}", not_yet,
                      "no HAS published yet" if not_yet
                      else "HAS missing after first checkpoint")
            else:
                check(f"archive-{i}", True,
                      f"currentLedger={has.current_ledger}")
        except Exception as e:  # corelint: disable=exception-hygiene -- the failure lands in the check result
            check(f"archive-{i}", False, str(e))

    return {"ok": all(c["ok"] for c in checks), "checks": checks}
