"""stellar_core_tpu — a TPU-native framework with the capabilities of stellar-core.

Brand-new implementation (not a port) of the Stellar validator-node stack:
XDR protocol types, Ed25519/StrKey crypto, bucket-list ledger store,
transaction apply engine, SCP consensus, P2P overlay, history publish and
catchup replay — with the two embarrassingly-parallel hot loops offloaded to
TPU via JAX:

- ``accel.ed25519``: batched Ed25519 signature verification (the
  ``TPUCryptoBackend`` behind the ``SignatureChecker`` seam; reference seam:
  src/crypto/SecretKey.cpp — PubKeyUtils::verifySig).
- ``accel.quorum``: bitmask-encoded quorum-intersection enumeration (the
  ``TPUQuorumIntersectionChecker``; reference seam:
  src/herder/QuorumIntersectionChecker.h — QuorumIntersectionChecker::create).

Layering mirrors SURVEY.md §1 (bottom → top): util/crypto/xdr → bucket/ledger
→ transactions → herder+scp → overlay → history/catchup → main.
"""

__version__ = "0.1.0"

# Sanitizer tier (ISSUE 15): with STPU_NATIVE_SANITIZE=1 the ASan+UBSan
# instrumented native extensions (build/asan/, see _native_build) shadow
# the regular in-place build for THIS process — `make native-asan` runs
# the differential + fuzz tiers through here with the runtime preloaded.
import os as _os

if _os.environ.get("STPU_NATIVE_SANITIZE") == "1":
    from . import _native_build as _nb

    _nb.activate_sanitized()
