"""stellar_core_tpu — a TPU-native framework with the capabilities of stellar-core.

Brand-new implementation (not a port) of the Stellar validator-node stack:
XDR protocol types, Ed25519/StrKey crypto, bucket-list ledger store,
transaction apply engine, SCP consensus, P2P overlay, history publish and
catchup replay — with the two embarrassingly-parallel hot loops offloaded to
TPU via JAX:

- ``accel.ed25519``: batched Ed25519 signature verification (the
  ``TPUCryptoBackend`` behind the ``SignatureChecker`` seam; reference seam:
  src/crypto/SecretKey.cpp — PubKeyUtils::verifySig).
- ``accel.quorum``: bitmask-encoded quorum-intersection enumeration (the
  ``TPUQuorumIntersectionChecker``; reference seam:
  src/herder/QuorumIntersectionChecker.h — QuorumIntersectionChecker::create).

Layering mirrors SURVEY.md §1 (bottom → top): util/crypto/xdr → bucket/ledger
→ transactions → herder+scp → overlay → history/catchup → main.
"""

__version__ = "0.1.0"
