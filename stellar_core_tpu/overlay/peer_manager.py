"""PeerManager: the address book + outbound connection policy.

Reference: src/overlay/PeerManager.{h,cpp} (peer records with numFailures /
nextAttempt backoff, persisted in the peers table), RandomPeerSource, and
OverlayManagerImpl::triggerPeerResolution / connectToMorePeers.

Addresses arrive from config (KNOWN_PEERS), from PEERS gossip, and from
the database on restart; the manager hands the application dial candidates
until the target outbound count is met, backing off failed addresses
exponentially.
"""

from __future__ import annotations

import random
from typing import Dict, List, Optional, Tuple

from .. import xdr as X
from ..util import logging as slog

log = slog.get("Overlay")

MAX_FAILURES_TO_FORGET = 10      # reference: REALLY_DEAD_NUM_FAILURES_CUTOFF
BACKOFF_BASE_SECONDS = 10.0
MAX_PEERS_TO_SEND = 50


class PeerRecord:
    __slots__ = ("host", "port", "num_failures", "next_attempt")

    def __init__(self, host: str, port: int, num_failures: int = 0,
                 next_attempt: float = 0.0):
        self.host = host
        self.port = port
        self.num_failures = num_failures
        self.next_attempt = next_attempt

    @property
    def addr(self) -> Tuple[str, int]:
        return (self.host, self.port)


class PeerManager:
    def __init__(self, clock, database=None,
                 rng: Optional[random.Random] = None,
                 self_port: int = 0):
        self.clock = clock
        self.db = database
        self._rng = rng or random.Random()
        self.self_port = self_port   # filter our own gossiped address
        self._records: Dict[Tuple[str, int], PeerRecord] = {}
        if database is not None:
            for host, port, failures in database.load_peers():
                self._records[(host, port)] = PeerRecord(host, port, failures)

    # -- intake -------------------------------------------------------------
    def _is_self(self, host: str, port: int) -> bool:
        return (self.self_port and port == self.self_port
                and host in ("127.0.0.1", "localhost", "::1"))

    def add_address(self, host: str, port: int) -> None:
        key = (host, int(port))
        if self._is_self(*key) or key in self._records:
            return
        self._records[key] = PeerRecord(host, int(port))
        self._persist(self._records[key])
        self._commit()

    def add_peer_addresses(self, peers) -> None:
        """PEERS message intake (reference: PeerManager::storePeerList);
        one DB commit for the whole batch."""
        for pa in peers:
            if pa.ip.switch != X.IPAddrType.IPv4:
                continue
            host = ".".join(str(b) for b in pa.ip.value)
            key = (host, int(pa.port))
            if not 0 < pa.port <= 65535 or self._is_self(*key) \
                    or key in self._records:
                continue
            self._records[key] = PeerRecord(*key)
            self._persist(self._records[key])
        self._commit()

    # -- outcomes -----------------------------------------------------------
    def record_success(self, host: str, port: int) -> None:
        rec = self._records.get((host, port))
        if rec is not None:
            rec.num_failures = 0
            rec.next_attempt = 0.0
            self._persist(rec)
            self._commit()

    def record_failure(self, host: str, port: int) -> None:
        rec = self._records.get((host, port))
        if rec is None:
            return
        rec.num_failures += 1
        if rec.num_failures > MAX_FAILURES_TO_FORGET:
            del self._records[(host, port)]
            if self.db is not None:
                self.db.delete_peer(host, port)
                self._commit()
            return
        backoff = BACKOFF_BASE_SECONDS * (2 ** min(rec.num_failures, 6))
        rec.next_attempt = self.clock.now() + backoff
        self._persist(rec)
        self._commit()

    def _persist(self, rec: PeerRecord) -> None:
        if self.db is not None:
            self.db.store_peer(rec.host, rec.port, rec.num_failures)

    def _commit(self) -> None:
        if self.db is not None:
            self.db.commit()

    # -- dialing ------------------------------------------------------------
    def dial_candidates(self, n: int, exclude=()) -> List[Tuple[str, int]]:
        """Up to n addresses ready for an attempt (reference:
        RandomPeerSource::getRandomPeers with backoff filtering)."""
        now = self.clock.now()
        ready = [r.addr for r in self._records.values()
                 if r.next_attempt <= now and r.addr not in set(exclude)]
        self._rng.shuffle(ready)
        return ready[:n]

    def peers_to_send(self) -> List[X.PeerAddress]:
        """Share the best-known addresses (reference:
        PeerManager::getPeersToSend — low-failure peers first)."""
        recs = sorted(self._records.values(), key=lambda r: r.num_failures)
        out = []
        for r in recs[:MAX_PEERS_TO_SEND]:
            try:
                octets = bytes(int(x) for x in r.host.split("."))
            except ValueError:
                continue
            if len(octets) != 4:
                continue
            out.append(X.PeerAddress(
                ip=X.PeerAddressIp.ipv4(octets), port=r.port,
                numFailures=r.num_failures))
        return out

    @property
    def size(self) -> int:
        return len(self._records)
