"""BanManager: persistent node-ID ban list.

Reference: src/overlay/BanManagerImpl.{h,cpp} — bans are by node identity
(not IP), stored in the ``ban`` DB table, enforced at authentication time
and consulted by `/bans` + `/unban` admin endpoints.
"""

from __future__ import annotations

from typing import List, Optional, Set

from ..util import eventlog


class BanManager:
    def __init__(self, database=None):
        self.db = database
        self._banned: Set[bytes] = set()
        if database is not None:
            self._banned.update(database.load_bans())

    def ban_node(self, node_id: bytes) -> None:
        if node_id in self._banned:
            return
        self._banned.add(node_id)
        eventlog.record("Overlay", "WARNING", "node banned",
                        node=node_id.hex()[:16])
        if self.db is not None:
            self.db.store_ban(node_id)
            self.db.commit()

    def unban_node(self, node_id: bytes) -> None:
        if node_id in self._banned:
            eventlog.record("Overlay", "INFO", "node unbanned",
                            node=node_id.hex()[:16])
        self._banned.discard(node_id)
        if self.db is not None:
            self.db.delete_ban(node_id)
            self.db.commit()

    def is_banned(self, node_id: Optional[bytes]) -> bool:
        return node_id is not None and node_id in self._banned

    def banned_nodes(self) -> List[bytes]:
        return sorted(self._banned)
