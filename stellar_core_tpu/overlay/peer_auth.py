"""Peer authentication: Curve25519 ECDH -> per-direction HMAC-SHA256 keys.

Reference: src/overlay/PeerAuth.{h,cpp} + PeerSharedKeyId — each node holds
an ephemeral Curve25519 keypair whose public half is certified by the
node's Ed25519 identity key inside an expiring AuthCert carried in HELLO;
the ECDH shared secret plus both HELLO nonces derive one HMAC key per
direction, and every post-HELLO message carries (sequence, mac) verified
with a strictly increasing counter (src/overlay/Peer.cpp recvAuthenticated
checks).

The derivation is HKDF-style (extract with a zero salt, expand with a
direction label).  It is self-consistent for this framework's networks;
byte-compatibility with the C++ implementation's HKDF labels is a non-goal
(the networks are disjoint), the *shape* of the protocol is kept.
"""

from __future__ import annotations

import hashlib
import hmac
import os
from typing import Optional, Tuple

from .. import xdr as X
from ..crypto import sodium
from ..crypto.keys import PublicKey, SecretKey, verify_sig
from ..crypto.sha import sha256

AUTH_CERT_LIFETIME = 60 * 60  # seconds (reference: one hour)

_CERT_PREFIX = b"stellar-core-tpu auth cert"


def _cert_payload(network_id: bytes, expiration: int, pubkey: bytes) -> bytes:
    return sha256(network_id + _CERT_PREFIX
                  + expiration.to_bytes(8, "big") + pubkey)


class PeerAuth:
    """Holds this node's auth keypair and mints/validates certs."""

    def __init__(self, node_secret: SecretKey, network_id: bytes,
                 now_fn, auth_seed: Optional[bytes] = None):
        self.node_secret = node_secret
        self.network_id = network_id
        self.now_fn = now_fn
        seed = auth_seed if auth_seed is not None else os.urandom(32)
        # clamped Curve25519 secret
        sec = bytearray(sha256(seed))
        sec[0] &= 248
        sec[31] &= 127
        sec[31] |= 64
        self.auth_secret = bytes(sec)
        self.auth_public = sodium.scalarmult_curve25519_base(self.auth_secret)
        self._cert: Optional[X.AuthCert] = None

    def get_cert(self) -> X.AuthCert:
        now = int(self.now_fn())
        if self._cert is None or self._cert.expiration <= now + 60:
            expiration = now + AUTH_CERT_LIFETIME
            payload = _cert_payload(self.network_id, expiration,
                                    self.auth_public)
            self._cert = X.AuthCert(
                pubkey=X.Curve25519Public(key=self.auth_public),
                expiration=expiration,
                sig=self.node_secret.sign(payload))
        return self._cert

    def verify_remote_cert(self, cert: X.AuthCert,
                           peer_id: bytes) -> bool:
        """peer_id: the claimed Ed25519 node id from HELLO."""
        if cert.expiration < int(self.now_fn()):
            return False
        payload = _cert_payload(self.network_id, cert.expiration,
                                cert.pubkey.key)
        return verify_sig(PublicKey(peer_id), cert.sig, payload)

    def shared_keys(self, remote_pub: bytes, local_nonce: bytes,
                    remote_nonce: bytes, we_called: bool
                    ) -> Tuple[bytes, bytes]:
        """(sending_key, receiving_key) for this side of the session."""
        shared = sodium.scalarmult_curve25519(self.auth_secret, remote_pub)
        if we_called:
            pubs = self.auth_public + remote_pub
            nonces = local_nonce + remote_nonce
        else:
            pubs = remote_pub + self.auth_public
            nonces = remote_nonce + local_nonce
        prk = hmac.new(b"\x00" * 32, shared + pubs + nonces,
                       hashlib.sha256).digest()
        caller_to_acceptor = hmac.new(prk, b"caller->acceptor\x01",
                                      hashlib.sha256).digest()
        acceptor_to_caller = hmac.new(prk, b"acceptor->caller\x02",
                                      hashlib.sha256).digest()
        if we_called:
            return caller_to_acceptor, acceptor_to_caller
        return acceptor_to_caller, caller_to_acceptor


def mac_message(key: bytes, sequence: int, message_xdr: bytes) -> bytes:
    return hmac.new(key, sequence.to_bytes(8, "big") + message_xdr,
                    hashlib.sha256).digest()


def mac_ok(key: bytes, sequence: int, message_xdr: bytes,
           mac: bytes) -> bool:
    return hmac.compare_digest(mac_message(key, sequence, message_xdr), mac)
