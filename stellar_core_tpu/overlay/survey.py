"""SurveyManager: time-sliced network-topology survey.

Reference: src/overlay/SurveyManager.{h,cpp} + SurveyDataManager — a
surveyor broadcasts a signed *start-collecting* message (scoped by a nonce);
every node relays it and starts recording peer/node stats; the surveyor then
sends signed per-node *requests*, each carrying an ephemeral Curve25519 key;
surveyed nodes reply with their recorded ``TopologyResponseBodyV2``
encrypted to that key; a signed *stop-collecting* ends the slice.  Results
feed the `/surveytopologytimesliced` + `/getsurveyresult` admin endpoints.

Survey messages flood through the overlay like SCP traffic (signature- and
nonce-gated), so non-neighbour nodes can be surveyed.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from .. import xdr as X
from ..crypto import box
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..util import logging as slog

log = slog.get("Overlay")

# one collecting phase may span at most this many ledgers (reference:
# SurveyDataManager::MAX_PHASE_DURATION ~ 30 min; scaled to ledgers here)
MAX_COLLECTING_LEDGERS = 120
MAX_RESPONSE_PEERS = 25
# relay-side nonce memory is attacker-writable (any permitted surveyor's
# START registers one) — hard-cap it
MAX_KNOWN_NONCES = 64


class CollectingState:
    """Stats recorded on a surveyed node between start/stop collecting."""

    __slots__ = ("surveyor", "nonce", "start_ledger", "added_peers",
                 "dropped_peers", "lost_sync_count")

    def __init__(self, surveyor: bytes, nonce: int, start_ledger: int):
        self.surveyor = surveyor
        self.nonce = nonce
        self.start_ledger = start_ledger
        self.added_peers = 0
        self.dropped_peers = 0
        self.lost_sync_count = 0


class SurveyManager:
    def __init__(self, overlay, node_secret: SecretKey):
        self.overlay = overlay
        self.node_secret = node_secret
        # surveyed side
        self.collecting: Optional[CollectingState] = None
        # surveyor side
        self._nonce: Optional[int] = None
        self._enc_sk: Optional[bytes] = None
        self._enc_pk: Optional[bytes] = None
        self._results: Dict[bytes, dict] = {}   # surveyed node id -> body
        self._bad_response_nodes: List[str] = []
        self._last_nonce: Optional[int] = None
        # relay side: nonces of surveys seen via a valid start-collecting
        # (nonce -> (surveyor node id, start ledgerNum)).  A relay node that
        # could not adopt the collecting phase (e.g. another survey was
        # live, or its own phase expired) must still forward requests for a
        # known active survey — the reference relays on the nonce belonging
        # to an active survey, not on local collecting state.  The surveyor
        # binding prevents an unprivileged peer from riding a live nonce
        # (relay amplification) or forging a stop that kills relaying.
        self._known_nonces: Dict[int, tuple] = {}

    # -- signing helpers -----------------------------------------------------
    # Domain-separated: start/stop (and request/response) messages have
    # identical XDR layouts, so signing raw XDR would let a signed START
    # replay as a valid STOP.  The reference signs type-tagged payloads
    # (SurveyManager signs ENVELOPE_TYPE-prefixed XDR); here each message
    # kind gets its own tag prefix.
    def _sign(self, tag: bytes, payload: bytes) -> bytes:
        return self.node_secret.sign(tag + payload)

    @staticmethod
    def _verify(node_id: bytes, tag: bytes, payload: bytes,
                sig: bytes) -> bool:
        from ..crypto import sodium
        return sodium.verify_detached(sig, tag + payload, node_id)

    TAG_START = b"surveyStartCollecting"
    TAG_STOP = b"surveyStopCollecting"
    TAG_REQUEST = b"surveyRequest"
    TAG_RESPONSE = b"surveyResponse"

    # -- surveyor side -------------------------------------------------------
    def start_survey(self, nonce: Optional[int] = None) -> int:
        """Broadcast start-collecting; returns the nonce identifying the
        run (reference: SurveyManager::broadcastStartSurveyCollecting)."""
        if nonce is None:
            import random
            nonce = random.getrandbits(32)
        self._nonce = nonce
        self._enc_pk, self._enc_sk = box.keypair()
        self._results = {}
        self._bad_response_nodes = []
        msg = X.TimeSlicedSurveyStartCollectingMessage(
            surveyorID=X.NodeID.ed25519(self.overlay.node_id),
            nonce=nonce,
            ledgerNum=self._ledger_num())
        signed = X.SignedTimeSlicedSurveyStartCollectingMessage(
            signature=self._sign(self.TAG_START, msg.to_xdr()),
            startCollecting=msg)
        sm = X.StellarMessage.signedTimeSlicedSurveyStartCollectingMessage(
            signed)
        self._flood(sm)
        # the surveyor records itself too
        self.recv_start_collecting(None, signed)
        return nonce

    def send_request(self, surveyed_node_id: bytes) -> None:
        """Signed, addressed survey request (reference:
        SurveyManager::sendTopologyRequest)."""
        if self._nonce is None:
            raise RuntimeError("no active survey")
        req = X.TimeSlicedSurveyRequestMessage(
            request=X.SurveyRequestMessage(
                surveyorPeerID=X.NodeID.ed25519(self.overlay.node_id),
                surveyedPeerID=X.NodeID.ed25519(surveyed_node_id),
                ledgerNum=self._ledger_num(),
                encryptionKey=X.Curve25519Public(key=self._enc_pk)),
            nonce=self._nonce)
        signed = X.SignedTimeSlicedSurveyRequestMessage(
            requestSignature=self._sign(self.TAG_REQUEST, req.to_xdr()),
            request=req)
        self._flood(
            X.StellarMessage.signedTimeSlicedSurveyRequestMessage(signed))

    def stop_survey(self) -> None:
        """Broadcast stop-collecting (reference:
        broadcastStopSurveyCollecting)."""
        if self._nonce is None:
            return
        msg = X.TimeSlicedSurveyStopCollectingMessage(
            surveyorID=X.NodeID.ed25519(self.overlay.node_id),
            nonce=self._nonce,
            ledgerNum=self._ledger_num())
        signed = X.SignedTimeSlicedSurveyStopCollectingMessage(
            signature=self._sign(self.TAG_STOP, msg.to_xdr()),
            stopCollecting=msg)
        self._flood(
            X.StellarMessage.signedTimeSlicedSurveyStopCollectingMessage(
                signed))
        self.recv_stop_collecting(None, signed)
        # the surveyor's run is over: allow a fresh start_survey later;
        # accumulated results stay readable via results()
        self._last_nonce = self._nonce
        self._nonce = None

    def results(self) -> dict:
        """The `/getsurveyresult` payload (reference:
        SurveyManager::getJsonResults)."""
        return {
            "surveyInProgress": self._nonce is not None,
            "nonce": self._nonce if self._nonce is not None
                     else self._last_nonce,
            "topology": {nid.hex(): body
                         for nid, body in self._results.items()},
            "badResponseNodes": self._bad_response_nodes,
        }

    # -- surveyed side -------------------------------------------------------
    def _surveyor_permitted(self, surveyor: bytes) -> bool:
        """Only nodes in the local transitive quorum (or self) may survey
        (reference: SurveyManager::surveyorPermitted — surveyors outside
        the quorum map are ignored so arbitrary peers cannot harvest
        topology or disrupt running surveys)."""
        if surveyor == self.overlay.node_id:
            return True
        herder = self.overlay.herder
        qmap = herder.quorum_map()
        if surveyor in qmap:
            return True
        from ..scp.quorum import qset_nodes
        for qset in qmap.values():
            if qset is not None and surveyor in qset_nodes(qset):
                return True
        return False

    def recv_start_collecting(self, peer, signed) -> bool:
        """Returns True if the message is fresh/valid (and should be
        relayed)."""
        msg = signed.startCollecting
        surveyor = msg.surveyorID.value
        if not self._surveyor_permitted(surveyor):
            return False
        if not self._verify(surveyor, self.TAG_START, msg.to_xdr(),
                            signed.signature):
            return False
        self.maybe_expire()
        # remember the nonce (bound to its surveyor) for request relaying
        # even when we cannot adopt the collecting phase locally.
        # First-writer wins: a later START reusing a live nonce must not
        # rebind it to a different surveyor (hijack).  The expiry basis is
        # OUR ledger, not the message's claimed ledgerNum — an attacker-
        # chosen ledgerNum far in the future would pin the entry forever.
        if msg.nonce not in self._known_nonces \
                and len(self._known_nonces) < MAX_KNOWN_NONCES:
            self._known_nonces[msg.nonce] = (surveyor, self._ledger_num())
        if self.collecting is not None:
            # one survey at a time; a fresh START must not clobber a live
            # collecting phase (an abandoned one expires via maybe_expire)
            return False
        # clamp the phase start to OUR ledger: a claimed far-future
        # ledgerNum would make the phase unexpirable and block every
        # future survey on this node
        self.collecting = CollectingState(
            surveyor, msg.nonce, min(msg.ledgerNum, self._ledger_num()))
        return True

    def recv_stop_collecting(self, peer, signed) -> bool:
        msg = signed.stopCollecting
        if not self._verify(msg.surveyorID.value, self.TAG_STOP,
                            msg.to_xdr(), signed.signature):
            return False
        # only the surveyor who started the survey may stop it — a stop
        # self-signed by any other peer must neither clear the nonce nor
        # be relayed
        entry = self._known_nonces.get(msg.nonce)
        known = entry is not None and entry[0] == msg.surveyorID.value
        if known:
            del self._known_nonces[msg.nonce]
        if self.collecting is None or self.collecting.nonce != msg.nonce \
                or self.collecting.surveyor != msg.surveyorID.value:
            # still relay a stop for a known survey so it reaches
            # collectors behind this node
            return known
        self.collecting = None
        return True

    def recv_request(self, peer, signed) -> bool:
        """Validate; if addressed to us, respond.  Returns relay verdict."""
        req = signed.request
        inner = req.request
        surveyor = inner.surveyorPeerID.value
        if not self._verify(surveyor, self.TAG_REQUEST, req.to_xdr(),
                            signed.requestSignature):
            return False
        self.maybe_expire()
        local = (self.collecting is not None
                 and self.collecting.nonce == req.nonce
                 and self.collecting.surveyor == surveyor)
        if inner.surveyedPeerID.value != self.overlay.node_id:
            # relay toward the surveyed node whenever the nonce belongs to
            # a known active survey AND the request comes from the surveyor
            # who started it, even if this node missed/expired the
            # collecting phase — nodes behind us may still be collecting
            entry = self._known_nonces.get(req.nonce)
            return local or (entry is not None and entry[0] == surveyor)
        if not local:
            return False  # addressed to us but we are not in this run
        body = self._build_response_body()
        blob = box.seal(inner.encryptionKey.key, body.to_xdr())
        resp = X.TimeSlicedSurveyResponseMessage(
            response=X.SurveyResponseMessage(
                surveyorPeerID=inner.surveyorPeerID,
                surveyedPeerID=inner.surveyedPeerID,
                ledgerNum=inner.ledgerNum,
                encryptedBody=blob),
            nonce=req.nonce)
        signed_resp = X.SignedTimeSlicedSurveyResponseMessage(
            responseSignature=self._sign(self.TAG_RESPONSE, resp.to_xdr()),
            response=resp)
        self._flood(
            X.StellarMessage.signedTimeSlicedSurveyResponseMessage(
                signed_resp))
        return True

    def recv_response(self, peer, signed) -> bool:
        resp = signed.response
        inner = resp.response
        surveyed = inner.surveyedPeerID.value
        if not self._verify(surveyed, self.TAG_RESPONSE, resp.to_xdr(),
                            signed.responseSignature):
            return False
        if inner.surveyorPeerID.value != self.overlay.node_id:
            return True   # relay toward the surveyor
        if self._nonce is None or resp.nonce != self._nonce:
            return False
        try:
            body_xdr = box.seal_open(self._enc_sk, bytes(inner.encryptedBody))
            body = X.SurveyResponseBody.from_xdr(body_xdr)
        except Exception as e:
            log.warning("undecryptable survey response from %s: %s",
                        surveyed.hex()[:8], e)
            self._bad_response_nodes.append(surveyed.hex())
            return False
        self._results[surveyed] = _body_to_json(body.value)
        return False  # addressed to us — no further relay

    # -- shared --------------------------------------------------------------
    def record_added_peer(self) -> None:
        if self.collecting is not None:
            self.collecting.added_peers += 1

    def record_dropped_peer(self) -> None:
        if self.collecting is not None:
            self.collecting.dropped_peers += 1

    def record_lost_sync(self) -> None:
        if self.collecting is not None:
            self.collecting.lost_sync_count += 1

    def maybe_expire(self) -> None:
        """Collecting phases time out rather than lingering (reference:
        SurveyDataManager::updateSurveyPhase)."""
        if self.collecting is not None and self._ledger_num() > \
                self.collecting.start_ledger + MAX_COLLECTING_LEDGERS:
            self.collecting = None
        now = self._ledger_num()
        stale = [n for n, (_sv, start) in self._known_nonces.items()
                 if now > start + MAX_COLLECTING_LEDGERS]
        for n in stale:
            del self._known_nonces[n]

    def _build_response_body(self) -> X.SurveyResponseBody:
        inbound, outbound = [], []
        for p in self.overlay._auth_peer_list():
            stats = X.TimeSlicedPeerData(peerStats=X.PeerStats(
                id=X.NodeID.ed25519(p.peer_id),
                versionStr=getattr(p, "remote_version_str", "") or "",
                messagesRead=p._recv_seq,
                messagesWritten=p._send_seq))
            bucket = outbound if p.we_called_remote else inbound
            if len(bucket) < MAX_RESPONSE_PEERS:
                bucket.append(stats)
        c = self.collecting
        node_data = X.TimeSlicedNodeData(
            addedAuthenticatedPeers=c.added_peers if c else 0,
            droppedAuthenticatedPeers=c.dropped_peers if c else 0,
            totalInboundPeerCount=len(inbound),
            totalOutboundPeerCount=len(outbound),
            lostSyncCount=c.lost_sync_count if c else 0,
            isValidator=1 if getattr(self.overlay.herder, "is_validator",
                                     True) else 0)
        return X.SurveyResponseBody.topologyResponseBodyV2(
            X.TopologyResponseBodyV2(inboundPeers=inbound,
                                     outboundPeers=outbound,
                                     nodeData=node_data))

    def _ledger_num(self) -> int:
        return self.overlay.herder.lm.last_closed_ledger_seq

    def _flood(self, sm: X.StellarMessage) -> None:
        h = sha256(sm.to_xdr())
        self.overlay.floodgate.add_record(h, self._ledger_num())
        self.overlay._broadcast(sm, h)


def _body_to_json(body: "X.TopologyResponseBodyV2") -> dict:
    def peers(lst):
        return [{
            "nodeId": pd.peerStats.id.value.hex(),
            "version": pd.peerStats.versionStr,
            "messagesRead": pd.peerStats.messagesRead,
            "messagesWritten": pd.peerStats.messagesWritten,
        } for pd in lst]
    nd = body.nodeData
    return {
        "inboundPeers": peers(body.inboundPeers),
        "outboundPeers": peers(body.outboundPeers),
        "nodeData": {
            "addedAuthenticatedPeers": nd.addedAuthenticatedPeers,
            "droppedAuthenticatedPeers": nd.droppedAuthenticatedPeers,
            "totalInbound": nd.totalInboundPeerCount,
            "totalOutbound": nd.totalOutboundPeerCount,
            "lostSyncCount": nd.lostSyncCount,
            "isValidator": bool(nd.isValidator),
        },
    }
