"""P2P overlay layer (reference: src/overlay/)."""

from .flood import Floodgate, ItemFetcher, TxAdverts
from .overlay_manager import OverlayManager
from .peer import (FrameDecoder, LoopbackPeer, Peer, frame_encode,
                   make_loopback_pair)
from .peer_auth import PeerAuth, mac_message, mac_ok
from .tcp import TCPPeer, TCPTransport

__all__ = [
    "Floodgate", "FrameDecoder", "ItemFetcher", "LoopbackPeer",
    "OverlayManager", "Peer", "PeerAuth", "TCPPeer", "TCPTransport",
    "TxAdverts", "frame_encode", "mac_message", "mac_ok",
    "make_loopback_pair",
]
