"""Floodgate + pull-mode tx adverts.

Reference: src/overlay/Floodgate.{h,cpp} — per-message flood records keyed
by hash, remembering which peers already have it; clearBelow GC by ledger
seq.  src/overlay/TxAdverts.{h,cpp} — pull-mode tx flooding: hashes are
advertised (FLOOD_ADVERT), interested peers demand (FLOOD_DEMAND), only
then the full TRANSACTION flows.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Set

from .. import xdr as X

ADVERT_FLUSH_BATCH = 50


class FloodRecord:
    __slots__ = ("ledger_seq", "peers_told")

    def __init__(self, ledger_seq: int):
        self.ledger_seq = ledger_seq
        # actual peer objects (NOT id()s: a freed peer's id can be reused
        # by a new allocation, silently aliasing flood state); records are
        # GC'd by clear_below so the references are bounded
        self.peers_told: Set[object] = set()


class Floodgate:
    def __init__(self) -> None:
        self._records: Dict[bytes, FloodRecord] = {}

    def add_record(self, msg_hash: bytes, ledger_seq: int,
                   from_peer=None) -> bool:
        """Record a message sighting; True when it is NEW (process it and
        re-flood), False when already seen (reference:
        Floodgate::addRecord).  The source peer is remembered either way so
        broadcast never echoes a message back."""
        rec = self._records.get(msg_hash)
        if rec is None:
            rec = self._records[msg_hash] = FloodRecord(ledger_seq)
            if from_peer is not None:
                rec.peers_told.add(from_peer)
            return True
        if from_peer is not None:
            rec.peers_told.add(from_peer)
        return False

    def seen(self, msg_hash: bytes) -> bool:
        return msg_hash in self._records

    def note_duplicate(self, msg_hash: bytes, from_peer) -> bool:
        """Record a repeat sighting WITHOUT needing the ledger seq: True
        when the hash is a known record (the peer is noted so broadcast
        never echoes back), False when the record is unknown/expired and
        the caller must take the full decode + add_record path."""
        rec = self._records.get(msg_hash)
        if rec is None:
            return False
        if from_peer is not None:
            rec.peers_told.add(from_peer)
        return True

    def note_told(self, msg_hash: bytes, peer) -> None:
        rec = self._records.get(msg_hash)
        if rec is not None:
            rec.peers_told.add(peer)

    def peers_told(self, msg_hash: bytes) -> Set[object]:
        rec = self._records.get(msg_hash)
        return rec.peers_told if rec is not None else set()

    def clear_below(self, ledger_seq: int) -> None:
        for h in [h for h, r in self._records.items()
                  if r.ledger_seq < ledger_seq]:
            del self._records[h]


class TxAdverts:
    """Per-peer advert/demand queues (pull-mode flooding)."""

    def __init__(self, send_advert: Callable, send_demand: Callable):
        self._send_advert = send_advert    # (peer, [hashes])
        self._send_demand = send_demand
        self._outgoing: Dict[int, List[bytes]] = {}   # id(peer) -> hashes
        self._peers: Dict[int, object] = {}

    def queue_advert(self, peer, tx_hash: bytes) -> None:
        pid = id(peer)
        self._peers[pid] = peer
        q = self._outgoing.setdefault(pid, [])
        q.append(tx_hash)
        if len(q) >= ADVERT_FLUSH_BATCH:
            self.flush_peer(peer)

    def flush_peer(self, peer) -> None:
        q = self._outgoing.pop(id(peer), None)
        self._peers.pop(id(peer), None)
        if q:
            self._send_advert(peer, q[:X.TX_ADVERT_VECTOR_MAX_SIZE])

    def flush_all(self) -> None:
        for pid in list(self._outgoing):
            peer = self._peers.get(pid)
            if peer is not None:
                self.flush_peer(peer)

    def forget_peer(self, peer) -> None:
        self._outgoing.pop(id(peer), None)
        self._peers.pop(id(peer), None)


class ItemFetcher:
    """Hash-addressed fetch of tx sets / quorum sets from peers.

    Reference: src/overlay/ItemFetcher.{h,cpp} + Tracker — one tracker per
    wanted hash, asking one peer at a time, advancing on DONT_HAVE or peer
    drop, re-asking as new peers authenticate, and RETRYING on a timer
    (reference: MS_TO_WAIT_FOR_FETCH_REPLY): a request or reply frame
    lost in flight (lossy link, peer severed mid-fetch) must not wedge
    the tracker until some unrelated peer happens to authenticate — the
    chaos link-degradation campaigns wedge exactly there without it.
    Once every current peer has been asked, a retry round clears the
    asked set and starts over; after RETRY_LIMIT rounds the tracker is
    dropped (the item is gone network-wide — e.g. a tx set purged past
    the peers' slot memory) so dead hashes don't re-arm timers forever."""

    RETRY_PERIOD_S = 1.5
    RETRY_LIMIT = 64

    def __init__(self, ask: Callable, clock=None,
                 peers_fn: Optional[Callable[[], List]] = None):
        self._ask = ask               # (peer, item_type, hash)
        self._clock = clock
        self._peers_fn = peers_fn
        self._tracking: Dict[bytes, dict] = {}

    def fetch(self, item_type: str, h: bytes, peers: List) -> None:
        if h in self._tracking:
            return
        self._tracking[h] = {"type": item_type, "asked": set(),
                             "retries": 0, "timer": None}
        self._try_next(h, peers)
        self._arm_retry(h)

    def _try_next(self, h: bytes, peers: List) -> None:
        tr = self._tracking.get(h)
        if tr is None:
            return
        for peer in peers:
            if peer not in tr["asked"]:
                tr["asked"].add(peer)
                self._ask(peer, tr["type"], h)
                return
        # nobody left to ask; the retry timer (or a new peer) re-opens

    def _arm_retry(self, h: bytes) -> None:
        if self._clock is None:
            return
        from ..util.clock import VirtualTimer
        tr = self._tracking.get(h)
        if tr is None:
            return
        timer = VirtualTimer(self._clock)
        tr["timer"] = timer
        timer.expires_from_now(self.RETRY_PERIOD_S,
                               lambda: self._retry(h))

    def _retry(self, h: bytes) -> None:
        tr = self._tracking.get(h)
        if tr is None:
            return   # answered (stop_fetch) since the timer was armed
        peers = self._peers_fn() if self._peers_fn is not None else []
        if all(p in tr["asked"] for p in peers):
            # full round exhausted (vacuously so when no peers exist):
            # count ROUNDS, not timer fires — with more peers than
            # RETRY_LIMIT every peer must still be asked once before the
            # tracker can be declared dead
            tr["retries"] += 1
            if tr["retries"] > self.RETRY_LIMIT:
                del self._tracking[h]
                return
            tr["asked"].clear()
        self._try_next(h, peers)
        self._arm_retry(h)

    def dont_have(self, h: bytes, from_peer, peers: List) -> None:
        self._try_next(h, peers)

    def peer_available(self, peer, peers: List) -> None:
        for h in list(self._tracking):
            self._try_next(h, peers)

    def stop_fetch(self, h: bytes) -> None:
        tr = self._tracking.pop(h, None)
        if tr is not None and tr.get("timer") is not None:
            tr["timer"].cancel()

    def wanted(self) -> List[bytes]:
        return list(self._tracking)
