"""OverlayManager: peer lifecycle + flooding + herder integration.

Reference: src/overlay/OverlayManagerImpl.{h,cpp} — peer registry with
pending/authenticated split, broadcastMessage through the Floodgate,
recvFloodedMsg dedup, tx advert queues, item fetch wiring into the herder
(PendingEnvelopes), GET_SCP_STATE serving, connectTo/acceptAuthenticated.

Transport-agnostic: peers are Peer subclasses (LoopbackPeer for
deterministic tests, TCPPeer for real sockets — tcp.py).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..util import eventlog
from ..util import logging as slog
from ..util.metrics import registry as _registry
from ..util.racetrace import race_checked
from .ban import BanManager
from .flood import Floodgate, ItemFetcher, TxAdverts
from .peer import Peer
from .peer_auth import PeerAuth
from .peer_manager import PeerManager
from .survey import SurveyManager

log = slog.get("Overlay")

# per-type intake meter names, precomputed: _message_received is the
# hottest overlay path and must not rebuild the slug per message
_RECV_METER = {t: "overlay.recv." + t.name.lower().replace("_", "-")
               for t in X.MessageType}


@race_checked
class OverlayManager:
    def __init__(self, clock, herder, network_id: bytes,
                 node_secret: SecretKey, listening_port: int = 0,
                 auth_seed: Optional[bytes] = None, database=None,
                 batching: bool = True, batch_max_messages: int = 64,
                 batch_max_bytes: int = 128 * 1024):
        self.clock = clock
        self.herder = herder
        self.network_id = network_id
        # batched authenticated transport (overlay/peer.py): willingness
        # to negotiate AUTH_FLAG_BATCH on every link this manager owns,
        # plus the per-peer coalescing-run caps.  Config surface:
        # OVERLAY_BATCHING / OVERLAY_BATCH_MAX_MESSAGES /
        # OVERLAY_BATCH_MAX_BYTES.
        self.batching = batching
        self.batch_max_messages = max(
            1, min(batch_max_messages, X.BATCH_WIRE_MAX_MESSAGES))
        self.batch_max_bytes = max(1, batch_max_bytes)
        self.node_id = node_secret.public_key.ed25519
        self.listening_port = listening_port
        self.peer_auth = PeerAuth(node_secret, network_id,
                                  now_fn=clock.system_now,
                                  auth_seed=auth_seed)
        self.pending_peers: List[Peer] = []
        # peer_id -> Peer; /peers snapshots this from admin threads
        self.authenticated_peers: Dict[bytes, Peer] = {}  # corelint: owned-by=main -- peer lifecycle runs on the crank loop; admin /peers reads are GIL-atomic snapshots
        self.peer_manager = PeerManager(clock, database,
                                        self_port=listening_port)
        self.floodgate = Floodgate()
        self.adverts = TxAdverts(self._send_advert, self._send_demand)
        self.fetcher = ItemFetcher(self._ask_for_item, clock=clock,
                                   peers_fn=self._auth_peer_list)
        self.ban_manager = BanManager(database)
        self.survey = SurveyManager(self, node_secret)
        herder.lost_sync_hook = self.survey.record_lost_sync
        self.stats = {"flooded": 0, "deduped": 0, "dropped_peers": 0,
              "txsets_served": 0, "qsets_served": 0}
        self._recv_meters: Dict[object, object] = {}
        # weak_gauge: must not pin a torn-down node's peer graph in the
        # process-global registry (dead source -> null gauge)
        _registry().weak_gauge("overlay.peer.authenticated", self,
                               lambda o: len(o.authenticated_peers))

        # herder wiring (same seams the in-process simulation uses)
        herder.broadcast = self.broadcast_scp_envelope
        herder.tx_flood = self.flood_transaction
        herder.pending.fetch_qset = lambda h: self.fetcher.fetch(
            "qset", h, self._auth_peer_list())
        herder.pending.fetch_txset = lambda h: self.fetcher.fetch(
            "txset", h, self._auth_peer_list())
        self._advert_timer = None
        self._start_advert_timer()

    ADVERT_FLUSH_INTERVAL = 0.5

    def _start_advert_timer(self) -> None:
        """Partially-filled advert batches flush on a cadence (reference:
        TxAdverts::startAdvertTimer ~100ms)."""
        from ..util.clock import VirtualTimer
        self._advert_timer = VirtualTimer(self.clock)

        def tick() -> None:
            self.adverts.flush_all()
            self._advert_timer.expires_from_now(
                self.ADVERT_FLUSH_INTERVAL, tick)

        self._advert_timer.expires_from_now(self.ADVERT_FLUSH_INTERVAL, tick)

    # -- peer registry ------------------------------------------------------
    def _register_peer(self, peer: Peer) -> None:
        self.pending_peers.append(peer)

    def _peer_authenticated(self, peer: Peer) -> None:
        if self.ban_manager.is_banned(peer.peer_id):
            peer.drop("banned node")
            return
        if peer in self.pending_peers:
            self.pending_peers.remove(peer)
        self.survey.record_added_peer()
        old = self.authenticated_peers.get(peer.peer_id)
        if old is not None and old is not peer:
            # simultaneous cross-connections: both sides must pick the SAME
            # survivor or each drops the other's keeper and the pair
            # disconnects entirely.  Symmetric rule: keep the connection
            # dialed by the lexicographically smaller node id.
            keep_new = peer.we_called_remote == (self.node_id < peer.peer_id)
            if not keep_new:
                peer.drop("duplicate connection (kept existing)")
                return
            old.drop("superseded by new connection")
        self.authenticated_peers[peer.peer_id] = peer
        log.info("peer %s authenticated (%s)", peer.peer_id.hex()[:8],
                 "outbound" if peer.we_called_remote else "inbound")
        eventlog.record("Overlay", "INFO", "peer authenticated",
                        peer=peer.peer_id.hex()[:8],
                        direction="outbound" if peer.we_called_remote
                        else "inbound",
                        authenticated=len(self.authenticated_peers))
        # learn the network (reference: Peer::recvAuth -> sendGetPeers)
        peer.send_message(X.StellarMessage.getPeers())
        if peer.remote_listening_port > 0 and hasattr(peer, "sock") \
                and peer.sock is not None:
            try:
                host = peer.sock.getpeername()[0]
                self.peer_manager.add_address(host,
                                              peer.remote_listening_port)
                self.peer_manager.record_success(host,
                                                 peer.remote_listening_port)
            except OSError:
                pass
        # bring the peer up to date on consensus (reference:
        # Peer::recvAuth -> sendSCPState... via Herder)
        for env in self.herder.get_scp_state(0):
            peer.send_message(X.StellarMessage.envelope(env))
        self.fetcher.peer_available(peer, self._auth_peer_list())

    def _peer_dropped(self, peer: Peer) -> None:
        _registry().counter("overlay.peer.drop").inc()
        self.stats["dropped_peers"] += 1
        eventlog.record("Overlay", "INFO", "peer dropped",
                        peer=peer.peer_id.hex()[:8]
                        if peer.peer_id else "(unauthenticated)",
                        reason=getattr(peer, "drop_reason", None) or "?")
        if peer.is_authenticated():
            self.survey.record_dropped_peer()
        # outbound dials that never authenticated feed the backoff policy
        dial = getattr(peer, "dial_addr", None)
        if dial is not None and peer.we_called_remote \
                and peer.state != Peer.GOT_AUTH and peer.peer_id is None:
            self.peer_manager.record_failure(*dial)
        if peer in self.pending_peers:
            self.pending_peers.remove(peer)
        if peer.peer_id is not None and \
                self.authenticated_peers.get(peer.peer_id) is peer:
            del self.authenticated_peers[peer.peer_id]
        self.adverts.forget_peer(peer)

    def _auth_peer_list(self) -> List[Peer]:
        return list(self.authenticated_peers.values())

    def num_authenticated(self) -> int:
        return len(self.authenticated_peers)

    def connected_addresses(self) -> set:
        """(host, listening_port) of live connections — the dial-exclusion
        set (pending dials included so a slow handshake isn't re-dialed)."""
        out = set()
        for peer in (*self.authenticated_peers.values(),
                     *self.pending_peers):
            dial = getattr(peer, "dial_addr", None)
            if dial is not None:
                out.add(dial)
            elif peer.peer_id is not None and peer.remote_listening_port \
                    and hasattr(peer, "sock") and peer.sock is not None:
                try:
                    out.add((peer.sock.getpeername()[0],
                             peer.remote_listening_port))
                except OSError:
                    pass
        return out

    # -- outbound flooding --------------------------------------------------
    def broadcast_scp_envelope(self, env) -> None:
        msg = X.StellarMessage.envelope(env)
        body = msg.to_xdr()
        h = sha256(body)
        self.floodgate.add_record(h, env.statement.slotIndex)
        self._broadcast(msg, h, body)

    def flood_transaction(self, frame) -> None:
        """Pull-mode: advertise the hash; peers demand what they miss."""
        h = frame.content_hash()
        self.floodgate.add_record(
            h, self.herder.tracking_consensus_ledger_index())
        for peer in self._auth_peer_list():
            if peer not in self.floodgate.peers_told(h):
                self.adverts.queue_advert(peer, h)

    def _broadcast(self, msg: X.StellarMessage, msg_hash: bytes,
                   body: Optional[bytes] = None) -> None:
        # `body` = the message's XDR encoding when the caller already has
        # it: a fleet-wide flood re-encoding the identical payload once
        # per peer was measurably hot at 150+ simulated nodes
        told = self.floodgate.peers_told(msg_hash)
        flood_meter = _registry().meter("overlay.message.flood")
        for peer in self._auth_peer_list():
            if peer not in told:
                peer.send_message(msg, body=body)
                self.floodgate.note_told(msg_hash, peer)
                self.stats["flooded"] += 1
                flood_meter.mark()

    def _send_advert(self, peer: Peer, hashes: List[bytes]) -> None:
        peer.send_message(X.StellarMessage.floodAdvert(
            X.FloodAdvert(txHashes=hashes)))

    def _send_demand(self, peer: Peer, hashes: List[bytes]) -> None:
        peer.send_message(X.StellarMessage.floodDemand(
            X.FloodDemand(txHashes=hashes)))

    def _ask_for_item(self, peer: Peer, item_type: str, h: bytes) -> None:
        if item_type == "txset":
            peer.send_message(X.StellarMessage.txSetHash(h))
        else:
            peer.send_message(X.StellarMessage.qSetHash(h))

    def request_scp_state(self) -> None:
        """Ask every authenticated peer for recent SCP state (reference:
        HerderImpl::getMoreSCPState → Peer::sendGetScpState) — the lagging
        node's first recovery step; archive catchup takes over when the
        gap exceeds the peers' slot memory."""
        seq = max(0, self.herder.tracking_consensus_ledger_index() - 1)
        for peer in self._auth_peer_list():
            peer.send_message(X.StellarMessage.getSCPLedgerSeq(seq))

    def flush_adverts(self) -> None:
        self.adverts.flush_all()

    # -- admission back-pressure (reference: FlowControl's capacity model
    #    is the valve; the admission queue depth is the signal) ------------
    def flood_grants_paused(self) -> bool:
        """True while the herder's admission pipeline is back-pressured:
        peers' earned flow-control capacity is deferred, throttling the
        flood intake feeding the backlog (overlay/peer.py)."""
        adm = getattr(self.herder, "admission", None)
        return adm is not None and adm.backpressured

    def release_flood_grants(self) -> None:
        """Back-pressure released: ship every deferred grant (wired to
        AdmissionPipeline.on_backpressure_release)."""
        for peer in self._auth_peer_list():
            peer.release_deferred_grant()

    def clear_below(self, ledger_seq: int) -> None:
        self.floodgate.clear_below(ledger_seq)
        self.survey.maybe_expire()

    # -- inbound dispatch ---------------------------------------------------
    def ledger_version(self) -> int:
        return self.herder.lm.lcl_header.ledgerVersion

    def _message_received(self, peer: Peer, msg: X.StellarMessage,
                          body: Optional[bytes] = None,
                          body_hash: Optional[bytes] = None) -> None:
        # `body` = the message's own XDR bytes as received (sliced from
        # the authenticated frame) — the SCP hot path hashes and
        # re-floods them without a re-encode
        t = msg.switch
        MT = X.MessageType
        # per-message-type intake meter (reference: the per-type
        # "overlay.recv.*" medida timers in Peer::recvMessage); meter
        # objects cached per manager — a registry lookup per message is
        # real money at simulated-fleet message rates
        meter = self._recv_meters.get(t)
        if meter is None:
            meter = self._recv_meters[t] = _registry().meter(_RECV_METER[t])
        meter.mark()
        if t in (MT.SEND_MORE, MT.SEND_MORE_EXTENDED):
            return  # handled in Peer flow control
        if t == MT.SCP_MESSAGE:
            self._recv_scp(peer, msg, body, body_hash)
        elif t == MT.TRANSACTION:
            self._recv_transaction(peer, msg)
        elif t == MT.FLOOD_ADVERT:
            self._recv_advert(peer, msg.value.txHashes)
        elif t == MT.FLOOD_DEMAND:
            self._recv_demand(peer, msg.value.txHashes)
        elif t == MT.GET_TX_SET:
            self._serve_txset(peer, msg.value)
        elif t in (MT.TX_SET, MT.GENERALIZED_TX_SET):
            txset = msg.value
            h = sha256(txset.to_xdr())
            self.fetcher.stop_fetch(h)
            self.herder.recv_tx_set(h, txset)
        elif t == MT.DONT_HAVE:
            self.fetcher.dont_have(msg.value.reqHash, peer,
                                   self._auth_peer_list())
        elif t == MT.GET_SCP_QUORUMSET:
            self._serve_qset(peer, msg.value)
        elif t == MT.SCP_QUORUMSET:
            qs = msg.value
            from ..scp.quorum import qset_hash
            self.fetcher.stop_fetch(qset_hash(qs))
            self.herder.recv_qset(qs)
        elif t == MT.GET_SCP_STATE:
            for env in self.herder.get_scp_state(msg.value):
                peer.send_message(X.StellarMessage.envelope(env))
        elif t == MT.GET_PEERS:
            peer.send_message(X.StellarMessage.peers(
                self.peer_manager.peers_to_send()))
        elif t == MT.PEERS:
            self.peer_manager.add_peer_addresses(msg.value)
        elif t in (MT.TIME_SLICED_SURVEY_REQUEST,
                   MT.TIME_SLICED_SURVEY_RESPONSE,
                   MT.TIME_SLICED_SURVEY_START_COLLECTING,
                   MT.TIME_SLICED_SURVEY_STOP_COLLECTING):
            self._recv_survey(peer, msg)
        else:
            log.warning("unhandled message type %s", t)

    def _recv_survey(self, peer: Peer, msg: X.StellarMessage) -> None:
        """Dedup + dispatch to the SurveyManager; relay when the handler
        accepts the message (reference: Peer::recvSurvey* →
        SurveyManager::relayOrProcess...)."""
        h = sha256(msg.to_xdr())
        if not self.floodgate.add_record(
                h, self.herder.tracking_consensus_ledger_index(), peer):
            self.stats["deduped"] += 1
            _registry().meter("overlay.flood.duplicate").mark()
            return
        t = msg.switch
        MT = X.MessageType
        handler = {
            MT.TIME_SLICED_SURVEY_REQUEST: self.survey.recv_request,
            MT.TIME_SLICED_SURVEY_RESPONSE: self.survey.recv_response,
            MT.TIME_SLICED_SURVEY_START_COLLECTING:
                self.survey.recv_start_collecting,
            MT.TIME_SLICED_SURVEY_STOP_COLLECTING:
                self.survey.recv_stop_collecting,
        }[t]
        if handler(peer, msg.value):
            self._broadcast(msg, h)

    # -- transport-level duplicate fast path --------------------------------
    # The batched receive path slices raw bodies before decoding them;
    # for SCP traffic (dedup-keyed on sha256 of the body bytes) that seam
    # lets a flood duplicate be recognised and dropped BEFORE paying the
    # XDR decode — at fleet scale most deliveries are duplicates, so this
    # is where the soak's receive-side codec time goes.
    def flood_seen(self, body_hash: bytes) -> bool:
        """Pure check (no mutation): is this body hash a known flood
        record?  Peer uses it during batch validation, where nothing may
        change observable state until the whole frame proves well-formed."""
        return self.floodgate.seen(body_hash)

    def _note_flood_duplicate(self, peer: Peer, body_hash: bytes) -> bool:
        """Account a pre-decode duplicate drop: notes the sender on the
        flood record (broadcast must not echo back) and marks the same
        dedup stats the decoded path would.  False when the record was
        GC'd between the frame's validation and dispatch phases (a ledger
        close mid-run ran clear_below) — the caller falls back to the
        full decode + dispatch path."""
        if not self.floodgate.note_duplicate(body_hash, peer):
            return False
        self.stats["deduped"] += 1
        _registry().meter("overlay.flood.duplicate").mark()
        return True

    def _recv_scp(self, peer: Peer, msg: X.StellarMessage,
                  body: Optional[bytes] = None,
                  body_hash: Optional[bytes] = None) -> None:
        env = msg.value
        if body is None:
            body = msg.to_xdr()
        h = body_hash if body_hash is not None else sha256(body)
        if not self.floodgate.add_record(h, env.statement.slotIndex, peer):
            self.stats["deduped"] += 1
            _registry().meter("overlay.flood.duplicate").mark()
            return
        status = self.herder.recv_scp_envelope(env)
        if status != "discarded":
            self._broadcast(msg, h, body)

    def _recv_transaction(self, peer: Peer, msg: X.StellarMessage) -> None:
        try:
            frame = self.herder.lm.make_frame(msg.value)
        except Exception:
            peer.drop("undecodable transaction")
            return
        h = frame.content_hash()
        if not self.floodgate.add_record(
                h, self.herder.tracking_consensus_ledger_index(), peer):
            self.stats["deduped"] += 1
            _registry().meter("overlay.flood.duplicate").mark()
            return
        res = self.herder.recv_transaction(frame, origin="overlay")
        if self.herder.admission is not None:
            # batched admission floods via on_admitted -> flood_transaction
            # once the frame actually verifies; re-advertising here would
            # announce txs that may still fail admission
            return
        if getattr(res, "code", None) == "pending":
            # re-advertise to everyone who hasn't seen it
            for p in self._auth_peer_list():
                if p not in self.floodgate.peers_told(h):
                    self.adverts.queue_advert(p, h)

    def _recv_advert(self, peer: Peer, hashes: List[bytes]) -> None:
        demand = [h for h in hashes if not self.floodgate.seen(h)]
        if demand:
            self._send_demand(peer, demand[:X.TX_DEMAND_VECTOR_MAX_SIZE])

    def _recv_demand(self, peer: Peer, hashes: List[bytes]) -> None:
        for h in hashes:
            frame = self.herder.tx_queue.by_hash.get(h)
            if frame is not None:
                peer.send_message(X.StellarMessage.transaction(
                    frame.envelope))
                self.floodgate.note_told(h, peer)

    def _serve_txset(self, peer: Peer, h: bytes) -> None:
        got = self.herder.pending.get_txset(h)
        if got is not None:
            self.stats["txsets_served"] += 1
            txset = got[0]
            if isinstance(txset, X.GeneralizedTransactionSet):
                peer.send_message(X.StellarMessage.generalizedTxSet(txset))
            else:
                peer.send_message(X.StellarMessage.txSet(txset))
        else:
            peer.send_message(X.StellarMessage.dontHave(X.DontHave(
                type=X.MessageType.GET_TX_SET, reqHash=h)))

    def _serve_qset(self, peer: Peer, h: bytes) -> None:
        qs = self.herder.pending.get_qset(h)
        if qs is not None:
            self.stats["qsets_served"] += 1
            peer.send_message(X.StellarMessage.qSet(qs))
        else:
            peer.send_message(X.StellarMessage.dontHave(X.DontHave(
                type=X.MessageType.GET_SCP_QUORUMSET, reqHash=h)))
