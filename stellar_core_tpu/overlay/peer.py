"""Peer: handshake + authenticated messaging + flow control state machine.

Reference: src/overlay/Peer.{h,cpp} (recvHello/recvAuth/recvAuthenticated
dispatch, DropReason semantics), src/overlay/FlowControl.{h,cpp}
(capacity-granted flood sending via SEND_MORE_EXTENDED), transport left to
subclasses (TCPPeer / LoopbackPeer, like the reference).

Wire format: RFC 5531 record marking — every frame is a 4-byte big-endian
header with the high bit set (single-fragment) and the payload length in
the low 31 bits, followed by an AuthenticatedMessage XDR.  HELLO and
ERROR_MSG travel with sequence 0 and a zero MAC (no keys yet); everything
else is HMAC'd with per-direction keys and strictly increasing sequences.

Batched transport (TPU extension): when both sides set AUTH_FLAG_BATCH in
their AUTH, batch-eligible sends coalesce into a per-peer pending run that
flushes on a message/byte cap or on the next crank edge as ONE
BATCHED_AUTH frame — one sequence number + one MAC over the packed run
(AuthenticatedMessage arm 1, spliced from the already-encoded bodies).
Latency-sensitive types (AUTH, ERROR, SEND_MORE[_EXTENDED]) flush the run
and go out immediately as classic per-message frames, as does a run of
one, so a lone send keeps the unbatched wire format and latency.  Flow
control stays PER MESSAGE: capacity is debited per contained message on
send and earned per contained message on receive.
"""

from __future__ import annotations

import os
import struct
from typing import Callable, List, Optional

from .. import xdr as X
from ..crypto.sha import sha256
from ..util import logging as slog
from ..util.metrics import registry as _registry
from .peer_auth import PeerAuth, mac_message, mac_ok

log = slog.get("Overlay")

OVERLAY_PROTOCOL_VERSION = 38
OVERLAY_PROTOCOL_MIN_VERSION = 35
VERSION_STR = "stellar-core-tpu 2.0"

# flow control (reference: FlowControl::start — these are the capacities a
# node grants its peer when the connection authenticates)
PEER_FLOOD_READING_CAPACITY = 200
FLOW_CONTROL_SEND_MORE_BATCH = 40
PEER_FLOOD_READING_CAPACITY_BYTES = 300_000
FLOW_CONTROL_BYTES_BATCH = 100_000

_ZERO_MAC = b"\x00" * 32
# AuthenticatedMessage union discriminants (see _emit_authenticated /
# _emit_batch — both paths splice frames from pre-encoded bodies)
_AM_V0_ARM = b"\x00\x00\x00\x00"
_AM_BATCH_ARM = b"\x00\x00\x00\x01"

_FLOOD_TYPES = frozenset((
    X.MessageType.TRANSACTION, X.MessageType.SCP_MESSAGE,
    X.MessageType.FLOOD_ADVERT, X.MessageType.FLOOD_DEMAND))

# latency-sensitive types that must never wait in a coalescing run: the
# handshake pair, errors and flow-control grants (a grant riding a full
# batch would add exactly the stall it exists to break).  HELLO/ERROR
# travel unauthenticated anyway; listed for the avoidance of doubt.
_BATCH_IMMEDIATE = frozenset((
    X.MessageType.HELLO, X.MessageType.AUTH, X.MessageType.ERROR_MSG,
    X.MessageType.SEND_MORE, X.MessageType.SEND_MORE_EXTENDED))

# a batch is authenticated traffic between two completed handshakes:
# handshake/error messages inside one are protocol violations
_BATCH_FORBIDDEN = frozenset((
    X.MessageType.HELLO, X.MessageType.AUTH, X.MessageType.ERROR_MSG))

# StellarMessage's union discriminant is its first 4 XDR bytes — the batch
# receive path peeks it from the raw body to route SCP traffic through the
# pre-decode duplicate drop (flood dedup keys SCP on sha256 of the body)
_SCP_MESSAGE_SWITCH = int(X.MessageType.SCP_MESSAGE)


def frame_encode(payload: bytes) -> bytes:
    assert len(payload) < (1 << 31)
    return (0x80000000 | len(payload)).to_bytes(4, "big") + payload


class FrameDecoder:
    """Incremental record-mark deframer for a byte stream."""

    MAX_FRAME = 32 * 1024 * 1024

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        out = []
        while True:
            if len(self._buf) < 4:
                break
            header = int.from_bytes(self._buf[:4], "big")
            if not header & 0x80000000:
                raise ValueError("fragmented records not supported")
            length = header & 0x7FFFFFFF
            if length > self.MAX_FRAME:
                raise ValueError("oversized frame")
            if len(self._buf) < 4 + length:
                break
            out.append(bytes(self._buf[4:4 + length]))
            del self._buf[:4 + length]
        return out


class Peer:
    # connection states (reference: Peer::State)
    CONNECTING = "connecting"
    CONNECTED = "connected"      # transport up, HELLO exchange in flight
    GOT_HELLO = "got-hello"
    GOT_AUTH = "authenticated"
    CLOSING = "closing"

    def __init__(self, overlay, we_called_remote: bool):
        self.overlay = overlay
        self.auth: PeerAuth = overlay.peer_auth
        self.we_called_remote = we_called_remote
        self.state = Peer.CONNECTING
        self.peer_id: Optional[bytes] = None       # remote ed25519
        self.remote_listening_port: int = 0
        self.local_nonce = os.urandom(32)
        self._decoder = FrameDecoder()
        self._send_seq = 0
        self._recv_seq = 0
        self._send_key: Optional[bytes] = None
        self._recv_key: Optional[bytes] = None
        self.drop_reason: Optional[str] = None
        # flow control
        self._outbound_capacity = 0
        self._outbound_capacity_bytes = 0
        self._flood_queue: List[X.StellarMessage] = []
        self._processed_since_grant = 0
        self._processed_bytes_since_grant = 0
        # back-pressure: grants the admission pipeline told us to hold —
        # (messages, bytes) owed to the peer once the backlog drains
        self._deferred_grant: Optional[List[int]] = None
        # batched transport: local willingness (flipped per-peer by tests;
        # seeded from the overlay's config knob), what we advertised in
        # our AUTH, what the remote advertised in theirs, and the pending
        # coalescing run of pre-encoded bodies
        self.batching_enabled: bool = bool(overlay.batching)
        self._advertised_batch = False
        self._remote_batch = False
        self._batch_run: List[bytes] = []
        self._batch_bytes = 0
        self._batch_flush_armed = False
        self._batch_max_msgs = overlay.batch_max_messages
        self._batch_max_bytes = overlay.batch_max_bytes
        # wire accounting metric objects, cached for the peer's lifetime
        reg = _registry()
        self._ctr_byte_read = reg.counter("overlay.byte.read")
        self._ctr_byte_write = reg.counter("overlay.byte.write")
        self._met_msg_read = reg.meter("overlay.message.read")
        self._met_msg_write = reg.meter("overlay.message.write")
        self._met_batch_msgs = reg.meter("overlay.batch.messages")
        self._met_batch_flush = reg.meter("overlay.batch.flush")
        self._ctr_batch_bytes = reg.counter("overlay.batch.bytes")

    # -- transport interface (subclass-provided) ----------------------------
    def _write_bytes(self, data: bytes) -> None:
        raise NotImplementedError

    def _close_transport(self) -> None:
        raise NotImplementedError

    # -- lifecycle ----------------------------------------------------------
    def connect_handler(self) -> None:
        """Transport established.  The dialer speaks first (reference:
        TCPPeer::connectHandler -> sendHello)."""
        self.state = Peer.CONNECTED
        if self.we_called_remote:
            self.send_hello()

    def drop(self, reason: str) -> None:
        if self.state == Peer.CLOSING:
            return
        self.drop_reason = reason
        self.state = Peer.CLOSING
        self._batch_run = []
        self._batch_bytes = 0
        log.info("dropping peer %s: %s",
                 self.peer_id.hex()[:8] if self.peer_id else "?", reason)
        self._close_transport()
        self.overlay._peer_dropped(self)

    def is_authenticated(self) -> bool:
        return self.state == Peer.GOT_AUTH

    # -- sending ------------------------------------------------------------
    def send_hello(self) -> None:
        lcl = self.overlay.ledger_version()
        msg = X.StellarMessage.hello(X.Hello(
            ledgerVersion=lcl,
            overlayVersion=OVERLAY_PROTOCOL_VERSION,
            overlayMinVersion=OVERLAY_PROTOCOL_MIN_VERSION,
            networkID=self.overlay.network_id,
            versionStr=VERSION_STR,
            listeningPort=self.overlay.listening_port,
            peerID=X.NodeID.ed25519(self.overlay.node_id),
            cert=self.auth.get_cert(),
            nonce=self.local_nonce))
        self._send_unauthenticated(msg)

    def send_error(self, code, text: str) -> None:
        self._send_unauthenticated(X.StellarMessage.error(
            X.Error(code=code, msg=text)))

    def _send_unauthenticated(self, msg: X.StellarMessage) -> None:
        # an ERROR racing a pending run must land AFTER it (frame order =
        # send order); HELLO happens before keys exist, run empty
        self._flush_batch()
        am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
            sequence=0, message=msg, mac=X.HmacSha256Mac(mac=_ZERO_MAC)))
        self._write_frame(frame_encode(am.to_xdr()))

    def _write_frame(self, data: bytes) -> None:
        # wire-level accounting: framed bytes + messages out (reference:
        # the overlay byte/message write medida meters in Peer); metric
        # objects are cached per peer — a registry lookup per frame is
        # measurable at simulated-fleet message rates
        self._ctr_byte_write.inc(len(data))
        self._met_msg_write.mark()
        self._write_bytes(data)

    def send_message(self, msg: X.StellarMessage,
                     body: Optional[bytes] = None) -> None:
        """Authenticated send; flood messages respect granted capacity and
        queue when the peer hasn't given us room (reference:
        FlowControl::maybeSendMessage).  The XDR body is encoded exactly
        once and threaded through queueing, size accounting and the MAC —
        callers broadcasting one message to many peers pass the shared
        encoding via `body`."""
        if self.state == Peer.CLOSING:
            return
        if body is None:
            body = msg.to_xdr()
        if msg.switch in _FLOOD_TYPES:
            if self._outbound_capacity <= 0 \
                    or self._outbound_capacity_bytes < len(body):
                self._flood_queue.append((msg, body))
                return
            self._outbound_capacity -= 1
            self._outbound_capacity_bytes -= len(body)
        self._send_authenticated(msg, body)

    def _send_authenticated(self, msg: X.StellarMessage,
                            body: Optional[bytes] = None) -> None:
        if self._send_key is None:
            self.drop("send before auth keys")
            return
        if body is None:
            body = msg.to_xdr()
        if self._remote_batch and self.batching_enabled \
                and msg.switch not in _BATCH_IMMEDIATE:
            self._batch_append(body)
            return
        # immediate types (and everything on an unbatched link) preserve
        # send order: drain the pending run before emitting — which is
        # also how a deferred-grant release rides a batch flush
        self._flush_batch()
        self._emit_authenticated(body)

    def _emit_authenticated(self, body: bytes) -> None:
        mac = mac_message(self._send_key, self._send_seq, body)
        # splice the AuthenticatedMessage from the already-encoded body
        # instead of re-packing the whole message through the codec:
        # union arm v0 (uint32 0) + sequence (uint64) + message + 32-byte
        # MAC.  Byte-identical to the object path (unit-tested) and the
        # dominant per-link cost of a fleet-wide flood at 300 simulated
        # nodes.
        am_xdr = _AM_V0_ARM + struct.pack(">Q", self._send_seq) + body + mac
        self._send_seq += 1
        self._write_frame(frame_encode(am_xdr))

    # -- batched transport (send side) --------------------------------------
    def _batch_append(self, body: bytes) -> None:
        self._batch_run.append(body)
        self._batch_bytes += len(body)
        if len(self._batch_run) >= self._batch_max_msgs \
                or self._batch_bytes >= self._batch_max_bytes:
            self._flush_batch()
        elif not self._batch_flush_armed:
            # crank-edge flush: ONE posted action per empty->nonempty
            # edge.  A lone message still leaves within the current crank
            # round (no flush-delay regression), while a broadcast storm
            # appending N bodies this crank rides out as one frame.
            self._batch_flush_armed = True
            self.overlay.clock.post_action(self._crank_flush,
                                           name="overlay-batch-flush")

    def _crank_flush(self) -> None:
        self._batch_flush_armed = False
        if self.state != Peer.CLOSING:
            self._flush_batch()

    def _flush_batch(self) -> None:
        run = self._batch_run
        if not run:
            return
        self._batch_run = []
        self._batch_bytes = 0
        if len(run) == 1:
            # a run of one goes out as a classic per-message frame: the
            # batched link's quiet-path wire bytes and latency are
            # identical to an unbatched link's
            self._emit_authenticated(run[0])
            return
        self._emit_batch(run)

    def _emit_batch(self, run: List[bytes]) -> None:
        self._write_frame(self._build_batch_frame(run))

    def _build_batch_frame(self, run: List[bytes]) -> bytes:
        """Splice one BATCHED_AUTH frame: union arm 1 + sequence + packed
        run (count + per-body length prefix; bodies are XDR encodings so
        they are already 4-aligned) + one MAC over the packed run.
        Byte-identical to the BatchedAuthenticatedMessage codec path
        (unit-tested) without re-encoding any body."""
        payload = bytearray(struct.pack(">I", len(run)))
        for body in run:
            payload += struct.pack(">I", len(body))
            payload += body
        payload = bytes(payload)
        mac = mac_message(self._send_key, self._send_seq, payload)
        am_xdr = _AM_BATCH_ARM + struct.pack(">Q", self._send_seq) \
            + payload + mac
        self._send_seq += 1
        self._met_batch_msgs.mark(len(run))
        self._met_batch_flush.mark()
        self._ctr_batch_bytes.inc(len(am_xdr))
        return frame_encode(am_xdr)

    def _flush_flood_queue(self) -> None:
        while self._flood_queue and self._outbound_capacity > 0:
            msg, body = self._flood_queue[0]
            if self._outbound_capacity_bytes < len(body):
                break
            self._flood_queue.pop(0)
            self._outbound_capacity -= 1
            self._outbound_capacity_bytes -= len(body)
            self._send_authenticated(msg, body)

    @property
    def flood_queue_len(self) -> int:
        return len(self._flood_queue)

    # -- receiving ----------------------------------------------------------
    def data_received(self, data: bytes) -> None:
        self._ctr_byte_read.inc(len(data))
        try:
            frames = self._decoder.feed(data)
        except ValueError as e:
            self.drop(f"bad framing: {e}")
            return
        for frame in frames:
            if self.state == Peer.CLOSING:
                return
            self._met_msg_read.mark()
            self._frame_received(frame)

    def _frame_received(self, frame: bytes) -> None:
        if frame[:4] == _AM_BATCH_ARM:
            self._batch_frame_received(frame)
            return
        try:
            am = X.AuthenticatedMessage.from_xdr(frame)
        except Exception:
            self.drop("undecodable message")
            return
        v0 = am.value
        msg = v0.message
        if msg.switch == X.MessageType.HELLO:
            if v0.sequence != 0 or v0.mac.mac != _ZERO_MAC:
                self.drop("HELLO must be unauthenticated")
                return
            self._recv_hello(msg.value)
            return
        if msg.switch == X.MessageType.ERROR_MSG:
            err = msg.value
            self.drop(f"peer error: {err.code.name} "
                      f"{err.msg.decode(errors='replace')}")
            return
        # everything else requires the MAC chain.  The MAC'd body is the
        # frame minus the 4-byte union arm, the 8-byte sequence and the
        # trailing 32-byte MAC — sliced instead of re-encoding the
        # message the codec just decoded (the decode above already
        # proved the frame is exactly this shape).
        if self._recv_key is None:
            self.drop("authenticated message before HELLO exchange")
            return
        body = frame[12:len(frame) - 32]
        if v0.sequence != self._recv_seq \
                or not mac_ok(self._recv_key, v0.sequence, body, v0.mac.mac):
            self.drop("bad MAC or sequence")
            return
        self._recv_seq += 1
        if msg.switch == X.MessageType.AUTH:
            self._recv_auth(msg.value)
            return
        if not self.is_authenticated():
            self.drop("message before AUTH")
            return
        self._account_flood_processing(msg, len(body))
        self.overlay._message_received(self, msg, body=body)

    def _batch_frame_received(self, frame: bytes) -> None:
        """One BATCHED_AUTH frame: verify the single sequence + MAC over
        the packed run, then slice and dispatch each contained body
        through the exact per-message recv path.  EVERY body is decoded
        before ANY is dispatched — a malformed run from a keyed peer
        fail-stops with nothing partially delivered."""
        if self._recv_key is None:
            self.drop("authenticated message before HELLO exchange")
            return
        if not self._advertised_batch:
            # we never offered AUTH_FLAG_BATCH on this link: a compliant
            # peer cannot send arm-1 frames here
            self.drop("unnegotiated batch frame")
            return
        if len(frame) < 48:   # arm + sequence + count + MAC
            self.drop("bad batch framing")
            return
        sequence = struct.unpack_from(">Q", frame, 4)[0]
        payload = frame[12:len(frame) - 32]
        if sequence != self._recv_seq or not mac_ok(
                self._recv_key, sequence, payload, frame[len(frame) - 32:]):
            self.drop("bad MAC or sequence")
            return
        self._recv_seq += 1
        if not self.is_authenticated():
            self.drop("message before AUTH")
            return
        count = struct.unpack_from(">I", payload, 0)[0]
        if count == 0 or count > X.BATCH_WIRE_MAX_MESSAGES:
            self.drop("bad batch framing")
            return
        msgs = []
        off, end = 4, len(payload)
        for _ in range(count):
            if off + 4 > end:
                self.drop("bad batch framing")
                return
            ln = struct.unpack_from(">I", payload, off)[0]
            off += 4
            if ln > end - off:
                self.drop("bad batch framing")
                return
            body = payload[off:off + ln]
            off += ln
            h = None
            if ln >= 4 \
                    and struct.unpack_from(">I", body, 0)[0] \
                    == _SCP_MESSAGE_SWITCH:
                # pre-decode duplicate drop: SCP flood dedup keys on
                # sha256 of exactly these bytes, so a hash hit means the
                # body is byte-identical to a message that already
                # decoded cleanly — validity holds without re-decoding,
                # and at fleet scale most deliveries land here
                h = sha256(body)
                if self.overlay.flood_seen(h):
                    msgs.append((None, body, h))
                    continue
            try:
                msg = X.StellarMessage.from_xdr(body)
            except Exception:
                self.drop("undecodable message")
                return
            if msg.switch in _BATCH_FORBIDDEN:
                self.drop("bad batch framing")
                return
            msgs.append((msg, body, h))
        if off != end:
            self.drop("bad batch framing")
            return
        # data_received marked message.read once for the frame; make the
        # meter count contained messages, not frames
        self._met_msg_read.mark(len(msgs) - 1)
        for msg, body, h in msgs:
            if self.state == Peer.CLOSING:
                return   # a handler dropped us mid-run: stop dispatching
            if msg is None:
                # duplicate fast path: flow-control capacity is still
                # earned per contained message and the sender is noted
                # on the flood record so broadcast never echoes back
                self._account_flood_switch(X.MessageType.SCP_MESSAGE,
                                           len(body))
                if self.overlay._note_flood_duplicate(self, h):
                    continue
                # record GC'd between validation and dispatch (a ledger
                # close mid-run ran clear_below): take the decoded path
                try:
                    msg = X.StellarMessage.from_xdr(body)
                except Exception:
                    self.drop("undecodable message")
                    return
                self.overlay._message_received(self, msg, body=body,
                                               body_hash=h)
                continue
            # flow-control capacity is earned PER CONTAINED MESSAGE —
            # grants under batching account identically to per-frame mode
            self._account_flood_processing(msg, len(body))
            self.overlay._message_received(self, msg, body=body,
                                           body_hash=h)

    def _recv_hello(self, hello) -> None:
        if self.state not in (Peer.CONNECTED, Peer.CONNECTING):
            self.drop("HELLO out of order")
            return
        if hello.networkID != self.overlay.network_id:
            self.send_error(X.ErrorCode.ERR_CONF, "wrong network")
            self.drop("wrong network id")
            return
        if hello.overlayVersion < OVERLAY_PROTOCOL_MIN_VERSION:
            self.send_error(X.ErrorCode.ERR_CONF, "overlay version too old")
            self.drop("overlay version")
            return
        peer_id = hello.peerID.value
        if peer_id == self.overlay.node_id:
            self.drop("connected to self")
            return
        if not self.auth.verify_remote_cert(hello.cert, peer_id):
            self.send_error(X.ErrorCode.ERR_AUTH, "bad auth cert")
            self.drop("bad auth cert")
            return
        self.peer_id = peer_id
        self.remote_listening_port = hello.listeningPort
        self._send_key, self._recv_key = self.auth.shared_keys(
            hello.cert.pubkey.key, self.local_nonce, hello.nonce,
            self.we_called_remote)
        self.state = Peer.GOT_HELLO
        if not self.we_called_remote:
            self.send_hello()
        else:
            self._send_auth()

    def _send_auth(self) -> None:
        """Our half of the AUTH exchange; advertises AUTH_FLAG_BATCH when
        this side is willing to speak the batched transport.  The flag is
        informational to peers that predate it (they read flags as 0-or-
        whatever and ignore it), so the handshake stays byte-compatible."""
        flags = X.AUTH_FLAG_BATCH if self.batching_enabled else 0
        self._advertised_batch = bool(flags)
        self._send_authenticated(X.StellarMessage.auth(X.Auth(flags=flags)))

    def _recv_auth(self, auth: X.Auth) -> None:
        if self.state != Peer.GOT_HELLO:
            self.drop("AUTH out of order")
            return
        # batching is active only when BOTH sides advertised the flag —
        # a flags=0 peer keeps today's per-message wire format verbatim
        self._remote_batch = bool(auth.flags & X.AUTH_FLAG_BATCH)
        if not self.we_called_remote:
            # acceptor completes the handshake with its own AUTH
            self._send_auth()
        self.state = Peer.GOT_AUTH
        self._grant_capacity(initial=True)
        self.overlay._peer_authenticated(self)

    # -- flow control -------------------------------------------------------
    def _grant_capacity(self, initial: bool = False) -> None:
        if initial:
            self.send_message(X.StellarMessage.sendMoreExtendedMessage(
                X.SendMoreExtended(
                    numMessages=PEER_FLOOD_READING_CAPACITY,
                    numBytes=PEER_FLOOD_READING_CAPACITY_BYTES)))

    def _account_flood_processing(self, msg: X.StellarMessage,
                                  size: int) -> None:
        """Receiver side: periodically hand the sender fresh capacity.
        Grants trigger on EITHER the message-count or the byte threshold
        (reference: FlowControl::maybeSendNextBatch does both) — otherwise
        a few large messages could exhaust the sender's byte allowance
        before the message counter ever reaches the batch size, stalling
        the connection permanently."""
        if msg.switch == X.MessageType.SEND_MORE:
            self._outbound_capacity += msg.value.numMessages
            self._outbound_capacity_bytes += FLOW_CONTROL_BYTES_BATCH
            self._flush_flood_queue()
            return
        if msg.switch == X.MessageType.SEND_MORE_EXTENDED:
            self._outbound_capacity += msg.value.numMessages
            self._outbound_capacity_bytes += msg.value.numBytes
            self._flush_flood_queue()
            return
        self._account_flood_switch(msg.switch, size)

    def _account_flood_switch(self, switch, size: int) -> None:
        """Grant-earning half of flow accounting, keyed on the message
        type discriminant alone — the batch path's pre-decode duplicate
        drop accounts here without ever materialising the message."""
        if switch in _FLOOD_TYPES:
            self._processed_since_grant += 1
            self._processed_bytes_since_grant += size
            if (self._processed_since_grant >= FLOW_CONTROL_SEND_MORE_BATCH
                    or self._processed_bytes_since_grant
                    >= FLOW_CONTROL_BYTES_BATCH):
                n = self._processed_since_grant
                nb = self._processed_bytes_since_grant
                self._processed_since_grant = 0
                self._processed_bytes_since_grant = 0
                if self.overlay.flood_grants_paused():
                    # admission back-pressure: the capacity is EARNED but
                    # not granted — the sender stays throttled until the
                    # local backlog drains, then the deferred grant ships
                    # in one SEND_MORE_EXTENDED (release_deferred_grant)
                    if self._deferred_grant is None:
                        self._deferred_grant = [0, 0]
                    self._deferred_grant[0] += n
                    self._deferred_grant[1] += nb
                    _registry().meter("overlay.flood.grant-deferred").mark()
                    return
                self.send_message(X.StellarMessage.sendMoreExtendedMessage(
                    X.SendMoreExtended(numMessages=n, numBytes=nb)))

    def release_deferred_grant(self) -> None:
        """Ship every flow-control grant withheld while admission was
        back-pressured (overlay_manager.release_flood_grants)."""
        if self._deferred_grant is None or self.state != Peer.GOT_AUTH:
            return
        n, nb = self._deferred_grant
        self._deferred_grant = None
        if n or nb:
            self.send_message(X.StellarMessage.sendMoreExtendedMessage(
                X.SendMoreExtended(numMessages=n, numBytes=nb)))


class LoopbackPeer(Peer):
    """In-process transport for deterministic tests (reference:
    src/overlay/test/LoopbackPeer) — bytes are delivered to the partner via
    clock-posted actions, so delivery interleaves with timers.

    Delivery is COALESCED per crank: every frame surviving fault injection
    joins a pending run, and one posted action per crank hands the whole
    run to the partner through a single data_received call — one scheduler
    dispatch per link-direction per crank instead of one per message,
    which is the sim-level half of the batched-transport speedup.  Fault
    semantics are unchanged: drop/damage/reorder draws stay per frame (and
    per contained message for BATCHED_AUTH frames, see _emit_batch)."""

    def __init__(self, overlay, we_called_remote: bool,
                 fault_rng=None):
        super().__init__(overlay, we_called_remote)
        self.partner: Optional["LoopbackPeer"] = None
        # fault-injection knobs (reference: LoopbackPeer's damage/drop/
        # reorder probabilities used by overlay tests).  The RNG feeding
        # them is injectable: the Simulation derives one seeded stream per
        # loopback pair so an entire chaos campaign replays bit-identically
        # from its logged seed; standalone tests keep the fixed default.
        self.drop_outbound = False       # black hole
        self.damage_probability = 0.0    # flip a byte in outbound frames
        self.drop_probability = 0.0      # silently drop outbound frames
        self.reorder_probability = 0.0   # delay a frame behind the next
        if fault_rng is None:
            import random as _random
            fault_rng = _random.Random(0)  # deterministic by default
        self.fault_rng = fault_rng
        self._held_back: Optional[bytes] = None
        self._backstop_gen = 0
        self._pending_out: List[bytes] = []
        self._delivery_armed = False

    def _write_bytes(self, data: bytes) -> None:
        if self.partner is None or self.drop_outbound:
            return
        rng = self.fault_rng
        held, self._held_back = self._held_back, None
        frames = []
        dropped = (self.drop_probability
                   and rng.random() < self.drop_probability)
        if not dropped:
            if self.damage_probability \
                    and rng.random() < self.damage_probability \
                    and len(data) > 4:
                # flip a PAYLOAD bit (offset >= 4): damaging the record
                # mark/length would stall the frame decoder rather than
                # exercise the MAC fail-stop (reference: LoopbackPeer
                # damages message bodies)
                buf = bytearray(data)
                buf[rng.randrange(4, len(buf))] ^= 1 << rng.randrange(8)
                data = bytes(buf)
            if self.reorder_probability \
                    and rng.random() < self.reorder_probability \
                    and held is None:
                # delivered behind the NEXT frame; a posted backstop keeps
                # quiesced traffic from turning 'reorder' into 'drop'
                self._held_back = data
                self._arm_backstop()
            else:
                frames.append(data)
        if held is not None:
            # the previously held frame lands AFTER this one (that's the
            # reorder) — and even if this frame was dropped, the held one
            # must not be silently lost
            frames.append(held)
        for frame in frames:
            self._enqueue_delivery(frame)

    def _enqueue_delivery(self, data: bytes) -> None:
        """Join the per-crank delivery run; the first frame of a run arms
        ONE posted action that delivers everything pending at once."""
        self._pending_out.append(data)
        if not self._delivery_armed:
            self._delivery_armed = True
            self.overlay.clock.post_action(self._deliver_pending,
                                           name="loopback-delivery")

    def _deliver_pending(self) -> None:
        self._delivery_armed = False
        pending, self._pending_out = self._pending_out, []
        partner = self.partner
        if not pending or partner is None:
            return
        partner.data_received(
            pending[0] if len(pending) == 1 else b"".join(pending))

    def _flush_held(self) -> None:
        """Deliver a reorder-held frame that nothing has overtaken."""
        held, self._held_back = self._held_back, None
        if held is not None and self.partner is not None:
            self._enqueue_delivery(held)

    def _emit_batch(self, run: List[bytes]) -> None:
        """Fault-aware BATCHED_AUTH emission: with any fault probability
        set, the drop/damage/reorder draws happen PER CONTAINED MESSAGE in
        send order — the same conditional draw sequence (and therefore the
        same RNG stream consumption per message) as the unbatched
        per-frame path in _write_bytes, so a seeded campaign replays
        identically in either transport mode.

        Outcome mapping keeps unbatched fail-stop semantics:
        - drop: any dropped message loses the WHOLE frame (the sequence
          number still advances), so the receiver hits the same seq-gap
          fail-stop a dropped per-message frame causes — and P(link
          survives k messages) is (1-p)^k in both modes;
        - damage: the flip lands in that message's body bytes when the
          drawn offset maps there (same randrange span as a per-message
          frame), else in the frame MAC — either way the one-MAC check
          fails and the link fail-stops, like an unbatched damaged frame;
        - reorder: the held message lands behind its successor INSIDE the
          run.  This is the one intentional semantic delta: intra-batch
          reordering is benign (one frame, one sequence number), whereas
          reordered per-message frames break the sequence chain.  The
          frame bypasses _write_bytes so nothing double-draws."""
        if not (self.drop_probability or self.damage_probability
                or self.reorder_probability):
            super()._emit_batch(run)
            return
        rng = self.fault_rng
        entries = []   # (body, flip-or-None) in final intra-run order
        held = None
        any_dropped = False
        for body in run:
            if self.drop_probability \
                    and rng.random() < self.drop_probability:
                any_dropped = True
                if held is not None:
                    entries.append(held)
                    held = None
                continue
            flip = None
            if self.damage_probability \
                    and rng.random() < self.damage_probability:
                # same span a per-message frame would offer _write_bytes:
                # 4-byte record mark excluded, arm+seq+body+mac included
                flip = (rng.randrange(4, len(body) + 48), rng.randrange(8))
            if self.reorder_probability \
                    and rng.random() < self.reorder_probability \
                    and held is None:
                held = (body, flip)
                continue
            entries.append((body, flip))
            if held is not None:
                entries.append(held)
                held = None
        if held is not None:
            entries.append(held)
        # the sender MACs what it sent: build the valid frame first (the
        # sequence number advances and the batch metrics mark even for a
        # frame the link then loses, like any transport), then corrupt it
        # in transit
        if not entries:
            # every message dropped: burn the sequence number the frame
            # would have consumed so the seq-gap fail-stop still fires
            self._send_seq += 1
            return
        frame = self._build_batch_frame([body for body, _ in entries])
        if any_dropped or self.partner is None or self.drop_outbound:
            return
        buf = bytearray(frame)
        # frame layout: 4 record mark + 4 arm + 8 seq + 4 count, then per
        # body: 4-byte length + body; MAC is the trailing 32 bytes
        off = 20
        for body, flip in entries:
            off += 4
            if flip is not None:
                pos, bit = flip
                if 16 <= pos < 16 + len(body):
                    # maps into the message body: flip that exact byte
                    buf[off + (pos - 16)] ^= 1 << bit
                else:
                    # arm/seq/MAC region of a per-message frame: flip a
                    # frame-MAC byte — same MAC-failure fail-stop class
                    buf[len(buf) - 32 + (pos % 32)] ^= 1 << bit
            off += len(body)
        self._enqueue_delivery(bytes(buf))

    def _arm_backstop(self) -> None:
        """Flush a still-held frame after a grace round — frames posted
        later in the same crank get to overtake (that's the reorder), but
        a quiesced stream still delivers everything eventually.  Each hold
        gets its own generation so a stale backstop from an earlier hold
        cannot shorten the current frame's grace period."""
        self._backstop_gen += 1
        gen = self._backstop_gen

        def tick(rounds: int = 2) -> None:
            if self._held_back is None or self._backstop_gen != gen:
                return  # released by a later send, or superseded
            if rounds > 1:
                self.overlay.clock.post_action(
                    lambda: tick(rounds - 1), name="loopback-reorder-flush")
            else:
                self._flush_held()

        self.overlay.clock.post_action(tick, name="loopback-reorder-flush")

    def _close_transport(self) -> None:
        self._flush_held()
        if self.partner is not None and self.partner.state != Peer.CLOSING:
            partner, self.partner = self.partner, None
            partner.partner = None
            partner.drop("partner closed")


def make_loopback_pair(overlay_a, overlay_b, fault_rng=None):
    """Wire two overlays with a loopback connection; a dials b.

    ``fault_rng`` (a seeded ``random.Random``) is shared by both
    directions of the link: every damage/drop/reorder decision on the
    pair draws from one deterministic stream, so a simulation that logs
    its seed can replay the exact same fault sequence."""
    pa = LoopbackPeer(overlay_a, we_called_remote=True, fault_rng=fault_rng)
    pb = LoopbackPeer(overlay_b, we_called_remote=False, fault_rng=fault_rng)
    pa.partner, pb.partner = pb, pa
    overlay_a._register_peer(pa)
    overlay_b._register_peer(pb)
    pb.connect_handler()
    pa.connect_handler()
    return pa, pb
