"""Peer: handshake + authenticated messaging + flow control state machine.

Reference: src/overlay/Peer.{h,cpp} (recvHello/recvAuth/recvAuthenticated
dispatch, DropReason semantics), src/overlay/FlowControl.{h,cpp}
(capacity-granted flood sending via SEND_MORE_EXTENDED), transport left to
subclasses (TCPPeer / LoopbackPeer, like the reference).

Wire format: RFC 5531 record marking — every frame is a 4-byte big-endian
header with the high bit set (single-fragment) and the payload length in
the low 31 bits, followed by an AuthenticatedMessage XDR.  HELLO and
ERROR_MSG travel with sequence 0 and a zero MAC (no keys yet); everything
else is HMAC'd with per-direction keys and strictly increasing sequences.
"""

from __future__ import annotations

import os
import struct
from typing import Callable, List, Optional

from .. import xdr as X
from ..util import logging as slog
from ..util.metrics import registry as _registry
from .peer_auth import PeerAuth, mac_message, mac_ok

log = slog.get("Overlay")

OVERLAY_PROTOCOL_VERSION = 38
OVERLAY_PROTOCOL_MIN_VERSION = 35
VERSION_STR = "stellar-core-tpu 2.0"

# flow control (reference: FlowControl::start — these are the capacities a
# node grants its peer when the connection authenticates)
PEER_FLOOD_READING_CAPACITY = 200
FLOW_CONTROL_SEND_MORE_BATCH = 40
PEER_FLOOD_READING_CAPACITY_BYTES = 300_000
FLOW_CONTROL_BYTES_BATCH = 100_000

_ZERO_MAC = b"\x00" * 32
# AuthenticatedMessage union discriminant for V0 (see _send_authenticated)
_AM_V0_ARM = b"\x00\x00\x00\x00"

_FLOOD_TYPES = frozenset((
    X.MessageType.TRANSACTION, X.MessageType.SCP_MESSAGE,
    X.MessageType.FLOOD_ADVERT, X.MessageType.FLOOD_DEMAND))


def frame_encode(payload: bytes) -> bytes:
    assert len(payload) < (1 << 31)
    return (0x80000000 | len(payload)).to_bytes(4, "big") + payload


class FrameDecoder:
    """Incremental record-mark deframer for a byte stream."""

    MAX_FRAME = 32 * 1024 * 1024

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> List[bytes]:
        self._buf += data
        out = []
        while True:
            if len(self._buf) < 4:
                break
            header = int.from_bytes(self._buf[:4], "big")
            if not header & 0x80000000:
                raise ValueError("fragmented records not supported")
            length = header & 0x7FFFFFFF
            if length > self.MAX_FRAME:
                raise ValueError("oversized frame")
            if len(self._buf) < 4 + length:
                break
            out.append(bytes(self._buf[4:4 + length]))
            del self._buf[:4 + length]
        return out


class Peer:
    # connection states (reference: Peer::State)
    CONNECTING = "connecting"
    CONNECTED = "connected"      # transport up, HELLO exchange in flight
    GOT_HELLO = "got-hello"
    GOT_AUTH = "authenticated"
    CLOSING = "closing"

    def __init__(self, overlay, we_called_remote: bool):
        self.overlay = overlay
        self.auth: PeerAuth = overlay.peer_auth
        self.we_called_remote = we_called_remote
        self.state = Peer.CONNECTING
        self.peer_id: Optional[bytes] = None       # remote ed25519
        self.remote_listening_port: int = 0
        self.local_nonce = os.urandom(32)
        self._decoder = FrameDecoder()
        self._send_seq = 0
        self._recv_seq = 0
        self._send_key: Optional[bytes] = None
        self._recv_key: Optional[bytes] = None
        self.drop_reason: Optional[str] = None
        # flow control
        self._outbound_capacity = 0
        self._outbound_capacity_bytes = 0
        self._flood_queue: List[X.StellarMessage] = []
        self._processed_since_grant = 0
        self._processed_bytes_since_grant = 0
        # back-pressure: grants the admission pipeline told us to hold —
        # (messages, bytes) owed to the peer once the backlog drains
        self._deferred_grant: Optional[List[int]] = None
        # wire accounting metric objects, cached for the peer's lifetime
        reg = _registry()
        self._ctr_byte_read = reg.counter("overlay.byte.read")
        self._ctr_byte_write = reg.counter("overlay.byte.write")
        self._met_msg_read = reg.meter("overlay.message.read")
        self._met_msg_write = reg.meter("overlay.message.write")

    # -- transport interface (subclass-provided) ----------------------------
    def _write_bytes(self, data: bytes) -> None:
        raise NotImplementedError

    def _close_transport(self) -> None:
        raise NotImplementedError

    # -- lifecycle ----------------------------------------------------------
    def connect_handler(self) -> None:
        """Transport established.  The dialer speaks first (reference:
        TCPPeer::connectHandler -> sendHello)."""
        self.state = Peer.CONNECTED
        if self.we_called_remote:
            self.send_hello()

    def drop(self, reason: str) -> None:
        if self.state == Peer.CLOSING:
            return
        self.drop_reason = reason
        self.state = Peer.CLOSING
        log.info("dropping peer %s: %s",
                 self.peer_id.hex()[:8] if self.peer_id else "?", reason)
        self._close_transport()
        self.overlay._peer_dropped(self)

    def is_authenticated(self) -> bool:
        return self.state == Peer.GOT_AUTH

    # -- sending ------------------------------------------------------------
    def send_hello(self) -> None:
        lcl = self.overlay.ledger_version()
        msg = X.StellarMessage.hello(X.Hello(
            ledgerVersion=lcl,
            overlayVersion=OVERLAY_PROTOCOL_VERSION,
            overlayMinVersion=OVERLAY_PROTOCOL_MIN_VERSION,
            networkID=self.overlay.network_id,
            versionStr=VERSION_STR,
            listeningPort=self.overlay.listening_port,
            peerID=X.NodeID.ed25519(self.overlay.node_id),
            cert=self.auth.get_cert(),
            nonce=self.local_nonce))
        self._send_unauthenticated(msg)

    def send_error(self, code, text: str) -> None:
        self._send_unauthenticated(X.StellarMessage.error(
            X.Error(code=code, msg=text)))

    def _send_unauthenticated(self, msg: X.StellarMessage) -> None:
        am = X.AuthenticatedMessage.v0(X.AuthenticatedMessageV0(
            sequence=0, message=msg, mac=X.HmacSha256Mac(mac=_ZERO_MAC)))
        self._write_frame(frame_encode(am.to_xdr()))

    def _write_frame(self, data: bytes) -> None:
        # wire-level accounting: framed bytes + messages out (reference:
        # the overlay byte/message write medida meters in Peer); metric
        # objects are cached per peer — a registry lookup per frame is
        # measurable at simulated-fleet message rates
        self._ctr_byte_write.inc(len(data))
        self._met_msg_write.mark()
        self._write_bytes(data)

    def send_message(self, msg: X.StellarMessage,
                     body: Optional[bytes] = None) -> None:
        """Authenticated send; flood messages respect granted capacity and
        queue when the peer hasn't given us room (reference:
        FlowControl::maybeSendMessage).  The XDR body is encoded exactly
        once and threaded through queueing, size accounting and the MAC —
        callers broadcasting one message to many peers pass the shared
        encoding via `body`."""
        if self.state == Peer.CLOSING:
            return
        if body is None:
            body = msg.to_xdr()
        if msg.switch in _FLOOD_TYPES:
            if self._outbound_capacity <= 0 \
                    or self._outbound_capacity_bytes < len(body):
                self._flood_queue.append((msg, body))
                return
            self._outbound_capacity -= 1
            self._outbound_capacity_bytes -= len(body)
        self._send_authenticated(msg, body)

    def _send_authenticated(self, msg: X.StellarMessage,
                            body: Optional[bytes] = None) -> None:
        if self._send_key is None:
            self.drop("send before auth keys")
            return
        if body is None:
            body = msg.to_xdr()
        mac = mac_message(self._send_key, self._send_seq, body)
        # splice the AuthenticatedMessage from the already-encoded body
        # instead of re-packing the whole message through the codec:
        # union arm v0 (uint32 0) + sequence (uint64) + message + 32-byte
        # MAC.  Byte-identical to the object path (unit-tested) and the
        # dominant per-link cost of a fleet-wide flood at 300 simulated
        # nodes.
        am_xdr = _AM_V0_ARM + struct.pack(">Q", self._send_seq) + body + mac
        self._send_seq += 1
        self._write_frame(frame_encode(am_xdr))

    def _flush_flood_queue(self) -> None:
        while self._flood_queue and self._outbound_capacity > 0:
            msg, body = self._flood_queue[0]
            if self._outbound_capacity_bytes < len(body):
                break
            self._flood_queue.pop(0)
            self._outbound_capacity -= 1
            self._outbound_capacity_bytes -= len(body)
            self._send_authenticated(msg, body)

    @property
    def flood_queue_len(self) -> int:
        return len(self._flood_queue)

    # -- receiving ----------------------------------------------------------
    def data_received(self, data: bytes) -> None:
        self._ctr_byte_read.inc(len(data))
        try:
            frames = self._decoder.feed(data)
        except ValueError as e:
            self.drop(f"bad framing: {e}")
            return
        for frame in frames:
            if self.state == Peer.CLOSING:
                return
            self._met_msg_read.mark()
            self._frame_received(frame)

    def _frame_received(self, frame: bytes) -> None:
        try:
            am = X.AuthenticatedMessage.from_xdr(frame)
        except Exception:
            self.drop("undecodable message")
            return
        v0 = am.value
        msg = v0.message
        if msg.switch == X.MessageType.HELLO:
            if v0.sequence != 0 or v0.mac.mac != _ZERO_MAC:
                self.drop("HELLO must be unauthenticated")
                return
            self._recv_hello(msg.value)
            return
        if msg.switch == X.MessageType.ERROR_MSG:
            err = msg.value
            self.drop(f"peer error: {err.code.name} "
                      f"{err.msg.decode(errors='replace')}")
            return
        # everything else requires the MAC chain.  The MAC'd body is the
        # frame minus the 4-byte union arm, the 8-byte sequence and the
        # trailing 32-byte MAC — sliced instead of re-encoding the
        # message the codec just decoded (the decode above already
        # proved the frame is exactly this shape).
        if self._recv_key is None:
            self.drop("authenticated message before HELLO exchange")
            return
        body = frame[12:len(frame) - 32]
        if v0.sequence != self._recv_seq \
                or not mac_ok(self._recv_key, v0.sequence, body, v0.mac.mac):
            self.drop("bad MAC or sequence")
            return
        self._recv_seq += 1
        if msg.switch == X.MessageType.AUTH:
            self._recv_auth()
            return
        if not self.is_authenticated():
            self.drop("message before AUTH")
            return
        self._account_flood_processing(msg, len(body))
        self.overlay._message_received(self, msg, body=body)

    def _recv_hello(self, hello) -> None:
        if self.state not in (Peer.CONNECTED, Peer.CONNECTING):
            self.drop("HELLO out of order")
            return
        if hello.networkID != self.overlay.network_id:
            self.send_error(X.ErrorCode.ERR_CONF, "wrong network")
            self.drop("wrong network id")
            return
        if hello.overlayVersion < OVERLAY_PROTOCOL_MIN_VERSION:
            self.send_error(X.ErrorCode.ERR_CONF, "overlay version too old")
            self.drop("overlay version")
            return
        peer_id = hello.peerID.value
        if peer_id == self.overlay.node_id:
            self.drop("connected to self")
            return
        if not self.auth.verify_remote_cert(hello.cert, peer_id):
            self.send_error(X.ErrorCode.ERR_AUTH, "bad auth cert")
            self.drop("bad auth cert")
            return
        self.peer_id = peer_id
        self.remote_listening_port = hello.listeningPort
        self._send_key, self._recv_key = self.auth.shared_keys(
            hello.cert.pubkey.key, self.local_nonce, hello.nonce,
            self.we_called_remote)
        self.state = Peer.GOT_HELLO
        if not self.we_called_remote:
            self.send_hello()
        else:
            self._send_authenticated(X.StellarMessage.auth(X.Auth(flags=0)))

    def _recv_auth(self) -> None:
        if self.state != Peer.GOT_HELLO:
            self.drop("AUTH out of order")
            return
        if not self.we_called_remote:
            # acceptor completes the handshake with its own AUTH
            self._send_authenticated(X.StellarMessage.auth(X.Auth(flags=0)))
        self.state = Peer.GOT_AUTH
        self._grant_capacity(initial=True)
        self.overlay._peer_authenticated(self)

    # -- flow control -------------------------------------------------------
    def _grant_capacity(self, initial: bool = False) -> None:
        if initial:
            self.send_message(X.StellarMessage.sendMoreExtendedMessage(
                X.SendMoreExtended(
                    numMessages=PEER_FLOOD_READING_CAPACITY,
                    numBytes=PEER_FLOOD_READING_CAPACITY_BYTES)))

    def _account_flood_processing(self, msg: X.StellarMessage,
                                  size: int) -> None:
        """Receiver side: periodically hand the sender fresh capacity.
        Grants trigger on EITHER the message-count or the byte threshold
        (reference: FlowControl::maybeSendNextBatch does both) — otherwise
        a few large messages could exhaust the sender's byte allowance
        before the message counter ever reaches the batch size, stalling
        the connection permanently."""
        if msg.switch == X.MessageType.SEND_MORE:
            self._outbound_capacity += msg.value.numMessages
            self._outbound_capacity_bytes += FLOW_CONTROL_BYTES_BATCH
            self._flush_flood_queue()
            return
        if msg.switch == X.MessageType.SEND_MORE_EXTENDED:
            self._outbound_capacity += msg.value.numMessages
            self._outbound_capacity_bytes += msg.value.numBytes
            self._flush_flood_queue()
            return
        if msg.switch in _FLOOD_TYPES:
            self._processed_since_grant += 1
            self._processed_bytes_since_grant += size
            if (self._processed_since_grant >= FLOW_CONTROL_SEND_MORE_BATCH
                    or self._processed_bytes_since_grant
                    >= FLOW_CONTROL_BYTES_BATCH):
                n = self._processed_since_grant
                nb = self._processed_bytes_since_grant
                self._processed_since_grant = 0
                self._processed_bytes_since_grant = 0
                if self.overlay.flood_grants_paused():
                    # admission back-pressure: the capacity is EARNED but
                    # not granted — the sender stays throttled until the
                    # local backlog drains, then the deferred grant ships
                    # in one SEND_MORE_EXTENDED (release_deferred_grant)
                    if self._deferred_grant is None:
                        self._deferred_grant = [0, 0]
                    self._deferred_grant[0] += n
                    self._deferred_grant[1] += nb
                    _registry().meter("overlay.flood.grant-deferred").mark()
                    return
                self.send_message(X.StellarMessage.sendMoreExtendedMessage(
                    X.SendMoreExtended(numMessages=n, numBytes=nb)))

    def release_deferred_grant(self) -> None:
        """Ship every flow-control grant withheld while admission was
        back-pressured (overlay_manager.release_flood_grants)."""
        if self._deferred_grant is None or self.state != Peer.GOT_AUTH:
            return
        n, nb = self._deferred_grant
        self._deferred_grant = None
        if n or nb:
            self.send_message(X.StellarMessage.sendMoreExtendedMessage(
                X.SendMoreExtended(numMessages=n, numBytes=nb)))


class LoopbackPeer(Peer):
    """In-process transport for deterministic tests (reference:
    src/overlay/test/LoopbackPeer) — bytes are delivered to the partner via
    clock-posted actions, so delivery interleaves with timers."""

    def __init__(self, overlay, we_called_remote: bool,
                 fault_rng=None):
        super().__init__(overlay, we_called_remote)
        self.partner: Optional["LoopbackPeer"] = None
        # fault-injection knobs (reference: LoopbackPeer's damage/drop/
        # reorder probabilities used by overlay tests).  The RNG feeding
        # them is injectable: the Simulation derives one seeded stream per
        # loopback pair so an entire chaos campaign replays bit-identically
        # from its logged seed; standalone tests keep the fixed default.
        self.drop_outbound = False       # black hole
        self.damage_probability = 0.0    # flip a byte in outbound frames
        self.drop_probability = 0.0      # silently drop outbound frames
        self.reorder_probability = 0.0   # delay a frame behind the next
        if fault_rng is None:
            import random as _random
            fault_rng = _random.Random(0)  # deterministic by default
        self.fault_rng = fault_rng
        self._held_back: Optional[bytes] = None
        self._backstop_gen = 0

    def _write_bytes(self, data: bytes) -> None:
        if self.partner is None or self.drop_outbound:
            return
        rng = self.fault_rng
        held, self._held_back = self._held_back, None
        frames = []
        dropped = (self.drop_probability
                   and rng.random() < self.drop_probability)
        if not dropped:
            if self.damage_probability \
                    and rng.random() < self.damage_probability \
                    and len(data) > 4:
                # flip a PAYLOAD bit (offset >= 4): damaging the record
                # mark/length would stall the frame decoder rather than
                # exercise the MAC fail-stop (reference: LoopbackPeer
                # damages message bodies)
                buf = bytearray(data)
                buf[rng.randrange(4, len(buf))] ^= 1 << rng.randrange(8)
                data = bytes(buf)
            if self.reorder_probability \
                    and rng.random() < self.reorder_probability \
                    and held is None:
                # delivered behind the NEXT frame; a posted backstop keeps
                # quiesced traffic from turning 'reorder' into 'drop'
                self._held_back = data
                self._arm_backstop()
            else:
                frames.append(data)
        if held is not None:
            # the previously held frame lands AFTER this one (that's the
            # reorder) — and even if this frame was dropped, the held one
            # must not be silently lost
            frames.append(held)
        partner = self.partner
        for frame in frames:
            self.overlay.clock.post_action(
                lambda f=frame: partner.data_received(f),
                name="loopback-delivery")

    def _flush_held(self) -> None:
        """Deliver a reorder-held frame that nothing has overtaken."""
        held, self._held_back = self._held_back, None
        if held is not None and self.partner is not None:
            partner = self.partner
            self.overlay.clock.post_action(
                lambda: partner.data_received(held),
                name="loopback-delivery")

    def _arm_backstop(self) -> None:
        """Flush a still-held frame after a grace round — frames posted
        later in the same crank get to overtake (that's the reorder), but
        a quiesced stream still delivers everything eventually.  Each hold
        gets its own generation so a stale backstop from an earlier hold
        cannot shorten the current frame's grace period."""
        self._backstop_gen += 1
        gen = self._backstop_gen

        def tick(rounds: int = 2) -> None:
            if self._held_back is None or self._backstop_gen != gen:
                return  # released by a later send, or superseded
            if rounds > 1:
                self.overlay.clock.post_action(
                    lambda: tick(rounds - 1), name="loopback-reorder-flush")
            else:
                self._flush_held()

        self.overlay.clock.post_action(tick, name="loopback-reorder-flush")

    def _close_transport(self) -> None:
        self._flush_held()
        if self.partner is not None and self.partner.state != Peer.CLOSING:
            partner, self.partner = self.partner, None
            partner.partner = None
            partner.drop("partner closed")


def make_loopback_pair(overlay_a, overlay_b, fault_rng=None):
    """Wire two overlays with a loopback connection; a dials b.

    ``fault_rng`` (a seeded ``random.Random``) is shared by both
    directions of the link: every damage/drop/reorder decision on the
    pair draws from one deterministic stream, so a simulation that logs
    its seed can replay the exact same fault sequence."""
    pa = LoopbackPeer(overlay_a, we_called_remote=True, fault_rng=fault_rng)
    pb = LoopbackPeer(overlay_b, we_called_remote=False, fault_rng=fault_rng)
    pa.partner, pb.partner = pb, pa
    overlay_a._register_peer(pa)
    overlay_b._register_peer(pb)
    pb.connect_handler()
    pa.connect_handler()
    return pa, pb
