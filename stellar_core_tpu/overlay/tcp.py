"""TCPPeer + TCPServer: non-blocking socket transport for the overlay.

Reference: src/overlay/TCPPeer.{h,cpp} — asio sockets owned by the
VirtualClock's io context.  Here a selectors.DefaultSelector is pumped from
the clock loop (VirtualClock.add_io_pump), so socket IO interleaves with
timers exactly like asio handlers do: each crank polls ready sockets with
zero timeout, reads feed Peer.data_received, writes drain per-peer buffers.
"""

from __future__ import annotations

import errno
import selectors
import socket
from typing import Dict, Optional

from ..util import logging as slog
from .peer import Peer

log = slog.get("Overlay")

READ_CHUNK = 256 * 1024
MAX_WRITE_BUFFER = 64 * 1024 * 1024


class TCPPeer(Peer):
    def __init__(self, overlay, we_called_remote: bool,
                 sock: socket.socket, transport: "TCPTransport"):
        super().__init__(overlay, we_called_remote)
        self.sock = sock
        self.transport = transport
        self._write_buf = bytearray()
        self._registered = False

    # -- Peer transport interface -------------------------------------------
    def _write_bytes(self, data: bytes) -> None:
        if self.sock is None:
            return
        self._write_buf += data
        if len(self._write_buf) > MAX_WRITE_BUFFER:
            self.drop("write buffer overflow")
            return
        self._try_flush()
        self.transport.update_interest(self)

    def _close_transport(self) -> None:
        if self.sock is not None:
            self.transport.forget(self)
            try:
                self.sock.close()
            except OSError:
                pass
            self.sock = None

    # -- IO pump callbacks ---------------------------------------------------
    def _try_flush(self) -> None:
        while self._write_buf:
            try:
                n = self.sock.send(self._write_buf)
            except BlockingIOError:
                return
            except OSError as e:
                self.drop(f"send error: {e}")
                return
            if n <= 0:
                return
            del self._write_buf[:n]

    def on_readable(self) -> None:
        try:
            data = self.sock.recv(READ_CHUNK)
        except BlockingIOError:
            return
        except OSError as e:
            self.drop(f"recv error: {e}")
            return
        if not data:
            self.drop("connection closed by peer")
            return
        self.data_received(data)

    def on_writable(self) -> None:
        if self.state == Peer.CONNECTING:
            # outbound connect completed (or failed)
            err = self.sock.getsockopt(socket.SOL_SOCKET, socket.SO_ERROR)
            if err:
                self.drop(f"connect failed: {errno.errorcode.get(err, err)}")
                return
            self.connect_handler()
        # IO-ready edge: a pending coalescing run rides this writability
        # event as one frame/one syscall instead of waiting for the next
        # crank-edge flush
        self._flush_batch()
        self._try_flush()
        self.transport.update_interest(self)

    def wants_write(self) -> bool:
        return bool(self._write_buf) or self.state == Peer.CONNECTING


class TCPTransport:
    """Owns the selector, the listening socket and the socket<->peer map;
    `pump()` is registered as a clock IO pump."""

    def __init__(self, overlay, listen_port: int = 0,
                 host: str = "127.0.0.1"):
        self.overlay = overlay
        self.selector = selectors.DefaultSelector()
        self.peers: Dict[socket.socket, TCPPeer] = {}
        self.listen_sock: Optional[socket.socket] = None
        self.host = host
        if listen_port is not None:
            self.listen_sock = socket.socket(socket.AF_INET,
                                             socket.SOCK_STREAM)
            self.listen_sock.setsockopt(socket.SOL_SOCKET,
                                        socket.SO_REUSEADDR, 1)
            self.listen_sock.bind((host, listen_port))
            self.listen_sock.listen(64)
            self.listen_sock.setblocking(False)
            self.selector.register(self.listen_sock, selectors.EVENT_READ)
            overlay.listening_port = self.listen_sock.getsockname()[1]
        overlay.clock.add_io_pump(self.pump)

    # -- connections ---------------------------------------------------------
    def connect(self, host: str, port: int) -> TCPPeer:
        sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        sock.setblocking(False)
        sync_error: Optional[OSError] = None
        try:
            sock.connect((host, port))
        except BlockingIOError:
            pass
        except OSError as e:
            # immediate connect failure (unroutable address, exhausted
            # ports): register the peer then drop it through the normal
            # path so PeerManager backoff records the failure instead of
            # the dial crashing the crank loop
            sync_error = e
        peer = TCPPeer(self.overlay, we_called_remote=True, sock=sock,
                       transport=self)
        peer.dial_addr = (host, port)   # feeds PeerManager backoff on drop
        self.peers[sock] = peer
        self.selector.register(sock, selectors.EVENT_READ
                               | selectors.EVENT_WRITE)
        peer._registered = True
        self.overlay._register_peer(peer)
        if sync_error is not None:
            peer.drop(f"connect failed: {sync_error}")
        return peer

    def _accept(self) -> None:
        while True:
            try:
                sock, addr = self.listen_sock.accept()
            except (BlockingIOError, OSError):
                return
            sock.setblocking(False)
            peer = TCPPeer(self.overlay, we_called_remote=False, sock=sock,
                           transport=self)
            self.peers[sock] = peer
            self.selector.register(sock, selectors.EVENT_READ)
            peer._registered = True
            self.overlay._register_peer(peer)
            peer.connect_handler()

    def update_interest(self, peer: TCPPeer) -> None:
        if peer.sock is None or not peer._registered:
            return
        events = selectors.EVENT_READ
        if peer.wants_write():
            events |= selectors.EVENT_WRITE
        try:
            self.selector.modify(peer.sock, events)
        except KeyError:
            pass

    def forget(self, peer: TCPPeer) -> None:
        if peer.sock is not None:
            try:
                self.selector.unregister(peer.sock)
            except KeyError:
                pass
            self.peers.pop(peer.sock, None)
            peer._registered = False

    # -- the pump ------------------------------------------------------------
    def pump(self) -> int:
        """One zero-timeout poll; returns number of IO events handled."""
        handled = 0
        try:
            events = self.selector.select(timeout=0)
        except (OSError, ValueError):
            # ValueError: selector already closed (shutdown race between a
            # signal handler's close() and the crank loop's pump)
            return 0
        for key, mask in events:
            if key.fileobj is self.listen_sock:
                self._accept()
                handled += 1
                continue
            peer = self.peers.get(key.fileobj)
            if peer is None:
                continue
            if mask & selectors.EVENT_WRITE:
                peer.on_writable()
                handled += 1
            if mask & selectors.EVENT_READ and peer.sock is not None:
                peer.on_readable()
                handled += 1
        return handled

    def close(self) -> None:
        self.overlay.clock.remove_io_pump(self.pump)
        for peer in list(self.peers.values()):
            peer.drop("shutdown")
        if self.listen_sock is not None:
            try:
                self.selector.unregister(self.listen_sock)
            except KeyError:
                pass
            self.listen_sock.close()
            self.listen_sock = None
        self.selector.close()
