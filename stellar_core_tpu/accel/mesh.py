"""Device-per-range mesh assignment for parallel catchup.

MULTICHIP dryruns prove the 8-device data-parallel kernels (sharded
verify, psum, e2e hash-identical) but a single process drives them; the
production path that actually multiplies throughput is N `catchup-range`
subprocess workers, each pinned to ONE device so ranges never contend
for chip 0 (ROADMAP item 2: "assign one device per range worker").

Pinning happens entirely through the worker's environment, threaded into
the subprocess command line by catchup/parallel.py exactly like the
existing PYTHONPATH pin — the variables are in place before the worker's
Python starts, so JAX sees only its assigned device at import, with no
in-process re-initialization gymnastics:

* ``tpu``  — ``TPU_VISIBLE_DEVICES=<k>`` plus single-chip process bounds
  (the libtpu runtime maps the one visible chip to logical device 0).
* ``cuda`` — ``CUDA_VISIBLE_DEVICES=<k>``.
* ``cpu``  — the CPU-simulated mesh (`make catchup-mesh`,
  ``XLA_FLAGS=--xla_force_host_platform_device_count=8``): each worker
  gets a rewritten ``XLA_FLAGS`` forcing exactly ONE host device, so the
  pinning path runs in every tier-1 verify, not only on-chip.

``STPU_DEVICE_INDEX`` / ``STPU_DEVICE_COUNT`` always ride along so the
worker can report its assignment in the stitch record.
"""

from __future__ import annotations

import os
from typing import Dict, Optional

ENV_DEVICE_INDEX = "STPU_DEVICE_INDEX"
ENV_DEVICE_COUNT = "STPU_DEVICE_COUNT"

_HOST_COUNT_FLAG = "--xla_force_host_platform_device_count"


def resolve_platform(explicit: str = "auto") -> str:
    """The platform the mesh env should pin for.  An explicit choice wins;
    "auto" reads JAX_PLATFORMS from the environment (set on every CPU
    verify/bench invocation) and only falls back to importing jax — the
    expensive probe — when nothing names the platform."""
    if explicit and explicit != "auto":
        return explicit
    env = os.environ.get("JAX_PLATFORMS", "")
    if env.strip():
        return env.split(",")[0].strip()
    try:
        import jax
        return jax.default_backend()
    except (ImportError, RuntimeError):
        # jax-less rigs (or a backend that fails to initialize) pin the
        # cpu path — the worker env is then a harmless host-count force
        return "cpu"


def worker_device_env(index: int, total: int,
                      platform: str = "auto") -> Dict[str, str]:
    """Environment additions pinning one range worker to one device
    (round-robin callers pass index = spec.index % total)."""
    platform = resolve_platform(platform)
    env = {ENV_DEVICE_INDEX: str(index), ENV_DEVICE_COUNT: str(total)}
    if platform == "tpu":
        env["TPU_VISIBLE_DEVICES"] = str(index)
        env["TPU_PROCESS_BOUNDS"] = "1,1,1"
        env["TPU_CHIPS_PER_PROCESS_BOUNDS"] = "1,1,1"
    elif platform in ("cuda", "gpu", "rocm"):
        env["CUDA_VISIBLE_DEVICES"] = str(index)
    else:
        # CPU-simulated mesh: strip any inherited force-N flag (the
        # orchestrator's own 8-device mesh) and force exactly one host
        # device in the worker
        flags = [f for f in os.environ.get("XLA_FLAGS", "").split()
                 if not f.startswith(_HOST_COUNT_FLAG)]
        flags.append(f"{_HOST_COUNT_FLAG}=1")
        env["XLA_FLAGS"] = " ".join(flags)
        env["JAX_PLATFORMS"] = "cpu"
    return env


def assigned_device_index() -> Optional[int]:
    """The worker side: the device index this process was pinned to by
    worker_device_env, or None when unpinned."""
    raw = os.environ.get(ENV_DEVICE_INDEX)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None
