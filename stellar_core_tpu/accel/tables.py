"""Per-key precomputed window tables for Ed25519 verification.

The catchup-replay workload (the north-star hot loop, reference:
src/catchup — ApplyCheckpointWork) re-verifies signatures from a heavily
repeated set of signing keys: per-account sequence numbers force each
account's transactions into a serial stream, so a checkpoint's signature
batch contains few distinct keys, each used many times.  The verify-result
cache exploits exact (sig, msg, key) repeats; this module exploits
*same-key, different-message* repeats, which the cache cannot.

For a key A (stored negated, matching the verification equation
R = [s]B + [h](−A)), we precompute T[w][d] = d·16^w·(−A) for the 64
4-bit windows of the 253-bit scalar.  Verification then needs **zero
point doublings** — just 64 table adds for [h](−A) and 64 more from the
constant base-point table for [s]B, ~2.4× fewer field multiplies than the
generic joint-window double-scalarmult.  Tables live in device HBM
(~0.5 MB/key) with LRU slot reuse; building one key's table costs ~1150
point ops, amortized after ~4 uses (the dispatcher in ed25519.py only
routes keys past that threshold here).

TPU-first design notes: the table walk is a 64-step lax.scan of uniform
9M point adds over the whole batch — no per-element control flow; the
per-step entry fetch is a single XLA gather from HBM, and all scalar→
digit decomposition happens on device from the raw 32-byte scalars (the
host↔device link is the scarcest resource — see PROFILE.md).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

from . import field
from .curve import (BX, BY, D2, P, PointBatch, fe_const, point_add, point_dbl,
                    point_encode)

NWIN = 64          # 4-bit windows covering 256 bits
NDIG = 16          # digits per window
BUILD_K = 32       # keys per table-build kernel call (padded)


def _digits_le(raw, w):
    """Nibble w of a (N, 32) little-endian scalar byte matrix (device)."""
    byte = raw[:, w // 2]
    return (byte >> (4 * (w % 2))) & 15


def build_tables(ax, ay):
    """(K,16)x2 affine int64 limbs -> (K, 64, 16, 4, 16) extended-coord
    window tables: out[k, w, d] = d * 16^w * A_k as (X, Y, Z, T) limbs.
    Digit 0 is the identity.  Jitted per K (callers pad to BUILD_K)."""
    k = ax.shape[0]
    d2 = fe_const(D2)
    one = jnp.zeros((k, field.NLIMB), dtype=jnp.int64).at[:, 0].set(1)
    base = PointBatch(ax, ay, one, field.fe_mul(ax, ay))

    def body(carry, _):
        """One window: emit multiples 0..15 of S, carry 16*S forward.
        A scan (not an unrolled loop) keeps the traced graph ~19 point ops
        — unrolling all 64 windows made XLA compilation explode."""
        s = PointBatch.from_tree(carry)
        mults = [PointBatch.identity_like(s), s]
        for _ in range(14):
            mults.append(point_add(mults[-1], s, d2))
        row = jnp.stack(
            [jnp.stack(m.tree(), axis=0) for m in mults], axis=0)
        s16 = point_dbl(point_dbl(point_dbl(point_dbl(s))))
        return s16.tree(), row

    _, rows = lax.scan(body, base.tree(), None, length=NWIN)
    # store entries in precomputed-add form (Y-X, Y+X, 2d*T, 2Z): the table
    # walk's add then needs 8 field mults instead of 10 (no d2 mult, no
    # doubling of ZZ) — see point_add_precomp
    ex, ey, ez, et = rows[:, :, 0], rows[:, :, 1], rows[:, :, 2], rows[:, :, 3]
    d2 = fe_const(D2)
    pre = jnp.stack([
        field.fe_sub(ey, ex),
        field.fe_add(ey, ex),
        field.fe_mul(et, jnp.broadcast_to(d2, et.shape)),
        field.fe_add(ez, ez),
    ], axis=2)
    # (64, 16, 4, K, 16) -> (K, 64, 16, 4, 16)
    return pre.transpose(3, 0, 1, 2, 4)


def point_add_precomp(p: PointBatch, entry) -> PointBatch:
    """Add a precomputed table entry (y-x, y+x, 2d*t, 2z) to an extended
    point: 8 field mults (the reference's ge25519_madd analog generalized to
    projective entries so table build needs no per-entry inversion)."""
    em, ep, e2dt, e2z = entry[:, 0], entry[:, 1], entry[:, 2], entry[:, 3]
    A = field.fe_mul(field.fe_sub(p.Y, p.X), em)
    B = field.fe_mul(field.fe_add(p.Y, p.X), ep)
    C = field.fe_mul(p.T, e2dt)
    Dd = field.fe_mul(p.Z, e2z)
    E = field.fe_sub(B, A)
    F = field.fe_sub(Dd, C)
    G = field.fe_add(Dd, C)
    H = field.fe_add(B, A)
    return PointBatch(field.fe_mul(E, F), field.fe_mul(G, H),
                      field.fe_mul(F, G), field.fe_mul(E, H))


_build_jit = jax.jit(build_tables)


def verify_tables_forward(s_raw, h_raw, slots, r_bytes, key_table, base_table,
                          unroll: int = 1):
    """Table-path verify: R' = [s]B + [h](-A) via a (64/unroll)-step scan
    doing 2*unroll precomputed-entry table adds per step, then canonical
    encode + byte compare.  The unroll knob exists because r2's profile
    blamed per-scan-step overhead; the round-3 interleaved A/B refuted
    that: u1/u2/u4/u8 measured 34.3/34.1/33.6/33.3k sigs/s at batch 8192
    and u1 also won at 32k/64k — XLA already pipelines the scan, so the
    default stays 1.  What actually moves the kernel is BATCH WIDTH
    (34k @ 8192 -> 54k @ 32768 -> 58k @ 65536 sigs/s): per-dispatch cost
    amortizes across wider batches (see PROFILE.md round 3).  All inputs
    device-resident; s_raw/h_raw/r_bytes are (N, 32) uint8 byte matrices
    (cast on device — the host link is slow, so the wire format is bytes,
    not int32)."""
    assert NWIN % unroll == 0
    s_raw = s_raw.astype(jnp.int32)
    h_raw = h_raw.astype(jnp.int32)
    n = s_raw.shape[0]
    zero = jnp.zeros((n, field.NLIMB), dtype=jnp.int64)
    r0 = PointBatch(zero, zero.at[:, 0].set(1), zero.at[:, 0].set(1), zero)
    digs_s = jnp.stack([_digits_le(s_raw, w) for w in range(NWIN)], axis=0)
    digs_h = jnp.stack([_digits_le(h_raw, w) for w in range(NWIN)], axis=0)
    nstep = NWIN // unroll

    def step(carry, xs):
        ws, dss, dhs = xs   # each (unroll,) / (unroll, N)
        r = PointBatch.from_tree(carry)
        for j in range(unroll):
            r = point_add_precomp(r, base_table[ws[j], dss[j]])
            r = point_add_precomp(r, key_table[slots, ws[j], dhs[j]])
        return r.tree(), None

    xs = (jnp.arange(NWIN, dtype=jnp.int32).reshape(nstep, unroll),
          digs_s.reshape(nstep, unroll, n),
          digs_h.reshape(nstep, unroll, n))
    final, _ = lax.scan(step, r0.tree(), xs)
    enc = point_encode(PointBatch.from_tree(final))
    return jnp.all(enc == r_bytes.astype(jnp.uint8), axis=-1)


_verify_tables_jit = jax.jit(verify_tables_forward,
                             static_argnames=("unroll",))


_base_table = None


def base_point_table():
    """(64, 16, 4, 16) table for the base point B, built on device once."""
    global _base_table
    if _base_table is None:
        ax = jnp.asarray(field.int_to_limbs(BX))[None, :]
        ay = jnp.asarray(field.int_to_limbs(BY))[None, :]
        _base_table = _build_jit(ax, ay)[0]
    return _base_table


class KeyTableCache:
    """Device-resident per-key window tables with LRU slot reuse."""

    def __init__(self, slots: int = 192):
        self.nslots = slots
        self.table = None           # (SLOTS, 64, 16, 4, 16) int64 device array
        self.slot_of: dict = {}     # pk bytes -> slot
        self._tick = 0
        self._last_used: dict = {}  # pk bytes -> tick

    def _ensure(self):
        if self.table is None:
            self.table = jnp.zeros(
                (self.nslots, NWIN, NDIG, 4, field.NLIMB), dtype=jnp.int64)

    def lookup(self, pk: bytes):
        slot = self.slot_of.get(pk)
        if slot is not None:
            self._tick += 1
            self._last_used[pk] = self._tick
        return slot

    def install(self, new_keys, protect=frozenset()):
        """new_keys: list of (pk_bytes, dec) where dec[0], dec[1] are the
        (cx, cy) affine limb arrays of -A (the pk-cache 3-tuple works as-is).
        Builds tables on device (batched, padded to BUILD_K) and scatters
        them into LRU slots.  Keys in `protect` (e.g. other keys used by the
        current batch) are never evicted.  Returns {pk: slot}; keys that
        could not get a slot (cache full of protected keys) are omitted."""
        if not new_keys:
            return {}
        self._ensure()
        # assign slots (evict least-recently-used unprotected keys)
        assigned = {}
        free = [s for s in range(self.nslots)
                if s not in set(self.slot_of.values())]
        victims = sorted(
            (k for k in self.slot_of if k not in protect),
            key=lambda k: self._last_used.get(k, 0))
        new_keys = list(new_keys)
        kept = []
        for pk, dec in new_keys:
            if free:
                slot = free.pop()
            elif victims:
                victim = victims.pop(0)
                slot = self.slot_of.pop(victim)
                self._last_used.pop(victim, None)
            else:
                continue  # cache exhausted by protected keys
            assigned[pk] = slot
            self.slot_of[pk] = slot
            self._tick += 1
            self._last_used[pk] = self._tick
            kept.append((pk, dec))
        new_keys = kept

        for start in range(0, len(new_keys), BUILD_K):
            batch = new_keys[start:start + BUILD_K]
            pad = BUILD_K - len(batch)
            ax = np.zeros((BUILD_K, field.NLIMB), dtype=np.int64)
            ay = np.zeros((BUILD_K, field.NLIMB), dtype=np.int64)
            ay[:, 0] = 1  # pad rows: identity-ish (x=0, y=1 is a valid point)
            for j, (_, dec) in enumerate(batch):
                ax[j] = dec[0]
                ay[j] = dec[1]
            built = _build_jit(jnp.asarray(ax), jnp.asarray(ay))
            idx = jnp.asarray(
                np.array([assigned[pk] for pk, _ in batch], dtype=np.int32))
            self.table = self.table.at[idx].set(built[:len(batch)])
        return assigned
