"""GF(2^255-19) arithmetic on batches, exact int64 limb math for JAX/TPU.

Representation: little-endian 16 limbs x 16 bits in int64 arrays of shape
(..., 16); values are kept partially reduced in [0, 2^256) between ops and
fully canonicalized only for encoding/compare.

Design notes (why not a port): libsodium's ref10 uses 10x25.5-bit limbs tuned
for 64-bit scalar CPUs.  On TPU the cost model is vector int ops, so we choose
a uniform 16-bit radix: 16x16 schoolbook products stay below 2^32, column sums
below 2^41, well inside exact int64 — and every op vectorizes over the batch
with no per-element control flow.  Reduction mod p uses 2^256 = 38 mod p.

Reference behavior mirrored: src/crypto (libsodium ed25519_ref10 fe25519_*).
"""

from __future__ import annotations

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax

NLIMB = 16
RADIX = 16
MASK = (1 << RADIX) - 1

P = (1 << 255) - 19
_P_LIMBS = tuple((P >> (RADIX * i)) & MASK for i in range(NLIMB))


def int_to_limbs(x: int) -> np.ndarray:
    return np.array([(x >> (RADIX * i)) & MASK for i in range(NLIMB)], dtype=np.int64)


def limbs_to_int(a) -> int:
    a = np.asarray(a)
    assert a.shape == (NLIMB,), "limbs_to_int expects one element"
    return sum(int(a[i]) << (RADIX * i) for i in range(NLIMB))


def ints_to_limbs(xs) -> np.ndarray:
    """Vector of python ints -> (n, 16) int64 limbs."""
    out = np.zeros((len(xs), NLIMB), dtype=np.int64)
    for j, x in enumerate(xs):
        for i in range(NLIMB):
            out[j, i] = (x >> (RADIX * i)) & MASK
    return out


def _carry_round(v):
    """One vectorized carry round: every limb sheds its carry to the next,
    limb 15's carry folds to limb 0 via 2^256 ≡ 38 (mod p)."""
    c = v >> RADIX
    shifted = jnp.concatenate([38 * c[..., NLIMB - 1:], c[..., :NLIMB - 1]], axis=-1)
    return (v & MASK) + shifted


def fe_carry(a):
    """Partially reduce with 3 vectorized rounds (see fe_mul's invariant)."""
    return _carry_round(_carry_round(_carry_round(a)))


# Lazy-reduction discipline (the int64 headroom makes carries after add/sub
# unnecessary — this is the main throughput lever on the VPU):
#
#   * fe_mul/fe_square outputs are carried (3 rounds): limbs <= 2^16 + eps.
#   * fe_add is a plain vector add, NO carry: limbs <= 2^17 + eps.
#   * fe_sub adds a 64p limbwise bias (each bias limb in [2^21, 2^22), value
#     == 64p == 0 mod p) and does NOT carry: limbs <= 2^22.2, and >= 0
#     because bias limbs dominate any subtrahend limb (<= 2^17.2).
#   * fe_mul accepts inputs with limbs <= 2^22.2: 16x16 products are
#     <= 2^44.4, column sums <= 2^48.4, and the 38-fold keeps everything
#     <= 624 * 2^44.4 < 2^54 — comfortably exact in int64.  Three carry
#     rounds bring the result back under 2^16 + eps, closing the loop.
_BIAS64P = tuple(64 * l for l in _P_LIMBS)  # limbwise 64*p, value == 64p


def fe_add(a, b):
    """Lazy add: no carry (safe straight into fe_mul — see invariant above)."""
    return a + b


def fe_sub(a, b):
    """Lazy subtract: adds a 64p limbwise bias so limbs stay non-negative;
    no carry (safe straight into fe_mul — see invariant above)."""
    bias = jnp.array(_BIAS64P, dtype=jnp.int64)
    return a + bias - b


def fe_mul(a, b):
    # 16x16 schoolbook: row i of the outer product lands at column offset i;
    # accumulate with 16 static slice-adds (compact XLA graph), then fold the
    # top 15 columns by 38 (2^256 ≡ 38 mod p).
    rows = a[..., :, None] * b[..., None, :]  # (..., 16, 16)
    cols = jnp.zeros(a.shape[:-1] + (2 * NLIMB - 1,), dtype=jnp.int64)
    for i in range(NLIMB):
        cols = cols.at[..., i:i + NLIMB].add(rows[..., i, :])
    folded = cols[..., :NLIMB].at[..., :NLIMB - 1].add(38 * cols[..., NLIMB:])
    return fe_carry(folded)


def fe_square(a):
    return fe_mul(a, a)


def _nsquare(x, n: int):
    return lax.fori_loop(0, n, lambda _, v: fe_mul(v, v), x)


def fe_invert(z):
    """z^(p-2) via the standard curve25519 addition chain (254 sq + 11 mul)."""
    z2 = fe_square(z)
    z8 = _nsquare(z2, 2)
    z9 = fe_mul(z, z8)
    z11 = fe_mul(z2, z9)
    z22 = fe_square(z11)
    z_5_0 = fe_mul(z9, z22)
    z_10_0 = fe_mul(_nsquare(z_5_0, 5), z_5_0)
    z_20_0 = fe_mul(_nsquare(z_10_0, 10), z_10_0)
    z_40_0 = fe_mul(_nsquare(z_20_0, 20), z_20_0)
    z_50_0 = fe_mul(_nsquare(z_40_0, 10), z_10_0)
    z_100_0 = fe_mul(_nsquare(z_50_0, 50), z_50_0)
    z_200_0 = fe_mul(_nsquare(z_100_0, 100), z_100_0)
    z_250_0 = fe_mul(_nsquare(z_200_0, 50), z_50_0)
    return fe_mul(_nsquare(z_250_0, 5), z11)


def fe_canonical(a):
    """Fully reduce to [0, p): exact carry normalization, then conditional
    subtract p twice with exact borrow."""
    p_limbs = jnp.array(_P_LIMBS, dtype=jnp.int64)

    def exact_pass(x):
        limbs = [x[..., i] for i in range(NLIMB)]
        carry = jnp.zeros_like(limbs[0])
        for i in range(NLIMB):
            v = limbs[i] + carry
            limbs[i] = v & MASK
            carry = v >> RADIX
        limbs[0] = limbs[0] + 38 * carry
        return jnp.stack(limbs, axis=-1)

    def cond_sub(x):
        # lexicographic x >= p, scanning from the top limb
        ge = jnp.ones(x.shape[:-1], dtype=jnp.bool_)
        decided = jnp.zeros(x.shape[:-1], dtype=jnp.bool_)
        for i in range(NLIMB - 1, -1, -1):
            gt = x[..., i] > p_limbs[i]
            lt = x[..., i] < p_limbs[i]
            ge = jnp.where(~decided & gt, True, jnp.where(~decided & lt, False, ge))
            decided = decided | gt | lt
        # subtract with borrow
        limbs = []
        borrow = jnp.zeros(x.shape[:-1], dtype=jnp.int64)
        for i in range(NLIMB):
            v = x[..., i] - p_limbs[i] - borrow
            borrow = (v < 0).astype(jnp.int64)
            limbs.append(v + borrow * (1 << RADIX))
        sub = jnp.stack(limbs, axis=-1)
        return jnp.where(ge[..., None], sub, x)

    return cond_sub(cond_sub(exact_pass(exact_pass(fe_carry(a)))))


def fe_const(x: int):
    """Constant field element as a (16,) int64 device-free array."""
    return jnp.array(int_to_limbs(x % P), dtype=jnp.int64)
