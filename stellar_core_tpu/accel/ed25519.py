"""Batched Ed25519 verification: the TPUCryptoBackend kernel.

Split of labor (SURVEY.md §7 hard-parts plan):

- CPU (numpy / python ints, exact): per-signature encoding checks in
  libsodium's order — S canonical (< L), R not small-order, pk canonical and
  not small-order, pk decompression — plus the SHA-512 challenge hash
  h = SHA512(R ‖ pk ‖ msg) mod L.  These are cheap, data-dependent-length
  operations; hashing on host also avoids shipping variable-length messages
  to the device.
- TPU (JAX, exact int64 limb math): the expensive part — for every signature
  the joint double-scalarmult R' = [s]B + [h](−A) over 127 2-bit-windowed
  scan steps (16-entry iB+jC table), then canonical encoding and
  byte-compare against R.

Verdict contract: bit-identical accept/reject with libsodium
``crypto_sign_verify_detached`` (reference: src/crypto/SecretKey.cpp —
PubKeyUtils::verifySig).  Enforced by differential tests incl. adversarial
encodings (tests/test_accel_ed25519.py).
"""

from __future__ import annotations

import hashlib
from typing import Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ..util.metrics import registry as _registry
from . import field
from .curve import (D, P, PointBatch, SQRT_M1, _recover_x,
                    double_scalarmult_w2, point_encode)

L = (1 << 252) + 27742317777372353535851937790883648493

_PK_UNSEEN = object()  # cache sentinel: distinguishes "never seen" from "rejected"


def _edwards_add_affine(p1, p2):
    x1, y1 = p1
    x2, y2 = p2
    x3 = (x1 * y2 + x2 * y1) * pow(1 + D * x1 * x2 * y1 * y2, P - 2, P) % P
    y3 = (y1 * y2 + x1 * x2) * pow(1 - D * x1 * x2 * y1 * y2, P - 2, P) % P
    return (x3, y3)


def _scalar_mul_affine(k, pt):
    r = (0, 1)
    q = pt
    while k:
        if k & 1:
            r = _edwards_add_affine(r, q)
        q = _edwards_add_affine(q, q)
        k >>= 1
    return r


def _derive_order8_ys() -> Tuple[int, int]:
    """The two order-8 torsion y-coordinates, derived (not hardcoded):
    an order-8 point R doubles to an order-4 point (±sqrt(-1), 0); working
    through the doubling formula with Y3=0 and the curve equation gives
    d·y^4 + 2·y^2 − 1 = 0, i.e. y² = (−1 ± sqrt(1+d))/d (mod p)."""
    sq = pow(1 + D, (P + 3) // 8, P)
    if (sq * sq - (1 + D)) % P != 0:
        sq = sq * SQRT_M1 % P
    assert (sq * sq - (1 + D)) % P == 0
    ys = []
    for root in (sq, P - sq):
        y2 = (root - 1) * pow(D, P - 2, P) % P
        y = pow(y2, (P + 3) // 8, P)
        if (y * y - y2) % P != 0:
            y = y * SQRT_M1 % P
        if (y * y - y2) % P != 0:
            continue
        for yy in (y, P - y):
            x = _recover_x(yy, 0)
            if x is None:
                continue
            pt = (x, yy)
            if (_scalar_mul_affine(8, pt) == (0, 1)
                    and _scalar_mul_affine(4, pt) != (0, 1)):
                ys.append(yy)
    ys = sorted(set(ys))
    assert len(ys) == 2, f"expected 2 order-8 y values, got {ys}"
    return ys[0], ys[1]


_Y8A, _Y8B = _derive_order8_ys()

_BLOCKLIST = np.stack([
    np.frombuffer((0).to_bytes(32, "little"), dtype=np.uint8),
    np.frombuffer((1).to_bytes(32, "little"), dtype=np.uint8),
    np.frombuffer(_Y8A.to_bytes(32, "little"), dtype=np.uint8),
    np.frombuffer(_Y8B.to_bytes(32, "little"), dtype=np.uint8),
    np.frombuffer((P - 1).to_bytes(32, "little"), dtype=np.uint8),
    np.frombuffer(P.to_bytes(32, "little"), dtype=np.uint8),
    np.frombuffer((P + 1).to_bytes(32, "little"), dtype=np.uint8),
])


_BLOCKLIST_MASKED = _BLOCKLIST.copy()
_BLOCKLIST_MASKED[:, 31] &= 0x7F

_P_BYTES = np.frombuffer(P.to_bytes(32, "little"), dtype=np.uint8)
_L_BYTES = np.frombuffer(L.to_bytes(32, "little"), dtype=np.uint8)


def _lt_vec(a: np.ndarray, bound: np.ndarray) -> np.ndarray:
    """(N, 32) LE byte matrix < bound (32 LE bytes), vectorized lexicographic
    compare from the most-significant byte down."""
    lt = np.zeros(a.shape[0], dtype=bool)
    decided = np.zeros(a.shape[0], dtype=bool)
    for i in range(31, -1, -1):
        bi = int(bound[i])
        lt |= (~decided) & (a[:, i] < bi)
        decided |= a[:, i] != bi
    return lt


def _small_order_vec(a: np.ndarray) -> np.ndarray:
    """(N, 32) encodings -> bool mask of small-order points (sign masked)."""
    m = a.copy()
    m[:, 31] &= 0x7F
    return np.any(np.all(m[:, None, :] == _BLOCKLIST_MASKED[None, :, :], axis=2),
                  axis=1)


def _windows_msb_first(s_raw: np.ndarray, h_raw: np.ndarray) -> np.ndarray:
    """(N, 32) LE scalar bytes x2 -> (127, N) int32 joint 2-bit windows,
    w = 4*s_window + h_window, MSB first (scalars < 2^253 < 2^254)."""
    sb = np.unpackbits(s_raw, axis=1, bitorder="little")
    hb = np.unpackbits(h_raw, axis=1, bitorder="little")
    s2 = sb[:, 0:254:2] + 2 * sb[:, 1:254:2]
    h2 = hb[:, 0:254:2] + 2 * hb[:, 1:254:2]
    w = (4 * s2 + h2).astype(np.int32)
    return w[:, ::-1].T.copy()


def verify_forward(windows, cx, cy, ct, r_bytes):
    """The jittable forward step (also the driver's compile-check target in
    __graft_entry__): windowed double-scalarmult + canonical encode +
    byte-compare."""
    n = cx.shape[0]
    cz = jnp.zeros((n, field.NLIMB), dtype=jnp.int64).at[:, 0].set(1)
    c = PointBatch(cx, cy, cz, ct)
    r = double_scalarmult_w2(windows, c)
    enc = point_encode(r)
    return jnp.all(enc == r_bytes, axis=-1)


_verify_kernel = jax.jit(verify_forward)


def _windows_on_device(s_raw, h_raw):
    """(N, 32) uint8 LE scalar bytes x2 -> (127, N) int32 joint 2-bit
    windows, MSB first — the device-side equivalent of _windows_msb_first
    (the host link is the scarcest resource: ship 64 bytes/sig, not a
    4-byte-per-window int32 matrix)."""
    s = s_raw.astype(jnp.int32)
    h = h_raw.astype(jnp.int32)
    j = jnp.arange(127, dtype=jnp.int32)
    byte_idx = j // 4
    shift = (2 * j) % 8
    s2 = (s[:, byte_idx] >> shift) & 3       # (N, 127)
    h2 = (h[:, byte_idx] >> shift) & 3
    w = 4 * s2 + h2
    return w[:, ::-1].T


def verify_forward_raw(s_raw, h_raw, key_idx, ucx, ucy, uct, r_bytes):
    """Transfer-lean generic path: raw scalar bytes + per-signature index
    into a deduplicated key-limb table; windows and key gathers happen on
    device."""
    windows = _windows_on_device(s_raw, h_raw)
    cx = ucx[key_idx]
    cy = ucy[key_idx]
    ct = uct[key_idx]
    return verify_forward(windows, cx, cy, ct, r_bytes)


_verify_kernel_raw = jax.jit(verify_forward_raw)


def _make_mesh():
    """1-D device mesh over all visible devices, or None single-device.
    Multi-chip scaling is pure data parallelism over the signature batch
    (SURVEY.md §2.5: DP == vmap over signatures), expressed with
    jax.sharding.Mesh + shard_map so the same code drives a v5e-8 and the
    virtual 8-device CPU mesh the test suite pins."""
    devs = jax.devices()
    if len(devs) <= 1:
        return None
    from jax.sharding import Mesh
    return Mesh(np.array(devs), ("data",))


def _shard(fn, mesh, in_specs):
    """jit(shard_map(fn)) with batch-sharded output, handling the
    jax.shard_map (check_vma) vs jax.experimental.shard_map (check_rep)
    API split — the kernels' scan carries start unvarying, so the
    varying-manual-axes check must be off either way."""
    from jax.sharding import PartitionSpec as P
    try:
        from jax import shard_map
        return jax.jit(shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=P("data"), check_vma=False))
    except (ImportError, TypeError):  # older jax
        from jax.experimental.shard_map import shard_map as esm
        return jax.jit(esm(fn, mesh=mesh, in_specs=in_specs,
                           out_specs=P("data"), check_rep=False))


def _sharded_generic(mesh):
    from jax.sharding import PartitionSpec as P
    b, rep = P("data"), P()
    return _shard(verify_forward_raw, mesh, (b, b, b, rep, rep, rep, b))


def _sharded_tables(mesh):
    from jax.sharding import PartitionSpec as P
    from . import tables as _tables
    b, rep = P("data"), P()
    return _shard(_tables.verify_tables_forward, mesh,
                  (b, b, b, b, rep, rep))


class Ed25519BatchVerifier:
    """Chunked, jit-cached batch verifier (one compile per chunk size).

    Two device paths, dispatched per signature by key temperature:

    * **table path** (accel/tables.py): keys seen >= `hot_threshold` times
      get a precomputed per-key window table in device HBM; verification is
      128 table adds with zero doublings (~2.4x fewer field mults).  This is
      the common case in catchup replay, where per-account sequence numbers
      serialize each account's transactions into a repeated-key stream.
    * **generic path**: joint 2-bit-windowed double-scalarmult for cold keys.

    Both paths ship raw bytes (96 B/sig + a key index) to the device and
    derive windows/digits there: the host<->device link, not the chip, is
    the scarcest resource (see PROFILE.md).
    """

    def __init__(self, chunk_size: int = 8192, table_slots: int = 192,
                 hot_threshold: int = 4, tail_floor: int = 256):
        self.chunk_size = chunk_size
        self.hot_threshold = hot_threshold
        # minimum pad width for tail batches: raising it to chunk_size
        # bounds jit compiles to ONE shape per path (catchup replay wants
        # this: compiles amortize over hundreds of checkpoints)
        self.tail_floor = min(tail_floor, chunk_size)
        # pk -> (cx, cy, ct) limbs of -A, or None if the key fails decoding /
        # canonicality / small-order checks.  Catchup replay re-verifies the
        # same accounts' keys constantly; decompression (two field exps in
        # python ints) is the dominant CPU prep cost, so this cache is load-
        # bearing for end-to-end throughput.
        self._pk_cache: dict = {}
        from . import tables as _tables
        self._tables = _tables.KeyTableCache(table_slots)
        # multi-chip: shard the batch over every visible device (v5e-8
        # topology or the tests' virtual CPU mesh); single device uses the
        # plain jitted kernels
        self._mesh = _make_mesh()
        if self._mesh is not None:
            self._ndev = self._mesh.devices.size
            self._kernel_raw = _sharded_generic(self._mesh)
            self._kernel_tables = _sharded_tables(self._mesh)
        else:
            self._ndev = 1
            self._kernel_raw = _verify_kernel_raw
            self._kernel_tables = _tables._verify_tables_jit
        self._use_counts: dict = {}
        # offload observability (VERDICT r1 weak #4): how much of the work
        # runs on which device path.
        self.stats = {"table_sigs": 0, "generic_sigs": 0, "rejected_prep": 0,
                      "tables_built": 0}

    @staticmethod
    def _decode_pk(pk: bytes):
        """Decompress pk to -A limbs; None if not on the curve.  Precondition:
        canonicality + small-order gates already applied (the single source of
        those rules is the vectorized _lt_vec/_small_order_vec pass in
        verify(); callers outside it must only pass honest keys)."""
        y = int.from_bytes(pk, "little") & ((1 << 255) - 1)
        x = _recover_x(y, pk[31] >> 7)
        if x is None:
            return None
        neg_x = (P - x) % P
        return (field.int_to_limbs(neg_x), field.int_to_limbs(y),
                field.int_to_limbs(neg_x * y % P))

    def verify(self, pks: Sequence[bytes], sigs: Sequence[bytes],
               msgs: Sequence[bytes]) -> np.ndarray:
        return self.verify_async(pks, sigs, msgs)()

    def verify_async(self, pks: Sequence[bytes], sigs: Sequence[bytes],
                     msgs: Sequence[bytes]):
        """Dispatch-only half: host prep + device kernel enqueue, NO sync.
        Returns a collector callable; invoking it blocks on the device
        results (np.asarray — block_until_ready is unreliable on this
        backend) and returns the verdict array.  JAX's async dispatch makes
        this the double-buffering seam: the caller can overlap the device
        compute with host work (SURVEY §5.8: dispatch batch k+1 while the
        CPU applies batch k)."""
        from . import tables as _tables

        n = len(pks)
        assert len(sigs) == n and len(msgs) == n
        _registry().histogram("accel.ed25519.batch-size").update(n)

        # -- vectorized encoding checks ---------------------------------
        # one join+frombuffer per matrix, not one frombuffer per signature:
        # per-sig numpy calls were ~8 us/sig of host prep, a real cost on
        # the 1-core bench host where prep competes with the apply thread
        ok = np.ones(n, dtype=bool)
        if all(len(s) == 64 for s in sigs) and all(len(p) == 32 for p in pks):
            sig_mat = np.frombuffer(b"".join(sigs), dtype=np.uint8) \
                .reshape(n, 64).copy()
            pk_mat = np.frombuffer(b"".join(pks), dtype=np.uint8) \
                .reshape(n, 32).copy()
        else:
            sig_mat = np.zeros((n, 64), dtype=np.uint8)
            pk_mat = np.zeros((n, 32), dtype=np.uint8)
            for i in range(n):
                s, p = sigs[i], pks[i]
                if len(s) == 64 and len(p) == 32:
                    sig_mat[i] = np.frombuffer(bytes(s), dtype=np.uint8)
                    pk_mat[i] = np.frombuffer(bytes(p), dtype=np.uint8)
                else:
                    ok[i] = False
        ok &= _lt_vec(sig_mat[:, 32:], _L_BYTES)            # S canonical
        ok &= ~_small_order_vec(sig_mat[:, :32])            # R not small order
        pk_no_sign = pk_mat.copy()
        pk_no_sign[:, 31] &= 0x7F
        ok &= _lt_vec(pk_no_sign, _P_BYTES)                 # pk canonical
        ok &= ~_small_order_vec(pk_mat)                     # pk not small order

        # -- per-element: pk decompress (cached) + challenge hash --------
        # h rows are accumulated as bytes and materialized with ONE
        # join+frombuffer at the end (same 1-core prep-cost rationale as
        # the sig/pk matrices above)
        _zero32 = b"\x00" * 32
        h_rows = [_zero32] * n
        decoded = [None] * n       # per-sig (cx, cy, ct) limbs of -A
        cache = self._pk_cache
        counts = self._use_counts
        sha512 = hashlib.sha512
        for i in range(n):
            if not ok[i]:
                continue
            pk = bytes(pks[i])
            cached = cache.get(pk, _PK_UNSEEN)
            if cached is _PK_UNSEEN:
                cached = self._decode_pk(pk)
                if len(cache) < 1_000_000:
                    cache[pk] = cached
            if cached is None:
                ok[i] = False
                continue
            decoded[i] = cached
            counts[pk] = counts.get(pk, 0) + 1
            sig = bytes(sigs[i])
            h = int.from_bytes(sha512(sig[:32] + pk + bytes(msgs[i])).digest(),
                               "little") % L
            h_rows[i] = h.to_bytes(32, "little")
        h_raw = np.frombuffer(b"".join(h_rows), dtype=np.uint8).reshape(n, 32)
        rejected = int(n - ok.sum())
        self.stats["rejected_prep"] += rejected
        _registry().counter("accel.ed25519.rejected-prep").inc(rejected)

        # -- hot/cold key split -----------------------------------------
        tabs = self._tables
        live = [i for i in range(n) if ok[i]]
        hot_pks = set()
        for i in live:
            pk = bytes(pks[i])
            if pk in tabs.slot_of or counts.get(pk, 0) >= self.hot_threshold:
                hot_pks.add(pk)
        to_install = [pk for pk in hot_pks if pk not in tabs.slot_of]
        if to_install:
            installed = tabs.install(
                [(pk, cache[pk]) for pk in to_install], protect=hot_pks)
            self.stats["tables_built"] += len(installed)
            _registry().counter("accel.ed25519.tables-built") \
                .inc(len(installed))
            hot_pks -= {pk for pk in to_install if pk not in installed}
        hot_idx = [i for i in live if bytes(pks[i]) in hot_pks]
        cold_idx = [i for i in live if bytes(pks[i]) not in hot_pks]
        self.stats["table_sigs"] += len(hot_idx)
        self.stats["generic_sigs"] += len(cold_idx)
        _registry().counter("accel.ed25519.table-sigs").inc(len(hot_idx))
        _registry().counter("accel.ed25519.generic-sigs").inc(len(cold_idx))

        out = np.zeros(n, dtype=bool)
        cs = self.chunk_size
        pending = []

        def _tail_width(count: int) -> int:
            """Full chunks stay chunk_size; a tail pads only to a
            power-of-two bucket (min 256) so a small remainder stream does
            not dispatch an almost-empty full-width kernel, while the set of
            compiled shapes stays bounded.  Widths are rounded up to a
            multiple of the device count so shard_map splits evenly."""
            if count >= cs:
                w = cs
            else:
                w = min(cs, max(self.tail_floor,
                                1 << (count - 1).bit_length()))
            ndev = self._ndev
            if w % ndev:
                w += ndev - (w % ndev)
            return w

        # -- table path (hot keys): raw bytes + slot ids, no doublings ---
        if hot_idx:
            idx = np.asarray(hot_idx)
            s_raw = sig_mat[idx, 32:]
            hh = h_raw[idx]
            rb = sig_mat[idx, :32]
            slots = np.asarray([tabs.lookup(bytes(pks[i])) for i in hot_idx],
                               dtype=np.int32)
            base_tab = _tables.base_point_table()
            for start in range(0, len(idx), cs):
                end = min(start + cs, len(idx))
                pad = _tail_width(end - start) - (end - start)

                def padded(a, pad=pad, start=start, end=end):
                    if pad == 0:
                        return a[start:end]
                    return np.concatenate(
                        [a[start:end],
                         np.zeros((pad,) + a.shape[1:], a.dtype)])

                verdict = self._kernel_tables(
                    jnp.asarray(padded(s_raw)), jnp.asarray(padded(hh)),
                    jnp.asarray(padded(slots)), jnp.asarray(padded(rb)),
                    tabs.table, base_tab)
                pending.append((idx[start:end], verdict, end - start))

        # -- generic path (cold keys): dedup'd key limbs + raw bytes -----
        if cold_idx:
            idx = np.asarray(cold_idx)
            key_of = {}
            key_rows = []
            kidx = np.zeros(len(idx), dtype=np.int32)
            for j, i in enumerate(cold_idx):
                pk = bytes(pks[i])
                ki = key_of.get(pk)
                if ki is None:
                    ki = key_of[pk] = len(key_rows)
                    key_rows.append(decoded[i])
                kidx[j] = ki
            # pad the key table to a power-of-two bucket: jit compiles once
            # per bucket size instead of once per distinct key count
            nk = max(64, 1 << (len(key_rows) - 1).bit_length())
            ucx = np.zeros((nk, field.NLIMB), dtype=np.int64)
            ucy = np.zeros((nk, field.NLIMB), dtype=np.int64)
            uct = np.zeros((nk, field.NLIMB), dtype=np.int64)
            ucx[:, 0] = ucy[:, 0] = uct[:, 0] = 1  # finite dummy rows
            for ki, r in enumerate(key_rows):
                ucx[ki], ucy[ki], uct[ki] = r
            ucx_d, ucy_d, uct_d = (jnp.asarray(ucx), jnp.asarray(ucy),
                                   jnp.asarray(uct))
            s_raw = sig_mat[idx, 32:]
            hh = h_raw[idx]
            rb = sig_mat[idx, :32]
            for start in range(0, len(idx), cs):
                end = min(start + cs, len(idx))
                pad = _tail_width(end - start) - (end - start)

                def padded(a, pad=pad, start=start, end=end):
                    if pad == 0:
                        return a[start:end]
                    return np.concatenate(
                        [a[start:end],
                         np.zeros((pad,) + a.shape[1:], a.dtype)])

                verdict = self._kernel_raw(
                    jnp.asarray(padded(s_raw)), jnp.asarray(padded(hh)),
                    jnp.asarray(padded(kidx)), ucx_d, ucy_d, uct_d,
                    jnp.asarray(padded(rb)))
                pending.append((idx[start:end], verdict, end - start))

        def collect() -> np.ndarray:
            for which, verdict, count in pending:
                out[which] = np.asarray(verdict)[:count]
            return out & ok

        return collect


_verifiers: dict = {}  # (chunk, floor) -> verifier (pk caches + jit warm)


def _verifier_for(chunk_size: int, tail_floor: int,
                  hot_threshold: int) -> Ed25519BatchVerifier:
    key = (chunk_size, tail_floor, hot_threshold)
    v = _verifiers.get(key)
    if v is None:
        v = _verifiers[key] = Ed25519BatchVerifier(
            chunk_size, tail_floor=tail_floor, hot_threshold=hot_threshold)
    return v


def verify_batch(pks, sigs, msgs, chunk_size: int = 512,
                 tail_floor: int = 256,
                 hot_threshold: int = 4) -> np.ndarray:
    return _verifier_for(chunk_size, tail_floor,
                         hot_threshold).verify(pks, sigs, msgs)


def verify_batch_async(pks, sigs, msgs, chunk_size: int = 512,
                       tail_floor: int = 256, hot_threshold: int = 4):
    """Dispatch now, sync later: returns the collector callable (see
    Ed25519BatchVerifier.verify_async)."""
    return _verifier_for(chunk_size, tail_floor,
                         hot_threshold).verify_async(pks, sigs, msgs)
