"""TPU quorum-intersection enumerator: the TPUQuorumIntersectionChecker.

The NP-hard min-quorum enumeration (reference: src/herder/
QuorumIntersectionCheckerImpl.{h,cpp} — MinQuorumEnumerator branch-and-
bound) restructured for the TPU execution model (SURVEY.md §3.5 design):

- node subsets are bitmasks packed into uint32 lanes ([B, W] words);
- the branch-and-bound DFS becomes a depth-synchronized frontier BFS with a
  *global* variable order (sorted by in-degree), so every frontier item at
  depth d shares the same remaining-mask and the whole frontier is pruned
  in one batched device dispatch;
- the expensive primitive — contract-to-maximal-quorum, a fixpoint of
  "keep nodes whose slice is satisfied" — is a jitted lax.while_loop whose
  body evaluates all N nodes' two-level quorum slices against all B subsets
  at once (popcounts via lax.population_count; the bool->bitmask repack is
  a uint32 power-of-two contraction, MXU/VPU friendly);
- rare events (a frontier item IS a quorum) drop to the exact CPU oracle
  (herder/quorum_intersection.py) for minimality + disjoint-complement
  checks, keeping verdicts bit-identical to the reference semantics;
- multi-chip: the frontier batch is sharded over a jax.sharding.Mesh with
  shard_map (data-parallel over subsets — the EP/SPMD analog per SURVEY.md
  §2.5); masks/thresholds are replicated.

Exactness: no sampling, no floating point — the verdict (intersects or
not) is differentially tested against the CPU oracle.
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as Pspec
try:
    from jax import shard_map
except ImportError:  # older jax
    from jax.experimental.shard_map import shard_map

from ..herder.quorum_intersection import (
    InterruptedError_, QuorumIntersectionChecker, QuorumIntersectionResult,
    flatten_qmap)
from ..util.metrics import registry as _registry

# Padding sentinel for inner-set thresholds: never satisfiable.
_PAD_THR = 1 << 30


def _masks_to_words(masks: List[int], n_words: int) -> np.ndarray:
    out = np.zeros((len(masks), n_words), dtype=np.uint32)
    for i, m in enumerate(masks):
        for w in range(n_words):
            out[i, w] = (m >> (32 * w)) & 0xFFFFFFFF
    return out


def _words_to_mask(words: np.ndarray) -> int:
    m = 0
    for w in range(words.shape[-1]):
        m |= int(words[w]) << (32 * w)
    return m


def _popcount_words(x):
    """Sum of set bits across the word axis: [..., W] uint32 -> [...] int32."""
    return jnp.sum(jax.lax.population_count(x).astype(jnp.int32), axis=-1)


def _satisfied(S, top_thr, top_masks, inner_thr, inner_masks):
    """For each subset and node: does S contain a slice of node's qset?

    S [B, W] uint32; top_thr [N]; top_masks [N, W]; inner_thr [N, K];
    inner_masks [N, K, W].  Returns [B, N] bool.
    """
    hits = _popcount_words(S[:, None, :] & top_masks[None, :, :])  # [B, N]
    k = inner_thr.shape[1]
    for j in range(k):  # K is small (org count); unrolled, fused by XLA
        inner_ok = (_popcount_words(S[:, None, :] & inner_masks[None, :, j, :])
                    >= inner_thr[None, :, j])
        hits = hits + inner_ok.astype(jnp.int32)
    return hits >= top_thr[None, :]


def _pack_bits(sat, n_words: int):
    """[B, N] bool -> [B, W] uint32 (bit n of word n//32 = sat[:, n])."""
    b, n = sat.shape
    pad = n_words * 32 - n
    bits = jnp.pad(sat, ((0, 0), (0, pad))).reshape(b, n_words, 32)
    powers = (jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32))
    return jnp.sum(bits.astype(jnp.uint32) * powers[None, None, :], axis=-1,
                   dtype=jnp.uint32)


def _contract_body(S, top_thr, top_masks, inner_thr, inner_masks):
    n_words = S.shape[-1]
    sat = _satisfied(S, top_thr, top_masks, inner_thr, inner_masks)
    return S & _pack_bits(sat, n_words)


def _contract_fixpoint(S, top_thr, top_masks, inner_thr, inner_masks):
    """Greatest quorum within each subset (0 if none): lax.while_loop to a
    fixpoint of the keep-satisfied-nodes contraction."""
    def cond(carry):
        s, changed = carry
        return changed

    def body(carry):
        s, _ = carry
        s2 = _contract_body(s, top_thr, top_masks, inner_thr, inner_masks)
        return s2, jnp.any(s2 != s)

    # initial flag derived from S so it has the same varying-axes type as
    # the loop output under shard_map (always True)
    out, _ = jax.lax.while_loop(cond, body, (S, jnp.any(S >= 0)))
    return out


def _child_flags(children, remaining, scc_words, top_thr, top_masks,
                 inner_thr, inner_masks):
    """Shared prune predicate: (dead [B], is_q [B], witness [B]) for
    candidate committed-masks `children` against the depth's
    remaining-mask.

    witness[b] means children[b] is a quorum AND its complement within the
    scc contains a quorum — a concrete split, found ON DEVICE.  Minimality
    of the hit is NOT checked: it only gates which hit finds a given split
    first (any split is witnessed by one of its side's minimal quorums,
    which this enumeration reaches on its own branch; a non-minimal hit's
    complement check can only surface another REAL split, never a false
    one, because both sides are quorums and disjoint by construction).
    Dropping it moves the entire hit-processing loop off the host — the r3
    path shipped every quorum hit to Python (1.37M hits at orgs=6, ~100 us
    each ≈ 140 s of host time) plus a 256-row buffer overflow cascade."""
    perimeter = children | remaining[None, :]
    mq = _contract_fixpoint(perimeter, top_thr, top_masks, inner_thr,
                            inner_masks)
    # prune: committed not inside the max quorum of its perimeter
    dead = jnp.any(children & ~mq, axis=-1) | ~jnp.any(mq, axis=-1)
    # committed IS a quorum iff every member's slice is satisfied
    # within committed — a single _satisfied pass, no fixpoint (the
    # fixpoint is only needed to find the GREATEST quorum inside a
    # non-quorum set)
    n_words = children.shape[-1]
    sat = _pack_bits(_satisfied(children, top_thr, top_masks, inner_thr,
                                inner_masks), n_words)
    nonzero = jnp.any(children, axis=-1)
    is_q = nonzero & ~jnp.any(children & ~sat, axis=-1)
    # split witness: greatest quorum of the scc-complement, batched.  The
    # input is masked to the (almost always empty) quorum-hit lanes so the
    # shared while_loop converges in one body iteration on hit-free
    # batches instead of running a full fixpoint for every lane whose
    # result would be discarded.
    comp_mq = _contract_fixpoint(
        jnp.where(is_q[:, None], scc_words[None, :] & ~children, 0),
        top_thr, top_masks, inner_thr, inner_masks)
    witness = is_q & jnp.any(comp_mq, axis=-1)
    return dead, is_q, witness


@partial(jax.jit, static_argnames=("mesh_size",))
def _prune_step(children, remaining, scc_words, top_thr, top_masks,
                inner_thr, inner_masks, mesh_size=None):
    """One frontier depth step, fully batched.

    children [B, W]: candidate committed-masks after the split expansion.
    remaining [W]: the shared remaining-mask at the children's depth.
    Returns (alive [B] bool — survives pruning and is not itself a quorum,
             is_quorum [B] bool — contract(committed)==committed != 0,
             witness [B] bool — is_quorum with a disjoint-quorum
             complement, i.e. a proven split).
    """
    dead, is_q, witness = _child_flags(children, remaining, scc_words,
                                       top_thr, top_masks, inner_thr,
                                       inner_masks)
    return ~dead & ~is_q, is_q, witness


# Depths fused per device dispatch on the resident-frontier path.  Fixed
# (inactive tail depths pass through via lax.cond) so the only compiled-
# shape axis is the frontier capacity bucket — one compile costs 20-40s on
# this backend, so the shape space must stay tiny (PROFILE.md round 3).
SEG_DEPTHS = 4
# Per-depth capacity of the split-WITNESS output buffer.  Witnesses are
# genuinely rare (zero on any intersecting map — quorum hits are filtered
# by the on-device complement check, not shipped to the host), and one
# witness already decides the verdict, so a tiny buffer suffices; a depth
# that somehow finds more keeps the first WITNESS_CAP (the verdict and a
# valid split are identical either way).
WITNESS_CAP = 8


@jax.jit
def _segment_step(frontier, count, bits_seq, rems_seq, active_seq,
                  scc_words, top_thr, top_masks, inner_thr, inner_masks):
    """SEG_DEPTHS frontier depths in ONE dispatch, frontier resident on
    device (VERDICT r3 weak #4: the old path round-tripped every batch
    host<->device once per chunk per depth on a ~0.3 s/dispatch tunnel).

    frontier [capacity, W] uint32 (rows >= count are padding);
    count      int32 — live frontier rows;
    bits_seq   [SEG_DEPTHS, W] — the split bit of each depth;
    rems_seq   [SEG_DEPTHS, W] — remaining-mask BELOW each depth;
    active_seq [SEG_DEPTHS] bool — False = padding depth (pass-through);
    scc_words  [W] — the main quorum-bearing SCC (complement universe).

    Returns (frontier', meta [2*SEG_DEPTHS+2] int32, w_rows [SEG_DEPTHS,
    WITNESS_CAP, W]) where meta = per-depth quorum-hit counts ++ per-depth
    witness counts ++ [count', ovf_depth] — ONE packed array so the host's
    segment sync is a single device->host transfer (each materialization
    is its own ~0.3 s RPC on the tunneled backend).  ovf_depth is the
    first depth index whose compacted frontier exceeded capacity, -1 if
    none; state stops advancing at the overflow depth so the host can
    finish that depth with the chunked fallback path.
    """
    C = frontier.shape[0]
    W = frontier.shape[1]

    def depth(carry, xs):
        fr, cnt, ovf, didx = carry
        bit, rem, is_active = xs

        def run(args):
            fr, cnt = args
            children = jnp.concatenate([fr, fr | bit[None, :]])   # [2C, W]
            valid = jnp.concatenate([jnp.arange(C) < cnt,
                                     jnp.arange(C) < cnt])
            dead, is_q, wit = _child_flags(children, rem, scc_words,
                                           top_thr, top_masks, inner_thr,
                                           inner_masks)
            alive = ~dead & ~is_q & valid
            is_q = is_q & valid
            wit = wit & valid
            # device-side compaction: stable argsort moves alive rows to
            # the front in order (exclude-branch children first, matching
            # the host path's concatenation order)
            order = jnp.argsort(~alive)
            new_fr = children[order][:C]
            new_cnt = jnp.sum(alive).astype(jnp.int32)
            w_order = jnp.argsort(~wit)
            w_rows = children[w_order][:WITNESS_CAP]
            q_cnt = jnp.sum(is_q).astype(jnp.int32)
            w_cnt = jnp.sum(wit).astype(jnp.int32)
            did_ovf = new_cnt > C
            return new_fr, new_cnt, w_rows, q_cnt, w_cnt, did_ovf

        def skip(args):
            fr, cnt = args
            return (fr, cnt, jnp.zeros((WITNESS_CAP, W), jnp.uint32),
                    jnp.int32(0), jnp.int32(0), jnp.bool_(False))

        live = is_active & (ovf < 0)
        new_fr, new_cnt, w_rows, q_cnt, w_cnt, did_ovf = jax.lax.cond(
            live, run, skip, (fr, cnt))
        # overflow: freeze the PRE-step state for the host to resume from
        new_fr = jnp.where(did_ovf, fr, new_fr)
        new_cnt = jnp.where(did_ovf, cnt, new_cnt)
        w_rows = jnp.where(did_ovf, jnp.zeros_like(w_rows), w_rows)
        q_cnt = jnp.where(did_ovf, 0, q_cnt)
        w_cnt = jnp.where(did_ovf, 0, w_cnt)
        new_ovf = jnp.where((ovf < 0) & did_ovf, didx, ovf)
        return ((new_fr, new_cnt, new_ovf, didx + 1),
                (w_rows, q_cnt, w_cnt))

    (fr, cnt, ovf, _), (w_rows, q_counts, w_counts) = jax.lax.scan(
        depth, (frontier, count, jnp.int32(-1), jnp.int32(0)),
        (bits_seq, rems_seq, active_seq))
    meta = jnp.concatenate([q_counts, w_counts, jnp.stack([cnt, ovf])])
    return fr, meta, w_rows


class TPUQuorumIntersectionChecker:
    """Drop-in TPU-accelerated intersection check over a quorum map.

    Same verdict contract as the CPU QuorumIntersectionChecker; requires
    the flattened two-level (org-form) qset shape (ValueError otherwise —
    callers fall back to the CPU oracle, as HerderImpl does).
    """

    def __init__(self, qmap: Dict[bytes, object],
                 interrupt: Optional[Callable[[], bool]] = None,
                 batch_size: Optional[int] = None,
                 mesh: Optional[Mesh] = None):
        (self.node_ids, tops, top_masks, inner_thrs,
         inner_masks) = flatten_qmap(qmap)
        self.n = len(self.node_ids)
        self.interrupt = interrupt or (lambda: False)
        # None = auto.  The chunked path (mesh, and the over-capacity
        # fallback when a frontier outgrows the largest resident bucket)
        # pays ~0.3 s of tunnel latency PER DISPATCH: at the old 2048
        # default an orgs=7 peak depth (~2M children) cost ~1000 dispatches
        # ≈ 300 s per depth.  Wide chunks amortize it; the frontier rows
        # are 1-2 uint32 words, so even 65536-row chunks are ~0.5 MB.
        self.batch_size = 65536 if batch_size is None else batch_size
        self.mesh = mesh
        # CPU oracle shares index order (flatten_qmap and the checker both
        # sort node ids) — used for SCC analysis and rare-event checks.
        self.oracle = QuorumIntersectionChecker(qmap, interrupt)
        assert self.oracle.node_ids == self.node_ids

        self.n_words = max((self.n + 31) // 32, 1)
        k = max((len(t) for t in inner_thrs), default=0)
        k = max(k, 1)
        n, w = self.n, self.n_words
        thr = np.full((n, k), _PAD_THR, dtype=np.int32)
        imask = np.zeros((n, k, w), dtype=np.uint32)
        for i in range(n):
            for j, t in enumerate(inner_thrs[i]):
                thr[i, j] = t
                imask[i, j] = _masks_to_words([inner_masks[i][j]], w)[0]
        self.top_thr = jnp.asarray(np.asarray(tops, dtype=np.int32))
        self.top_masks = jnp.asarray(_masks_to_words(top_masks, w))
        self.inner_thr = jnp.asarray(thr)
        self.inner_masks = jnp.asarray(imask)

        if mesh is not None:
            ndev = mesh.devices.size
            spec_b = Pspec("data", None)
            sharded = shard_map(
                lambda c, r, sw, tt, tm, it, im: _prune_step(
                    c, r, sw, tt, tm, it, im),
                mesh=mesh,
                in_specs=(spec_b, Pspec(None), Pspec(None), Pspec(None),
                          Pspec(None, None), Pspec(None, None),
                          Pspec(None, None, None)),
                out_specs=(Pspec("data"), Pspec("data"), Pspec("data")),
                # the contraction fixpoint is a lax.while_loop over
                # replicated operands; this jax has no replication rule
                # for `while`, and every output is explicitly sharded
                # along "data" anyway — replication checking buys nothing
                check_rep=False)
            self._step = jax.jit(sharded)
            self._pad_to = ndev
        else:
            self._step = _prune_step
            self._pad_to = 1
        # set by check() once the quorum-bearing SCC is known; the device
        # complement check runs against this universe
        self._scc_words = None

    # -- batched pruning over the device ---------------------------------
    def _prune(self, children: np.ndarray, remaining_words: np.ndarray
               ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        alive = np.zeros(len(children), dtype=bool)
        is_q = np.zeros(len(children), dtype=bool)
        wit = np.zeros(len(children), dtype=bool)
        bs = self.batch_size
        rem = jnp.asarray(remaining_words)
        scc_w = jnp.asarray(self._scc_words)
        for lo in range(0, len(children), bs):
            if self.interrupt():
                raise InterruptedError_()
            chunk = children[lo:lo + bs]
            n_real = len(chunk)
            # pad to a power-of-two bucket (min 256, multiple of the mesh):
            # the frontier doubles every depth, and one jit compile per
            # distinct batch shape costs ~20-40s on this backend — shape
            # discipline is the whole ballgame (same lesson as the sig
            # kernel's tail_floor)
            width = max(256, 1 << (n_real - 1).bit_length())
            width += (-width) % self._pad_to
            pad = width - n_real
            if pad:
                # padded rows are committed=0 perimeter=remaining — they
                # compute a real (discarded) contraction, never an error
                chunk = np.concatenate(
                    [chunk, np.zeros((pad, self.n_words), dtype=np.uint32)])
            a, q, w = self._step(jnp.asarray(chunk), rem, scc_w,
                                 self.top_thr, self.top_masks,
                                 self.inner_thr, self.inner_masks)
            alive[lo:lo + bs] = np.asarray(a)[:n_real]
            is_q[lo:lo + bs] = np.asarray(q)[:n_real]
            wit[lo:lo + bs] = np.asarray(w)[:n_real]
        return alive, is_q, wit

    # -- the frontier search ---------------------------------------------
    def check(self) -> QuorumIntersectionResult:
        # enumeration-scale observability: map size, peak frontier width
        # and quorum hits land in the registry (accel.quorum.*).  Both
        # accumulators reset HERE: an aborted run must not re-count the
        # previous check()'s hits in the finally block below
        self._frontier_peak = 0
        self._quorum_hits = 0
        _registry().counter("accel.quorum.checks").inc()
        _registry().histogram("accel.quorum.nodes").update(self.n)
        try:
            return self._check()
        finally:
            _registry().histogram("accel.quorum.frontier-peak").update(
                self._frontier_peak)
            _registry().counter("accel.quorum.quorum-hits").inc(
                self._quorum_hits)

    def _check(self) -> QuorumIntersectionResult:
        oracle = self.oracle
        n = self.n
        if n == 0:
            return QuorumIntersectionResult(True, node_count=0)

        # SCC phase on CPU (cheap, irregular graph walk)
        from ..herder.quorum_intersection import tarjan_sccs
        oracle._indegree = indeg = [0] * n
        for qb in oracle.qbs:
            m = qb.successors
            while m:
                bit = m & -m
                indeg[bit.bit_length() - 1] += 1
                m ^= bit
        sccs = tarjan_sccs([qb.successors for qb in oracle.qbs], n)
        quorum_sccs = [(s, mq) for s in sccs
                       if (mq := oracle.contract_to_max_quorum(s))]
        if not quorum_sccs:
            return QuorumIntersectionResult(True, node_count=n,
                                            main_scc_size=0)
        if len(quorum_sccs) > 1:
            (_, q1), (_, q2) = quorum_sccs[0], quorum_sccs[1]
            return QuorumIntersectionResult(
                False, split=(oracle._names(q1), oracle._names(q2)),
                node_count=n, main_scc_size=0)
        scc, _ = quorum_sccs[0]

        # global variable order: in-degree desc (matches the CPU split
        # heuristic; a fixed order is what lets the frontier share masks)
        order = sorted((i for i in range(n) if (scc >> i) & 1),
                       key=lambda i: -indeg[i])
        depth_remaining = [0] * (len(order) + 1)
        for d in range(len(order) - 1, -1, -1):
            depth_remaining[d] = depth_remaining[d + 1] | (1 << order[d])

        D = len(order)
        bits_all = np.stack([_masks_to_words([1 << order[d]], self.n_words)[0]
                             for d in range(D)])
        rems_all = np.stack(
            [_masks_to_words([depth_remaining[d + 1]], self.n_words)[0]
             for d in range(D)])
        self._scc_words = _masks_to_words([scc], self.n_words)[0]

        self._quorum_hits = 0

        def process_witness(words) -> QuorumIntersectionResult:
            """A device-reported split witness: committed claims to be a
            quorum whose scc-complement contains one.  BOTH sides are
            re-verified on the exact CPU oracle before the verdict leaves
            this class — a fault on the flaky tunneled device must
            fail-stop, never fabricate a 'proven non-intersection'."""
            committed = _words_to_mask(words)
            if not oracle.is_quorum(committed):
                raise RuntimeError(
                    "device split witness rejected by CPU oracle: committed "
                    "set is not a quorum (device fault?)")
            disjoint = oracle.contract_to_max_quorum(scc & ~committed)
            if not disjoint:
                raise RuntimeError(
                    "device split witness rejected by CPU oracle: "
                    "complement has no quorum (device fault?)")
            return QuorumIntersectionResult(
                False,
                split=(oracle._names(committed), oracle._names(disjoint)),
                node_count=n, main_scc_size=scc.bit_count(),
                max_quorums_found=self._quorum_hits)

        if self.mesh is None:
            res = self._run_resident(bits_all, rems_all, process_witness)
        else:
            # the sharded multi-chip path keeps the per-depth chunked step
            # (device-side argsort compaction is shard-local under
            # shard_map; cross-shard compaction would need a gather that
            # defeats the residency win)
            res = self._run_chunked(bits_all, rems_all, process_witness)
        if res is not None:
            return res
        return QuorumIntersectionResult(
            True, node_count=n, main_scc_size=scc.bit_count(),
            max_quorums_found=self._quorum_hits)

    def _run_chunked(self, bits_all, rems_all, process_witness
                     ) -> Optional[QuorumIntersectionResult]:
        """Per-depth host-chunked frontier walk (the round-3 path; still
        used under a mesh and as the overflow fallback)."""
        frontier = np.zeros((1, self.n_words), dtype=np.uint32)  # committed=0
        for d in range(len(bits_all)):
            if len(frontier) == 0:
                break
            frontier, res = self._chunked_depth(frontier, bits_all[d],
                                                rems_all[d], process_witness)
            self._note_frontier(len(frontier))
            if res is not None:
                return res
        return None

    def _note_frontier(self, width: int) -> None:
        self._frontier_peak = max(getattr(self, "_frontier_peak", 0), width)

    def _chunked_depth(self, frontier, bit_words, rem_words, process_witness):
        """Expand + prune ONE depth on the host-chunked path; returns
        (new_frontier, early_result_or_None)."""
        children = np.concatenate([frontier, frontier | bit_words])
        alive, is_q, wit = self._prune(children, rem_words)
        self._quorum_hits += int(is_q.sum())
        w_idx = np.nonzero(wit)[0]
        if len(w_idx):
            return children[alive], process_witness(children[w_idx[0]])
        return children[alive], None

    # capacity buckets for the device-resident frontier: pow4-spaced —
    # coarse enough that jit compiles stay few (one compile per bucket
    # costs 20-40s on this backend), fine enough that padded rows stay
    # within ~4x of the worst-case segment need.  The top buckets exist
    # for the adversarial asym-org maps whose frontiers peak in the
    # hundreds of thousands: falling off the resident path there costs
    # hundreds of chunked dispatches per depth (W is 1-2 words, so even
    # 1M rows is only ~8 MB of frontier).
    # top bucket 4M rows (r5): at orgs=8 the frontier outgrows 1M and the
    # resident path fell back to 65536-row host chunks for most depths
    # (r4: 1995s).  A 4M-row frontier is 16 MB/word-column in HBM —
    # trivial against 16 GB — and keeps orgs=8 device-resident.
    CAPACITY_BUCKETS = (1024, 4096, 16384, 65536, 262144, 1048576, 4194304)

    def _run_resident(self, bits_all, rems_all, process_witness
                      ) -> Optional[QuorumIntersectionResult]:
        """Device-resident frontier walk: SEG_DEPTHS depths per dispatch,
        compaction on device; per segment the host syncs scalars, the rare
        split-witness rows, and the frontier array only when the capacity
        bucket changes (VERDICT r3 weak #4: the old path shipped every
        chunk host<->device once per depth)."""
        D = len(bits_all)
        fr_host = np.zeros((1, self.n_words), dtype=np.uint32)
        fr_dev = None        # device-resident [cur_cap, W] when not None
        cur_cap = None
        count = 1
        d = 0

        def to_host(n):
            return (np.asarray(fr_dev)[:n] if fr_dev is not None
                    else fr_host[:n])

        while d < D and count > 0:
            self._note_frontier(count)
            if self.interrupt():
                raise InterruptedError_()
            # worst case the frontier doubles every depth of the segment;
            # bucket >= need means in-segment overflow is only possible at
            # the largest bucket
            need = count << SEG_DEPTHS
            cap = next((c for c in self.CAPACITY_BUCKETS if c >= need),
                       self.CAPACITY_BUCKETS[-1])
            if count * 2 > cap:
                # frontier too wide even for the largest bucket: finish
                # this depth host-chunked, then retry residency
                fr_host, res = self._chunked_depth(
                    to_host(count), bits_all[d], rems_all[d],
                    process_witness)
                fr_dev = None
                if res is not None:
                    return res
                count = len(fr_host)
                d += 1
                continue
            k = min(SEG_DEPTHS, D - d)
            bits = np.zeros((SEG_DEPTHS, self.n_words), dtype=np.uint32)
            rems = np.zeros((SEG_DEPTHS, self.n_words), dtype=np.uint32)
            active = np.zeros(SEG_DEPTHS, dtype=bool)
            bits[:k] = bits_all[d:d + k]
            rems[:k] = rems_all[d:d + k]
            active[:k] = True
            if fr_dev is None or cur_cap != cap:
                pad = np.zeros((cap, self.n_words), dtype=np.uint32)
                pad[:count] = to_host(count)
                fr_in = jnp.asarray(pad)
            else:
                fr_in = fr_dev   # already device-resident at this capacity
            fr, meta, w_rows = _segment_step(
                fr_in, jnp.int32(count), jnp.asarray(bits),
                jnp.asarray(rems), jnp.asarray(active),
                jnp.asarray(self._scc_words), self.top_thr,
                self.top_masks, self.inner_thr, self.inner_masks)
            # ONE sync per segment: the packed meta array carries the
            # per-depth hit/witness counts + count' + ovf in a single
            # transfer (materialization is what executes on this lazy
            # backend); witness rows transfer only when one exists —
            # i.e. never, on an intersecting map
            meta = np.asarray(meta)
            q_counts = meta[:SEG_DEPTHS]
            w_counts = meta[SEG_DEPTHS:2 * SEG_DEPTHS]
            count = int(meta[2 * SEG_DEPTHS])
            ovf = int(meta[2 * SEG_DEPTHS + 1])
            fr_dev, cur_cap = fr, cap
            done_depths = k if ovf < 0 else min(ovf, k)
            if w_counts[:done_depths].any():
                # count quorum hits only up to and including the witnessing
                # depth, so max_quorums_found matches the CPU oracle's count
                # at the moment the split is found (the whole segment ran on
                # device, but depths past the witness are diagnostically
                # "after" it)
                rows = np.asarray(w_rows)
                for j in range(done_depths):
                    if w_counts[j]:
                        self._quorum_hits += int(q_counts[:j + 1].sum())
                        return process_witness(rows[j, 0])
            self._quorum_hits += int(q_counts[:done_depths].sum())
            if ovf >= 0:
                # the overflow depth never ran: state froze at its input —
                # finish that depth host-chunked and continue
                fr_host, res = self._chunked_depth(
                    to_host(count), bits_all[d + ovf], rems_all[d + ovf],
                    process_witness)
                fr_dev = None
                if res is not None:
                    return res
                count = len(fr_host)
                d += ovf + 1
            else:
                d += k
        return None


def check_intersection_tpu(qmap, interrupt=None, mesh=None,
                           batch_size=None) -> QuorumIntersectionResult:
    """One-shot API mirroring herder.quorum_intersection.check_intersection."""
    return TPUQuorumIntersectionChecker(qmap, interrupt, batch_size,
                                        mesh).check()
