"""Edwards25519 point arithmetic on batches (extended coordinates).

Formulas are the complete twisted-Edwards a=-1 add/double from RFC 8032
§5.1.4 (the same ones libsodium's verify path computes via ge25519_*).
All ops are data-parallel over a leading batch dimension; there is no
per-element control flow, so the whole double-scalarmult lowers to one
fused XLA scan — the TPU-first reformulation of the reference's
sequential ge25519_double_scalarmult_vartime
(reference: src/crypto — libsodium ed25519_ref10).
"""

from __future__ import annotations

import numpy as np

import jax.numpy as jnp
from jax import lax

from .field import (NLIMB, P, fe_add, fe_canonical, fe_const, fe_invert,
                    fe_mul, fe_square, fe_sub)

# curve constants
D = (-121665 * pow(121666, P - 2, P)) % P
D2 = (2 * D) % P
SQRT_M1 = pow(2, (P - 1) // 4, P)

# base point B
BY = (4 * pow(5, P - 2, P)) % P
BX = None


def _recover_x(y: int, sign: int):
    x2 = (y * y - 1) * pow(D * y * y + 1, P - 2, P) % P
    x = pow(x2, (P + 3) // 8, P)
    if (x * x - x2) % P != 0:
        x = x * SQRT_M1 % P
    if (x * x - x2) % P != 0:
        return None
    if x == 0 and sign == 1:
        return None
    if x % 2 != sign:
        x = P - x
    return x


BX = _recover_x(BY, 0)
assert BX is not None


class PointBatch:
    """Extended-coordinate points (X, Y, Z, T), each (..., 16) int64 limbs."""

    __slots__ = ("X", "Y", "Z", "T")

    def __init__(self, X, Y, Z, T):
        self.X, self.Y, self.Z, self.T = X, Y, Z, T

    @staticmethod
    def identity(shape=()):
        zero = jnp.zeros(shape + (NLIMB,), dtype=jnp.int64)
        one = jnp.zeros(shape + (NLIMB,), dtype=jnp.int64).at[..., 0].set(1)
        return PointBatch(zero, one, one, zero)

    @staticmethod
    def identity_like(ref: "PointBatch") -> "PointBatch":
        """Identity derived from an existing batch so the result inherits its
        sharding/varying type (required for lax.scan carries under
        shard_map)."""
        zero = ref.X * 0
        one = zero.at[..., 0].set(1)
        return PointBatch(zero, one, one, zero)

    def tree(self):
        return (self.X, self.Y, self.Z, self.T)

    @staticmethod
    def from_tree(t):
        return PointBatch(*t)


def point_dbl(p: PointBatch) -> PointBatch:
    A = fe_square(p.X)
    B = fe_square(p.Y)
    C = fe_add(fe_square(p.Z), fe_square(p.Z))
    H = fe_add(A, B)
    E = fe_sub(H, fe_square(fe_add(p.X, p.Y)))
    G = fe_sub(A, B)
    F = fe_add(C, G)
    return PointBatch(fe_mul(E, F), fe_mul(G, H), fe_mul(F, G), fe_mul(E, H))


def point_add(p: PointBatch, q: PointBatch, d2_limbs) -> PointBatch:
    A = fe_mul(fe_sub(p.Y, p.X), fe_sub(q.Y, q.X))
    B = fe_mul(fe_add(p.Y, p.X), fe_add(q.Y, q.X))
    C = fe_mul(fe_mul(p.T, q.T), d2_limbs)
    ZZ = fe_mul(p.Z, q.Z)
    Dd = fe_add(ZZ, ZZ)
    E = fe_sub(B, A)
    F = fe_sub(Dd, C)
    G = fe_add(Dd, C)
    H = fe_add(B, A)
    return PointBatch(fe_mul(E, F), fe_mul(G, H), fe_mul(F, G), fe_mul(E, H))


def _affine_mult(k: int):
    """k*B as exact affine ints (python, setup-time only)."""
    x, y = BX, BY
    rx, ry = 0, 1
    kk = k
    while kk:
        if kk & 1:
            nx = (rx * y + x * ry) * pow(1 + D * rx * x * ry * y, P - 2, P) % P
            ny = (ry * y + rx * x) * pow(1 - D * rx * x * ry * y, P - 2, P) % P
            rx, ry = nx, ny
        nx2 = (x * y + x * y) * pow(1 + D * x * x * y * y, P - 2, P) % P
        ny2 = (y * y + x * x) * pow(1 - D * x * x * y * y, P - 2, P) % P
        x, y = nx2, ny2
        kk >>= 1
    return rx, ry


_B_MULTS = [_affine_mult(k) for k in range(4)]  # 0B..3B (0B = identity)


def double_scalarmult_w2(windows, c_point: PointBatch):
    """R = [s]B + [h]C via joint 2-bit windows: per step R=4R; R+=T[w] where
    T[4i+j] = iB + jC (16-entry table built on device per batch).

    windows: (127, N) int32, w = 4*s_window + h_window, MSB-first (scalars
    < 2^254).  ~2x fewer field mults than bit-serial double-and-add; the
    table gather is one take_along_axis per coordinate.
    """
    n = windows.shape[1]
    d2 = fe_const(D2)

    def bcast(v):
        return jnp.broadcast_to(v, (n, NLIMB))

    # C multiples: identity, C, 2C, 3C
    ident = PointBatch.identity_like(c_point)
    c2 = point_dbl(c_point)
    c3 = point_add(c2, c_point, d2)
    c_mults = [ident, c_point, c2, c3]

    entries = []
    for i in range(4):
        if i == 0:
            row = c_mults
        else:
            bx, by = _B_MULTS[i]
            bp = PointBatch(bcast(fe_const(bx)), bcast(fe_const(by)),
                            bcast(fe_const(1)), bcast(fe_const(bx * by % P)))
            row = [bp] + [point_add(bp, c_mults[j], d2) for j in range(1, 4)]
        entries.extend(row)

    # (N, 16, NLIMB) per coordinate
    tab = [jnp.stack([getattr(e, coord) for e in entries], axis=1)
           for coord in ("X", "Y", "Z", "T")]

    def step(carry, w):
        r = PointBatch.from_tree(carry)
        r = point_dbl(point_dbl(r))
        idx = w[:, None, None]
        picked = PointBatch(*(jnp.take_along_axis(t, idx, axis=1)[:, 0, :]
                              for t in tab))
        r = point_add(r, picked, d2)
        return r.tree(), None

    final, _ = lax.scan(step, PointBatch.identity_like(c_point).tree(), windows,
                        unroll=1)
    return PointBatch.from_tree(final)


def point_encode(p: PointBatch):
    """Canonical 32-byte encoding as (N, 32) uint8: y LE with sign(x) in bit 255."""
    zinv = fe_invert(p.Z)
    x = fe_canonical(fe_mul(p.X, zinv))
    y = fe_canonical(fe_mul(p.Y, zinv))
    sign = (x[..., 0] & 1).astype(jnp.int64)
    y = y.at[..., NLIMB - 1].add(sign << 15)
    # limbs (16 bit) -> bytes LE
    lo = (y & 0xFF).astype(jnp.uint8)
    hi = ((y >> 8) & 0xFF).astype(jnp.uint8)
    return jnp.stack([lo, hi], axis=-1).reshape(y.shape[:-1] + (32,))
