"""TPU acceleration layer (JAX).

The two offloaded hot loops (BASELINE.json north star):

- ``ed25519``: batched signature verification — the ``TPUCryptoBackend``
  behind the SignatureChecker seam (reference seam: src/crypto/SecretKey.cpp —
  PubKeyUtils::verifySig).
- ``quorum``: quorum-intersection subset enumeration — the
  ``TPUQuorumIntersectionChecker`` (reference seam:
  src/herder/QuorumIntersectionCheckerImpl.cpp).

Field arithmetic uses 16x16-bit limbs held in int64, so x64 must be enabled
before any accel arrays are built (TPU emulates int64 with int32 pairs; the
kernels are exact integer math end to end).
"""

import jax

# HARD REQUIREMENT, process-global: the limb kernels are meaningless with
# int64 silently truncated to int32 (x64 off is jax's default).  This is an
# import side effect by design — importing this package opts the process into
# x64, and embedders who need 32-bit weak-type defaults elsewhere must isolate
# accel work in its own process.  We fail loudly if the flag didn't stick.
jax.config.update("jax_enable_x64", True)
if not jax.config.jax_enable_x64:  # pragma: no cover
    raise RuntimeError(
        "stellar_core_tpu.accel requires jax_enable_x64; the flag could not "
        "be enabled (frozen config?) — exact int64 field arithmetic is "
        "impossible without it")
