"""Simulation layer: in-process multi-node networks + load generation +
chaos campaigns.

Reference: src/simulation/ (SURVEY.md §2.1).
"""

from .loadgen import LoadGenerator
from .simulation import (SimNode, Simulation, make_asymmetric_topology,
                         make_core_topology, make_cycle_topology,
                         make_hierarchical_topology, qset_of)

__all__ = ["LoadGenerator", "SimNode", "Simulation",
           "make_asymmetric_topology", "make_core_topology",
           "make_cycle_topology", "make_hierarchical_topology",
           "qset_of"]
