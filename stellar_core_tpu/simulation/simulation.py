"""Simulation — N full in-process nodes on one shared VirtualClock.

Reference: src/simulation/Simulation.{h,cpp} — addNode, addPendingConnection,
startAllNodes, crankUntil/crankForAtLeast, Topologies (src/simulation/
Topologies.cpp — core, cycle, hierarchical); nodes wired OVER_LOOPBACK.
This is THE deterministic multi-node test pattern (SURVEY.md §4): no
threads, no sockets, no wall clock — every message delivery is a posted
clock action, every timeout is virtual.

Transport is the real overlay over LoopbackPeer pairs (reference:
Simulation::OVER_LOOPBACK + LoopbackPeerConnection): every SimNode runs a
full OverlayManager, so consensus traffic traverses the authenticated
handshake, flow-control windows, pull-mode tx flooding (advert/demand) and
hash-addressed item fetch — the same machinery production uses.
Partitions sever the loopback connections; healing redials them.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..herder.herder import Herder, HerderState
from ..herder.upgrades import Upgrades
from ..ledger.manager import LedgerManager
from ..main.status import StatusManager
from ..overlay.overlay_manager import OverlayManager
from ..overlay.peer import make_loopback_pair
from ..scp.quorum import qset_hash
from ..util import logging as slog
from ..util.clock import ClockMode, VirtualClock
from ..xdr import scp as SX
from ..xdr import types as XT

log = slog.get("Herder")


class SimNode:
    """One in-process validator: ledger manager + herder + overlay.
    Reference analog: a full Application instance inside Simulation."""

    def __init__(self, sim: "Simulation", secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None,
                 ledger_manager: Optional[LedgerManager] = None):
        self.sim = sim
        self.secret = secret
        self.node_id = secret.public_key.ed25519
        if ledger_manager is not None:   # restart path: resumed from disk
            self.lm = ledger_manager
        else:
            self.lm = LedgerManager(sim.network_id)
            self.lm.start_new_ledger()
        self.herder = Herder(sim.clock, self.lm, secret, qset,
                             is_validator=is_validator, upgrades=upgrades)
        # the OverlayManager rewires herder.broadcast / tx_flood / fetch_*
        # onto the real flood/fetch machinery
        self.overlay = OverlayManager(sim.clock, self.herder, sim.network_id,
                                      secret)
        self.partition = 0   # connection-group tag (see partition_nodes)
        self.closed: Dict[int, bytes] = {}  # seq -> ledger hash
        # per-category status lines, same manager a full Application runs
        # (main/status) — evaluate_health reuses it unchanged
        self.status = StatusManager()
        self.herder.ledger_closed_hook = self._on_ledger_closed
        self.herder.out_of_sync_handler = self._on_out_of_sync

    def _on_out_of_sync(self) -> None:
        # pull recent SCP state from peers (reference: getMoreSCPState;
        # archive-based catchup takes over when the gap exceeds
        # MAX_SLOTS_TO_REMEMBER)
        self.overlay.request_scp_state()

    def _on_ledger_closed(self, arts) -> None:
        self.closed[arts.header_entry.header.ledgerSeq] = arts.header_entry.hash

    # -- convenience -------------------------------------------------------
    @property
    def lcl(self) -> int:
        return self.lm.last_closed_ledger_seq

    @property
    def lcl_hash(self) -> bytes:
        return self.lm.lcl_hash

    @property
    def clock(self) -> VirtualClock:
        return self.sim.clock

    def submit(self, frame) -> object:
        return self.herder.recv_transaction(frame)

    def evaluate_health(self) -> dict:
        """The same ``/health`` document a full Application serves
        (main/status.evaluate_health over this node's ledger age, herder
        state, tx-queue depth and peer count) — the chaos runner's
        degraded/recovered assertions reuse production health logic
        instead of re-deriving it."""
        from ..main.status import evaluate_health
        return evaluate_health(self)

    def is_healthy(self) -> bool:
        return self.evaluate_health()["status"] == "ok"


class Simulation:
    OVER_LOOPBACK = "loopback"

    def __init__(self, network_passphrase: bytes = b"sim network",
                 mode: str = OVER_LOOPBACK,
                 seed: Optional[int] = None):
        self.network_id = sha256(network_passphrase)
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.nodes: List[SimNode] = []
        self.by_id: Dict[bytes, SimNode] = {}
        # live loopback connections: frozenset({id_a, id_b}) -> (pa, pb)
        self._connections: Dict[frozenset, Tuple] = {}
        # fault-injection determinism: when a seed is given, every
        # loopback pair gets its own random stream derived from
        # (seed, the two node ids) — stable under dial order and under
        # redials, so one logged integer replays a whole campaign's
        # damage/drop/reorder decisions.  `self.rng` is the scheduler-level
        # stream (fault timing jitter etc.).
        self.seed = seed
        self.rng = random.Random(seed)

    def _pair_rng(self, id_a: bytes, id_b: bytes) -> Optional[random.Random]:
        if self.seed is None:
            return None
        lo, hi = sorted((id_a, id_b))
        material = sha256(b"link-fault-rng|" +
                          self.seed.to_bytes(8, "big", signed=True) +
                          lo + hi)
        return random.Random(int.from_bytes(material, "big"))

    # -- topology ----------------------------------------------------------
    def add_node(self, secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None,
                 ledger_manager: Optional[LedgerManager] = None) -> SimNode:
        node = SimNode(self, secret, qset, is_validator, upgrades,
                       ledger_manager=ledger_manager)
        self.nodes.append(node)
        self.by_id[node.node_id] = node
        return node

    def connect(self, a: SimNode, b: SimNode) -> None:
        """Dial a loopback connection a->b (reference:
        Simulation::addPendingConnection + LoopbackPeerConnection).  A pair
        whose peers dropped THEMSELVES (overlay error paths, bans) counts
        as absent — otherwise heal_partitions would silently no-op on it
        and the mesh would stay severed while the sim believes it healed."""
        from ..overlay.peer import Peer
        if a is b:
            return
        key = frozenset((a.node_id, b.node_id))
        pair = self._connections.get(key)
        if pair is not None:
            if pair[0].state != Peer.CLOSING and \
                    pair[1].state != Peer.CLOSING:
                return  # still live
            del self._connections[key]
        self._connections[key] = make_loopback_pair(
            a.overlay, b.overlay,
            fault_rng=self._pair_rng(a.node_id, b.node_id))

    def disconnect(self, a: SimNode, b: SimNode) -> None:
        """Sever a link.  BOTH ends are dropped explicitly: drop() on a
        peer that is already CLOSING (it dropped itself earlier — ban,
        overlay error) is a no-op that never reaches its partner, so
        dropping only pair[0] could leak a half-open connection that a
        later flapping redial would then refuse to replace."""
        from ..overlay.peer import Peer
        key = frozenset((a.node_id, b.node_id))
        pair = self._connections.pop(key, None)
        if pair is not None:
            pair[0].drop("sim disconnect")
            pair[1].drop("sim disconnect")
            assert pair[0].state == Peer.CLOSING \
                and pair[1].state == Peer.CLOSING, \
                "severed loopback pair must close both ends"

    def is_connected(self, a: SimNode, b: SimNode) -> bool:
        from ..overlay.peer import Peer
        pair = self._connections.get(frozenset((a.node_id, b.node_id)))
        return pair is not None and pair[0].state != Peer.CLOSING \
            and pair[1].state != Peer.CLOSING

    def start_all_nodes(self, mesh: bool = True) -> None:
        # default mesh: every node pair connected (the bus the herder sims
        # assume); explicit connect() calls before start override nothing —
        # connect() is idempotent per pair.  mesh=False keeps whatever
        # sparse graph the caller dialed (large chaos topologies would be
        # O(n^2) links otherwise).
        if mesh:
            for i, a in enumerate(self.nodes):
                for b in self.nodes[i + 1:]:
                    self.connect(a, b)
        # let the auth handshakes complete before consensus starts
        self.clock.crank_for(0.1)
        for n in self.nodes:
            if n.herder.is_validator:
                n.herder.bootstrap()
            else:
                n.herder.start()

    # -- partitions (fault injection) --------------------------------------
    def partition_nodes(self, groups: List[List[SimNode]]) -> None:
        """Sever every loopback connection crossing group boundaries
        (reference: Simulation::partitionNodes — connection-level cuts)."""
        for i, grp in enumerate(groups):
            for n in grp:
                n.partition = i
        for key in list(self._connections):
            ids = list(key)
            a, b = self.by_id[ids[0]], self.by_id[ids[1]]
            if a.partition != b.partition:
                self.disconnect(a, b)

    def heal_partitions(self) -> None:
        """Redial the full mesh (reference: healing a Simulation
        partition reconnects the pending connections)."""
        for n in self.nodes:
            n.partition = 0
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1:]:
                self.connect(a, b)

    # -- cranking ----------------------------------------------------------
    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 120.0) -> bool:
        return self.clock.crank_until(pred, timeout)

    def crank_for_at_least(self, duration: float) -> None:
        self.clock.crank_for(duration)

    def crank_until_ledger(self, seq: int, timeout: float = 120.0) -> bool:
        """Crank until every validator has closed ledger `seq`."""
        vs = [n for n in self.nodes if n.herder.is_validator]
        return self.crank_until(lambda: all(n.lcl >= seq for n in vs),
                                timeout)

    def hashes_agree(self, seq: Optional[int] = None) -> bool:
        """All validators that closed ledger `seq` derived the same hash
        (default: highest ledger every validator has closed)."""
        vs = [n for n in self.nodes if n.herder.is_validator]
        if not vs:
            return True
        if seq is None:
            seq = min(n.lcl for n in vs)
        hashes = {n.closed.get(seq) for n in vs if seq in n.closed}
        return len(hashes) <= 1


def qset_of(node_ids: List[bytes], threshold: int):
    return SX.SCPQuorumSet(threshold=threshold,
                           validators=[XT.node_id(n) for n in node_ids],
                           innerSets=[])


def make_core_topology(n: int, threshold: Optional[int] = None,
                       passphrase: bytes = b"sim network",
                       seed: Optional[int] = None) -> Simulation:
    """Fully-connected n-validator network with a shared flat qset.
    Reference: Topologies::core."""
    sim = Simulation(passphrase, seed=seed)
    secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(n)]
    ids = [s.public_key.ed25519 for s in secrets]
    q = qset_of(ids, threshold if threshold is not None else (2 * n + 2) // 3)
    for s in secrets:
        sim.add_node(s, q)
    return sim


def make_cycle_topology(n: int,
                        passphrase: bytes = b"sim cycle",
                        seed: Optional[int] = None) -> Simulation:
    """Ring: each validator trusts itself and both ring neighbours (2-of-3
    slices).  Reference: Topologies::cycle — connectivity-limited liveness
    testing; intersection holds because adjacent slices chain around the
    ring."""
    sim = Simulation(passphrase, seed=seed)
    secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(n)]
    ids = [s.public_key.ed25519 for s in secrets]
    for i, s in enumerate(secrets):
        neigh = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, qset_of(neigh, 2))
    return sim


def make_hierarchical_topology(n_orgs: int, nodes_per_org: int = 3,
                               passphrase: bytes = b"sim tiers",
                               seed: Optional[int] = None
                               ) -> Simulation:
    """Tiered: org-inner 2-of-3 qsets nested under a 2/3-of-orgs outer
    threshold — the tier-1 shape (reference: Topologies::hierarchicalQuorum;
    same org structure the quorum-intersection bench uses)."""
    from ..crypto.sha import sha256
    sim = Simulation(passphrase, seed=seed)
    secrets = [[SecretKey(sha256(b"hier-node-%d-%d" % (o, g)))
                for g in range(nodes_per_org)] for o in range(n_orgs)]
    inner = [qset_of([s.public_key.ed25519 for s in org],
                     (2 * nodes_per_org + 2) // 3) for org in secrets]
    outer_threshold = (2 * n_orgs + 2) // 3
    outer = SX.SCPQuorumSet(threshold=outer_threshold, validators=[],
                            innerSets=inner)
    for org in secrets:
        for s in org:
            sim.add_node(s, outer)
    return sim


def make_asymmetric_topology(n_core_orgs: int, nodes_per_org: int = 3,
                             n_leaf: int = 10,
                             passphrase: bytes = b"sim asym",
                             seed: Optional[int] = None) -> Simulation:
    """Asymmetric tiers: a hierarchical tier-1 core plus ``n_leaf``
    second-tier validators whose quorum slices point AT the core's org
    structure but who appear in nobody else's slices — they vote and
    close ledgers yet cannot block the core (the shape of real public
    networks, where most validators trust the tier-1 orgs one-way).
    Reference shape: Topologies::hierarchicalQuorumSimplified's
    middle-tier variants."""
    from ..crypto.sha import sha256
    sim = Simulation(passphrase, seed=seed)
    secrets = [[SecretKey(sha256(b"asym-core-%d-%d" % (o, g)))
                for g in range(nodes_per_org)] for o in range(n_core_orgs)]
    inner = [qset_of([s.public_key.ed25519 for s in org],
                     (2 * nodes_per_org + 2) // 3) for org in secrets]
    outer = SX.SCPQuorumSet(threshold=(2 * n_core_orgs + 2) // 3,
                            validators=[], innerSets=inner)
    for org in secrets:
        for s in org:
            sim.add_node(s, outer)
    for i in range(n_leaf):
        sim.add_node(SecretKey(sha256(b"asym-leaf-%d" % i)), outer)
    return sim
