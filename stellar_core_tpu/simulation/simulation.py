"""Simulation — N full in-process nodes on one shared VirtualClock.

Reference: src/simulation/Simulation.{h,cpp} — addNode, addPendingConnection,
startAllNodes, crankUntil/crankForAtLeast, Topologies (src/simulation/
Topologies.cpp — core, cycle, hierarchical); nodes wired over loopback.
This is THE deterministic multi-node test pattern (SURVEY.md §4): no
threads, no sockets, no wall clock — every message delivery is a posted
clock action, every timeout is virtual.

Until the TCP overlay lands, message transport is a direct loopback bus:
broadcast posts delivery actions to every peer; hash-addressed item fetch
(tx sets / qsets) asks peers' caches asynchronously, standing in for
overlay ItemFetcher round-trips with the same observable semantics.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..herder.herder import Herder, HerderState
from ..herder.upgrades import Upgrades
from ..ledger.manager import LedgerManager
from ..scp.quorum import qset_hash
from ..util import logging as slog
from ..util.clock import ClockMode, VirtualClock
from ..xdr import scp as SX
from ..xdr import types as XT

log = slog.get("Herder")


class SimNode:
    """One in-process validator: ledger manager + herder (+ history later).
    Reference analog: a full Application instance inside Simulation."""

    def __init__(self, sim: "Simulation", secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None,
                 ledger_manager: Optional[LedgerManager] = None):
        self.sim = sim
        self.secret = secret
        self.node_id = secret.public_key.ed25519
        if ledger_manager is not None:   # restart path: resumed from disk
            self.lm = ledger_manager
        else:
            self.lm = LedgerManager(sim.network_id)
            self.lm.start_new_ledger()
        self.herder = Herder(sim.clock, self.lm, secret, qset,
                             is_validator=is_validator, upgrades=upgrades)
        self.herder.broadcast = self._broadcast
        self.herder.tx_flood = self._tx_flood
        self.herder.pending.fetch_qset = self._fetch_qset
        self.herder.pending.fetch_txset = self._fetch_txset
        self.partition = 0  # nodes only hear peers in the same partition
        self.closed: Dict[int, bytes] = {}  # seq -> ledger hash
        self.herder.ledger_closed_hook = self._on_ledger_closed
        self.herder.out_of_sync_handler = self._on_out_of_sync

    def _on_out_of_sync(self) -> None:
        # pull recent SCP state from peers (reference: getMoreSCPState;
        # archive-based catchup takes over when the gap exceeds
        # MAX_SLOTS_TO_REMEMBER)
        self.sim.request_scp_state(self)

    def _on_ledger_closed(self, arts) -> None:
        self.closed[arts.header_entry.header.ledgerSeq] = arts.header_entry.hash

    # -- transport ---------------------------------------------------------
    def _broadcast(self, env) -> None:
        self.sim.broadcast_from(self, env)

    def _tx_flood(self, frame) -> None:
        # epidemic flooding with dedup: peers re-flood only on first sight
        # (STATUS_PENDING), mirroring Floodgate semantics
        for peer in self.sim._reachable(self):
            self.sim.clock.post_action(
                lambda p=peer, f=frame: p.herder.recv_transaction(f),
                name="flood-tx")

    def _fetch_qset(self, h: bytes) -> None:
        self.sim.fetch_item(self, "qset", h)

    def _fetch_txset(self, h: bytes) -> None:
        self.sim.fetch_item(self, "txset", h)

    # -- convenience -------------------------------------------------------
    @property
    def lcl(self) -> int:
        return self.lm.last_closed_ledger_seq

    @property
    def lcl_hash(self) -> bytes:
        return self.lm.lcl_hash

    def submit(self, frame) -> object:
        return self.herder.recv_transaction(frame)


class Simulation:
    OVER_LOOPBACK = "loopback"

    def __init__(self, network_passphrase: bytes = b"sim network",
                 mode: str = OVER_LOOPBACK):
        self.network_id = sha256(network_passphrase)
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.nodes: List[SimNode] = []
        self.by_id: Dict[bytes, SimNode] = {}
        self.dropped_messages = 0

    # -- topology ----------------------------------------------------------
    def add_node(self, secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None,
                 ledger_manager: Optional[LedgerManager] = None) -> SimNode:
        node = SimNode(self, secret, qset, is_validator, upgrades,
                       ledger_manager=ledger_manager)
        self.nodes.append(node)
        self.by_id[node.node_id] = node
        return node

    def start_all_nodes(self) -> None:
        for n in self.nodes:
            if n.herder.is_validator:
                n.herder.bootstrap()
            else:
                n.herder.start()

    # -- transport ---------------------------------------------------------
    def _reachable(self, src: SimNode) -> List[SimNode]:
        return [n for n in self.nodes
                if n is not src and n.partition == src.partition]

    def broadcast_from(self, src: SimNode, env) -> None:
        for peer in self._reachable(src):
            self.clock.post_action(
                lambda p=peer, e=env: p.herder.recv_scp_envelope(e),
                name="deliver-scp")

    def fetch_item(self, requester: SimNode, kind: str, h: bytes) -> None:
        """Async hash-addressed fetch from any reachable peer (stands in
        for overlay ItemFetcher; one posted round-trip of latency)."""
        def attempt():
            for peer in self._reachable(requester):
                if kind == "qset":
                    q = peer.herder.get_qset(h)
                    if q is not None:
                        requester.herder.recv_qset(q)
                        return
                else:
                    got = peer.herder.pending.get_txset(h)
                    if got is not None:
                        requester.herder.recv_tx_set(h, got[0])
                        return
            self.dropped_messages += 1
        self.clock.post_action(attempt, name=f"fetch-{kind}")

    def request_scp_state(self, requester: SimNode) -> None:
        """Deliver peers' remembered SCP envelopes for slots the requester
        is missing (reference: GET_SCP_STATE overlay message)."""
        def attempt():
            for peer in self._reachable(requester):
                for env in peer.herder.get_scp_state(requester.lcl + 1):
                    requester.herder.recv_scp_envelope(env)
        self.clock.post_action(attempt, name="fetch-scp-state")

    # -- partitions (fault injection) --------------------------------------
    def partition_nodes(self, groups: List[List[SimNode]]) -> None:
        for i, grp in enumerate(groups):
            for n in grp:
                n.partition = i

    def heal_partitions(self) -> None:
        for n in self.nodes:
            n.partition = 0

    # -- cranking ----------------------------------------------------------
    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 120.0) -> bool:
        return self.clock.crank_until(pred, timeout)

    def crank_for_at_least(self, duration: float) -> None:
        self.clock.crank_for(duration)

    def crank_until_ledger(self, seq: int, timeout: float = 120.0) -> bool:
        """Crank until every validator has closed ledger `seq`."""
        vs = [n for n in self.nodes if n.herder.is_validator]
        return self.crank_until(lambda: all(n.lcl >= seq for n in vs),
                                timeout)

    def hashes_agree(self, seq: Optional[int] = None) -> bool:
        """All validators that closed ledger `seq` derived the same hash
        (default: highest ledger every validator has closed)."""
        vs = [n for n in self.nodes if n.herder.is_validator]
        if not vs:
            return True
        if seq is None:
            seq = min(n.lcl for n in vs)
        hashes = {n.closed.get(seq) for n in vs if seq in n.closed}
        return len(hashes) <= 1


def qset_of(node_ids: List[bytes], threshold: int):
    return SX.SCPQuorumSet(threshold=threshold,
                           validators=[XT.node_id(n) for n in node_ids],
                           innerSets=[])


def make_core_topology(n: int, threshold: Optional[int] = None,
                       passphrase: bytes = b"sim network") -> Simulation:
    """Fully-connected n-validator network with a shared flat qset.
    Reference: Topologies::core."""
    sim = Simulation(passphrase)
    secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(n)]
    ids = [s.public_key.ed25519 for s in secrets]
    q = qset_of(ids, threshold if threshold is not None else (2 * n + 2) // 3)
    for s in secrets:
        sim.add_node(s, q)
    return sim


def make_cycle_topology(n: int,
                        passphrase: bytes = b"sim cycle") -> Simulation:
    """Ring: each validator trusts itself and both ring neighbours (2-of-3
    slices).  Reference: Topologies::cycle — connectivity-limited liveness
    testing; intersection holds because adjacent slices chain around the
    ring."""
    sim = Simulation(passphrase)
    secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(n)]
    ids = [s.public_key.ed25519 for s in secrets]
    for i, s in enumerate(secrets):
        neigh = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, qset_of(neigh, 2))
    return sim


def make_hierarchical_topology(n_orgs: int, nodes_per_org: int = 3,
                               passphrase: bytes = b"sim tiers"
                               ) -> Simulation:
    """Tiered: org-inner 2-of-3 qsets nested under a 2/3-of-orgs outer
    threshold — the tier-1 shape (reference: Topologies::hierarchicalQuorum;
    same org structure the quorum-intersection bench uses)."""
    from ..crypto.sha import sha256
    sim = Simulation(passphrase)
    secrets = [[SecretKey(sha256(b"hier-node-%d-%d" % (o, g)))
                for g in range(nodes_per_org)] for o in range(n_orgs)]
    inner = [qset_of([s.public_key.ed25519 for s in org],
                     (2 * nodes_per_org + 2) // 3) for org in secrets]
    outer_threshold = (2 * n_orgs + 2) // 3
    outer = SX.SCPQuorumSet(threshold=outer_threshold, validators=[],
                            innerSets=inner)
    for org in secrets:
        for s in org:
            sim.add_node(s, outer)
    return sim
