"""Simulation — N full in-process nodes on one shared VirtualClock.

Reference: src/simulation/Simulation.{h,cpp} — addNode, addPendingConnection,
startAllNodes, crankUntil/crankForAtLeast, Topologies (src/simulation/
Topologies.cpp — core, cycle, hierarchical); nodes wired OVER_LOOPBACK.
This is THE deterministic multi-node test pattern (SURVEY.md §4): no
threads, no sockets, no wall clock — every message delivery is a posted
clock action, every timeout is virtual.

Transport is the real overlay over LoopbackPeer pairs (reference:
Simulation::OVER_LOOPBACK + LoopbackPeerConnection): every SimNode runs a
full OverlayManager, so consensus traffic traverses the authenticated
handshake, flow-control windows, pull-mode tx flooding (advert/demand) and
hash-addressed item fetch — the same machinery production uses.
Partitions sever the loopback connections; healing redials them.
"""

from __future__ import annotations

import random
from typing import Callable, Dict, List, Optional, Tuple

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..herder.herder import Herder, HerderState
from ..herder.upgrades import Upgrades
from ..ledger.manager import LedgerManager
from ..main.status import StatusManager
from ..overlay.overlay_manager import OverlayManager
from ..overlay.peer import make_loopback_pair
from ..scp.quorum import qset_hash
from ..util import logging as slog
from ..util.clock import ClockMode, VirtualClock
from ..xdr import scp as SX
from ..xdr import types as XT

log = slog.get("Herder")


class SimNode:
    """One in-process validator: ledger manager + herder + overlay.
    Reference analog: a full Application instance inside Simulation."""

    def __init__(self, sim: "Simulation", secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None,
                 ledger_manager: Optional[LedgerManager] = None):
        self.sim = sim
        self.secret = secret
        self.node_id = secret.public_key.ed25519
        if ledger_manager is not None:   # restart path: resumed from disk
            self.lm = ledger_manager
        else:
            self.lm = LedgerManager(sim.network_id)
            self.lm.start_new_ledger()
        self.herder = Herder(sim.clock, self.lm, secret, qset,
                             is_validator=is_validator, upgrades=upgrades)
        # the OverlayManager rewires herder.broadcast / tx_flood / fetch_*
        # onto the real flood/fetch machinery
        self.overlay = OverlayManager(sim.clock, self.herder, sim.network_id,
                                      secret, batching=sim.batching)
        self.partition = 0   # connection-group tag (see partition_nodes)
        self.closed: Dict[int, bytes] = {}  # seq -> ledger hash
        # per-category status lines, same manager a full Application runs
        # (main/status) — evaluate_health reuses it unchanged
        self.status = StatusManager()
        # in-sim history archive (attach_history): real publish path +
        # real archive catchup when the gap exceeds the fleet's slot memory
        self.archive = None
        self.history = None
        self.catchup_parallel = 1
        self._catching_up = False
        self.herder.ledger_closed_hook = self._on_ledger_closed
        self.herder.out_of_sync_handler = self._on_out_of_sync
        self.herder.sync_gap_hook = self.maybe_archive_catchup

    def attach_history(self, archive, publish: bool = True,
                       parallel: int = 1) -> None:
        """Attach a history archive (history.archive.FileHistoryArchive —
        typically one directory SHARED by the fleet, like a production
        network's archive mirrors): with `publish` this node writes real
        checkpoints as ledgers close (HistoryManager), and either way a
        stall past ``MAX_SLOTS_TO_REMEMBER`` recovers through real
        archive catchup (``parallel`` > 1 routes it through
        ``catchup --parallel``-style range workers)."""
        from ..history.manager import HistoryManager
        self.archive = archive
        self.catchup_parallel = parallel
        if publish:
            self.history = HistoryManager(
                self.lm, self.sim.network_passphrase.decode(), [archive])

    def _on_out_of_sync(self) -> None:
        # pull recent SCP state from peers (reference: getMoreSCPState);
        # the sync_gap_hook hands off to archive catchup when the
        # buffered-externalize queue proves the gap exceeds the peers'
        # slot memory
        self.overlay.request_scp_state()

    def _on_ledger_closed(self, arts) -> None:
        self.closed[arts.header_entry.header.ledgerSeq] = arts.header_entry.hash
        # floodgate GC, exactly like a full Application's close hook: a
        # bounded record map ALSO means a replayed stale envelope reads
        # as new and reaches the herder's slot-memory discard (the
        # byzantine stale-replay scenarios assert that path)
        self.overlay.clear_below(
            max(0, self.lm.last_closed_ledger_seq - 100))
        if self.history is not None:
            self.history.ledger_closed(arts)
        if self.status.get_status("history-catchup") is not None \
                and self.herder.state == HerderState.TRACKING:
            # archive recovery complete: the node is closing live ledgers
            # again — /health flips from "catching-up" back to "ok"
            self.status.clear_status("history-catchup")

    # -- archive catchup (out-of-sync -> archive -> re-tracking) -----------
    def maybe_archive_catchup(self) -> None:
        """The handoff the reference calls CatchupManager::processLedger →
        startCatchup: when the next slot this node needs is older than
        any peer remembers (gap > MAX_SLOTS_TO_REMEMBER), SCP-state
        replays cannot help — resync from the archive, then bridge the
        remaining slots through the normal buffered-externalize path."""
        from ..herder.herder import MAX_SLOTS_TO_REMEMBER
        if self.archive is None or self._catching_up:
            return
        buffered = self.herder._buffered
        net_tip = max(buffered, default=self.lcl)
        if net_tip - self.lcl <= MAX_SLOTS_TO_REMEMBER:
            return   # peers' slot memory still covers the gap
        try:
            has = self.archive.get_state()
        except (ValueError, OSError):
            return   # unreadable HAS: keep trying the SCP-state path
        if has is None or has.current_ledger <= self.lcl:
            return   # nothing newer published yet
        self.run_archive_catchup()

    def run_archive_catchup(self) -> None:
        """Run REAL archive catchup (hash-verified header chain, bucket
        apply, tx replay — `catchup --parallel` range workers when
        `catchup_parallel` > 1) and adopt the resulting ledger state into
        the live node."""
        from ..catchup.catchup import CatchupError, CatchupManager
        from ..history.archive import checkpoint_frequency
        from ..util import eventlog
        self._catching_up = True
        self.herder.recovery_stats["archive_catchups"] += 1
        self.status.set_status(
            "history-catchup",
            f"catching up from archive (lcl {self.lcl} is beyond the "
            f"fleet's slot memory)")
        eventlog.record("History", "INFO", "sim archive catchup start",
                        node=self.node_id.hex()[:8], lcl=self.lcl,
                        parallel=self.catchup_parallel)
        try:
            if self.catchup_parallel > 1:
                from ..catchup.parallel import ParallelCatchup
                pc = ParallelCatchup(
                    self.archive.root,
                    self.sim.network_passphrase.decode(),
                    workers=self.catchup_parallel)
                try:
                    pc.run()
                    new_lm = pc.load_manager()
                    # sim nodes are in-memory: detach the loaded
                    # manager's persistence (it points into the
                    # throwaway range workdir) BEFORE that dir is
                    # reclaimed below
                    new_lm.db = None
                    new_lm.bucket_dir = None
                finally:
                    pc.cleanup()
            else:
                cm = CatchupManager(self.sim.network_id,
                                    self.sim.network_passphrase.decode())
                new_lm = cm.catchup_recent(self.archive,
                                           count=checkpoint_frequency())
        except CatchupError as e:
            log.warning("sim archive catchup failed at lcl=%d: %s",
                        self.lcl, e)
            eventlog.record("History", "ERROR", "sim archive catchup FAILED",
                            node=self.node_id.hex()[:8], detail=str(e))
            # the node is NOT catching up anymore — it is stuck.  Clear
            # the category so /health reports plain "degraded" (needs
            # attention), not the transient "catching-up" ("will be
            # back"); the failure detail lives in the flight recorder.
            # A later gap signal retries and re-sets the status.
            self.status.clear_status("history-catchup")
            self._catching_up = False
            return
        self._adopt_ledger_manager(new_lm)
        try:
            # bridge archive tip -> live consensus: apply whatever the
            # buffered-externalize queue already holds, then re-pull SCP
            # state for the remainder (guard still held: the drain's own
            # dead-end signal must not re-enter catchup against the same
            # archive tip)
            self.herder._drain_buffered()
            self.overlay.request_scp_state()
        finally:
            self._catching_up = False

    def _adopt_ledger_manager(self, new_lm: LedgerManager) -> None:
        from ..util import eventlog
        old = self.lcl
        self.lm = new_lm
        self.herder.lm = new_lm
        self.herder.tx_queue.lm = new_lm
        if self.history is not None:
            self.history.ledger_mgr = new_lm
            # artifacts for the skipped range were never closed here; the
            # straddling checkpoint window must not be published with holes
            self.history.resume_from(new_lm.last_closed_ledger_seq + 1)
        eventlog.record("History", "INFO", "sim archive state adopted",
                        node=self.node_id.hex()[:8], from_lcl=old,
                        to_lcl=new_lm.last_closed_ledger_seq)
        log.info("sim node %s adopted archive state: lcl %d -> %d",
                 self.node_id.hex()[:8], old,
                 new_lm.last_closed_ledger_seq)

    # -- convenience -------------------------------------------------------
    @property
    def lcl(self) -> int:
        return self.lm.last_closed_ledger_seq

    @property
    def lcl_hash(self) -> bytes:
        return self.lm.lcl_hash

    @property
    def clock(self) -> VirtualClock:
        return self.sim.clock

    def submit(self, frame) -> object:
        return self.herder.recv_transaction(frame)

    def evaluate_health(self) -> dict:
        """The same ``/health`` document a full Application serves
        (main/status.evaluate_health over this node's ledger age, herder
        state, tx-queue depth and peer count) — the chaos runner's
        degraded/recovered assertions reuse production health logic
        instead of re-deriving it."""
        from ..main.status import evaluate_health
        return evaluate_health(self)

    def is_healthy(self) -> bool:
        return self.evaluate_health()["status"] == "ok"


class Simulation:
    OVER_LOOPBACK = "loopback"

    def __init__(self, network_passphrase: bytes = b"sim network",
                 mode: str = OVER_LOOPBACK,
                 seed: Optional[int] = None,
                 batching: bool = True):
        # batched authenticated transport for every node this sim creates
        # (chaos campaigns and benches flip it to compare modes; links
        # negotiate per-pair so mixed fleets also work)
        self.batching = batching
        self.network_passphrase = network_passphrase
        self.network_id = sha256(network_passphrase)
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.nodes: List[SimNode] = []
        self.by_id: Dict[bytes, SimNode] = {}
        # live loopback connections: frozenset({id_a, id_b}) -> (pa, pb)
        self._connections: Dict[frozenset, Tuple] = {}
        # fault-injection determinism: when a seed is given, every
        # loopback pair gets its own random stream derived from
        # (seed, the two node ids) — stable under dial order and under
        # redials, so one logged integer replays a whole campaign's
        # damage/drop/reorder decisions.  `self.rng` is the scheduler-level
        # stream (fault timing jitter etc.).
        self.seed = seed
        self.rng = random.Random(seed)

    def _pair_rng(self, id_a: bytes, id_b: bytes) -> Optional[random.Random]:
        if self.seed is None:
            return None
        lo, hi = sorted((id_a, id_b))
        material = sha256(b"link-fault-rng|" +
                          self.seed.to_bytes(8, "big", signed=True) +
                          lo + hi)
        return random.Random(int.from_bytes(material, "big"))

    # -- topology ----------------------------------------------------------
    def add_node(self, secret: SecretKey, qset,
                 is_validator: bool = True,
                 upgrades: Optional[Upgrades] = None,
                 ledger_manager: Optional[LedgerManager] = None) -> SimNode:
        node = SimNode(self, secret, qset, is_validator, upgrades,
                       ledger_manager=ledger_manager)
        self.nodes.append(node)
        self.by_id[node.node_id] = node
        return node

    def connect(self, a: SimNode, b: SimNode) -> None:
        """Dial a loopback connection a->b (reference:
        Simulation::addPendingConnection + LoopbackPeerConnection).  A pair
        whose peers dropped THEMSELVES (overlay error paths, bans) counts
        as absent — otherwise heal_partitions would silently no-op on it
        and the mesh would stay severed while the sim believes it healed."""
        from ..overlay.peer import Peer
        if a is b:
            return
        key = frozenset((a.node_id, b.node_id))
        pair = self._connections.get(key)
        if pair is not None:
            if pair[0].state != Peer.CLOSING and \
                    pair[1].state != Peer.CLOSING:
                return  # still live
            del self._connections[key]
        self._connections[key] = make_loopback_pair(
            a.overlay, b.overlay,
            fault_rng=self._pair_rng(a.node_id, b.node_id))

    def disconnect(self, a: SimNode, b: SimNode) -> None:
        """Sever a link.  BOTH ends are dropped explicitly: drop() on a
        peer that is already CLOSING (it dropped itself earlier — ban,
        overlay error) is a no-op that never reaches its partner, so
        dropping only pair[0] could leak a half-open connection that a
        later flapping redial would then refuse to replace."""
        from ..overlay.peer import Peer
        key = frozenset((a.node_id, b.node_id))
        pair = self._connections.pop(key, None)
        if pair is not None:
            pair[0].drop("sim disconnect")
            pair[1].drop("sim disconnect")
            assert pair[0].state == Peer.CLOSING \
                and pair[1].state == Peer.CLOSING, \
                "severed loopback pair must close both ends"

    def is_connected(self, a: SimNode, b: SimNode) -> bool:
        from ..overlay.peer import Peer
        pair = self._connections.get(frozenset((a.node_id, b.node_id)))
        return pair is not None and pair[0].state != Peer.CLOSING \
            and pair[1].state != Peer.CLOSING

    def start_all_nodes(self, mesh: bool = True) -> None:
        # default mesh: every node pair connected (the bus the herder sims
        # assume); explicit connect() calls before start override nothing —
        # connect() is idempotent per pair.  mesh=False keeps whatever
        # sparse graph the caller dialed (large chaos topologies would be
        # O(n^2) links otherwise).
        if mesh:
            for i, a in enumerate(self.nodes):
                for b in self.nodes[i + 1:]:
                    self.connect(a, b)
        # let the auth handshakes complete before consensus starts
        self.clock.crank_for(0.1)
        for n in self.nodes:
            if n.herder.is_validator:
                n.herder.bootstrap()
            else:
                n.herder.start()

    # -- partitions (fault injection) --------------------------------------
    def partition_nodes(self, groups: List[List[SimNode]]) -> None:
        """Sever every loopback connection crossing group boundaries
        (reference: Simulation::partitionNodes — connection-level cuts)."""
        for i, grp in enumerate(groups):
            for n in grp:
                n.partition = i
        for key in list(self._connections):
            ids = list(key)
            a, b = self.by_id[ids[0]], self.by_id[ids[1]]
            if a.partition != b.partition:
                self.disconnect(a, b)

    def heal_partitions(self) -> None:
        """Redial the full mesh (reference: healing a Simulation
        partition reconnects the pending connections)."""
        for n in self.nodes:
            n.partition = 0
        for i, a in enumerate(self.nodes):
            for b in self.nodes[i + 1:]:
                self.connect(a, b)

    # -- cranking ----------------------------------------------------------
    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 120.0) -> bool:
        return self.clock.crank_until(pred, timeout)

    def crank_for_at_least(self, duration: float) -> None:
        self.clock.crank_for(duration)

    def crank_until_ledger(self, seq: int, timeout: float = 120.0) -> bool:
        """Crank until every validator has closed ledger `seq`."""
        vs = [n for n in self.nodes if n.herder.is_validator]
        return self.crank_until(lambda: all(n.lcl >= seq for n in vs),
                                timeout)

    def hashes_agree(self, seq: Optional[int] = None) -> bool:
        """All validators that closed ledger `seq` derived the same hash
        (default: highest ledger every validator has closed)."""
        vs = [n for n in self.nodes if n.herder.is_validator]
        if not vs:
            return True
        if seq is None:
            seq = min(n.lcl for n in vs)
        hashes = {n.closed.get(seq) for n in vs if seq in n.closed}
        return len(hashes) <= 1


def qset_of(node_ids: List[bytes], threshold: int):
    return SX.SCPQuorumSet(threshold=threshold,
                           validators=[XT.node_id(n) for n in node_ids],
                           innerSets=[])


def make_core_topology(n: int, threshold: Optional[int] = None,
                       passphrase: bytes = b"sim network",
                       seed: Optional[int] = None) -> Simulation:
    """Fully-connected n-validator network with a shared flat qset.
    Reference: Topologies::core."""
    sim = Simulation(passphrase, seed=seed)
    secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(n)]
    ids = [s.public_key.ed25519 for s in secrets]
    q = qset_of(ids, threshold if threshold is not None else (2 * n + 2) // 3)
    for s in secrets:
        sim.add_node(s, q)
    return sim


def make_cycle_topology(n: int,
                        passphrase: bytes = b"sim cycle",
                        seed: Optional[int] = None) -> Simulation:
    """Ring: each validator trusts itself and both ring neighbours (2-of-3
    slices).  Reference: Topologies::cycle — connectivity-limited liveness
    testing; intersection holds because adjacent slices chain around the
    ring."""
    sim = Simulation(passphrase, seed=seed)
    secrets = [SecretKey(bytes([i + 1]) * 32) for i in range(n)]
    ids = [s.public_key.ed25519 for s in secrets]
    for i, s in enumerate(secrets):
        neigh = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, qset_of(neigh, 2))
    return sim


def make_hierarchical_topology(n_orgs: int, nodes_per_org: int = 3,
                               passphrase: bytes = b"sim tiers",
                               seed: Optional[int] = None
                               ) -> Simulation:
    """Tiered: org-inner 2-of-3 qsets nested under a 2/3-of-orgs outer
    threshold — the tier-1 shape (reference: Topologies::hierarchicalQuorum;
    same org structure the quorum-intersection bench uses)."""
    from ..crypto.sha import sha256
    sim = Simulation(passphrase, seed=seed)
    secrets = [[SecretKey(sha256(b"hier-node-%d-%d" % (o, g)))
                for g in range(nodes_per_org)] for o in range(n_orgs)]
    inner = [qset_of([s.public_key.ed25519 for s in org],
                     (2 * nodes_per_org + 2) // 3) for org in secrets]
    outer_threshold = (2 * n_orgs + 2) // 3
    outer = SX.SCPQuorumSet(threshold=outer_threshold, validators=[],
                            innerSets=inner)
    for org in secrets:
        for s in org:
            sim.add_node(s, outer)
    return sim


def make_intersection_violation_topology(group_size: int = 2,
                                         passphrase: bytes = b"sim split",
                                         seed: Optional[int] = None
                                         ) -> Simulation:
    """GENERATED INTERSECTION-VIOLATION AXIS: two disjoint near-quorums
    bridged by ONE shared validator z (the last node).  Group A nodes
    trust {A, z} unanimously, group B nodes trust {B, z} unanimously, and
    z itself follows side A — so every A-side quorum is {A, z} and every
    B-side quorum is {B, z}: they intersect ONLY at z.  The survey's
    safety precondition (quorum intersection at honest nodes —
    `QuorumIntersectionChecker`) fails by exactly one node: with z
    honest the network behaves (z's value reaches both sides), with z
    equivocating the two sides can commit different values for the same
    slot, and the per-crank safety assertion MUST flag the fork.
    Unanimous thresholds make every member v-blocking for its group
    (one equivocator drives each side's federated accepts), and z
    announces a SELF-SINGLETON quorum set — required for either side's
    transitive quorum to close over z, and the honest-looking shape a
    real saboteur would pick."""
    from ..crypto.sha import sha256
    from ..scp.quorum import singleton_qset
    sim = Simulation(passphrase, seed=seed)
    a = [SecretKey(sha256(b"split-a-%d" % i)) for i in range(group_size)]
    b = [SecretKey(sha256(b"split-b-%d" % i)) for i in range(group_size)]
    z = SecretKey(sha256(b"split-bridge"))
    a_ids = [s.public_key.ed25519 for s in a]
    b_ids = [s.public_key.ed25519 for s in b]
    z_id = z.public_key.ed25519
    qset_a = qset_of(a_ids + [z_id], group_size + 1)   # unanimous
    qset_b = qset_of(b_ids + [z_id], group_size + 1)
    for s in a:
        sim.add_node(s, qset_a)
    for s in b:
        sim.add_node(s, qset_b)
    sim.add_node(z, singleton_qset(z_id))
    return sim


def split_brain_links(group_size: int = 2):
    """Overlay graph for the intersection-violation topology: each group
    meshed internally, the bridge z connected to everyone, NO direct
    A-B links (each side hears the other only through z's relay)."""
    a = list(range(group_size))
    b = list(range(group_size, 2 * group_size))
    z = 2 * group_size
    links = {frozenset((i, j)) for i in a for j in a if i < j}
    links |= {frozenset((i, j)) for i in b for j in b if i < j}
    links |= {frozenset((i, z)) for i in a + b}
    return links


def make_asymmetric_topology(n_core_orgs: int, nodes_per_org: int = 3,
                             n_leaf: int = 10,
                             passphrase: bytes = b"sim asym",
                             seed: Optional[int] = None) -> Simulation:
    """Asymmetric tiers: a hierarchical tier-1 core plus ``n_leaf``
    second-tier validators whose quorum slices point AT the core's org
    structure but who appear in nobody else's slices — they vote and
    close ledgers yet cannot block the core (the shape of real public
    networks, where most validators trust the tier-1 orgs one-way).
    Reference shape: Topologies::hierarchicalQuorumSimplified's
    middle-tier variants."""
    from ..crypto.sha import sha256
    sim = Simulation(passphrase, seed=seed)
    secrets = [[SecretKey(sha256(b"asym-core-%d-%d" % (o, g)))
                for g in range(nodes_per_org)] for o in range(n_core_orgs)]
    inner = [qset_of([s.public_key.ed25519 for s in org],
                     (2 * nodes_per_org + 2) // 3) for org in secrets]
    outer = SX.SCPQuorumSet(threshold=(2 * n_core_orgs + 2) // 3,
                            validators=[], innerSets=inner)
    for org in secrets:
        for s in org:
            sim.add_node(s, outer)
    for i in range(n_leaf):
        sim.add_node(SecretKey(sha256(b"asym-leaf-%d" % i)), outer)
    return sim
