"""ApplyLoad: maximum-throughput apply benchmark without consensus.

Reference: src/simulation/ApplyLoad.{h,cpp} + the `apply-load` CLI — build
a synthetic account universe, then close ledgers full of payments as fast
as the apply path allows, reporting tx/s, op/s and ledgers/s.  SCP, the
overlay and history are all bypassed: this isolates the tx-apply +
bucket-merge + hashing pipeline that bounds catchup replay (BASELINE.md
config #1's apply-side ceiling).
"""

from __future__ import annotations

import time

from ..ledger.manager import LedgerManager
from ..util.metrics import registry
from .loadgen import LoadGenerator


class ApplyLoad:
    def __init__(self, n_accounts: int = 1000, seed: int = 7,
                 network_id: bytes = b"\x5a" * 32):
        # invariants off: this is the max-throughput configuration the
        # reference uses (hash checks remain the oracle)
        self.mgr = LedgerManager(network_id, invariant_manager=None)
        self.mgr.start_new_ledger()
        self.lg = LoadGenerator(self.mgr, seed=seed)
        self.lg.create_accounts(n_accounts,
                                per_ledger=min(500, max(50, n_accounts)))

    def run(self, n_ledgers: int = 20, txs_per_ledger: int = 200,
            mode: str = "pay") -> dict:
        start_seq = self.mgr.last_closed_ledger_seq
        t0 = time.perf_counter()
        if mode == "pay":
            self.lg.payment_ledgers(n_ledgers, txs_per_ledger)
        else:
            self.lg.pretend_ledgers(n_ledgers, txs_per_ledger)
        dt = time.perf_counter() - t0
        n_txs = n_ledgers * txs_per_ledger
        close_timer = registry().timer("ledger.ledger.close").snapshot()
        return {
            "mode": mode,
            "ledgers": n_ledgers,
            "txs": n_txs,
            "seconds": round(dt, 3),
            "tx_per_s": round(n_txs / dt, 1),
            "ledgers_per_s": round(n_ledgers / dt, 2),
            "from_seq": start_seq,
            "to_seq": self.mgr.last_closed_ledger_seq,
            "ledger_close_timer": close_timer,
        }
