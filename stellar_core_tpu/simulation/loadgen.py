"""LoadGenerator: synthetic traffic for tests and benchmarks.

Reference: src/simulation/LoadGenerator.{h,cpp} — modes: create accounts /
pay / pretend (we add per-ledger batching identical in spirit to
generateLoad's txrate pacing, minus the timer loop: callers drive ledgers
explicitly).  Soroban modes are out of scope (SURVEY.md §2.4).
"""

from __future__ import annotations

import random
from typing import List, Optional

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..history.manager import HistoryManager
from ..ledger.manager import LedgerManager
from ..testutils import TestAccount, create_account_op, native_payment_op


class LoadGenerator:
    def __init__(self, mgr: LedgerManager,
                 history: Optional[HistoryManager] = None, seed: int = 1):
        self.mgr = mgr
        self.history = history
        self.rng = random.Random(seed)
        root_sk = mgr.root_account_secret()
        root_entry = mgr.root.get_entry(
            X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
        self.root = TestAccount(mgr, root_sk, root_entry.data.value.seqNum)
        self.accounts: List[TestAccount] = []
        self._close_time = 1_600_000_000

    def _close(self, frames) -> None:
        self._close_time += 5
        arts = self.mgr.close_ledger(frames, self._close_time)
        if self.history is not None:
            self.history.ledger_closed(arts)

    def close_empty_ledger(self) -> None:
        self._close([])

    def create_accounts(self, n: int, per_ledger: int = 50,
                        balance: int = 10_000_000_000) -> None:
        from ..xdr.transaction import MAX_OPS_PER_TX
        created = 0
        while created < n:
            batch = min(per_ledger, n - created)
            ops = []
            new_accounts = []
            for _ in range(batch):
                sk = SecretKey.pseudo_random_for_testing(self.rng)
                ops.append(create_account_op(
                    X.AccountID.ed25519(sk.public_key.ed25519), balance))
                new_accounts.append(sk)
            # a ledger batch larger than the per-tx op cap splits into
            # several root txs within the same ledger
            frames = [self.root.tx(ops[j:j + MAX_OPS_PER_TX])
                      for j in range(0, len(ops), MAX_OPS_PER_TX)]
            self._close(frames)
            header = self.mgr.lcl_header
            for sk in new_accounts:
                self.accounts.append(TestAccount(
                    self.mgr, sk, (header.ledgerSeq) << 32))
            created += batch

    def payment_ledgers(self, n_ledgers: int, txs_per_ledger: int = 20) -> None:
        assert len(self.accounts) >= 2, "create accounts first"
        for _ in range(n_ledgers):
            frames = []
            for _ in range(txs_per_ledger):
                src, dst = self.rng.sample(self.accounts, 2)
                amount = self.rng.randrange(1, 1_000_000)
                frames.append(src.tx([native_payment_op(dst.account_id,
                                                        amount)]))
            self._close(frames)

    def pretend_ledgers(self, n_ledgers: int, txs_per_ledger: int = 20,
                        ops_per_tx: int = 3) -> None:
        """'Pretend' mode: load-shaped but state-light traffic — each tx
        carries benign ManageData/BumpSequence ops (reference: LoadGenerator
        LOAD_PRETEND mode's setOptions/manageData fillers)."""
        assert self.accounts, "create accounts first"
        for _ in range(n_ledgers):
            frames = []
            for _ in range(txs_per_ledger):
                src = self.rng.choice(self.accounts)
                ops = []
                for k in range(ops_per_tx):
                    if self.rng.random() < 0.5:
                        name = f"pretend-{self.rng.randrange(4)}"
                        ops.append(X.Operation(
                            sourceAccount=None,
                            body=X.OperationBody.manageDataOp(
                                X.ManageDataOp(
                                    dataName=name.encode(),
                                    dataValue=bytes([self.rng.randrange(
                                        256)]) * 8))))
                    else:
                        ops.append(X.Operation(
                            sourceAccount=None,
                            body=X.OperationBody.bumpSequenceOp(
                                X.BumpSequenceOp(bumpTo=0))))
                frames.append(src.tx(ops))
            self._close(frames)

    def run_to_checkpoint_boundary(self) -> None:
        """Close empty ledgers until a checkpoint publishes (seq ≡ 63 mod 64)."""
        from ..history.archive import is_checkpoint_boundary
        while not is_checkpoint_boundary(self.mgr.last_closed_ledger_seq):
            self.close_empty_ledger()
