"""LoadGenerator: synthetic traffic for tests and benchmarks.

Reference: src/simulation/LoadGenerator.{h,cpp} — modes: create accounts /
pay / pretend (we add per-ledger batching identical in spirit to
generateLoad's txrate pacing, minus the timer loop: callers drive ledgers
explicitly), plus a Soroban invoke mode over the bounded host (ISSUE 17):
``AdmissionCampaign(soroban_mix=...)`` blends InvokeHostFunction traffic
into the paced admission stream, and ``SorobanMixCampaign`` closes the
same seed-derived mixed tx sets under serial AND footprint-parallel
apply, asserting per-close bucket-list hash identity.

Sustained-ingestion additions (ROADMAP item 3):

- ``SeedAccountPool`` — millions of distinct accounts in O(1) RAM: account
  i's key is derived from (seed, i) on demand, never stored;
- ``LoadGenerator.install_account_pool`` — materializes the pool straight
  into the bucket list in bounded chunks through
  ``LedgerManager.close_ledger_synthetic`` (no CreateAccount replay; over
  BucketListDB the pool lives in indexed on-disk bucket files, which is
  what the disk-resident bucket work was for);
- ``AdmissionCampaign`` — paced submission through the batched admission
  pipeline (herder/admission.py) rather than pre-built ledgers: offered
  load per close target, admission verdicts counted, sustained TPS and
  queue-depth behavior measured, overload answered by try-again-later.

Close times are derived from the injected VirtualClock (or advanced by
``close_target`` from the LCL when no clock is injected) — never from a
hardcoded wall-clock constant.
"""

from __future__ import annotations

import random
import time as _time  # perf_counter only (campaign wall-clock reporting)
from typing import Dict, List, Optional

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..history.manager import HistoryManager
from ..ledger.manager import LedgerManager
from ..testutils import (TestAccount, build_tx, create_account_op,
                         native_payment_op)

DEFAULT_CLOSE_TARGET_S = 5  # reference: EXP_LEDGER_TIMESPAN_SECONDS


class LoadGenerator:
    def __init__(self, mgr: LedgerManager,
                 history: Optional[HistoryManager] = None, seed: int = 1,
                 clock=None, close_target: int = DEFAULT_CLOSE_TARGET_S):
        """``clock``: an optional VirtualClock — close times then track
        ``clock.system_now()``; without one they advance by
        ``close_target`` per close from the LCL's own closeTime."""
        self.mgr = mgr
        self.history = history
        self.rng = random.Random(seed)
        self.clock = clock
        self.close_target = close_target
        root_sk = mgr.root_account_secret()
        root_entry = mgr.root.get_entry(
            X.LedgerKey.account(X.LedgerKeyAccount(
                accountID=X.AccountID.ed25519(
                    root_sk.public_key.ed25519))).to_xdr())
        self.root = TestAccount(mgr, root_sk, root_entry.data.value.seqNum)
        self.accounts: List[TestAccount] = []

    def _next_close_time(self) -> int:
        """Monotone close time derived from the injected clock (or the
        configured close target), never a wall-clock constant."""
        floor = int(self.mgr.lcl_header.scpValue.closeTime) + 1
        if self.clock is not None:
            return max(floor, int(self.clock.system_now()))
        return max(floor,
                   int(self.mgr.lcl_header.scpValue.closeTime)
                   + self.close_target)

    def _close(self, frames) -> None:
        arts = self.mgr.close_ledger(frames, self._next_close_time())
        if self.history is not None:
            self.history.ledger_closed(arts)

    def close_empty_ledger(self) -> None:
        self._close([])

    def create_accounts(self, n: int, per_ledger: int = 50,
                        balance: int = 10_000_000_000) -> None:
        from ..xdr.transaction import MAX_OPS_PER_TX
        created = 0
        while created < n:
            batch = min(per_ledger, n - created)
            ops = []
            new_accounts = []
            for _ in range(batch):
                sk = SecretKey.pseudo_random_for_testing(self.rng)
                ops.append(create_account_op(
                    X.AccountID.ed25519(sk.public_key.ed25519), balance))
                new_accounts.append(sk)
            # a ledger batch larger than the per-tx op cap splits into
            # several root txs within the same ledger
            frames = [self.root.tx(ops[j:j + MAX_OPS_PER_TX])
                      for j in range(0, len(ops), MAX_OPS_PER_TX)]
            self._close(frames)
            header = self.mgr.lcl_header
            for sk in new_accounts:
                self.accounts.append(TestAccount(
                    self.mgr, sk, (header.ledgerSeq) << 32))
            created += batch

    def payment_ledgers(self, n_ledgers: int, txs_per_ledger: int = 20) -> None:
        assert len(self.accounts) >= 2, "create accounts first"
        for _ in range(n_ledgers):
            frames = []
            for _ in range(txs_per_ledger):
                src, dst = self.rng.sample(self.accounts, 2)
                amount = self.rng.randrange(1, 1_000_000)
                frames.append(src.tx([native_payment_op(dst.account_id,
                                                        amount)]))
            self._close(frames)

    def pretend_ledgers(self, n_ledgers: int, txs_per_ledger: int = 20,
                        ops_per_tx: int = 3) -> None:
        """'Pretend' mode: load-shaped but state-light traffic — each tx
        carries benign ManageData/BumpSequence ops (reference: LoadGenerator
        LOAD_PRETEND mode's setOptions/manageData fillers)."""
        assert self.accounts, "create accounts first"
        for _ in range(n_ledgers):
            frames = []
            for _ in range(txs_per_ledger):
                src = self.rng.choice(self.accounts)
                ops = []
                for k in range(ops_per_tx):
                    if self.rng.random() < 0.5:
                        name = f"pretend-{self.rng.randrange(4)}"
                        ops.append(X.Operation(
                            sourceAccount=None,
                            body=X.OperationBody.manageDataOp(
                                X.ManageDataOp(
                                    dataName=name.encode(),
                                    dataValue=bytes([self.rng.randrange(
                                        256)]) * 8))))
                    else:
                        ops.append(X.Operation(
                            sourceAccount=None,
                            body=X.OperationBody.bumpSequenceOp(
                                X.BumpSequenceOp(bumpTo=0))))
                frames.append(src.tx(ops))
            self._close(frames)

    def run_to_checkpoint_boundary(self) -> None:
        """Close empty ledgers until a checkpoint publishes (seq ≡ 63 mod 64)."""
        from ..history.archive import is_checkpoint_boundary
        while not is_checkpoint_boundary(self.mgr.last_closed_ledger_seq):
            self.close_empty_ledger()

    def run_checkpoints(self, n: int, txs_per_ledger: int = 0) -> None:
        """Advance the chain through `n` MORE published checkpoint
        boundaries — payment traffic when txs_per_ledger > 0 (needs
        accounts), empty closes otherwise.  The cheap way to grow the
        multi-checkpoint archives range-parallel catchup and its bench
        replay (each range needs whole checkpoints to own)."""
        from ..history.archive import is_checkpoint_boundary
        done = 0
        while done < n:
            if txs_per_ledger > 0 and len(self.accounts) >= 2:
                self.payment_ledgers(1, txs_per_ledger)
            else:
                self.close_empty_ledger()
            if is_checkpoint_boundary(self.mgr.last_closed_ledger_seq):
                done += 1

    # ------------------------------------------------------------------
    # seed-derived account pools (millions of accounts, O(1) RAM)
    # ------------------------------------------------------------------
    def install_account_pool(self, pool: "SeedAccountPool",
                             chunk: int = 20_000) -> None:
        """Materialize `pool` into the ledger in bounded chunks via the
        synthetic-close seam — at no point do more than `chunk` decoded
        entries exist for the install (the bucket list's residency policy
        bounds what the merges keep decoded)."""
        if self.history is not None:
            # synthetic closes emit no ClosedLedgerArtifacts to publish:
            # silently advancing past the archive would leave a broken
            # header chain in the next checkpoint
            raise ValueError(
                "install_account_pool is incompatible with a history "
                "archive: synthetic closes produce no publishable "
                "artifacts (use a LoadGenerator without history)")
        for lo in range(0, pool.n, chunk):
            hi = min(pool.n, lo + chunk)
            self.mgr.close_ledger_synthetic(
                [pool.entry(i) for i in range(lo, hi)],
                self._next_close_time())


class SeedAccountPool:
    """O(1)-RAM pool of `n` seed-derived accounts.

    Account i's secret key is SHA256(tag, seed, i) — derived on demand,
    never stored; the pool object holds only the seed, the size and a
    sequence-number dict for the (bounded) set of accounts a campaign has
    actually touched.  Entries install with seqNum 0 so derived sequence
    numbers are position-independent (chunked installs land accounts in
    different ledgers).
    """

    def __init__(self, n: int, seed: int = 1,
                 balance: int = 10_000_000_000):
        self.n = n
        self.seed = seed
        self.balance = balance
        self._touched: Dict[int, int] = {}   # index -> last used seq num

    def secret(self, i: int) -> SecretKey:
        return SecretKey(sha256(
            b"loadgen account pool %d %d" % (self.seed, i)))

    def account_id(self, i: int) -> X.AccountID:
        return X.AccountID.ed25519(self.secret(i).public_key.ed25519)

    def entry(self, i: int) -> X.LedgerEntry:
        return X.LedgerEntry(
            lastModifiedLedgerSeq=1,
            data=X.LedgerEntryData.account(X.AccountEntry(
                accountID=self.account_id(i), balance=self.balance,
                seqNum=0)))

    def next_seq(self, i: int) -> int:
        cur = self._touched.get(i, 0) + 1
        self._touched[i] = cur
        return cur

    @property
    def touched(self) -> int:
        return len(self._touched)


class AdmissionCampaign:
    """Paced load through the batched admission pipeline over BucketListDB.

    One node's ingestion path without consensus: txs from a seed-derived
    account pool are offered to ``AdmissionPipeline.submit`` in per-close
    rounds, the pipeline batches/verifies/back-pressures, and each round
    closes a ledger from ``tx_queue.tx_set_frames()`` (surge-priced) like
    the herder would.  Reports sustained TPS, admission latency
    percentiles, batch-size distribution, per-status counts and
    queue-depth behavior — the bench ``admission`` section and the load
    tests both drive this.
    """

    def __init__(self, n_accounts: int, workdir: Optional[str] = None,
                 seed: int = 7, accel: bool = False,
                 batch_size: int = 256, flush_delay_s: float = 0.05,
                 max_backlog: int = 4096,
                 max_tx_set_ops: int = 1000,
                 entry_cache_size: int = 8192,
                 resident_levels: int = 1,
                 install_chunk: int = 20_000,
                 network_passphrase: str = "admission campaign",
                 soroban_mix: float = 0.0):
        from ..herder.admission import AdmissionPipeline
        from ..herder.tx_queue import TransactionQueue
        from ..util.clock import ClockMode, VirtualClock

        self.nid = sha256(network_passphrase.encode())
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.store = None
        if workdir is not None:
            from ..bucket.manager import BucketListStore
            self.store = BucketListStore(workdir)
        # invariants off — max-throughput configuration, like ApplyLoad
        self.mgr = LedgerManager(self.nid, invariant_manager=None,
                                 bucket_store=self.store,
                                 entry_cache_size=entry_cache_size,
                                 resident_levels=resident_levels)
        self.mgr.start_new_ledger()
        # campaign ledgers carry up to max_tx_set_ops ops (surge-pricing
        # trim limit; the queue bounds itself at 4x this)
        self.mgr.lcl_header.maxTxSetSize = max_tx_set_ops
        self.lg = LoadGenerator(self.mgr, seed=seed, clock=self.clock)
        self.pool = SeedAccountPool(n_accounts, seed=seed)
        self.lg.install_account_pool(self.pool, chunk=install_chunk)
        self.rng = random.Random(seed ^ 0x5eed)
        self.tx_queue = TransactionQueue(self.mgr)
        self.admission = AdmissionPipeline(
            self.tx_queue, self.mgr, self.clock, accel=accel,
            batch_size=batch_size, flush_delay_s=flush_delay_s,
            max_backlog=max_backlog)
        # soroban_mix: fraction of offered txs that are InvokeHostFunction
        # invokes against a per-account contract (the Soroban traffic-mix
        # knob, ISSUE 17) — they ride the tx queue's resource-limited
        # Soroban lane and close as the generalized set's second phase
        self.soroban_mix = soroban_mix
        self.soroban_offered = 0
        self.statuses: Dict[str, int] = {}
        self.peak_queue_depth = 0
        self.peak_admission_depth = 0
        self.backpressure_engaged = 0

    def _payment_frame(self, i: int, j: int):
        return build_tx(self.nid, self.pool.secret(i), self.pool.next_seq(i),
                        [native_payment_op(self.pool.account_id(j), 100)],
                        fee=100 + self.rng.randrange(200))

    def _soroban_frame(self, i: int):
        return _soroban_pool_frame(self.nid, self.pool, i,
                                   self.rng.randrange(2 ** 32))

    def _offer(self, n_txs: int, submit_burst: int = 64) -> None:
        """Offer `n_txs` payment txs this round, cranking between bursts
        so flush timers and collects interleave with arrivals (paced
        submission, not one monolithic dump)."""
        offered = 0
        while offered < n_txs:
            burst = min(submit_burst, n_txs - offered)
            for _ in range(burst):
                i = self.rng.randrange(self.pool.n)
                j = self.rng.randrange(self.pool.n)
                if self.rng.random() < self.soroban_mix:
                    frame = self._soroban_frame(i)
                    self.soroban_offered += 1
                else:
                    frame = self._payment_frame(i, j)
                res = self.admission.submit(frame)
                self.statuses[res.code] = self.statuses.get(res.code, 0) + 1
            offered += burst
            was = self.admission.backpressured
            self.clock.crank()
            if self.admission.backpressured and not was:
                self.backpressure_engaged += 1
            self.peak_admission_depth = max(self.peak_admission_depth,
                                            self.admission.depth)
            self.peak_queue_depth = max(self.peak_queue_depth,
                                        self.tx_queue.size)

    def run(self, n_ledgers: int, offered_per_ledger: int) -> dict:
        """Run `n_ledgers` close rounds at `offered_per_ledger` offered
        txs each; returns the campaign report.

        The admission latency/batch-size percentiles are reset at run
        start so the report describes THIS run — the registry is
        process-global and would otherwise aggregate every earlier
        run/pipeline in the process (e.g. bench's floor measurement)."""
        from ..util.metrics import registry
        registry().timer("herder.admission.latency").reset()
        registry().histogram("herder.admission.batch-size").reset()
        t0 = _time.perf_counter()
        applied = 0
        for _ in range(n_ledgers):
            self._offer(offered_per_ledger)
            self.admission.drain()
            frames = self.tx_queue.tx_set_frames()
            self.clock.crank_for(self.lg.close_target)
            self.mgr.close_ledger(frames, self.lg._next_close_time())
            applied += len(frames)
            self.tx_queue.remove_applied(frames)
            self.tx_queue.shift()
        wall = _time.perf_counter() - t0
        lat = registry().snapshot(prefix="herder.admission.").get(
            "herder.admission.latency", {})
        bsz = registry().snapshot(prefix="herder.admission.").get(
            "herder.admission.batch-size", {})
        report = {
            "accounts": self.pool.n,
            "accounts_touched": self.pool.touched,
            "ledgers": n_ledgers,
            "offered": n_ledgers * offered_per_ledger,
            "applied": applied,
            "soroban_offered": self.soroban_offered,
            "wall_s": round(wall, 2),
            "sustained_tps": round(applied / wall, 1) if wall else 0.0,
            "statuses": dict(self.statuses),
            "peak_queue_depth": self.peak_queue_depth,
            "peak_admission_depth": self.peak_admission_depth,
            "backpressure_engaged": self.backpressure_engaged,
            "admission_stats": {
                k: (round(v, 4) if isinstance(v, float) else v)
                for k, v in self.admission.stats.items()},
        }
        for q in ("p50", "p90", "p99"):
            if f"{q}_s" in lat:
                report[f"admission_{q}_us"] = round(lat[f"{q}_s"] * 1e6, 1)
        if "count" in bsz:
            report["batches"] = bsz["count"]
            report["batch_size_p50"] = bsz.get("p50", 0.0)
            report["batch_size_p99"] = bsz.get("p99", 0.0)
            report["batch_size_max"] = bsz.get("max", 0.0)
        report["bucketlistdb"] = self.store is not None
        if self.store is not None:
            bl = self.mgr.bucket_list
            report["peak_decoded_entries"] = bl.peak_decoded_entries
            report["live_entries"] = self.mgr.root.entry_count()
        return report

    def close(self) -> None:
        self.admission.close()


def _soroban_pool_frame(nid: bytes, pool: SeedAccountPool, i: int,
                        value: int):
    """One InvokeHostFunction frame from pool account `i` against ITS
    OWN contract (contract id derived from the account index), writing
    one persistent CONTRACT_DATA key.  Distinct accounts therefore have
    disjoint write sets — the footprint scheduler can fan them out as
    separate clusters."""
    from ..soroban.storage import contract_data_key
    from ..testutils import contract_address, invoke_op, make_soroban_data

    c = contract_address(1 + (i % 250))
    key = X.SCVal.sym("v")
    dk = contract_data_key(c, key, X.ContractDataDurability.PERSISTENT)
    sd = make_soroban_data(read_write=[dk])
    op = invoke_op(c, "put", [key, X.SCVal.u64(value),
                              X.SCVal.sym("persistent")])
    return build_tx(nid, pool.secret(i), pool.next_seq(i), [op],
                    fee=1000 + sd.resourceFee, soroban_data=sd)


class SorobanMixCampaign:
    """Mixed classic+Soroban close campaign with serial-vs-parallel
    hash identity (ISSUE 17 acceptance driver).

    The same seed-derived traffic (payments from a ``SeedAccountPool``
    interleaved with per-account contract invokes) is closed twice —
    once with the footprint scheduler disabled (serial apply) and once
    with it fanning disjoint write-set clusters across threads — and
    EVERY per-close bucket-list hash must match byte-for-byte.  Each
    ledger's Soroban phase draws ``soroban_per_ledger`` DISTINCT
    accounts, so its write sets are disjoint and the parallel side
    genuinely runs that many concurrent clusters."""

    def __init__(self, n_accounts: int = 8, classic_per_ledger: int = 3,
                 soroban_per_ledger: int = 5, seed: int = 11,
                 network_passphrase: str = "soroban mix campaign"):
        assert soroban_per_ledger <= n_accounts
        self.nid = sha256(network_passphrase.encode())
        self.n_accounts = n_accounts
        self.classic_per_ledger = classic_per_ledger
        self.soroban_per_ledger = soroban_per_ledger
        self.seed = seed

    def _run_side(self, n_ledgers: int, parallel: bool) -> dict:
        from ..soroban import cluster_footprints, is_soroban_frame

        pool = SeedAccountPool(self.n_accounts, seed=self.seed)
        mgr = LedgerManager(self.nid)
        mgr.start_new_ledger()
        mgr.soroban_parallel_apply = parallel
        lg = LoadGenerator(mgr, seed=self.seed)
        lg.install_account_pool(pool)
        rng = random.Random(self.seed * 7919)
        hashes: List[bytes] = []
        max_clusters = 0
        applied = 0
        t0 = _time.perf_counter()
        for ledger in range(n_ledgers):
            frames = []
            for _ in range(self.classic_per_ledger):
                i, j = rng.sample(range(pool.n), 2)
                frames.append(build_tx(
                    self.nid, pool.secret(i), pool.next_seq(i),
                    [native_payment_op(pool.account_id(j),
                                       100 + rng.randrange(10 ** 6))],
                    fee=100 + rng.randrange(200)))
            for i in rng.sample(range(pool.n), self.soroban_per_ledger):
                frames.append(_soroban_pool_frame(
                    self.nid, pool, i, rng.randrange(2 ** 32)))
            sb = [f for f in frames if is_soroban_frame(f)]
            max_clusters = max(max_clusters, len(cluster_footprints(sb)))
            lg._close(frames)
            applied += len(frames)
            hashes.append(bytes(mgr.lcl_header.bucketListHash))
        return {"hashes": hashes, "lcl": mgr.lcl_hash,
                "max_clusters": max_clusters, "applied": applied,
                "wall_s": _time.perf_counter() - t0}

    def run(self, n_ledgers: int = 50) -> dict:
        serial = self._run_side(n_ledgers, parallel=False)
        par = self._run_side(n_ledgers, parallel=True)
        assert serial["hashes"] == par["hashes"], \
            "footprint-parallel close diverged from serial apply"
        assert serial["lcl"] == par["lcl"]
        return {
            "ledgers": n_ledgers,
            "applied": serial["applied"],
            "max_disjoint_clusters": par["max_clusters"],
            "serial_wall_s": round(serial["wall_s"], 2),
            "parallel_wall_s": round(par["wall_s"], 2),
            "hashes_identical": True,
            "bucket_hashes": serial["hashes"],
        }
