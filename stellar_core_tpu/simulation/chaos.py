"""Chaos campaign runner: 50-300-node consensus soaks with scripted
faults, continuous safety/liveness assertions and measured recovery.

Reference shape: src/simulation/Simulation + Topologies give the
deterministic multi-node substrate (SURVEY.md §4); this module composes
every fault ingredient the repo already has — partitions with healing
(`Simulation.partition`-style link cuts), LoopbackPeer damage/drop/
reorder knobs, validator stall+rejoin (forcing buffered-externalize /
out-of-sync SCP-state recovery), node bans, and corrupted floods — into
**scripted fault schedules**: typed events fired at virtual times on the
shared VirtualClock.

While the schedule plays, the runner continuously asserts the three
invariants that define a correct validator fleet:

- **safety** — no two nodes ever externalize different hashes for the
  same slot (checked every crank over each `SimNode.closed` map against
  a campaign-global canonical slot->hash table);
- **liveness** — ledgers keep closing: a network-wide progress stall
  longer than `liveness_grace_targets` close targets, outside a
  scenario-declared `allow_stall` window, is a violation (a
  quorum-splitting partition is *detected*, not survived);
- **bounded recovery** — after a heal marked `measure_recovery`, every
  validator must converge to one LCL hash within
  `recovery_close_targets` close targets; the measured virtual recovery
  time is reported.

A failing scenario emits a replayable post-mortem: the process flight
recorder is dumped via ``util/eventlog.write_crash_bundle`` (with a
``chaos`` bundle source carrying scenario name, RNG seed, fault schedule
and violations) plus a per-node record file — node ids, LCLs, herder
state + recovery stats, health verdicts, recent closes — and the seed
needed to re-run the identical campaign (`Simulation(seed=...)` threads
it into every loopback pair's fault RNG).

Topology note: 50+-node campaigns run on *sparse* overlay graphs (org
meshes + org rings, leaf uplinks) — consensus traffic traverses the real
flood/fetch relay machinery rather than an all-pairs bus, which is both
realistic and what keeps 300-node soaks tractable in-process.
"""

from __future__ import annotations

import json
import os
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Set, Tuple

from .. import xdr as X
from ..util import eventlog
from ..util import logging as slog
from ..util.clock import VirtualTimer
from ..xdr import scp as SX
from .simulation import (SimNode, Simulation,
                         make_asymmetric_topology, make_core_topology,
                         make_cycle_topology, make_hierarchical_topology,
                         make_intersection_violation_topology,
                         split_brain_links)

log = slog.get("Sim")

# how many recent closes each per-node flight record keeps in artifacts
NODE_RECORD_TAIL = 8


# ---------------------------------------------------------------------------
# typed fault events (all times are virtual seconds after campaign start)
# ---------------------------------------------------------------------------

class FaultEvent:
    """Base: one scripted fault at virtual time `at`."""

    at: float

    def describe(self) -> str:
        kv = ", ".join(f"{k}={v!r}" for k, v in sorted(vars(self).items())
                       if k != "at")
        return f"{type(self).__name__}(at={self.at:g}, {kv})"


class Partition(FaultEvent):
    """Named link cut: sever every base link crossing a group boundary.
    `groups` are node-index lists; nodes in none of them form an implicit
    remainder group.  Overlapping cuts COMPOSE — a link is down while ANY
    active cut severs it."""

    def __init__(self, at: float, groups: Sequence[Sequence[int]],
                 name: str = "cut"):
        self.at = at
        self.groups = [list(g) for g in groups]
        self.name = name


class CutLink(FaultEvent):
    """Named cut of ONE link — degrades connectivity without splitting
    the overlay graph (flooding reroutes around it).  This is the right
    fault for topologies whose quorum slices lack global intersection
    (a cycle's 2-of-3 neighbour slices): a group Partition there can
    create two disjoint quorums and a *legitimate* fork, which the
    safety checker will flag."""

    def __init__(self, at: float, a: int, b: int,
                 name: Optional[str] = None):
        self.at = at
        self.a = a
        self.b = b
        self.name = name if name is not None else f"link-{a}-{b}"


class Heal(FaultEvent):
    """Remove the named cut (None = all cuts).  `measure_recovery` arms
    the bounded-recovery assertion: all validators must converge to one
    LCL hash within the scenario's recovery budget."""

    def __init__(self, at: float, name: Optional[str] = None,
                 measure_recovery: bool = False):
        self.at = at
        self.name = name
        self.measure_recovery = measure_recovery


class Flap(FaultEvent):
    """`count` partition/heal alternations of `period` seconds each —
    expanded at schedule build time into Partition/Heal pairs."""

    def __init__(self, at: float, groups: Sequence[Sequence[int]],
                 period: float, count: int, name: str = "flap"):
        self.at = at
        self.groups = [list(g) for g in groups]
        self.period = period
        self.count = count
        self.name = name

    def expand(self) -> List[FaultEvent]:
        out: List[FaultEvent] = []
        t = self.at
        for i in range(self.count):
            out.append(Partition(t, self.groups, name=f"{self.name}-{i}"))
            out.append(Heal(t + self.period, name=f"{self.name}-{i}"))
            t += 2 * self.period
        return out


class LinkFault(FaultEvent):
    """Set damage/drop/reorder probabilities on loopback links: both ends
    of every live link of node `node` (or of ALL nodes when None).
    Ramps are several LinkFaults at increasing `at`.  A damaged frame
    fails the receiver's MAC check and fail-stops the connection — the
    runner's periodic reconcile redials it, modelling an operator
    restart."""

    def __init__(self, at: float, node: Optional[int] = None,
                 damage: float = 0.0, drop: float = 0.0,
                 reorder: float = 0.0):
        self.at = at
        self.node = node
        self.damage = damage
        self.drop = drop
        self.reorder = reorder


class StallNode(FaultEvent):
    """Isolate one validator (its own named cut) — long enough past the
    peers' slot memory this forces buffered-externalize catchup and
    out-of-sync SCP-state recovery at rejoin."""

    def __init__(self, at: float, node: int):
        self.at = at
        self.node = node


class RejoinNode(FaultEvent):
    def __init__(self, at: float, node: int,
                 measure_recovery: bool = False):
        self.at = at
        self.node = node
        self.measure_recovery = measure_recovery


class Ban(FaultEvent):
    """`node` bans `target`'s identity: the live link drops and the
    runner stops redialing it until Unban."""

    def __init__(self, at: float, node: int, target: int):
        self.at = at
        self.node = node
        self.target = target


class Unban(FaultEvent):
    def __init__(self, at: float, node: int, target: int):
        self.at = at
        self.node = node
        self.target = target


class CorruptFlood(FaultEvent):
    """`node` emits `frames` corrupted frames to each authenticated peer
    (one-shot damage on the outbound path).  Receivers must fail-stop the
    connection (bad MAC), never apply the payload; the reconcile pass
    redials afterwards."""

    def __init__(self, at: float, node: int, frames: int = 2):
        self.at = at
        self.node = node
        self.frames = frames


class ByzantineNode(FaultEvent):
    """Turn SIGNING validator `node` Byzantine at virtual time `at`:
    from then on its outbound SCP traffic is adversarial — properly
    signed with its real validator key, so receivers cannot tell it from
    an honest (if confusing) peer.  Modes (composable — several events on
    one node accumulate):

    - ``equivocate`` — every outgoing statement (nomination AND ballot)
      is re-signed per peer *group* with a mutated ``StellarValue``: a
      different value to different peers for the same slot/ballot, the
      textbook SCP equivocation fault.
    - ``conflicting-nominate`` — only NOMINATE statements equivocate
      (conflicting nomination votes; the ballot protocol stays truthful).
    - ``stale-replay`` — alongside live traffic the node re-sends its own
      recorded envelopes from slots older than the fleet's slot memory;
      receivers must discard them via the ``MAX_SLOTS_TO_REMEMBER``
      window check (observable: ``herder.scp.envelope-discarded``).

    `groups`: node-index lists assigning peers to equivocation variants
    (group k receives the value mutated by +k seconds of closeTime; nodes
    in no list form implicit group 0 = the node's true statements).
    Default None splits authenticated peers deterministically by node-id
    parity.  In a healthy-intersection topology every quorum crosses the
    groups, so honest nodes must never externalize divergent hashes; in
    an intersection-violation topology (two disjoint near-quorums bridged
    only by this node) the equivocation FORKS the network and the
    per-crank safety assertion must flag it."""

    def __init__(self, at: float, node: int, mode: str = "equivocate",
                 groups: Optional[Sequence[Sequence[int]]] = None):
        if mode not in ("equivocate", "conflicting-nominate",
                        "stale-replay"):
            raise ValueError(f"unknown byzantine mode {mode!r}")
        self.at = at
        self.node = node
        self.mode = mode
        self.groups = [list(g) for g in groups] if groups is not None \
            else None


# ---------------------------------------------------------------------------
# sparse overlay graphs (node-index link sets)
# ---------------------------------------------------------------------------

def mesh_links(n: int) -> Set[frozenset]:
    return {frozenset((i, j)) for i in range(n) for j in range(i + 1, n)}


def ring_links(n: int, hops: int = 2) -> Set[frozenset]:
    """Ring plus `hops`-neighbour chords (so one severed node cannot cut
    the ring)."""
    out: Set[frozenset] = set()
    for i in range(n):
        for h in range(1, hops + 1):
            out.add(frozenset((i, (i + h) % n)))
    return out


def hierarchical_links(n_orgs: int, nodes_per_org: int = 3
                       ) -> Set[frozenset]:
    """Org-internal meshes + two independent org rings (org i node k <->
    org i+1 node k for k in {0,1}) — severing one inter-org edge never
    disconnects the org graph."""
    out: Set[frozenset] = set()
    for o in range(n_orgs):
        base = o * nodes_per_org
        for i in range(nodes_per_org):
            for j in range(i + 1, nodes_per_org):
                out.add(frozenset((base + i, base + j)))
        nxt = ((o + 1) % n_orgs) * nodes_per_org
        for k in range(min(2, nodes_per_org)):
            out.add(frozenset((base + k, nxt + k)))
    return out


def asymmetric_links(n_core_orgs: int, nodes_per_org: int,
                     n_leaf: int) -> Set[frozenset]:
    """Hierarchical core graph + each leaf uplinked to two core nodes
    (deterministic spread)."""
    out = hierarchical_links(n_core_orgs, nodes_per_org)
    n_core = n_core_orgs * nodes_per_org
    for i in range(n_leaf):
        leaf = n_core + i
        out.add(frozenset((leaf, (2 * i) % n_core)))
        out.add(frozenset((leaf, (2 * i + 1 + n_core // 2) % n_core)))
    return out


def org_indices(org: int, nodes_per_org: int = 3) -> List[int]:
    return list(range(org * nodes_per_org, (org + 1) * nodes_per_org))


# ---------------------------------------------------------------------------
# scenario + result
# ---------------------------------------------------------------------------

class ChaosScenario:
    """One scripted campaign: a topology builder, a fault schedule, and
    assertion budgets.  `build(seed)` returns `(sim, links)` where
    `links` is the base overlay graph as node-index pairs."""

    def __init__(self, name: str,
                 build: Callable[[int], Tuple[Simulation, Set[frozenset]]],
                 schedule: Sequence[FaultEvent],
                 duration_s: float = 60.0,
                 seed: int = 0,
                 recovery_close_targets: float = 12.0,
                 liveness_grace_targets: float = 8.0,
                 allow_stall: Sequence[Tuple[float, float]] = (),
                 expect_failure: Optional[str] = None,
                 checkpoint_frequency: Optional[int] = None,
                 batching: bool = True,
                 description: str = ""):
        self.name = name
        self.build = build
        self.schedule = list(schedule)
        self.duration_s = duration_s
        self.seed = seed
        self.recovery_close_targets = recovery_close_targets
        self.liveness_grace_targets = liveness_grace_targets
        self.allow_stall = [tuple(w) for w in allow_stall]
        # set on intentionally-broken scenarios: the violation kind the
        # runner MUST detect ("liveness", "safety", "recovery")
        self.expect_failure = expect_failure
        # archive-recovery scenarios accelerate the checkpoint cadence
        # (the cadence is archive FORMAT: runner sets it process-wide for
        # the campaign and restores it after)
        self.checkpoint_frequency = checkpoint_frequency
        # batched authenticated transport for the whole fleet (the runner
        # applies it to every node before any link is dialed); False
        # replays a campaign over classic per-message frames — the
        # replay-identity and bench comparisons run both modes
        self.batching = batching
        self.description = description
        # optional teardown the runner invokes after the campaign —
        # scenarios that provision on-disk state (a shared history
        # archive) reclaim it here
        self.cleanup: Optional[Callable[[], None]] = None


class Violation:
    def __init__(self, kind: str, at_vt: float, detail: str):
        self.kind = kind
        self.at_vt = at_vt
        self.detail = detail

    def to_dict(self) -> dict:
        return {"kind": self.kind, "at_vt": round(self.at_vt, 3),
                "detail": self.detail}

    def __repr__(self) -> str:
        return f"Violation({self.kind!r}, at={self.at_vt:g}, {self.detail!r})"


class ChaosResult:
    def __init__(self, scenario: ChaosScenario):
        self.scenario = scenario.name
        self.seed = scenario.seed
        self.violations: List[Violation] = []
        self.recoveries: List[dict] = []   # {heal_vt, recovery_s, slot}
        self.ledgers_closed = 0
        self.nodes = 0
        self.virtual_s = 0.0
        self.event_trace: List[Tuple[float, str]] = []
        self.slot_hashes: Dict[int, bytes] = {}    # canonical slot -> hash
        self.node_records: List[dict] = []
        self.artifact_path: Optional[str] = None
        self.crash_bundle_path: Optional[str] = None

    @property
    def passed(self) -> bool:
        return not self.violations

    def to_report(self) -> dict:
        out = {
            "scenario": self.scenario,
            "seed": self.seed,
            "passed": self.passed,
            "nodes": self.nodes,
            "ledgers_closed": self.ledgers_closed,
            "virtual_s": round(self.virtual_s, 1),
            "violations": [v.to_dict() for v in self.violations],
        }
        if self.recoveries:
            out["recovery_s"] = [round(r["recovery_s"], 2)
                                 for r in self.recoveries]
        if self.artifact_path:
            out["artifact"] = self.artifact_path
        return out


# ---------------------------------------------------------------------------
# byzantine emission engine
# ---------------------------------------------------------------------------

class _ByzantineEngine:
    """Installed over one SimNode's SCP emission path (herder.broadcast)
    by a ByzantineNode event.  All adversarial statements are REAL SCP
    statements re-signed with the node's actual validator key; the only
    lie is WHICH statement each peer receives.

    Equivocation mutates the StellarValue inside every pledge uniformly
    (+delta seconds of closeTime per peer group, delta 0 = the true
    statement).  A uniform per-group delta is order-preserving on value
    bytes (the tx-set hash is shared and closeTime is big-endian), so the
    mutated statement still passes the receiver's isStatementSane checks
    — the attack is semantic, not syntactic, which is exactly what makes
    it dangerous and what the honest-fleet scenarios must survive."""

    # keep this many of our own emitted envelopes for stale replays
    REPLAY_MEMORY = 256
    # a replayed envelope must be at least this many slots behind the
    # live one so receivers are FORCED through the slot-memory discard
    STALE_GAP = 13   # MAX_SLOTS_TO_REMEMBER + 1
    REPLAYS_PER_EMIT = 2

    def __init__(self, runner: "ChaosRunner", node_index: int):
        self.runner = runner
        self.node_index = node_index
        self.node = runner.sim.nodes[node_index]
        self.equivocate = False
        self.conflicting_nominate = False
        self.stale_replay = False
        # node_id -> variant group (from ByzantineNode.groups); empty =
        # deterministic node-id parity split
        self.group_of_id: Dict[bytes, int] = {}
        self._emitted = deque(maxlen=self.REPLAY_MEMORY)
        self._orig_broadcast = self.node.herder.broadcast
        self.node.herder.broadcast = self._on_emit
        self.stats = {"equivocal_sent": 0, "stale_replayed": 0}

    def enable(self, mode: str,
               groups: Optional[Sequence[Sequence[int]]]) -> None:
        if mode == "equivocate":
            self.equivocate = True
        elif mode == "conflicting-nominate":
            self.conflicting_nominate = True
        else:
            self.stale_replay = True
        if groups is not None:
            # listed group k receives variant k+1 (delta k+1 seconds);
            # unlisted nodes form implicit group 0 = the true statements
            sim = self.runner.sim
            for gi, grp in enumerate(groups):
                for idx in grp:
                    self.group_of_id[sim.nodes[idx].node_id] = gi + 1

    # -- variant crafting --------------------------------------------------
    def _group_of(self, peer_id: bytes) -> int:
        got = self.group_of_id.get(peer_id)
        if got is not None:
            return got
        if self.group_of_id:
            return 0          # nodes outside every declared group
        return peer_id[0] & 1  # deterministic parity split

    def _mutate_value(self, vbytes: bytes, delta: int) -> bytes:
        try:
            sv = X.StellarValue.from_xdr(vbytes)
        except X.XdrError:
            return vbytes
        return X.StellarValue(txSetHash=sv.txSetHash,
                              closeTime=sv.closeTime + delta,
                              upgrades=list(sv.upgrades)).to_xdr()

    def _mutate_ballot(self, xb, delta: int):
        return SX.SCPBallot(counter=xb.counter,
                            value=self._mutate_value(xb.value, delta))

    def _variant(self, env, delta: int, force: bool = False):
        """The envelope peer group `delta` receives: the statement with
        every embedded value shifted, re-signed with our real key.
        `force` mutates ballot statements even when only
        conflicting-nominate mode is armed (stale replays must be bytes
        nobody's floodgate remembers, or dedup absorbs them before the
        herder's window check ever sees them)."""
        if delta == 0:
            return env
        st = env.statement
        pl = st.pledges
        t = pl.type
        if t == SX.SCPStatementType.SCP_ST_NOMINATE:
            nom = pl.nominate
            pledges = SX.SCPStatementPledges.nominate(SX.SCPNomination(
                quorumSetHash=nom.quorumSetHash,
                votes=[self._mutate_value(v, delta) for v in nom.votes],
                accepted=[self._mutate_value(v, delta)
                          for v in nom.accepted]))
        elif not self.equivocate and not force:
            return env   # conflicting-nominate only lies in nominations
        elif t == SX.SCPStatementType.SCP_ST_PREPARE:
            pr = pl.prepare
            pledges = SX.SCPStatementPledges.prepare(SX.SCPPrepare(
                quorumSetHash=pr.quorumSetHash,
                ballot=self._mutate_ballot(pr.ballot, delta),
                prepared=(self._mutate_ballot(pr.prepared, delta)
                          if pr.prepared is not None else None),
                preparedPrime=(self._mutate_ballot(pr.preparedPrime, delta)
                               if pr.preparedPrime is not None else None),
                nC=pr.nC, nH=pr.nH))
        elif t == SX.SCPStatementType.SCP_ST_CONFIRM:
            co = pl.confirm
            pledges = SX.SCPStatementPledges.confirm(SX.SCPConfirm(
                ballot=self._mutate_ballot(co.ballot, delta),
                nPrepared=co.nPrepared, nCommit=co.nCommit, nH=co.nH,
                quorumSetHash=co.quorumSetHash))
        else:
            ex = pl.externalize
            pledges = SX.SCPStatementPledges.externalize(SX.SCPExternalize(
                commit=self._mutate_ballot(ex.commit, delta),
                nH=ex.nH, commitQuorumSetHash=ex.commitQuorumSetHash))
        st2 = SX.SCPStatement(nodeID=st.nodeID, slotIndex=st.slotIndex,
                              pledges=pledges)
        env2 = SX.SCPEnvelope(statement=st2, signature=b"\x00" * 64)
        self.node.herder.sign_envelope(env2)
        return env2

    # -- emission hook -----------------------------------------------------
    def _on_emit(self, env) -> None:
        self._emitted.append(env)
        if not (self.equivocate or self.conflicting_nominate):
            # truthful consensus traffic still floods normally
            self._orig_broadcast(env)
        else:
            variants: Dict[int, object] = {}
            for peer in list(self.node.overlay._auth_peer_list()):
                g = self._group_of(peer.peer_id)
                out = variants.get(g)
                if out is None:
                    out = variants[g] = self._variant(env, g)
                peer.send_message(X.StellarMessage.envelope(out))
                if out is not env:
                    # count only genuinely equivocal sends — in
                    # conflicting-nominate-only mode ballot statements
                    # pass through unmutated even for non-zero groups
                    self.stats["equivocal_sent"] += 1
        if self.stale_replay:
            self._replay_stale(env.statement.slotIndex)

    def _replay_stale(self, live_slot: int) -> None:
        """Re-send properly-signed statements for slots older than the
        fleet's slot memory.  Each replay carries a FRESH value delta:
        a byte-identical replay dies in the receivers' floodgate dedup
        (a fine first line of defense, but silent), while a never-seen
        statement for a dead slot must reach the herder and be binned by
        the MAX_SLOTS_TO_REMEMBER window check — the observable,
        metered discard path this fault exists to exercise."""
        stale = [e for e in self._emitted
                 if e.statement.slotIndex <= live_slot - self.STALE_GAP]
        for env in stale[-self.REPLAYS_PER_EMIT:]:
            self._replay_seq = getattr(self, "_replay_seq", 0) + 1
            out = self._variant(env, 2 + self._replay_seq % 5, force=True)
            for peer in list(self.node.overlay._auth_peer_list()):
                peer.send_message(X.StellarMessage.envelope(out))
                self.stats["stale_replayed"] += 1


# ---------------------------------------------------------------------------
# the runner
# ---------------------------------------------------------------------------

class ChaosRunner:
    """Executes one ChaosScenario on a shared VirtualClock."""

    # reconcile (redial non-severed base links lost to faults) at most
    # once per this many virtual seconds
    RECONCILE_EVERY_VT = 1.0

    def __init__(self, scenario: ChaosScenario,
                 artifact_dir: Optional[str] = None):
        self.scenario = scenario
        self.artifact_dir = artifact_dir or os.environ.get("STPU_CRASH_DIR")
        self.result = ChaosResult(scenario)
        self.sim: Optional[Simulation] = None
        self.base_links: Set[frozenset] = set()
        # name -> ("groups", [[idx,...],...]) | ("link", frozenset);
        # overlapping cuts compose: a link is severed while ANY active
        # cut severs it
        self.cuts: Dict[str, tuple] = {}
        self.banned_pairs: Set[frozenset] = set()
        # node indices turned Byzantine (ByzantineNode events): their own
        # closes are excluded from the canonical safety table — the
        # assertion must flag honest-node divergence, not the adversary's
        # bookkeeping.  index -> installed emission engine.
        self.byz_engines: Dict[int, _ByzantineEngine] = {}
        # active LinkFault state: node index (None = every node) ->
        # (damage, drop, reorder).  Kept so _reconcile can REAPPLY the
        # declared probabilities to redialed links — a damage fail-stop
        # followed by a redial must not silently clear the rest of the
        # scheduled ramp on that link.
        self.link_faults: Dict[Optional[int], Tuple[float, float, float]] = {}
        # safety bookkeeping
        self._canonical: Dict[int, bytes] = {}     # slot -> hash
        self._checked_upto: List[int] = []         # per node index
        self._node_tail: List[deque] = []
        # liveness bookkeeping
        self._lcl_sum = 0
        self._last_progress_vt = 0.0
        self._pending_recovery: Optional[dict] = None
        self._fatal = False
        self._last_reconcile_vt = -1.0
        self._start_vt = 0.0
        self._timers: List[VirtualTimer] = []

    # -- wiring ------------------------------------------------------------

    def _trace(self, msg: str) -> None:
        vt = self.sim.clock.now() - self._start_vt
        self.result.event_trace.append((round(vt, 3), msg))

    def _severed(self, ia: int, ib: int) -> bool:
        key = frozenset((ia, ib))
        if key in self.banned_pairs:
            return True
        for kind, data in self.cuts.values():
            if kind == "link":
                if key == data:
                    return True
                continue
            ga = gb = -1   # -1 = implicit remainder group
            for gi, grp in enumerate(data):
                if ia in grp:
                    ga = gi
                if ib in grp:
                    gb = gi
            if ga != gb:
                return True
        return False

    def _reconcile(self) -> None:
        """Drive connectivity to the desired state: base links not under
        any active cut are (re)dialed — covering links lost to corrupted
        floods / damage fail-stops — and links under a cut are severed.
        connect() is idempotent and replaces CLOSING pairs, so flapping
        schedules cannot leak half-open connections."""
        sim = self.sim
        for key in self.base_links:
            ia, ib = tuple(key)
            a, b = sim.nodes[ia], sim.nodes[ib]
            if self._severed(ia, ib):
                if sim.is_connected(a, b):
                    sim.disconnect(a, b)
            else:
                sim.connect(a, b)
                if self.link_faults:
                    # both directions, matching what a LinkFault event
                    # applies via _peers_of (the LINK is faulty, not one
                    # node's outbound half).  Most-recently-applied
                    # matching entry wins (dict order = event application
                    # order, see _apply) — a redial must restore what the
                    # LAST LinkFault left on the live link, not whichever
                    # endpoint has the lower index
                    fault = None
                    for fkey in reversed(self.link_faults):
                        if fkey is None or fkey == ia or fkey == ib:
                            fault = self.link_faults[fkey]
                            break
                    pair = sim._connections.get(
                        frozenset((a.node_id, b.node_id)))
                    if fault is not None and pair is not None:
                        for peer in pair:
                            peer.damage_probability, \
                                peer.drop_probability, \
                                peer.reorder_probability = fault
        self._last_reconcile_vt = sim.clock.now()

    # -- event application -------------------------------------------------

    def _apply(self, ev: FaultEvent) -> None:
        sim = self.sim
        self._trace(ev.describe())
        eventlog.record("Sim", "WARNING", "chaos fault",
                        scenario=self.scenario.name, event=ev.describe())
        if isinstance(ev, Partition):
            self.cuts[ev.name] = ("groups", ev.groups)
            self._reconcile()
        elif isinstance(ev, CutLink):
            self.cuts[ev.name] = ("link", frozenset((ev.a, ev.b)))
            self._reconcile()
        elif isinstance(ev, Heal):
            if ev.name is None:
                self.cuts.clear()
            else:
                self.cuts.pop(ev.name, None)
            self._reconcile()
            if ev.measure_recovery:
                self._arm_recovery()
        elif isinstance(ev, StallNode):
            self.cuts[f"stall-{ev.node}"] = ("groups", [[ev.node]])
            self._reconcile()
        elif isinstance(ev, RejoinNode):
            self.cuts.pop(f"stall-{ev.node}", None)
            self._reconcile()
            if ev.measure_recovery:
                self._arm_recovery()
        elif isinstance(ev, LinkFault):
            if ev.node is None:
                # a fleet-wide LinkFault supersedes every per-node one —
                # _apply just overwrote all live peers, so stale per-node
                # entries must not shadow this on a later redial
                self.link_faults.clear()
            # pop-then-set keeps dict order = application order, which is
            # what _reconcile's last-match-wins redial lookup relies on
            self.link_faults.pop(ev.node, None)
            self.link_faults[ev.node] = (ev.damage, ev.drop, ev.reorder)
            for peer in self._peers_of(ev.node):
                peer.damage_probability = ev.damage
                peer.drop_probability = ev.drop
                peer.reorder_probability = ev.reorder
        elif isinstance(ev, Ban):
            node, target = sim.nodes[ev.node], sim.nodes[ev.target]
            node.overlay.ban_manager.ban_node(target.node_id)
            self.banned_pairs.add(frozenset((ev.node, ev.target)))
            if sim.is_connected(node, target):
                sim.disconnect(node, target)
        elif isinstance(ev, Unban):
            node, target = sim.nodes[ev.node], sim.nodes[ev.target]
            node.overlay.ban_manager.unban_node(target.node_id)
            self.banned_pairs.discard(frozenset((ev.node, ev.target)))
            self._reconcile()
        elif isinstance(ev, CorruptFlood):
            self._corrupt_flood(ev)
        elif isinstance(ev, ByzantineNode):
            engine = self.byz_engines.get(ev.node)
            if engine is None:
                engine = self.byz_engines[ev.node] = \
                    _ByzantineEngine(self, ev.node)
            engine.enable(ev.mode, ev.groups)
        else:
            raise ValueError(f"unknown fault event {ev!r}")

    def _peers_of(self, node: Optional[int]):
        """Both directions of every live loopback link touching `node`
        (all links when None)."""
        for key, pair in list(self.sim._connections.items()):
            if node is not None:
                nid = self.sim.nodes[node].node_id
                if nid not in key:
                    continue
            yield from pair

    def _corrupt_flood(self, ev: CorruptFlood) -> None:
        from .. import xdr as X
        node = self.sim.nodes[ev.node]
        sent = 0
        for key, pair in list(self.sim._connections.items()):
            if node.node_id not in key:
                continue
            for peer in pair:
                if peer.overlay is not node.overlay:
                    continue
                if not peer.is_authenticated():
                    continue
                saved = peer.damage_probability
                peer.damage_probability = 1.0
                try:
                    for _ in range(ev.frames):
                        peer.send_message(X.StellarMessage.getPeers())
                        sent += 1
                finally:
                    peer.damage_probability = saved
        self._trace(f"corrupt-flood sent {sent} damaged frames "
                    f"from node {ev.node}")

    # -- assertions --------------------------------------------------------

    def _close_target(self) -> float:
        return float(self.sim.nodes[0].herder.ledger_timespan)

    def _honest_validators(self) -> List[SimNode]:
        return [n for i, n in enumerate(self.sim.nodes)
                if n.herder.is_validator and i not in self.byz_engines]

    def _arm_recovery(self) -> None:
        vs = self._honest_validators()
        target = max(n.lcl for n in vs) + 1
        self._pending_recovery = {
            "heal_vt": self.sim.clock.now(),
            "target_slot": target,
            "deadline": self.sim.clock.now()
            + self.scenario.recovery_close_targets * self._close_target(),
        }
        self._trace(f"recovery armed: converge at slot >= {target}")

    def _stall_allowed(self, vt: float) -> bool:
        return any(t0 <= vt <= t1 for t0, t1 in self.scenario.allow_stall)

    def _violate(self, kind: str, detail: str) -> None:
        vt = self.sim.clock.now() - self._start_vt
        self.result.violations.append(Violation(kind, vt, detail))
        self._trace(f"VIOLATION[{kind}] {detail}")
        log.error("chaos %s violation in %r at vt=%.1f: %s",
                  kind, self.scenario.name, vt, detail)
        eventlog.record("Sim", "ERROR", "chaos violation",
                        scenario=self.scenario.name, kind=kind,
                        detail=detail)
        self._fatal = True

    def _observe(self) -> bool:
        """Ran every crank: safety over newly-closed slots, liveness
        stall detection, recovery convergence.  Returns True to stop
        cranking (fatal violation or campaign complete)."""
        sim = self.sim
        now = sim.clock.now()
        nodes = sim.nodes
        lcl_sum = 0
        for idx, node in enumerate(nodes):
            lcl = node.lcl
            lcl_sum += lcl
            upto = self._checked_upto[idx]
            if lcl > upto:
                closed = node.closed
                byz = idx in self.byz_engines
                for slot in range(upto + 1, lcl + 1):
                    h = closed.get(slot)
                    if h is None:
                        continue   # genesis/assumed state, nothing to check
                    if byz:
                        # an adversarial validator's own closes neither
                        # define nor violate the canonical chain — the
                        # safety assertion is about the HONEST fleet
                        self._node_tail[idx].append(
                            (round(now - self._start_vt, 2), slot,
                             h.hex()[:16]))
                        continue
                    canon = self._canonical.get(slot)
                    if canon is None:
                        self._canonical[slot] = h
                    elif canon != h:
                        self._violate(
                            "safety",
                            f"node {idx} externalized {h.hex()[:16]} at "
                            f"slot {slot}, network externalized "
                            f"{canon.hex()[:16]}")
                    self._node_tail[idx].append(
                        (round(now - self._start_vt, 2), slot, h.hex()[:16]))
                self._checked_upto[idx] = lcl
        if lcl_sum > self._lcl_sum:
            self._lcl_sum = lcl_sum
            self._last_progress_vt = now
        else:
            stalled_for = now - self._last_progress_vt
            grace = self.scenario.liveness_grace_targets \
                * self._close_target()
            if stalled_for > grace \
                    and not self._stall_allowed(now - self._start_vt):
                self._violate(
                    "liveness",
                    f"no ledger closed anywhere for {stalled_for:.1f}s "
                    f"virtual (> {grace:.0f}s grace); quorum lost?")
        rec = self._pending_recovery
        if rec is not None:
            vs = self._honest_validators()
            target = rec["target_slot"]
            if all(n.lcl >= target for n in vs):
                hashes = {n.closed.get(target) for n in vs}
                if len(hashes) == 1 and None not in hashes:
                    recovery_s = now - rec["heal_vt"]
                    self.result.recoveries.append({
                        "heal_vt": round(rec["heal_vt"] - self._start_vt, 2),
                        "recovery_s": recovery_s,
                        "slot": target,
                    })
                    self._trace(f"recovered in {recovery_s:.1f}s virtual "
                                f"(slot {target})")
                    self._pending_recovery = None
            if self._pending_recovery is not None \
                    and now > rec["deadline"]:
                spread = sorted({n.lcl for n in vs})
                self._violate(
                    "recovery",
                    f"no convergence at slot {target} within "
                    f"{self.scenario.recovery_close_targets:g} close "
                    f"targets after heal (lcl spread {spread[:5]}..)")
        if self._fatal:
            return True
        if now - self._last_reconcile_vt >= self.RECONCILE_EVERY_VT:
            self._reconcile()
        done = now >= self._start_vt + self.scenario.duration_s
        return done and self._pending_recovery is None

    # -- artifacts ---------------------------------------------------------

    def _node_record(self, idx: int, node: SimNode) -> dict:
        health = node.evaluate_health()
        rec = {
            "node": idx,
            "id": node.node_id.hex()[:16],
            "lcl": node.lcl,
            "lcl_hash": node.lcl_hash.hex()[:16],
            "herder_state": node.herder.get_state_human(),
            "recovery_stats": dict(node.herder.recovery_stats),
            "authenticated_peers": node.overlay.num_authenticated(),
            "health": health["status"],
            "health_reasons": health["reasons"],
            "recent_closes": list(self._node_tail[idx]),
        }
        engine = self.byz_engines.get(idx)
        if engine is not None:
            rec["byzantine"] = dict(engine.stats)
        return rec

    def _emit_artifacts(self, reason: str) -> None:
        res = self.result
        res.node_records = [self._node_record(i, n)
                            for i, n in enumerate(self.sim.nodes)]
        if not self.artifact_dir:
            return
        eventlog.register_bundle_source("chaos", lambda: {
            "scenario": self.scenario.name,
            "seed": self.scenario.seed,
            "schedule": [ev.describe() for ev in self.scenario.schedule],
            "violations": [v.to_dict() for v in res.violations],
        })
        try:
            res.crash_bundle_path = eventlog.write_crash_bundle(
                reason, crash_dir=self.artifact_dir)
        finally:
            eventlog.unregister_bundle_source("chaos")
        # the merged cross-node trace: every SimNode shares this process,
        # so the phase-mark buffer splits per node attribution into the
        # same row-per-node Chrome trace shape the fleet soak emits
        trace_path = os.path.join(
            self.artifact_dir,
            f"chaos-{self.scenario.name}-seed{self.scenario.seed}"
            "-trace.json")
        os.makedirs(self.artifact_dir, exist_ok=True)
        from ..util.fleettrace import merge_local_trace
        trace_events = merge_local_trace(trace_path)
        artifact = {
            "scenario": self.scenario.name,
            "description": self.scenario.description,
            "reason": reason,
            "seed": self.scenario.seed,
            "replay": f"ChaosRunner(scenario with seed={self.scenario.seed})"
                      " — the seed threads into every loopback fault RNG",
            "schedule": [ev.describe() for ev in self.scenario.schedule],
            "violations": [v.to_dict() for v in res.violations],
            "event_trace": res.event_trace,
            "node_records": res.node_records,
            "crash_bundle": res.crash_bundle_path,
            "merged_trace": trace_path,
            "merged_trace_events": trace_events,
        }
        path = os.path.join(
            self.artifact_dir,
            f"chaos-{self.scenario.name}-seed{self.scenario.seed}.json")
        with open(path, "w") as f:
            json.dump(artifact, f, indent=1, default=str)
        res.artifact_path = path
        log.warning("chaos scenario %r failed: %s -> %s",
                    self.scenario.name, reason, path)

    # -- main entry --------------------------------------------------------

    def run(self) -> ChaosResult:
        """Execute the campaign.  Scenario-scoped environment — the
        checkpoint cadence (archive format, process-global) and any
        provisioned on-disk state — is installed before build and
        restored afterwards, so campaigns compose in one process."""
        sc = self.scenario
        prev_freq = None
        if sc.checkpoint_frequency is not None:
            from ..history import archive as _arch
            prev_freq = _arch.checkpoint_frequency()
            _arch.set_checkpoint_frequency(sc.checkpoint_frequency)
        try:
            return self._run_campaign()
        finally:
            if prev_freq is not None:
                from ..history import archive as _arch
                _arch.set_checkpoint_frequency(prev_freq)
            if sc.cleanup is not None:
                try:
                    sc.cleanup()
                except OSError:   # teardown best-effort; artifacts are out
                    pass

    def _run_campaign(self) -> ChaosResult:
        sc = self.scenario
        self.sim, self.base_links = sc.build(sc.seed)
        sim = self.sim
        # transport mode is campaign-scoped: set before any link is
        # dialed so every peer negotiates (or declines) batching
        sim.batching = sc.batching
        for node in sim.nodes:
            node.overlay.batching = sc.batching
        n = len(sim.nodes)
        self.result.nodes = n
        self._checked_upto = [0] * n
        self._node_tail = [deque(maxlen=NODE_RECORD_TAIL) for _ in range(n)]
        eventlog.record("Sim", "INFO", "chaos scenario start",
                        scenario=sc.name, nodes=n, seed=sc.seed,
                        events=len(sc.schedule))
        for key in self.base_links:
            ia, ib = tuple(key)
            sim.connect(sim.nodes[ia], sim.nodes[ib])
        sim.start_all_nodes(mesh=False)
        self._start_vt = sim.clock.now()
        self._last_progress_vt = self._start_vt
        self._last_reconcile_vt = self._start_vt

        # expand Flaps, then arm one virtual timer per event
        events: List[FaultEvent] = []
        for ev in sc.schedule:
            events.extend(ev.expand() if isinstance(ev, Flap) else [ev])
        events.sort(key=lambda e: e.at)
        for ev in events:
            t = VirtualTimer(sim.clock)
            t.expires_at(self._start_vt + ev.at,
                         lambda e=ev: self._apply(e))
            self._timers.append(t)

        # crank the campaign; generous wall-clock-free virtual timeout —
        # duration plus the recovery budget plus slack for armed timers
        budget = sc.duration_s \
            + (sc.recovery_close_targets + 4) * self._close_target()
        finished = sim.crank_until(self._observe, timeout=budget)
        if not finished and not self._fatal:
            if self._pending_recovery is not None:
                self._violate("recovery",
                              "campaign ended before post-heal convergence")
            else:
                self._violate("timeout",
                              f"campaign did not complete within "
                              f"{budget:.0f}s virtual")
        self.result.ledgers_closed = max(self._canonical, default=1) - 1
        self.result.slot_hashes = dict(self._canonical)
        self.result.virtual_s = sim.clock.now() - self._start_vt
        if not self.result.passed:
            kinds = {v.kind for v in self.result.violations}
            self._emit_artifacts(
                f"chaos scenario {sc.name!r}: {', '.join(sorted(kinds))} "
                f"violation")
        else:
            self.result.node_records = [self._node_record(i, node)
                                        for i, node in enumerate(sim.nodes)]
        eventlog.record("Sim", "INFO", "chaos scenario end",
                        scenario=sc.name, passed=self.result.passed,
                        ledgers=self.result.ledgers_closed)
        sim.clock.stop()
        return self.result


def run_scenario(scenario: ChaosScenario,
                 artifact_dir: Optional[str] = None) -> ChaosResult:
    return ChaosRunner(scenario, artifact_dir=artifact_dir).run()


# ---------------------------------------------------------------------------
# scenario catalogue
# ---------------------------------------------------------------------------

def _hier_build(n_orgs: int, nodes_per_org: int = 3
                ) -> Callable[[int], Tuple[Simulation, Set[frozenset]]]:
    def build(seed: int):
        sim = make_hierarchical_topology(n_orgs, nodes_per_org, seed=seed)
        return sim, hierarchical_links(n_orgs, nodes_per_org)
    return build


def _core_build(n: int) -> Callable[[int], Tuple[Simulation, Set[frozenset]]]:
    def build(seed: int):
        return make_core_topology(n, seed=seed), mesh_links(n)
    return build


def _cycle_build(n: int) -> Callable[[int], Tuple[Simulation, Set[frozenset]]]:
    def build(seed: int):
        return make_cycle_topology(n, seed=seed), ring_links(n)
    return build


def _asym_build(n_core_orgs: int, nodes_per_org: int, n_leaf: int
                ) -> Callable[[int], Tuple[Simulation, Set[frozenset]]]:
    def build(seed: int):
        sim = make_asymmetric_topology(n_core_orgs, nodes_per_org, n_leaf,
                                       seed=seed)
        return sim, asymmetric_links(n_core_orgs, nodes_per_org, n_leaf)
    return build


def scenario_partition_flap_heal(n_orgs: int = 17, nodes_per_org: int = 3,
                                 seed: int = 7) -> ChaosScenario:
    """The flagship: a minority org block is partitioned away, the cut
    flaps (heal/sever alternation), then heals for good — the majority
    must keep closing throughout, nobody may fork, and the whole fleet
    must reconverge within the recovery budget."""
    minority = [i for o in range(max(1, n_orgs // 4))
                for i in org_indices(o, nodes_per_org)]
    return ChaosScenario(
        name=f"partition-flap-heal-{n_orgs * nodes_per_org}",
        build=_hier_build(n_orgs, nodes_per_org),
        schedule=[
            Partition(12.0, [minority], name="minority"),
            Heal(22.0, name="minority"),
            Flap(26.0, [minority], period=3.0, count=2, name="flap"),
            Heal(40.0, name=None, measure_recovery=True),
        ],
        duration_s=55.0,
        seed=seed,
        description="minority partition -> flapping cut -> heal; "
                    "safety + majority liveness + bounded recovery")


def scenario_quorum_split(n_orgs: int = 4, nodes_per_org: int = 3,
                          seed: int = 11) -> ChaosScenario:
    """INTENTIONALLY BROKEN: a clean half/half org split leaves neither
    side a 2/3-of-orgs quorum, so the whole network stalls.  The runner
    must DETECT this as a liveness violation and emit the replayable
    artifact — this scenario existing (and failing) is the proof the
    assertions have teeth."""
    half = [i for o in range(n_orgs // 2)
            for i in org_indices(o, nodes_per_org)]
    return ChaosScenario(
        name=f"quorum-split-{n_orgs * nodes_per_org}",
        build=_hier_build(n_orgs, nodes_per_org),
        schedule=[Partition(8.0, [half], name="split")],
        duration_s=70.0,
        seed=seed,
        liveness_grace_targets=6.0,
        expect_failure="liveness",
        description="half/half org split: no side retains quorum; the "
                    "runner must flag the global stall as a liveness "
                    "failure")


def scenario_link_degradation(n: int = 12, seed: int = 3) -> ChaosScenario:
    """Per-link fault probability ramp: drop and reorder climb across all
    links, then a burst of damaged frames (MAC fail-stops), then clean.
    Consensus must survive the whole ramp without forking or stalling."""
    return ChaosScenario(
        name=f"link-degradation-{n}",
        build=_core_build(n),
        schedule=[
            LinkFault(8.0, drop=0.02, reorder=0.05),
            LinkFault(16.0, drop=0.05, reorder=0.10),
            LinkFault(24.0, drop=0.10, reorder=0.15),
            LinkFault(32.0, damage=0.02, drop=0.05),
            LinkFault(40.0),   # all probabilities back to zero
        ],
        duration_s=50.0,
        seed=seed,
        liveness_grace_targets=10.0,
        description="drop/reorder probability ramp + damage burst over "
                    "every link of a core mesh")


def scenario_stall_rejoin(n_orgs: int = 4, nodes_per_org: int = 3,
                          seed: int = 5) -> ChaosScenario:
    """One validator is isolated long past the peers' slot memory, then
    rejoins: it must come back through buffered-externalize / out-of-sync
    SCP-state recovery and the fleet must reconverge."""
    return ChaosScenario(
        name=f"stall-rejoin-{n_orgs * nodes_per_org}",
        build=_hier_build(n_orgs, nodes_per_org),
        schedule=[
            StallNode(10.0, node=0),
            RejoinNode(45.0, node=0, measure_recovery=True),
        ],
        duration_s=60.0,
        seed=seed,
        description="validator stall past slot memory + rejoin through "
                    "buffered-ledger recovery")


def scenario_corrupt_flood(n_orgs: int = 4, nodes_per_org: int = 3,
                           seed: int = 13) -> ChaosScenario:
    """A node floods damaged frames (receivers must fail-stop, never
    apply), gets banned by a victim, later unbanned; the mesh redials
    and consensus never forks."""
    return ChaosScenario(
        name=f"corrupt-flood-{n_orgs * nodes_per_org}",
        build=_hier_build(n_orgs, nodes_per_org),
        schedule=[
            CorruptFlood(10.0, node=1, frames=2),
            Ban(14.0, node=4, target=1),
            CorruptFlood(20.0, node=1, frames=2),
            Unban(30.0, node=4, target=1),
            Heal(34.0, measure_recovery=True),
        ],
        duration_s=48.0,
        seed=seed,
        description="corrupted floods fail-stop connections; ban/unban; "
                    "mesh heals and reconverges")


def scenario_cycle_partition(n: int = 12, seed: int = 17) -> ChaosScenario:
    """Ring topology: sever individual ring links (the overlay graph
    stays connected through the remaining chords, so flooding reroutes)
    and heal — the reference uses cycle topologies for exactly this
    connectivity-limited liveness testing.  A *group* partition is
    deliberately NOT used here: a cycle's 2-of-3 neighbour slices lack
    global quorum intersection, so splitting the graph can fork
    legitimately (the safety checker catches it — that discovery is
    recorded in ROADMAP item 5)."""
    return ChaosScenario(
        name=f"cycle-partition-{n}",
        build=_cycle_build(n),
        schedule=[
            CutLink(10.0, 0, 1),
            CutLink(12.0, 0, 2),
            CutLink(14.0, n // 2, n // 2 + 1),
            Heal(24.0, name=None, measure_recovery=True),
        ],
        duration_s=45.0,
        seed=seed,
        description="ring link cuts (graph stays connected) + heal")


def scenario_asym_tier_partition(n_core_orgs: int = 4,
                                 nodes_per_org: int = 3,
                                 n_leaf: int = 6,
                                 seed: int = 19) -> ChaosScenario:
    """Asymmetric tiers: partition the leaf tier away from the core (the
    core keeps closing — leaves are in nobody's slices), heal, and the
    leaves must catch back up."""
    n_core = n_core_orgs * nodes_per_org
    leaves = list(range(n_core, n_core + n_leaf))
    return ChaosScenario(
        name=f"asym-tier-partition-{n_core + n_leaf}",
        build=_asym_build(n_core_orgs, nodes_per_org, n_leaf),
        schedule=[
            Partition(10.0, [leaves], name="leaf-cut"),
            Heal(30.0, name="leaf-cut", measure_recovery=True),
        ],
        duration_s=48.0,
        seed=seed,
        description="second-tier validators cut from the tier-1 core, "
                    "then healed; core liveness unaffected")


def scenario_byzantine_equivocation(n_orgs: int = 4, nodes_per_org: int = 3,
                                    seed: int = 31) -> ChaosScenario:
    """Byzantine SCP traffic in a HEALTHY-intersection hierarchical
    topology: one signing validator equivocates (different value to
    different peers for the same slot/ballot), later starts replaying
    its own stale-slot envelopes, and a second validator emits
    conflicting nominations.  Because quorum intersection holds, every
    quorum crosses the equivocation groups — honest nodes must never
    externalize divergent hashes and the fleet must keep closing (SCP's
    safety claim under Byzantine faults, PAPER.md).  Stale replays must
    die at the receivers' slot-memory window check
    (herder.scp.envelope-discarded)."""
    return ChaosScenario(
        name=f"byzantine-equivocation-{n_orgs * nodes_per_org}",
        build=_hier_build(n_orgs, nodes_per_org),
        schedule=[
            ByzantineNode(8.0, node=1, mode="equivocate"),
            # node 3 = org 1's ring node: it carries inter-org links, so
            # its conflicting nominations actually cross the org boundary
            ByzantineNode(20.0, node=3, mode="conflicting-nominate"),
            ByzantineNode(70.0, node=1, mode="stale-replay"),
        ],
        duration_s=95.0,
        seed=seed,
        liveness_grace_targets=10.0,
        description="equivocation + conflicting nominations + stale "
                    "replays from signing validators; healthy "
                    "intersection, so honest nodes must not fork")


def scenario_intersection_violation(group_size: int = 2,
                                    seed: int = 37) -> ChaosScenario:
    """INTENTIONALLY BROKEN: the generated intersection-violation
    topology (two disjoint near-quorums bridged by one validator) plus
    that bridge equivocating — side A hears value X, side B hears X+1 —
    makes both sides commit different values for the same slot.  The
    per-crank safety assertion MUST flag the fork (attributing it to the
    divergent honest closes, never to the adversary's own bookkeeping)
    and emit the replayable artifact.  This is the scenario axis the
    survey's quorum-intersection precondition exists for: one shared
    node is exactly one Byzantine failure away from a fork."""
    n = 2 * group_size + 1
    bridge = n - 1
    b_side = list(range(group_size, 2 * group_size))
    return ChaosScenario(
        name=f"intersection-violation-{n}",
        build=lambda seed_: (
            make_intersection_violation_topology(group_size, seed=seed_),
            split_brain_links(group_size)),
        schedule=[ByzantineNode(6.0, node=bridge, mode="equivocate",
                                groups=[b_side])],
        duration_s=45.0,
        seed=seed,
        liveness_grace_targets=10.0,
        expect_failure="safety",
        description="two disjoint near-quorums + an equivocating bridge: "
                    "the runner must flag the fork as a safety failure")


def scenario_archive_recovery(n_orgs: int = 4, nodes_per_org: int = 3,
                              seed: int = 29,
                              archive_dir: Optional[str] = None,
                              parallel: int = 1) -> ChaosScenario:
    """The most common real-world incident shape, end to end IN-SIM: a
    validator is stalled well past ``MAX_SLOTS_TO_REMEMBER`` while the
    healthy fleet publishes REAL checkpoints to a shared archive
    (accelerated cadence, the fleet harness's 8); at rejoin the
    SCP-state pull dead-ends (nobody remembers the slots it needs), the
    herder's sync-gap signal hands off to real archive catchup
    (hash-verified chain + bucket apply + replay; ``parallel`` > 1 runs
    the range-parallel worker path), the node adopts the verified state
    and re-tracks through the buffered-externalize bridge."""
    last = n_orgs * nodes_per_org - 1
    state = {"tmp": None}

    def build(seed_: int):
        sim = make_hierarchical_topology(n_orgs, nodes_per_org, seed=seed_)
        from ..history.archive import FileHistoryArchive
        root = archive_dir
        if root is None:
            import tempfile
            root = state["tmp"] = tempfile.mkdtemp(prefix="chaos-archive-")
        archive = FileHistoryArchive(root)
        for i, node in enumerate(sim.nodes):
            # org 0 publishes (identical bytes from each — the archive
            # write path is atomic + content-addressed); EVERY node can
            # read it for catchup
            node.attach_history(archive, publish=(i < nodes_per_org),
                                parallel=(parallel if i == last else 1))
        return sim, hierarchical_links(n_orgs, nodes_per_org)

    sc = ChaosScenario(
        name=f"archive-recovery-{n_orgs * nodes_per_org}",
        build=build,
        schedule=[
            StallNode(10.0, node=last),
            RejoinNode(85.0, node=last, measure_recovery=True),
        ],
        duration_s=100.0,
        seed=seed,
        checkpoint_frequency=8,
        recovery_close_targets=14.0,
        description="validator stalled past slot memory; rejoin must "
                    "hand off to real archive catchup and re-track")

    def cleanup():
        if state["tmp"] is not None:
            import shutil
            shutil.rmtree(state["tmp"], ignore_errors=True)
            state["tmp"] = None
    sc.cleanup = cleanup
    return sc


def scenario_soak(n_orgs: int = 50, nodes_per_org: int = 3,
                  seed: int = 23, duration_s: float = 45.0
                  ) -> ChaosScenario:
    """The soak: a large hierarchical fleet through link degradation, a
    Byzantine equivocator, partition, a stalled validator, flapping and
    a measured heal — every fault class in one compressed schedule.
    Default 150 nodes (the -m slow tier, ~4 min wall); 300 nodes
    (`n_orgs=100`) runs the same schedule and, since the incremental
    per-slot quorum state landed (scp/quorum.StatementIndex), completes
    in ~19 min wall instead of offline-scale hours — the remaining
    floor is per-link transport (~n^2 deliveries), ROADMAP item 4(b)
    (PROFILE round 11)."""
    minority = [i for o in range(max(1, n_orgs // 5))
                for i in org_indices(o, nodes_per_org)]
    last = n_orgs * nodes_per_org - 1
    return ChaosScenario(
        name=f"soak-{n_orgs * nodes_per_org}",
        build=_hier_build(n_orgs, nodes_per_org),
        schedule=[
            LinkFault(6.0, drop=0.02, reorder=0.05),
            # a signing validator outside the partitioned minority turns
            # equivocator for the whole campaign: intersection holds, so
            # the honest fleet must shrug it off under every other fault
            ByzantineNode(8.0, node=n_orgs * nodes_per_org // 2,
                          mode="equivocate"),
            Partition(10.0, [minority], name="minority"),
            StallNode(12.0, node=last),
            Heal(25.0, name="minority"),
            Flap(28.0, [minority], period=3.0, count=1, name="flap"),
            RejoinNode(36.0, node=last),
            LinkFault(38.0),
            Heal(40.0, measure_recovery=True),
        ],
        duration_s=duration_s,
        seed=seed,
        recovery_close_targets=16.0,
        description="soak: every fault class incl. a byzantine "
                    "equivocator in one schedule")


# small-topology tier (tier-1-eligible; `make chaos`) and the full
# catalogue (300-node soaks ride behind -m slow).  Each entry is
# (factory, est_wall_s): the estimate is what bench.py budgets against,
# and the list is the single enumeration its `chaos` section iterates
# (cheapest first) — a scenario added here gets bench coverage
# automatically instead of needing a parallel plan list.
SMALL_SCENARIOS: List[Tuple[Callable[[], ChaosScenario], float]] = [
    (lambda: scenario_stall_rejoin(4, 3), 8.0),
    (lambda: scenario_corrupt_flood(4, 3), 8.0),
    (lambda: scenario_cycle_partition(12), 10.0),
    (lambda: scenario_link_degradation(12), 12.0),
    (lambda: scenario_asym_tier_partition(4, 3, 6), 12.0),
    (lambda: scenario_byzantine_equivocation(4, 3), 15.0),
    (lambda: scenario_archive_recovery(4, 3), 20.0),
    (lambda: scenario_partition_flap_heal(17, 3), 60.0),
]

SOAK_SCENARIOS: List[Tuple[Callable[[], ChaosScenario], float]] = [
    (lambda: scenario_partition_flap_heal(34, 3), 150.0),   # 102 nodes
    (lambda: scenario_soak(50, 3), 240.0),                  # 150 nodes
    (lambda: scenario_soak(100, 3), 1150.0),                # 300 nodes
    # the 300-node soak moved from offline-scale (hours) to ~19 min
    # when the incremental per-slot quorum state landed (PROFILE round
    # 11); the test suite still gates it behind STPU_CHAOS_SOAK_ORGS=100
    # so the default -m slow run stays under ten minutes total
]
