"""Fleet harness: N real `stellar-core-tpu run` processes over real TCP,
driven through scripted production events with SLO assertions.

Every production ingredient exists in isolation — TCP overlay, batched
admission with back-pressure, range-parallel catchup, `/health`, crash
bundles — and this module composes them end to end (ROADMAP item 5).  The
reference deployment shape is reproduced literally: a quorum of real
processes tracks a live network (`Herder`), one of them publishes
checkpoints to a shared archive (`HistoryManager`), and other nodes catch
up from that archive while the network keeps closing ledgers.

Shape of a run:

    fleet = Fleet(workdir, n_nodes=5)
    fleet.provision()                 # workdirs, configs, quorum, archive
    fleet.start()                     # N real processes via ProcessManager
    report = fleet.run(schedule=standard_schedule())
    fleet.teardown()                  # SIGTERM -> grace -> SIGKILL

The schedule is a list of events executed SEQUENTIALLY (each event must
complete before the next starts — production incidents are scripted, not
racy):

    wait-ledger / wait-s      advance time or chain height
    traffic                   set the offered tx rate (0 pauses)
    kill                      SIGKILL a validator mid-slot
    rejoin                    wipe the node, `catchup --parallel` against
                              the fleet's live archive, restart, re-track
    partition / heal          drop TCP links between groups (ban + drop
                              on both sides), later restore them
    rolling-config            roll a config change through the fleet one
                              node at a time (graceful stop -> rewrite ->
                              restart -> wait tracking)

SLOs are asserted, not just safety: zero ledger-hash divergence across
nodes, p99 close time under load, admission shed rate bounded, and
time-to-retracking after a kill under budget.  Violations (and healthy
runs) produce a replayable artifact — ``fleet-report.json`` with per-node
flight records (process logs), health timelines, the event log, and the
exact schedule/config inputs — plus whatever crash bundles the nodes
themselves wrote into the fleet's crash dir.

Everything here runs the REAL binary surface: `run`, `catchup
--parallel`, `/tx`, `/health`, `/ban`, `/droppeer` — the harness never
reaches into another process's memory.
"""

from __future__ import annotations

import json
import os
import random
import shlex
import sys
import time
import urllib.error
import urllib.request
from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..util import logging as slog
from ..util.clock import ClockMode, VirtualClock, monotonic_now
from ..util.fleettrace import FleetScraper, FleetTraceCollector
from ..util.process import ProcessManager
from ..util.slo import SLOTracker, default_objectives
from .loadgen import SeedAccountPool

log = slog.get("Sim")

DEFAULT_CHECKPOINT_FREQUENCY = 8   # accelerated cadence (reference: 8)


# ---------------------------------------------------------------------------
# SLOs
# ---------------------------------------------------------------------------

@dataclass
class FleetSLOs:
    """Service-level objectives the run asserts.  None disables a check
    (divergence is never disableable — a fork is a fork)."""
    max_p99_close_s: Optional[float] = 0.9      # apply work per close
    max_shed_rate: Optional[float] = 0.35       # shed / offered under load
    max_retracking_s: Optional[float] = 90.0    # kill -> tracking again
    max_roll_node_s: Optional[float] = 60.0     # per-node rolling restart
    min_sustained_tps: Optional[float] = None   # accepted tx/s (soak only)
    # fleet-wide SLO burn budget: the scraper drives an util/slo tracker
    # with every node's snapshot; a run whose burn rate exceeds the
    # budget for any objective fails.  None disables burn tracking.
    burn_budget: Optional[float] = 0.25


# ---------------------------------------------------------------------------
# one node
# ---------------------------------------------------------------------------

class FleetNode:
    """One real `stellar-core-tpu run` process and its HTTP surface."""

    def __init__(self, index: int, workdir: str, secret: SecretKey,
                 peer_port: int, http_port: int):
        self.index = index
        self.workdir = workdir
        self.secret = secret
        self.peer_port = peer_port
        self.http_port = http_port
        self.conf_path = os.path.join(workdir, "node.cfg")
        self.log_path = os.path.join(workdir, "node.log")
        self.db_path = os.path.join(workdir, "node.db")
        self.bucket_dir = os.path.join(workdir, "buckets")
        self.config: Dict = {}          # the dict form of node.cfg
        self.proc_ev = None             # ProcessExitEvent while running
        self.exit_code: Optional[int] = None
        self.killed_at_seq: Optional[int] = None
        self.health_timeline: List[Tuple[float, str]] = []
        self.last_info: Optional[dict] = None

    # -- HTTP ---------------------------------------------------------------
    def http_json(self, path: str, timeout: float = 2.0) -> Optional[dict]:
        url = f"http://127.0.0.1:{self.http_port}{path}"
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return json.loads(r.read())
        except urllib.error.HTTPError as e:
            try:
                return json.loads(e.read())
            except ValueError:
                return None
        except (urllib.error.URLError, OSError, ValueError):
            return None

    def info(self) -> Optional[dict]:
        doc = self.http_json("/info")
        if doc is not None:
            self.last_info = doc.get("info")
        return self.last_info if doc is not None else None

    def health_status(self) -> str:
        doc = self.http_json("/health")
        if doc is None:
            return "unreachable"
        return doc.get("status", "unreachable")

    @property
    def running(self) -> bool:
        return self.proc_ev is not None and self.proc_ev.exit_code is None

    @property
    def node_id_hex(self) -> str:
        return self.secret.public_key.ed25519.hex()


# ---------------------------------------------------------------------------
# traffic client
# ---------------------------------------------------------------------------

class TrafficClient:
    """SeedAccountPool-keyed load over real `/tx`.

    The pool's seed-derived keys fund real accounts (root create-account
    txs through the admission pipeline like everyone else), then drive
    surge-priced payments — randomized fees, sources rotated round-robin
    so each account has at most one tx in flight per close — across the
    fleet's live nodes.  Statuses are counted client-side: `pending` is
    accepted load, `try-again-later` is shed (the back-pressure surface),
    `error` triggers a sequence-number refetch."""

    def __init__(self, fleet: "Fleet", n_accounts: int = 60, seed: int = 7):
        self.fleet = fleet
        self.pool = SeedAccountPool(n_accounts, seed=seed)
        self.rng = random.Random(seed ^ 0xF1EE7)
        self.seqs: Dict[int, int] = {}     # pool index -> last used seqNum
        self.statuses: Dict[str, int] = {}
        self.offered = 0
        self.rate_per_s = 0.0              # offered tx/s (0 = paused)
        self._accum = 0.0
        self._last_pump = monotonic_now()
        self.first_accept_t: Optional[float] = None
        self.last_accept_t: Optional[float] = None
        # a well-behaved client keeps ONE tx in flight per account (the
        # queue is replace-by-fee): account -> fleet ledger at submission,
        # released once a close has had a chance to apply it
        self._in_flight: Dict[int, int] = {}

    # -- funding ------------------------------------------------------------
    def _ledger_entry_seq(self, node: FleetNode,
                          account_id: X.AccountID) -> Optional[int]:
        key = X.LedgerKey.account(
            X.LedgerKeyAccount(accountID=account_id)).to_xdr().hex()
        doc = node.http_json(f"/getledgerentry?key={key}", timeout=5.0)
        if not doc or not doc.get("found"):
            return None
        entry = X.LedgerEntry.from_xdr(bytes.fromhex(doc["entry_xdr"]))
        return entry.data.value.seqNum

    def fund(self, timeout_s: float = 60.0) -> None:
        """Create every pool account from root, through a live node."""
        from ..testutils import build_tx, create_account_op
        fleet = self.fleet
        node = fleet.live_nodes()[0]
        root_sk = SecretKey(fleet.network_id)
        root_id = X.AccountID.ed25519(root_sk.public_key.ed25519)
        root_seq = self._ledger_entry_seq(node, root_id)
        if root_seq is None:
            raise RuntimeError("root account unreadable; node not serving")
        ops = [create_account_op(self.pool.account_id(i), 10_000_000_000)
               for i in range(self.pool.n)]
        from ..xdr.transaction import MAX_OPS_PER_TX
        deadline = monotonic_now() + timeout_s
        # one wave per root tx, externalized before the next: the queue
        # holds ONE pending tx per source account (replace-by-fee), so
        # back-to-back root txs would shed each other
        for lo in range(0, len(ops), MAX_OPS_PER_TX):
            hi = min(len(ops), lo + MAX_OPS_PER_TX)
            root_seq += 1
            frame = build_tx(fleet.network_id, root_sk, root_seq,
                             ops[lo:hi], fee=10_000)
            res = self._submit(node, frame)
            if res not in ("PENDING", "DUPLICATE"):
                raise RuntimeError(f"funding tx rejected: {res}")
            sentinel = self.pool.account_id(hi - 1)
            while monotonic_now() < deadline:
                if self._ledger_entry_seq(node, sentinel) is not None:
                    break
                time.sleep(0.2)
            else:
                raise RuntimeError(
                    f"funding wave at {lo} never externalized")
        # learn every account's creation seq (creation ledger << 32)
        for i in range(self.pool.n):
            seq = self._ledger_entry_seq(node, self.pool.account_id(i))
            if seq is None:
                raise RuntimeError(f"pool account {i} missing after fund")
            self.seqs[i] = seq

    # -- pumping ------------------------------------------------------------
    def _submit(self, node: FleetNode, frame) -> str:
        blob = frame.envelope.to_xdr().hex()
        doc = node.http_json(f"/tx?blob={blob}", timeout=12.0)
        status = (doc or {}).get("status", "UNREACHABLE")
        self.statuses[status] = self.statuses.get(status, 0) + 1
        return status

    def pump(self) -> None:
        """Offer the load accrued since the last call at `rate_per_s`."""
        from ..testutils import build_tx, native_payment_op
        now = monotonic_now()
        dt, self._last_pump = now - self._last_pump, now
        if self.rate_per_s <= 0 or not self.seqs:
            return
        self._accum = min(self._accum + dt * self.rate_per_s,
                          3.0 * self.rate_per_s)
        # each submission is a blocking HTTP round trip: bound the burst a
        # stall can accrue so one pump() never wedges the run loop
        n = min(int(self._accum), 50)
        if n <= 0:
            return
        self._accum -= n
        # route like a load balancer: prefer nodes whose /health answers
        # ok (a partitioned/stalled node is exactly what the probe is for)
        live = self.fleet.healthy_nodes() or self.fleet.live_nodes()
        if not live:
            return
        cur_seq = self.fleet.max_seq()
        # a tx submitted at ledger S externalizes in S+1 or S+2: hold the
        # account until then or the resubmission just TALs on the queue's
        # replace-by-fee rule
        self._in_flight = {i: s for i, s in self._in_flight.items()
                           if s > cur_seq - 2}
        for k in range(n):
            i = self._pick_account()
            if i is None:
                break   # every account has a tx in flight; next tick
            j = self.rng.randrange(self.pool.n)
            node = live[(self.offered + k) % len(live)]
            seq = self.seqs[i] + 1
            frame = build_tx(
                self.fleet.network_id, self.pool.secret(i), seq,
                [native_payment_op(self.pool.account_id(j), 100)],
                fee=100 + self.rng.randrange(400))   # surge-priced spread
            status = self._submit(node, frame)
            self.offered += 1
            if status == "PENDING":
                self.seqs[i] = seq
                self._in_flight[i] = cur_seq
                if self.first_accept_t is None:
                    self.first_accept_t = now
                self.last_accept_t = now
            elif status == "ERROR":
                # usually a seq desync after shedding: refetch and go on
                got = self._ledger_entry_seq(node, self.pool.account_id(i))
                if got is not None:
                    self.seqs[i] = got

    def _pick_account(self) -> Optional[int]:
        for _ in range(8):
            i = self.rng.randrange(self.pool.n)
            if i not in self._in_flight:
                return i
        free = [i for i in range(self.pool.n) if i not in self._in_flight]
        return self.rng.choice(free) if free else None

    # -- reporting ----------------------------------------------------------
    @property
    def accepted(self) -> int:
        return self.statuses.get("PENDING", 0)

    @property
    def shed(self) -> int:
        # AddResult.STATUS_TRY_AGAIN_LATER upper-cased by submit_tx
        return self.statuses.get("TRY-AGAIN-LATER", 0)

    def shed_rate(self) -> float:
        return self.shed / self.offered if self.offered else 0.0

    def report(self) -> dict:
        out = {
            "accounts": self.pool.n,
            "offered": self.offered,
            "statuses": dict(self.statuses),
            "shed_rate": round(self.shed_rate(), 4),
        }
        if self.first_accept_t is not None \
                and self.last_accept_t is not None \
                and self.last_accept_t > self.first_accept_t:
            out["accepted_tps"] = round(
                self.accepted / (self.last_accept_t - self.first_accept_t),
                1)
        return out


# ---------------------------------------------------------------------------
# schedule events
# ---------------------------------------------------------------------------

class FleetEvent:
    """One scripted production event.  ``start`` fires once; ``poll``
    returns True when the event has fully completed (the schedule is
    strictly sequential)."""

    kind = "?"

    def __init__(self, **params):
        self.params = params
        self.started_at: Optional[float] = None

    def start(self, fleet: "Fleet") -> None:
        pass

    def poll(self, fleet: "Fleet") -> bool:
        return True

    def describe(self) -> dict:
        return {"kind": self.kind, **self.params}


class WaitLedger(FleetEvent):
    kind = "wait-ledger"

    def poll(self, fleet):
        return fleet.max_seq() >= self.params["seq"]


class WaitSeconds(FleetEvent):
    kind = "wait-s"

    def poll(self, fleet):
        return monotonic_now() - self.started_at >= self.params["s"]


class SetTraffic(FleetEvent):
    kind = "traffic"

    def start(self, fleet):
        fleet.client.rate_per_s = float(self.params["rate_per_s"])


class KillNode(FleetEvent):
    kind = "kill"

    def start(self, fleet):
        node = fleet.nodes[self.params["node"]]
        node.killed_at_seq = fleet.max_seq()
        fleet.kill_node(node.index)
        fleet.note(f"killed node {node.index} at fleet ledger "
                   f"{node.killed_at_seq} (SIGKILL mid-slot)")

    def poll(self, fleet):
        return not fleet.nodes[self.params["node"]].running


class RejoinNode(FleetEvent):
    """Wipe the node's state, replay the fleet's live archive with
    `catchup --parallel`, restart the process, and wait until it tracks
    the live network again.  Measures kill -> tracking wall seconds."""

    kind = "rejoin"

    def __init__(self, **params):
        super().__init__(**params)
        self._phase = "wait-archive"
        self._catchup_ev = None
        self._restarted_at: Optional[float] = None

    def start(self, fleet):
        self._node = fleet.nodes[self.params["node"]]
        self._workers = int(self.params.get("parallel", 2))

    def poll(self, fleet):
        node = self._node
        if self._phase == "wait-archive":
            # the archive must cover the kill point before a catchup can
            # bridge the gap (the writer keeps publishing meanwhile)
            has_seq = fleet.archive_tip()
            need = (node.killed_at_seq or 0)
            if has_seq is None or has_seq < need:
                return False
            fleet.note(f"rejoin node {node.index}: archive tip {has_seq} "
                       f"covers kill seq {need}; wiping state and starting "
                       f"catchup --parallel {self._workers}")
            fleet.wipe_node_state(node.index)
            self._catchup_ev = fleet.start_catchup(node.index,
                                                   self._workers)
            self._phase = "catchup"
            return False
        if self._phase == "catchup":
            if self._catchup_ev.exit_code is None:
                return False
            if self._catchup_ev.exit_code != 0:
                fleet.violation(
                    f"rejoin node {node.index}: catchup --parallel exited "
                    f"{self._catchup_ev.exit_code} (see "
                    f"{node.workdir}/catchup.log)")
                return True
            fleet.note(f"rejoin node {node.index}: parallel catchup "
                       "complete; restarting")
            # a restarted node syncs from its peers (FORCE_SCP only
            # bootstraps a genesis network)
            node.config["FORCE_SCP"] = False
            fleet.write_config(node.index)
            fleet.start_node(node.index)
            self._restarted_at = monotonic_now()
            self._phase = "retrack"
            return False
        # retrack: tracking again and within a slot of the fleet tip
        info = node.info()
        if info and info.get("state") == "tracking" \
                and info["ledger"]["num"] >= fleet.max_seq() - 2:
            secs = monotonic_now() - self._restarted_at
            total = monotonic_now() - self.started_at
            fleet.metrics["retracking_s"] = round(secs, 1)
            fleet.metrics["kill_to_retracking_s"] = round(total, 1)
            fleet.note(f"rejoin node {node.index}: TRACKING again at "
                       f"ledger {info['ledger']['num']} "
                       f"({secs:.1f}s after restart, {total:.1f}s after "
                       "the rejoin began)")
            return True
        return False


class Partition(FleetEvent):
    """Drop the TCP links between node groups and keep them down: both
    sides ban each other (auth-time refusal beats redial) and the live
    connections are dropped through the admin surface."""

    kind = "partition"

    def start(self, fleet):
        groups: List[List[int]] = self.params["groups"]
        fleet.partition_pairs = []
        for gi, ga in enumerate(groups):
            for gb in groups[gi + 1:]:
                for a in ga:
                    for b in gb:
                        fleet.partition_pairs.append((a, b))
        for a, b in fleet.partition_pairs:
            fleet.sever_link(a, b)
        fleet.note(f"partitioned overlay into {groups} "
                   f"({len(fleet.partition_pairs)} links severed)")


class Heal(FleetEvent):
    kind = "heal"

    def start(self, fleet):
        for a, b in fleet.partition_pairs:
            fleet.restore_link(a, b)
        fleet.note(f"healed partition ({len(fleet.partition_pairs)} links "
                   "restored)")
        fleet.partition_pairs = []

    def poll(self, fleet):
        # healed means: every live node tracks again within the timeout
        timeout = float(self.params.get("timeout_s", 60.0))
        lagging = []
        for node in fleet.live_nodes():
            info = node.info()
            if not info or info.get("state") != "tracking" \
                    or info["ledger"]["num"] < fleet.max_seq() - 3:
                lagging.append(node.index)
        if not lagging:
            fleet.note("partition healed: every node tracking again")
            return True
        if monotonic_now() - self.started_at > timeout:
            fleet.violation(
                f"heal: nodes {lagging} never re-tracked within "
                f"{timeout:.0f}s")
            return True
        return False


class RollingConfig(FleetEvent):
    """Roll a config change through the fleet one node at a time:
    graceful stop, rewrite config with the overrides, restart, wait for
    tracking — the next node only rolls once the previous one is back."""

    kind = "rolling-config"

    def __init__(self, **params):
        super().__init__(**params)
        self._queue: List[int] = []
        self._current: Optional[int] = None
        self._phase = "idle"
        self._node_t0 = 0.0

    def start(self, fleet):
        self._queue = list(self.params.get(
            "nodes", [n.index for n in fleet.nodes]))
        fleet.metrics.setdefault("roll_node_s", {})

    def poll(self, fleet):
        timeout = float(self.params.get("per_node_timeout_s", 60.0))
        if self._phase == "idle":
            if not self._queue:
                fleet.note("rolling config change complete")
                return True
            self._current = self._queue.pop(0)
            self._node_t0 = monotonic_now()
            node = fleet.nodes[self._current]
            fleet.note(f"rolling node {self._current}: graceful stop")
            fleet.stop_node(self._current)
            self._phase = "stopping"
            return False
        node = fleet.nodes[self._current]
        if self._phase == "stopping":
            if node.running:
                return False
            node.config.update(self.params["overrides"])
            node.config["FORCE_SCP"] = False
            fleet.write_config(self._current)
            fleet.start_node(self._current)
            self._phase = "restarting"
            return False
        # restarting: wait tracking (or per-node timeout -> violation)
        info = node.info()
        if info and info.get("state") == "tracking" \
                and info["ledger"]["num"] >= fleet.max_seq() - 2:
            secs = round(monotonic_now() - self._node_t0, 1)
            fleet.metrics["roll_node_s"][str(self._current)] = secs
            fleet.note(f"rolling node {self._current}: tracking again "
                       f"with new config ({secs}s)")
            self._phase = "idle"
            return False
        if monotonic_now() - self._node_t0 > timeout:
            fleet.violation(
                f"rolling-config: node {self._current} never re-tracked "
                f"within {timeout:.0f}s")
            self._phase = "idle"
            return False
        return False


_EVENT_KINDS = {
    "wait-ledger": WaitLedger,
    "wait-s": WaitSeconds,
    "traffic": SetTraffic,
    "kill": KillNode,
    "rejoin": RejoinNode,
    "partition": Partition,
    "heal": Heal,
    "rolling-config": RollingConfig,
}


_REQUIRED_PARAMS = {
    "wait-ledger": ("seq",),
    "wait-s": ("s",),
    "traffic": ("rate_per_s",),
    "kill": ("node",),
    "rejoin": ("node",),
    "partition": ("groups",),
    "heal": (),
    "rolling-config": ("overrides",),
}


def parse_schedule(entries: List[dict],
                   n_nodes: Optional[int] = None) -> List[FleetEvent]:
    """JSON-friendly schedule -> event objects (the `fleet --schedule`
    file format; see README §Fleet soak).  Schedules are user input:
    missing required params — and, when `n_nodes` is known, node indices
    out of range — fail HERE, with the entry index, not as a
    KeyError/IndexError mid-soak after the fleet booted."""
    events = []
    for idx, entry in enumerate(entries):
        entry = dict(entry)
        kind = entry.pop("kind", None)
        cls = _EVENT_KINDS.get(kind)
        if cls is None:
            raise ValueError(f"schedule entry {idx}: unknown fleet event "
                             f"kind {kind!r} (know: {sorted(_EVENT_KINDS)})")
        missing = [p for p in _REQUIRED_PARAMS[kind] if p not in entry]
        if missing:
            raise ValueError(f"schedule entry {idx} ({kind}): missing "
                             f"required param(s) {missing}")
        if n_nodes is not None:
            named = []
            if "node" in entry:
                named.append(entry["node"])
            for group in entry.get("groups", []):
                named.extend(group)
            named.extend(entry.get("nodes", []))
            bad = [n for n in named
                   if not isinstance(n, int) or not 0 <= n < n_nodes]
            if bad:
                raise ValueError(
                    f"schedule entry {idx} ({kind}): node index(es) {bad} "
                    f"out of range for a {n_nodes}-node fleet")
        events.append(cls(**entry))
    return events


def standard_schedule(n_nodes: int = 5, kill_node: int = 2,
                      traffic_rate: float = 25.0,
                      partition_s: float = 6.0,
                      roll_nodes: Optional[List[int]] = None) -> List[dict]:
    """The acceptance-bar production-event script: sustained traffic
    through a kill + parallel-catchup rejoin, an overlay partition +
    heal, and a rolling config change.  The minority side of the
    partition is the last (n-1)//2 nodes, so the majority side always
    still meets the n//2+1 threshold (even fleet sizes included) and
    node 0 keeps closing ledgers and publishing checkpoints
    throughout."""
    minority = [i for i in range(n_nodes)
                if i >= n_nodes - ((n_nodes - 1) // 2)]
    majority = [i for i in range(n_nodes) if i not in minority]
    return [
        {"kind": "traffic", "rate_per_s": traffic_rate},
        {"kind": "wait-ledger", "seq": 6},
        {"kind": "kill", "node": kill_node},
        {"kind": "rejoin", "node": kill_node, "parallel": 2},
        {"kind": "partition", "groups": [majority, minority]},
        {"kind": "wait-s", "s": partition_s},
        {"kind": "heal"},
        {"kind": "rolling-config",
         "overrides": {"ADMISSION_BATCH_SIZE": 128, "LOG_LEVEL": "WARNING"},
         "nodes": roll_nodes if roll_nodes is not None
         else list(range(n_nodes))},
        {"kind": "wait-s", "s": 3.0},
    ]


# ---------------------------------------------------------------------------
# the fleet
# ---------------------------------------------------------------------------

class Fleet:
    def __init__(self, workdir: str, n_nodes: int = 5,
                 passphrase: str = "fleet soak net",
                 checkpoint_frequency: int = DEFAULT_CHECKPOINT_FREQUENCY,
                 threshold: Optional[int] = None,
                 n_accounts: int = 60,
                 slos: Optional[FleetSLOs] = None,
                 native_close_differential: int = 8,
                 python: str = sys.executable):
        self.workdir = os.path.abspath(workdir)
        self.n_nodes = n_nodes
        self.passphrase = passphrase
        self.network_id = sha256(passphrase.encode())
        self.checkpoint_frequency = checkpoint_frequency
        # simple majority: any two quorums intersect (t + t > n) while a
        # minority partition side stalls instead of forking
        self.threshold = threshold or (n_nodes // 2 + 1)
        # every soak carries native-live-close differential spot-checks
        # (ROADMAP 1c): each node's Nth close also runs the Python oracle
        # on a scratch copy and fail-stops with a crash bundle on any
        # divergence — a silent C-engine regression cannot survive a soak.
        # 0 disables (pure-Python closes keep the cadence key harmless).
        self.native_close_differential = max(0, native_close_differential)
        self.archive_dir = os.path.join(self.workdir, "archive")
        self.crash_dir = os.path.join(self.workdir, "crash-bundles")
        self.clock = VirtualClock(ClockMode.REAL_TIME)
        self.pm = ProcessManager(self.clock, max_concurrent=4 * n_nodes)
        self.nodes: List[FleetNode] = []
        self.client = TrafficClient(self, n_accounts=n_accounts)
        self.slos = slos or FleetSLOs()
        self.python = python
        self.hash_by_seq: Dict[int, Dict[int, str]] = {}
        self.events_log: List[dict] = []
        self.violations: List[str] = []
        self.metrics: Dict = {}
        self.partition_pairs: List[Tuple[int, int]] = []
        self._t0 = monotonic_now()
        self._last_sample = 0.0
        self._last_trace_poll = 0.0
        self._archive_tip_cache: Tuple[float, Optional[int]] = (0.0, None)
        # observability plane: incremental /tracespans collection into
        # ONE merged cross-node Chrome trace, plus a /metrics scraper
        # feeding SLO curves and a fleet-wide burn tracker
        self.trace_collector = FleetTraceCollector()
        self.slo_tracker: Optional[SLOTracker] = None
        if self.slos.burn_budget is not None:
            self.slo_tracker = SLOTracker(
                default_objectives(
                    close_p99_s=self.slos.max_p99_close_s or 2.0,
                    budget=self.slos.burn_budget),
                source="fleet")
        self.scraper: Optional[FleetScraper] = None

    # -- provisioning -------------------------------------------------------
    @staticmethod
    def _free_ports(n: int) -> List[int]:
        import socket as pysock
        socks, ports = [], []
        for _ in range(n):
            s = pysock.socket()
            s.bind(("127.0.0.1", 0))
            socks.append(s)
            ports.append(s.getsockname()[1])
        for s in socks:
            s.close()
        return ports

    def provision(self) -> None:
        """Per-node workdirs, deterministic validator keys, the shared
        quorum set, and one shared file archive (node 0 is the single
        writer — every node reads it for catchup)."""
        os.makedirs(self.archive_dir, exist_ok=True)
        os.makedirs(self.crash_dir, exist_ok=True)
        ports = self._free_ports(2 * self.n_nodes)
        peer_ports, http_ports = (ports[:self.n_nodes],
                                  ports[self.n_nodes:])
        secrets = [SecretKey(sha256(b"fleet node %d " % i
                                    + self.network_id))
                   for i in range(self.n_nodes)]
        validators = [s.public_key.to_strkey() for s in secrets]
        for i in range(self.n_nodes):
            nd = os.path.join(self.workdir, f"node-{i}")
            os.makedirs(nd, exist_ok=True)
            node = FleetNode(i, nd, secrets[i], peer_ports[i],
                             http_ports[i])
            peers = [f"127.0.0.1:{peer_ports[j]}"
                     for j in range(self.n_nodes) if j != i]
            node.config = {
                "NETWORK_PASSPHRASE": self.passphrase,
                "NODE_SEED": secrets[i].to_strkey_seed(),
                "NODE_IS_VALIDATOR": True,
                "FORCE_SCP": True,     # genesis bootstrap; restarts clear it
                "PEER_PORT": node.peer_port,
                "HTTP_PORT": node.http_port,
                "KNOWN_PEERS": peers,
                "TARGET_PEER_CONNECTIONS": self.n_nodes + 2,
                "DATABASE": node.db_path,
                "BUCKET_DIR_PATH": node.bucket_dir,
                "ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING": True,
                "CHECKPOINT_FREQUENCY": self.checkpoint_frequency,
                # live-close differential cadence rides in every node
                # config, so every soak spot-checks the native engine
                "NATIVE_CLOSE_DIFFERENTIAL": self.native_close_differential,
                # observability plane: stable node name on every span,
                # flight event and phase mark, plus in-process SLO burn
                # evaluation on a 1s cadence
                "NODE_NAME": f"node-{i}",
                "SLO_EVAL_CADENCE_S": 1.0,
                "LOG_LEVEL": "INFO",
                "QUORUM_SET": {"THRESHOLD": self.threshold,
                               "VALIDATORS": validators},
                # one shared archive every validator publishes to and
                # catches up from.  Concurrent writers are safe: the
                # objects are content-identical at a given checkpoint
                # (same headers, same buckets) and FileHistoryArchive
                # writes are atomic with pid-unique tmp files, so a
                # reader never observes a torn object.
                "HISTORY": {"fleet": {"get": self.archive_dir,
                                      "put": self.archive_dir}},
            }
            self.nodes.append(node)
            self.write_config(i)
        self.note(f"provisioned {self.n_nodes} nodes "
                  f"(threshold {self.threshold}, checkpoint frequency "
                  f"{self.checkpoint_frequency}, archive {self.archive_dir})")

    def write_config(self, index: int) -> None:
        """Render the node's config dict as the TOML subset node.cfg."""
        node = self.nodes[index]
        cfg = node.config
        lines = []
        for key, val in cfg.items():
            if key in ("QUORUM_SET", "HISTORY"):
                continue
            lines.append(f"{key} = {json.dumps(val)}")
        q = cfg["QUORUM_SET"]
        lines.append("\n[QUORUM_SET]")
        lines.append(f"THRESHOLD = {q['THRESHOLD']}")
        lines.append(f"VALIDATORS = {json.dumps(q['VALIDATORS'])}")
        for name, spec in cfg["HISTORY"].items():
            lines.append(f"\n[HISTORY.{name}]")
            for k, v in spec.items():
                lines.append(f"{k} = {json.dumps(v)}")
        with open(node.conf_path, "w") as f:
            f.write("\n".join(lines) + "\n")

    # -- process control ----------------------------------------------------
    def _run_cmdline(self, node: FleetNode) -> str:
        return " ".join(shlex.quote(a) for a in [
            self.python, "-m", "stellar_core_tpu", "run",
            "--conf", node.conf_path])

    def start_node(self, index: int) -> None:
        node = self.nodes[index]
        if node.running:
            return
        node.exit_code = None

        def on_exit(code: int, node=node) -> None:
            node.exit_code = code
            log.info("fleet node %d exited %d", node.index, code)

        node.proc_ev = self.pm.run_command(
            self._run_cmdline(node), on_exit, output_path=node.log_path)
        self.crank()

    def start(self) -> None:
        # children inherit the env: node crashes dump bundles into the
        # fleet's artifact dir (restored on teardown)
        self._prev_crash_dir = os.environ.get("STPU_CRASH_DIR")
        os.environ["STPU_CRASH_DIR"] = self.crash_dir
        for i in range(self.n_nodes):
            self.start_node(i)
        self.scraper = FleetScraper(
            {f"node-{i}": self._metrics_fetcher(self.nodes[i])
             for i in range(self.n_nodes)},
            tracker=self.slo_tracker,
            # per-node regression verdicts in fleet-report.json; bound
            # ring memory against nodes that leave the fleet for good
            anomaly=True, retention_s=600.0).start()
        self.note(f"launched {self.n_nodes} run processes")

    def _metrics_fetcher(self, node: FleetNode):
        """Scraper fetcher: the node's flat metric registry snapshot;
        raises when the node is unreachable (killed / mid-restart) so
        the sweep counts it as a scrape error."""
        def fetch() -> dict:
            doc = node.http_json("/metrics", timeout=5.0)
            if doc is None:
                raise RuntimeError(
                    f"node {node.index} /metrics unreachable")
            return doc.get("metrics", {}).get("registry", {})
        return fetch

    def kill_node(self, index: int) -> None:
        """SIGKILL — the crash shape (rejoin brings it back)."""
        node = self.nodes[index]
        if node.proc_ev is not None and node.proc_ev.exit_code is None \
                and node.proc_ev.proc is not None:
            node.proc_ev.proc.kill()
        self.crank()

    def stop_node(self, index: int, grace_s: float = 8.0) -> None:
        """Graceful stop with SIGTERM -> SIGKILL escalation."""
        node = self.nodes[index]
        if node.proc_ev is not None:
            self.pm.stop(node.proc_ev, grace_s=grace_s)
        self.crank()

    def wipe_node_state(self, index: int) -> None:
        """Drop a dead node's durable state (db + wal + buckets) so the
        rejoin replays the fleet's archive from scratch — the 'new node
        joins the network' production shape."""
        import shutil
        node = self.nodes[index]
        for suffix in ("", "-wal", "-shm"):
            try:
                os.unlink(node.db_path + suffix)
            except FileNotFoundError:
                pass
        shutil.rmtree(node.bucket_dir, ignore_errors=True)
        shutil.rmtree(os.path.join(node.workdir, "catchup-par"),
                      ignore_errors=True)

    def start_catchup(self, index: int, workers: int):
        """`catchup --parallel N` against the fleet's live archive, as a
        real subprocess (its log is part of the flight record)."""
        node = self.nodes[index]
        cmd = " ".join(shlex.quote(a) for a in [
            self.python, "-m", "stellar_core_tpu", "catchup",
            "--conf", node.conf_path, "--parallel", str(workers)])
        return self.pm.run_command(
            cmd, lambda code: None,
            output_path=os.path.join(node.workdir, "catchup.log"))

    def teardown(self, grace_s: float = 8.0) -> None:
        if self.scraper is not None:
            self.scraper.stop()
        self.pm.shutdown(grace_s=grace_s)
        prev = getattr(self, "_prev_crash_dir", None)
        if prev is None:
            os.environ.pop("STPU_CRASH_DIR", None)
        else:
            os.environ["STPU_CRASH_DIR"] = prev

    # -- overlay surgery ----------------------------------------------------
    def sever_link(self, a: int, b: int) -> None:
        """Drop the TCP link a<->b and keep it down: mutual bans (refused
        at auth time, beating the redial timer) plus dropping the live
        connections — all through the real admin surface."""
        na, nb = self.nodes[a], self.nodes[b]
        na.http_json(f"/ban?node={nb.node_id_hex}")
        nb.http_json(f"/ban?node={na.node_id_hex}")
        na.http_json(f"/droppeer?node={nb.node_id_hex}")
        nb.http_json(f"/droppeer?node={na.node_id_hex}")

    def restore_link(self, a: int, b: int) -> None:
        na, nb = self.nodes[a], self.nodes[b]
        na.http_json(f"/unban?node={nb.node_id_hex}")
        nb.http_json(f"/unban?node={na.node_id_hex}")

    # -- observation --------------------------------------------------------
    def crank(self) -> None:
        self.clock.crank()

    def live_nodes(self) -> List[FleetNode]:
        return [n for n in self.nodes if n.running]

    def healthy_nodes(self) -> List[FleetNode]:
        """Nodes whose most recent /health sample answered ok — the
        load-balancer routing set."""
        return [n for n in self.nodes if n.running and n.health_timeline
                and n.health_timeline[-1][1] == "ok"]

    def max_seq(self) -> int:
        return max((n.last_info["ledger"]["num"] for n in self.nodes
                    if n.last_info), default=0)

    def archive_tip(self) -> Optional[int]:
        """The shared archive's HAS currentLedger (cached ~1s — the HAS
        is a tiny JSON file but the run loop is hot)."""
        now = monotonic_now()
        at, tip = self._archive_tip_cache
        if now - at < 1.0:
            return tip
        tip = None
        try:
            with open(os.path.join(
                    self.archive_dir,
                    ".well-known/stellar-history.json")) as f:
                tip = json.load(f).get("currentLedger")
        except (OSError, ValueError):
            pass
        self._archive_tip_cache = (now, tip)
        return tip

    def sample(self) -> None:
        """Poll every node's /info + /health into the timelines; collect
        (seq -> hash) pairs for the divergence proof."""
        t = round(monotonic_now() - self._t0, 1)
        for node in self.nodes:
            if not node.running:
                node.health_timeline.append((t, "down"))
                continue
            info = node.info()
            node.health_timeline.append((t, node.health_status()))
            if info:
                seq = info["ledger"]["num"]
                h = info["ledger"]["hash"]
                seen = self.hash_by_seq.setdefault(seq, {})
                prev = seen.get(node.index)
                if prev is not None and prev != h:
                    self.violation(
                        f"node {node.index} changed its hash for ledger "
                        f"{seq}: {prev[:16]} -> {h[:16]}")
                seen[node.index] = h

    def poll_traces(self) -> int:
        """Incremental /tracespans sweep over live nodes into the trace
        collector; returns new marks+spans folded in.  An unreachable
        node keeps what it already contributed."""
        got = 0
        for node in self.live_nodes():
            def fetch(path: str, node=node) -> dict:
                doc = node.http_json(path, timeout=5.0)
                if doc is None:
                    raise RuntimeError(
                        f"node {node.index} {path} unreachable")
                return doc
            try:
                got += self.trace_collector.poll(
                    f"node-{node.index}", fetch)
            except Exception:  # corelint: disable=exception-hygiene -- a killed node must not stop the sweep; its already-collected spans stay in the merge
                continue
        return got

    def note(self, msg: str) -> None:
        t = round(monotonic_now() - self._t0, 1)
        self.events_log.append({"t_s": t, "event": msg})
        log.info("[%.1fs] %s", t, msg)

    def violation(self, msg: str) -> None:
        self.violations.append(msg)
        self.note(f"SLO VIOLATION: {msg}")

    # -- readiness ----------------------------------------------------------
    def wait_all_healthy(self, timeout_s: float = 60.0) -> None:
        deadline = monotonic_now() + timeout_s
        while monotonic_now() < deadline:
            self.crank()
            self.sample()
            dead = [n.index for n in self.nodes
                    if n.proc_ev is not None and n.proc_ev.exit_code
                    not in (None, 0)]
            if dead:
                raise RuntimeError(
                    f"nodes {dead} died during boot (see node.log)")
            if all(n.health_timeline and n.health_timeline[-1][1] == "ok"
                   for n in self.nodes):
                self.note("all nodes healthy (every /health answers ok)")
                return
            time.sleep(0.3)
        states = {n.index: (n.health_timeline[-1][1]
                            if n.health_timeline else "?")
                  for n in self.nodes}
        raise RuntimeError(f"fleet never became healthy: {states}")

    # -- the run loop -------------------------------------------------------
    def run(self, schedule: List[dict],
            settle_ledgers: int = 3,
            timeout_s: float = 600.0) -> dict:
        """Execute the event schedule sequentially against the live
        fleet while traffic pumps and samples accumulate, then evaluate
        the SLOs and write the replayable report."""
        self._schedule_input = list(schedule)
        events = parse_schedule(schedule, n_nodes=self.n_nodes)
        deadline = monotonic_now() + timeout_s
        idx = 0
        current: Optional[FleetEvent] = None
        while monotonic_now() < deadline:
            self.crank()
            self.client.pump()
            now = monotonic_now()
            if now - self._last_sample >= 0.25:
                self._last_sample = now
                self.sample()
            if now - self._last_trace_poll >= 1.0:
                self._last_trace_poll = now
                self.poll_traces()
            if current is None:
                if idx >= len(events):
                    break
                current = events[idx]
                current.started_at = now
                self.note(f"event {idx}: {current.describe()}")
                current.start(self)
            if current.poll(self):
                idx += 1
                current = None
            time.sleep(0.05)
        else:
            self.violation(f"schedule never completed within {timeout_s}s "
                           f"(stalled at event {idx})")
        # settle: stop traffic, let the tail externalize so the final
        # divergence sweep compares settled hashes
        self.client.rate_per_s = 0.0
        settle_to = self.max_seq() + settle_ledgers
        settle_deadline = monotonic_now() + 30.0
        while monotonic_now() < settle_deadline \
                and self.max_seq() < settle_to:
            self.crank()
            self.sample()
            time.sleep(0.2)
        return self.finalize()

    # -- verdicts -----------------------------------------------------------
    def check_divergence(self) -> int:
        """Zero ledger-hash divergence: every (seq, node) sample must
        agree per seq.  Returns the number of seqs compared."""
        compared = 0
        for seq in sorted(self.hash_by_seq):
            hashes = set(self.hash_by_seq[seq].values())
            if len(self.hash_by_seq[seq]) > 1:
                compared += 1
            if len(hashes) > 1:
                self.violation(
                    f"HASH DIVERGENCE at ledger {seq}: "
                    + ", ".join(f"node {n}={h[:16]}" for n, h in
                                sorted(self.hash_by_seq[seq].items())))
        return compared

    def p99_close_s(self) -> Optional[float]:
        """Worst per-node ledger.ledger.close p99 from /metrics."""
        worst = None
        for node in self.live_nodes():
            doc = node.http_json("/metrics", timeout=5.0)
            if not doc:
                continue
            reg = doc.get("metrics", {}).get("registry", {})
            row = reg.get("ledger.ledger.close")
            if row and "p99_s" in row:
                worst = max(worst or 0.0, row["p99_s"])
        return worst

    def native_close_counters(self) -> Dict[str, int]:
        """Fleet-wide native live-close evidence from /metrics: closes
        driven by the C engine, differential spot-checks actually run
        (the NATIVE_CLOSE_DIFFERENTIAL cadence provisioned into every
        node), and per-close Python fallbacks.  A divergence never shows
        up here — the node fail-stops with a crash bundle and the soak's
        crash-dir/violation machinery reports it."""
        out = {"native_closes": 0, "native_differential_checks": 0,
               "native_fallbacks": 0}
        for node in self.live_nodes():
            doc = node.http_json("/metrics", timeout=5.0)
            if not doc:
                continue
            reg = doc.get("metrics", {}).get("registry", {})
            for key, name in (("native_closes", "ledger.native.closes"),
                              ("native_differential_checks",
                               "ledger.native.differential-checks"),
                              ("native_fallbacks",
                               "ledger.native.fallbacks")):
                row = reg.get(name)
                if row and isinstance(row.get("count"), int):
                    out[key] += row["count"]
        return out

    def finalize(self) -> dict:
        # drain the observability plane while nodes are still up: one
        # last metric sweep + trace poll so the merged artifact carries
        # the run's tail
        if self.scraper is not None:
            self.scraper.stop()
            self.scraper.sweep()
        self.poll_traces()
        compared = self.check_divergence()
        slo = self.slos
        p99 = self.p99_close_s()
        if self.native_close_differential:
            self.metrics.update(self.native_close_counters())
        shed = self.client.shed_rate()
        if slo.max_p99_close_s is not None and p99 is not None \
                and p99 > slo.max_p99_close_s:
            self.violation(f"p99 close time {p99:.3f}s exceeds "
                           f"{slo.max_p99_close_s}s")
        if slo.max_shed_rate is not None and shed > slo.max_shed_rate:
            self.violation(f"admission shed rate {shed:.2%} exceeds "
                           f"{slo.max_shed_rate:.0%}")
        retr = self.metrics.get("retracking_s")
        if slo.max_retracking_s is not None and retr is not None \
                and retr > slo.max_retracking_s:
            self.violation(f"time-to-retracking {retr}s exceeds "
                           f"{slo.max_retracking_s}s")
        for n_idx, secs in self.metrics.get("roll_node_s", {}).items():
            if slo.max_roll_node_s is not None \
                    and secs > slo.max_roll_node_s:
                self.violation(f"rolling restart of node {n_idx} took "
                               f"{secs}s (> {slo.max_roll_node_s}s)")
        tps = self.client.report().get("accepted_tps")
        if slo.min_sustained_tps is not None and tps is not None \
                and tps < slo.min_sustained_tps:
            self.violation(f"sustained TPS {tps} below "
                           f"{slo.min_sustained_tps}")
        if self.slo_tracker is not None \
                and not self.slo_tracker.within_budget():
            for o in self.slo_tracker.objectives:
                if self.slo_tracker.burning(o.name):
                    rate = self.slo_tracker.burn_rate(o.name)
                    self.violation(
                        f"SLO burn: objective {o.name} burned "
                        f"{rate:.0%} of its recent windows "
                        f"(budget {o.budget:.0%})")
        trace_path = os.path.join(self.workdir, "fleet-trace.json")
        trace_events = self.trace_collector.write_merged_trace(trace_path)
        self.note(f"merged cross-node trace written to {trace_path} "
                  f"({trace_events} events from "
                  f"{len(self.trace_collector.nodes())} nodes)")
        report = {
            "passed": not self.violations,
            "violations": list(self.violations),
            "nodes": self.n_nodes,
            "threshold": self.threshold,
            "checkpoint_frequency": self.checkpoint_frequency,
            "wall_s": round(monotonic_now() - self._t0, 1),
            "max_ledger": self.max_seq(),
            "divergence_seqs_compared": compared,
            "p99_close_s": p99,
            "traffic": self.client.report(),
            "metrics": self.metrics,
            "archive_tip": self.archive_tip(),
            "observability": {
                "trace_path": trace_path,
                "trace_events": trace_events,
                "trace_nodes": self.trace_collector.nodes(),
                "clock_offsets_s": self.trace_collector.align_offsets(),
                "scraper": (self.scraper.report()
                            if self.scraper is not None else None),
            },
            "schedule": getattr(self, "_schedule_input", []),
            "events": self.events_log,
            "node_artifacts": [
                {"index": n.index,
                 "log": n.log_path,
                 "conf": n.conf_path,
                 "final_info": n.last_info,
                 "health_timeline": n.health_timeline[-200:]}
                for n in self.nodes],
            "crash_dir": self.crash_dir,
        }
        path = os.path.join(self.workdir, "fleet-report.json")
        with open(path, "w") as f:
            json.dump(report, f, indent=1)
        report["report_path"] = path
        self.note(f"fleet report written to {path} "
                  f"({'PASS' if report['passed'] else 'FAIL'})")
        return report


# ---------------------------------------------------------------------------
# one-call soak (the CLI + bench entry point)
# ---------------------------------------------------------------------------

def run_fleet_soak(workdir: str, n_nodes: int = 5,
                   schedule: Optional[List[dict]] = None,
                   traffic_rate: float = 25.0,
                   n_accounts: int = 60,
                   slos: Optional[FleetSLOs] = None,
                   native_close_differential: int = 8,
                   timeout_s: float = 600.0) -> dict:
    """Provision, boot, fund, run the schedule, tear down.  Returns the
    fleet report (never leaks processes — teardown escalates).  Every
    soak provisions NATIVE_CLOSE_DIFFERENTIAL into the node configs so
    live closes carry C-vs-Python spot-checks (0 disables)."""
    if schedule is None:
        schedule = standard_schedule(n_nodes=n_nodes,
                                     traffic_rate=traffic_rate)
    # validate user input (incl. node indices) BEFORE booting anything
    parse_schedule(schedule, n_nodes=n_nodes)
    fleet = Fleet(workdir, n_nodes=n_nodes, n_accounts=n_accounts,
                  slos=slos,
                  native_close_differential=native_close_differential)
    fleet.provision()
    try:
        fleet.start()
        fleet.wait_all_healthy(timeout_s=90.0)
        fleet.client.fund()
        fleet.note(f"traffic pool funded ({n_accounts} accounts)")
        return fleet.run(schedule, timeout_s=timeout_s)
    finally:
        fleet.teardown()
