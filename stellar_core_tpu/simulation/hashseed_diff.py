"""Hash-seed divergence differential: the dynamic proof of determinism.

Python's str/bytes hashing is randomized per process (PYTHONHASHSEED),
so any consensus-path dependence on set/dict hash order forks the
replicated state machine between two validators that happen to boot
with different seeds.  The determinism lint rules ban those shapes
statically; this harness proves the property end-to-end: it runs the
same campaign in paired subprocesses under two *different*
PYTHONHASHSEED values (the seed must be fixed before interpreter start,
hence subprocesses) and asserts the canonical consensus artifacts are
byte-identical:

  flagship  the 51-node partition-flap-heal chaos campaign — the
            campaign-global slot → ledger-hash table
  soroban   the Soroban mixed classic/contract campaign — per-ledger
            bucket-list hashes plus the serial-vs-parallel identity bit

Both children run with the detguard runtime sanitizer armed
(STPU_DETGUARD=1): a wall-clock read, unseeded RNG draw, or str/bytes
hash() inside a guarded consensus region fail-stops the child, so a
green differential also certifies zero guard trips over the whole
campaign.  Wired into `make determinism` and the bench `determinism`
section.
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple

from ..util.clock import monotonic_now

# two arbitrary, distinct seeds: any consensus hash-order dependence
# shows up as a payload diff between them
DEFAULT_SEEDS = (0, 424242)
DEFAULT_FLAGSHIP_ORGS = 17      # 17 orgs x 3 validators = 51 nodes
DEFAULT_SOROBAN_LEDGERS = 50
CAMPAIGNS = ("flagship", "soroban")

_REPO_ROOT = os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))))


# ---------------------------------------------------------------------------
# child mode: run one campaign, emit canonical JSON
# ---------------------------------------------------------------------------

def _campaign_payload(campaign: str, orgs: int, ledgers: int) -> dict:
    import logging
    logging.getLogger("stellar").setLevel(logging.ERROR)
    if campaign == "flagship":
        from . import chaos
        res = chaos.run_scenario(
            chaos.scenario_partition_flap_heal(n_orgs=orgs))
        return {
            "campaign": "flagship",
            "passed": bool(res.passed),
            "nodes": orgs * 3,
            "slot_hashes": {str(s): h.hex()
                            for s, h in sorted(res.slot_hashes.items())},
        }
    if campaign == "soroban":
        from .loadgen import SorobanMixCampaign
        res = SorobanMixCampaign().run(n_ledgers=ledgers)
        return {
            "campaign": "soroban",
            "passed": bool(res["hashes_identical"]),
            "ledgers": int(res["ledgers"]),
            "applied": int(res["applied"]),
            "bucket_hashes": [h.hex() if isinstance(h, (bytes, bytearray))
                              else str(h) for h in res["bucket_hashes"]],
        }
    raise ValueError(f"unknown campaign {campaign!r}")


def _run_child(campaign: str, orgs: int, ledgers: int, out: str) -> None:
    from ..util import detguard
    payload = _campaign_payload(campaign, orgs, ledgers)
    doc = {
        "payload": payload,
        "hashseed": os.environ.get("PYTHONHASHSEED", ""),
        "detguard": {"armed": detguard.enabled(), **detguard.stats()},
    }
    with open(out, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=2, sort_keys=True)


# ---------------------------------------------------------------------------
# parent mode: paired subprocesses under distinct hash seeds
# ---------------------------------------------------------------------------

def _spawn(campaign: str, seed: int, orgs: int, ledgers: int,
           out: str) -> subprocess.Popen:
    env = dict(os.environ)
    env["PYTHONHASHSEED"] = str(seed)
    env["STPU_DETGUARD"] = "1"
    env.setdefault("JAX_PLATFORMS", "cpu")
    env["PYTHONPATH"] = _REPO_ROOT + os.pathsep + env.get("PYTHONPATH", "")
    return subprocess.Popen(
        [sys.executable, "-m", "stellar_core_tpu.simulation.hashseed_diff",
         "--child", "--campaign", campaign, "--orgs", str(orgs),
         "--ledgers", str(ledgers), "--out", out],
        cwd=_REPO_ROOT, env=env,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)


def _first_divergence(a: dict, b: dict) -> Optional[str]:
    if a == b:
        return None
    table_key = "slot_hashes" if "slot_hashes" in a else "bucket_hashes"
    ta, tb = a.get(table_key), b.get(table_key)
    if isinstance(ta, dict) and isinstance(tb, dict):
        for slot in sorted(set(ta) | set(tb), key=int):
            if ta.get(slot) != tb.get(slot):
                return (f"{table_key}[{slot}]: "
                        f"{ta.get(slot)} != {tb.get(slot)}")
    elif isinstance(ta, list) and isinstance(tb, list):
        for i, (x, y) in enumerate(zip(ta, tb)):
            if x != y:
                return f"{table_key}[{i}]: {x} != {y}"
        if len(ta) != len(tb):
            return f"{table_key} length: {len(ta)} != {len(tb)}"
    return "payloads differ outside the hash table"


def run_pair(campaign: str,
             seeds: Tuple[int, int] = DEFAULT_SEEDS,
             orgs: int = DEFAULT_FLAGSHIP_ORGS,
             ledgers: int = DEFAULT_SOROBAN_LEDGERS,
             timeout_s: float = 900.0) -> dict:
    """Run `campaign` under both hash seeds concurrently and compare the
    canonical payloads byte-for-byte.  Returns a report dict; raises
    nothing — failures are encoded in the report (``ok`` False)."""
    t0 = monotonic_now()
    outs, procs = [], []
    for seed in seeds:
        fd, path = tempfile.mkstemp(
            prefix=f"hashseed-{campaign}-{seed}-", suffix=".json")
        os.close(fd)
        outs.append(path)
        procs.append(_spawn(campaign, seed, orgs, ledgers, path))
    errors: List[str] = []
    docs: List[Optional[dict]] = []
    for seed, proc, path in zip(seeds, procs, outs):
        try:
            _, err = proc.communicate(timeout=timeout_s)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            errors.append(f"seed {seed}: timeout after {timeout_s:.0f}s")
            docs.append(None)
            continue
        if proc.returncode != 0:
            tail = err.decode("utf-8", "replace").strip().splitlines()[-3:]
            errors.append(f"seed {seed}: exit {proc.returncode}: "
                          + " | ".join(tail))
            docs.append(None)
            continue
        try:
            with open(path, "r", encoding="utf-8") as f:
                docs.append(json.load(f))
        except (OSError, ValueError) as e:
            errors.append(f"seed {seed}: unreadable payload: {e}")
            docs.append(None)
    for path in outs:
        try:
            os.unlink(path)
        except OSError:
            pass
    report = {
        "campaign": campaign,
        "seeds": list(seeds),
        "wall_s": monotonic_now() - t0,
        "errors": errors,
        "ok": False,
        "identical": False,
        "divergence": None,
        "detguard": [],
    }
    if any(d is None for d in docs):
        return report
    payloads = [d["payload"] for d in docs]
    report["detguard"] = [d["detguard"] for d in docs]
    report["divergence"] = _first_divergence(payloads[0], payloads[1])
    report["identical"] = report["divergence"] is None
    campaign_passed = all(p.get("passed") for p in payloads)
    guard_ok = all(g["armed"] and g["trips"] == 0 and g["regions"] > 0
                   for g in report["detguard"])
    if not campaign_passed:
        report["errors"].append("campaign reported failure in a child")
    if not guard_ok:
        report["errors"].append(
            "detguard not armed, no regions entered, or trips > 0: "
            + json.dumps(report["detguard"]))
    report["ok"] = report["identical"] and campaign_passed and guard_ok
    return report


def run_all(seeds: Tuple[int, int] = DEFAULT_SEEDS,
            orgs: int = DEFAULT_FLAGSHIP_ORGS,
            ledgers: int = DEFAULT_SOROBAN_LEDGERS,
            timeout_s: float = 900.0) -> List[dict]:
    return [run_pair(c, seeds=seeds, orgs=orgs, ledgers=ledgers,
                     timeout_s=timeout_s) for c in CAMPAIGNS]


def main(argv: Optional[Sequence[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m stellar_core_tpu.simulation.hashseed_diff",
        description="paired-subprocess PYTHONHASHSEED divergence check")
    ap.add_argument("--child", action="store_true", help=argparse.SUPPRESS)
    ap.add_argument("--campaign", choices=CAMPAIGNS, default=None)
    ap.add_argument("--orgs", type=int, default=DEFAULT_FLAGSHIP_ORGS)
    ap.add_argument("--ledgers", type=int, default=DEFAULT_SOROBAN_LEDGERS)
    ap.add_argument("--seeds", type=int, nargs=2, default=DEFAULT_SEEDS,
                    metavar=("A", "B"))
    ap.add_argument("--timeout", type=float, default=900.0)
    ap.add_argument("--out", default=None, help=argparse.SUPPRESS)
    args = ap.parse_args(argv)

    if args.child:
        _run_child(args.campaign, args.orgs, args.ledgers, args.out)
        return 0

    campaigns = [args.campaign] if args.campaign else list(CAMPAIGNS)
    rc = 0
    for campaign in campaigns:
        rep = run_pair(campaign, seeds=tuple(args.seeds), orgs=args.orgs,
                       ledgers=args.ledgers, timeout_s=args.timeout)
        guard = rep["detguard"] or [{"regions": 0, "trips": "?"}] * 2
        status = "IDENTICAL" if rep["ok"] else "DIVERGED/FAILED"
        print(f"hashseed-diff [{campaign}] seeds={rep['seeds']} "
              f"{status} wall={rep['wall_s']:.1f}s "
              f"regions={[g.get('regions') for g in guard]} "
              f"trips={[g.get('trips') for g in guard]}")
        if rep["divergence"]:
            print(f"  first divergence: {rep['divergence']}")
        for e in rep["errors"]:
            print(f"  error: {e}")
        if not rep["ok"]:
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
