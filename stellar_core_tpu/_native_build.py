"""Build-on-demand for the native C extensions (_cxdr, _cquorum, ...).

The compiled .so files are NOT tracked in git (a prebuilt binary can go
silently stale relative to native/*.c, defeating the differential tests
that are supposed to validate it).  Instead, every entry point that wants
native speed (tests/conftest.py, bench.py, __graft_entry__.py) calls
ensure_native(), which (re)builds in-place iff a .so is missing or older
than its C source.  Pure-Python fallbacks exist for every extension, so a
failed build degrades to slow-but-correct.
"""

import glob
import os
import subprocess
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "stellar_core_tpu")

# module name -> C source (relative to repo root)
_EXTENSIONS = {
    "_cxdr": "native/cxdr.c",
    "_cquorum": "native/cquorum.c",
    "_capply": "native/capply.c",
}


def _stale():
    out = []
    for mod, src in _EXTENSIONS.items():
        src_path = os.path.join(_REPO, src)
        if not os.path.exists(src_path):
            continue
        sos = glob.glob(os.path.join(_PKG, mod + ".*.so"))
        if not sos or any(
                os.path.getmtime(so) < os.path.getmtime(src_path)
                for so in sos):
            out.append(mod)
    return out


def ensure_native(quiet=True):
    """Build missing/stale native extensions in-place.  Returns True when
    everything that has a source is built and current."""
    stale = _stale()
    if not stale:
        return True
    try:
        res = subprocess.run(
            [sys.executable, "setup.py", "build_ext", "--inplace"],
            cwd=_REPO, capture_output=True, text=True, timeout=300)
        if res.returncode != 0 and not quiet:
            sys.stderr.write(res.stdout + res.stderr)
    except Exception as e:  # corelint: disable=exception-hygiene -- missing compiler: fall back to Python
        if not quiet:
            sys.stderr.write(f"native build failed: {e}\n")
    return not _stale()


class StaleNativeExtensionError(RuntimeError):
    """A shipped .so is older than its C source and could not be rebuilt —
    importing it would silently run stale code past the differential
    tests that are supposed to validate it."""


def require_fresh(mod):
    """Staleness guard for import sites that load `mod` directly (the
    native bridge, bench): a MISSING .so degrades to Python as before,
    but a PRESENT-and-stale one must either rebuild or fail-stop —
    silently loading it would pin every differential guarantee to bytes
    that no longer match native/*.c.  No-op when the module has no
    shipped .so at all."""
    if mod not in _EXTENSIONS:
        raise ValueError(f"unknown native extension {mod!r}")
    if not glob.glob(os.path.join(_PKG, mod + ".*.so")):
        return False            # nothing shipped: caller's fallback rules
    if mod not in _stale():
        return True
    ensure_native()
    if mod in _stale():
        src = _EXTENSIONS[mod]
        raise StaleNativeExtensionError(
            f"{mod} is older than {src} and the in-place rebuild failed; "
            f"run `make native` (or set STELLAR_TPU_NO_CAPPLY=1 to force "
            f"the Python engine)")
    return True
