"""Build-on-demand for the native C extensions (_cxdr, _cquorum, ...).

The compiled .so files are NOT tracked in git (a prebuilt binary can go
silently stale relative to native/*.c, defeating the differential tests
that are supposed to validate it).  Instead, every entry point that wants
native speed (tests/conftest.py, bench.py, __graft_entry__.py) calls
ensure_native(), which (re)builds in-place iff a .so is missing or older
than its C source.  Pure-Python fallbacks exist for every extension, so a
failed build degrades to slow-but-correct.
"""

import glob
import os
import shutil
import subprocess
import sys
import sysconfig

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_PKG = os.path.join(_REPO, "stellar_core_tpu")

# module name -> C source (relative to repo root)
_EXTENSIONS = {
    "_cxdr": "native/cxdr.c",
    "_cquorum": "native/cquorum.c",
    "_capply": "native/capply.c",
}

# the default build is warning-clean under these (setup.py mirrors them);
# --warn-check re-compiles with -Werror so the lint/CI path fail-stops on
# any new warning while end-user builds merely warn
_WARN_FLAGS = ["-Wall", "-Wextra"]

# sanitizer build (ISSUE 15): ASan+UBSan over the whole engine.  Its .so
# cache lives under build/asan/ — a separate cache key from the regular
# in-place build, so the two can never shadow each other silently; the
# sanitized modules are activated by PREPENDING build/asan to the
# package __path__ (see activate_sanitized), which wins import priority
# only when STPU_NATIVE_SANITIZE=1 is set.
_SANITIZE_FLAGS = ["-fsanitize=address,undefined", "-fno-omit-frame-pointer",
                   "-g", "-O1"]
_ASAN_DIR = os.path.join(_REPO, "build", "asan")
_ASAN_OPTIONS = "detect_leaks=0:halt_on_error=1:abort_on_error=1"
_UBSAN_OPTIONS = "halt_on_error=1:print_stacktrace=1"


def _cc():
    return os.environ.get("CC") or shutil.which("cc") or shutil.which("gcc")


def _py_include():
    return sysconfig.get_paths()["include"]


def _stale():
    out = []
    for mod, src in _EXTENSIONS.items():
        src_path = os.path.join(_REPO, src)
        if not os.path.exists(src_path):
            continue
        sos = glob.glob(os.path.join(_PKG, mod + ".*.so"))
        if not sos or any(
                os.path.getmtime(so) < os.path.getmtime(src_path)
                for so in sos):
            out.append(mod)
    return out


def ensure_native(quiet=True):
    """Build missing/stale native extensions in-place.  Returns True when
    everything that has a source is built and current."""
    stale = _stale()
    if not stale:
        return True
    try:
        res = subprocess.run(
            [sys.executable, "setup.py", "build_ext", "--inplace"],
            cwd=_REPO, capture_output=True, text=True, timeout=300)
        if res.returncode != 0 and not quiet:
            sys.stderr.write(res.stdout + res.stderr)
    except Exception as e:  # corelint: disable=exception-hygiene -- missing compiler: fall back to Python
        if not quiet:
            sys.stderr.write(f"native build failed: {e}\n")
    return not _stale()


class StaleNativeExtensionError(RuntimeError):
    """A shipped .so is older than its C source and could not be rebuilt —
    importing it would silently run stale code past the differential
    tests that are supposed to validate it."""


def _stale_sanitized():
    out = []
    for mod, src in _EXTENSIONS.items():
        src_path = os.path.join(_REPO, src)
        if not os.path.exists(src_path):
            continue
        so = os.path.join(_ASAN_DIR, mod + ".so")
        if not os.path.exists(so) \
                or os.path.getmtime(so) < os.path.getmtime(src_path):
            out.append(mod)
    return out


def ensure_sanitized(quiet=True):
    """Build the ASan+UBSan instrumented extensions under build/asan/
    iff missing or older than their C sources.  Returns True when every
    extension with a source is built and current; False (never raises)
    when the compiler is missing or a compile fails — callers skip the
    sanitizer tier cleanly, exactly like the plain-build fallback."""
    stale = _stale_sanitized()
    if not stale:
        return True
    cc = _cc()
    if cc is None:
        return False
    os.makedirs(_ASAN_DIR, exist_ok=True)
    for mod in stale:
        src_path = os.path.join(_REPO, _EXTENSIONS[mod])
        so = os.path.join(_ASAN_DIR, mod + ".so")
        cmd = [cc, "-shared", "-fPIC"] + _WARN_FLAGS + _SANITIZE_FLAGS + \
            ["-I", _py_include(), src_path, "-o", so]
        try:
            res = subprocess.run(cmd, capture_output=True, text=True,
                                 timeout=300)
        except Exception as e:  # corelint: disable=exception-hygiene -- missing/failed sanitizer toolchain: fall back like the plain build
            if os.path.exists(so):
                os.unlink(so)       # a killed cc can leave a fresh-mtime
                                    # truncated .so the cache would trust
            if not quiet:
                sys.stderr.write(f"sanitized build failed: {e}\n")
            return False
        if res.returncode != 0:
            if not quiet:
                sys.stderr.write(res.stdout + res.stderr)
            if os.path.exists(so):
                os.unlink(so)       # never leave a half-written .so
            return False
    return not _stale_sanitized()


def libasan_path():
    """Path to the compiler's dynamic ASan runtime, or None.  The
    instrumented .so files are loaded into an UNinstrumented python, so
    the runtime must be LD_PRELOADed into the process."""
    cc = _cc()
    if cc is None:
        return None
    try:
        res = subprocess.run([cc, "-print-file-name=libasan.so"],
                             capture_output=True, text=True, timeout=30)
    except Exception:  # corelint: disable=exception-hygiene -- probe-only: absent toolchain means no sanitizer tier
        return None
    path = res.stdout.strip()
    if res.returncode != 0 or not path or not os.path.isabs(path) \
            or not os.path.exists(path):
        return None
    return path


def sanitizer_available():
    return _cc() is not None and libasan_path() is not None


def sanitizer_env(base=None):
    """Environment for running python with the sanitized engine active:
    LD_PRELOAD the ASan runtime, fail-stop sanitizer options
    (halt_on_error=1; leak checking off — CPython frees nothing at
    exit), and STPU_NATIVE_SANITIZE=1 so the package prepends the
    instrumented build to its import path."""
    env = dict(os.environ if base is None else base)
    lib = libasan_path()
    if lib:
        prev = env.get("LD_PRELOAD")
        env["LD_PRELOAD"] = lib + ((" " + prev) if prev else "")
    env.setdefault("ASAN_OPTIONS", _ASAN_OPTIONS)
    env.setdefault("UBSAN_OPTIONS", _UBSAN_OPTIONS)
    env["STPU_NATIVE_SANITIZE"] = "1"
    return env


def activate_sanitized(quiet=True):
    """Make `from stellar_core_tpu import _capply` (and friends) resolve
    to the instrumented build: build if stale, then prepend build/asan/
    to the package __path__.  Called from the package __init__ when
    STPU_NATIVE_SANITIZE=1.  Returns False (plain modules stay active)
    when the sanitizer toolchain is unavailable."""
    for mod in _EXTENSIONS:
        # too late to swap an already-imported engine — fail BEFORE the
        # (expensive) sanitized build, not after
        if f"stellar_core_tpu.{mod}" in sys.modules:
            raise RuntimeError(
                f"activate_sanitized() after stellar_core_tpu.{mod} was "
                f"already imported — set STPU_NATIVE_SANITIZE=1 in the "
                f"process environment instead")
    if not ensure_sanitized(quiet=quiet):
        return False
    import stellar_core_tpu
    if _ASAN_DIR not in stellar_core_tpu.__path__:
        stellar_core_tpu.__path__.insert(0, _ASAN_DIR)
    return True


def warn_check(werror=True, quiet=False):
    """Compile every native source with -Wall -Wextra (-Werror when
    `werror`) in syntax-only mode: the lint/CI gate that keeps the
    default build warning-clean.  Returns (ok, details); ok is True
    with a notice when no compiler is available (missing-compiler
    fallback intact — the build itself would also have fallen back)."""
    cc = _cc()
    if cc is None:
        return True, ["warn-check skipped: no C compiler on PATH"]
    details = []
    ok = True
    # a REAL -O2 compile (to /dev/null), not -fsyntax-only: the
    # optimization-dependent dataflow warnings (-Wmaybe-uninitialized,
    # -Wstrict-aliasing) only fire when the passes that feed them run —
    # the gate must see everything the default -O2 build would emit
    flags = ["-c", "-O2", "-o", os.devnull] + _WARN_FLAGS \
        + (["-Werror"] if werror else [])
    for mod, src in _EXTENSIONS.items():
        src_path = os.path.join(_REPO, src)
        if not os.path.exists(src_path):
            continue
        try:
            res = subprocess.run(
                [cc] + flags + ["-I", _py_include(), src_path],
                capture_output=True, text=True, timeout=300)
        except Exception as e:  # corelint: disable=exception-hygiene -- wedged compiler: report as a structured FAIL, not a traceback
            ok = False
            details.append(f"{src}: FAIL (compiler did not finish: {e})")
            continue
        if res.returncode != 0:
            ok = False
            details.append(f"{src}: FAIL\n{res.stderr.strip()}")
        else:
            details.append(f"{src}: warning-clean")
    return ok, details


def require_fresh(mod):
    """Staleness guard for import sites that load `mod` directly (the
    native bridge, bench): a MISSING .so degrades to Python as before,
    but a PRESENT-and-stale one must either rebuild or fail-stop —
    silently loading it would pin every differential guarantee to bytes
    that no longer match native/*.c.  No-op when the module has no
    shipped .so at all."""
    if mod not in _EXTENSIONS:
        raise ValueError(f"unknown native extension {mod!r}")
    if not glob.glob(os.path.join(_PKG, mod + ".*.so")):
        return False            # nothing shipped: caller's fallback rules
    if mod not in _stale():
        return True
    ensure_native()
    if mod in _stale():
        src = _EXTENSIONS[mod]
        raise StaleNativeExtensionError(
            f"{mod} is older than {src} and the in-place rebuild failed; "
            f"run `make native` (or set STELLAR_TPU_NO_CAPPLY=1 to force "
            f"the Python engine)")
    return True


def _main(argv):
    """CLI: `python -m stellar_core_tpu._native_build <mode>`.

    --warn-check         -Wall -Wextra -Werror syntax-only compile of
                         every native source (the `make lint` gate);
                         exit 1 on any warning, 0 when clean or when no
                         compiler exists (fallback intact, notice printed)
    --sanitize           build the ASan+UBSan .so cache under build/asan
    --asan-exec CMD...   build sanitized, then exec CMD with the
                         sanitizer environment (LD_PRELOAD runtime,
                         halt_on_error, STPU_NATIVE_SANITIZE=1); exits 0
                         with a SKIPPED notice when the toolchain is
                         missing so CI tiers degrade instead of erroring
    """
    if not argv:
        sys.stderr.write(_main.__doc__ + "\n")
        return 2
    mode, rest = argv[0], argv[1:]
    if mode == "--warn-check":
        ok, details = warn_check()
        for d in details:
            print(d)
        return 0 if ok else 1
    if mode == "--sanitize":
        if not sanitizer_available():
            print("sanitize SKIPPED: no cc/libasan in this environment")
            return 0
        ok = ensure_sanitized(quiet=False)
        print("sanitized build: " + ("ok" if ok else "FAILED"))
        return 0 if ok else 1
    if mode == "--asan-exec":
        if not rest:
            sys.stderr.write("--asan-exec needs a command\n")
            return 2
        if not sanitizer_available():
            print("native-asan SKIPPED: no cc/libasan in this environment")
            return 0
        if not ensure_sanitized(quiet=False):
            sys.stderr.write("sanitized build FAILED\n")
            return 1
        os.execvpe(rest[0], rest, sanitizer_env())
    sys.stderr.write(f"unknown mode {mode!r}\n{_main.__doc__}\n")
    return 2


if __name__ == "__main__":
    sys.exit(_main(sys.argv[1:]))
