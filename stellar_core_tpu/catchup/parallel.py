"""Range-parallel catchup: N concurrent checkpoint ranges stitched by
assume-state.

The survey's history design (PAPER.md §2 rows 17-19: one HAS per
checkpoint, per-bucket hashes, `catchup_minimal` assume-state) makes every
checkpoint range independently seedable: worker k assumes the hash-verified
bucket snapshot at checkpoint k·R into its own BucketListDB dir, replays
its R checkpoints with full signature/tx-set/bucket verification, and
reports its final ledger header.  The stitch is PROVEN, not assumed —
range k's final ledger hash must equal range k+1's seed header hash (the
same 32 bytes the worker verified the assumed bucket list against), so the
concatenation of verified ranges is exactly the single-stream replay:

    range 0: genesis ──replay──▶ H(c_1)   ═╗ equal, or fail-stop
    range 1: assume c_1 [hash H(c_1)] ─────╝ ──replay──▶ H(c_2) ═╗
    range 2: assume c_2 [hash H(c_2)] ──────────────────────────╝ ─▶ ...

Workers are real subprocesses (`python -m stellar_core_tpu catchup-range`)
driven by util/process.ProcessManager — ranges get genuine CPU parallelism
past the GIL, and each worker's own PreverifyPipeline keeps the accel path
live per range.  Results travel through JSON files; a failed or corrupt
range retries with the Work framework's standard truncated-exponential
backoff (the same machinery the single-stream per-checkpoint download
uses), and any stitch mismatch fail-stops the whole catchup with a crash
bundle naming the offending boundary — the node's authoritative ledger dir
is only ever written AFTER every boundary has verified.

Reference sequencing: src/catchup/CatchupWork.cpp runs ApplyBucketsWork
once, then ApplyCheckpointWork strictly sequentially; this module runs N
CatchupWork-shaped pipelines whose ApplyBuckets seeds are interior
checkpoints, then proves the seams.

ISSUE 14 additions:

* **Device-per-range mesh** — with ``mesh_devices=N`` each worker's env
  pins it to one JAX device round-robin (accel/mesh.py), threaded through
  the subprocess cmdline like the PYTHONPATH pin, so N ranges × N devices
  multiply instead of contending for chip 0.
* **Checkpoint-granular work stealing** — the PROFILE round 9 curve is
  capped by the straggler range.  Each worker heartbeats its LCL into a
  control dir (``ctl-XX/progress.json``, survives retry wipes of the
  range dir); when a worker finishes, the orchestrator picks the slowest
  running range, splits its REMAINING checkpoints at a published boundary
  (plan_steal: the thief adopts the later half), and negotiates via a
  limit/ack handshake: the victim's CatchupWork truncates its target at
  the split boundary ONLY after writing an explicit accept ack, and the
  thief subprocess (seeded at the split via assume-state, like any range)
  is spawned only after that ack — so the seam is deterministic even
  though progress races the negotiation.  verify_stitches proves the
  dynamically-split seams exactly like the planned ones; a forged steal
  seam fail-stops the whole catchup with a crash bundle.
"""

from __future__ import annotations

import json
import os
import shlex
import shutil
import sys
import time as _time
from dataclasses import dataclass
from typing import Dict, List, Optional

from ..history.archive import (checkpoint_containing, checkpoint_frequency,
                               make_archive)
from ..util import eventlog
from ..util import logging as slog
from ..util.clock import ClockMode, VirtualClock
from ..util.metrics import registry as _registry
from ..util.process import ProcessManager
from ..util.racetrace import race_checked
from ..work.work import RETRY_A_FEW, BasicWork, State
from .catchup import CatchupError

log = slog.get("History")


# ---------------------------------------------------------------------------
# planning
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class RangeSpec:
    """One contiguous checkpoint range of a parallel catchup plan.

    ``seed_checkpoint`` is the published boundary whose bucket snapshot the
    worker assumes before replaying (None = range 0, which replays from
    genesis); ``replay_to`` is the last ledger the range applies (a
    checkpoint boundary for every range but possibly the last)."""
    index: int
    seed_checkpoint: Optional[int]
    replay_to: int

    @property
    def replay_from(self) -> int:
        return 2 if self.seed_checkpoint is None else self.seed_checkpoint + 1

    @property
    def n_ledgers(self) -> int:
        return self.replay_to - self.replay_from + 1


def remaining_checkpoint_units(progress: int, replay_to: int) -> int:
    """How many checkpoint-granular work units are left in (progress,
    replay_to]: one per published boundary plus the partial tail (when
    replay_to is not itself a boundary)."""
    if replay_to <= progress:
        return 0
    freq = checkpoint_frequency()
    boundaries = [b for b in range(freq - 1, replay_to + 1, freq)
                  if b > progress]
    tail = 0 if boundaries and boundaries[-1] == replay_to else 1
    return len(boundaries) + tail


def plan_steal(progress: int, replay_to: int) -> Optional[int]:
    """Split the remaining (progress, replay_to] work of a straggler range
    at a published checkpoint boundary.  Returns the boundary the victim
    stops at — the thief seeds there via assume-state and replays
    (boundary, replay_to] — or None when fewer than two units remain.
    The thief adopts HALF the remaining checkpoints (rounded down), the
    LATER half, so the victim never rewinds; seams stay
    checkpoint-aligned because only published boundaries are split
    points."""
    freq = checkpoint_frequency()
    candidates = [b for b in range(freq - 1, replay_to, freq)
                  if b > progress]
    if not candidates:
        return None
    total_units = remaining_checkpoint_units(progress, replay_to)
    steal_units = total_units // 2
    if steal_units < 1:
        return None
    keep_units = total_units - steal_units
    # the victim keeps units 1..keep: its new end is the keep-th boundary
    return candidates[keep_units - 1]


def _write_json_atomic(path: str, doc: dict) -> None:
    """Both halves of the steal handshake write through here — a torn
    limit/ack would desynchronize the seam negotiation."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f)
    os.replace(tmp, path)


def _read_json(path: str) -> Optional[dict]:
    try:
        with open(path) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


@race_checked
class RangeControl:
    """The worker-side half of the stealing protocol, rooted in a control
    dir that OUTLIVES retry wipes of the range dir:

    * ``progress.json`` — heartbeat: the LCL after every applied
      checkpoint (and the throttle seam for straggler-injection tests:
      STPU_CATCHUP_THROTTLE_S sleeps that long per checkpoint).
    * ``limit.json`` — orchestrator-written steal limit: a published
      boundary this range should stop at.
    * ``limit-ack.json`` — the worker's verdict.  ``accepted`` means the
      worker WILL stop exactly at the limit (its CatchupWork target is
      truncated); ``rejected`` means progress already passed it.  The
      orchestrator spawns the thief only on an accept, so a lost race
      never tears a seam.
    """

    PROGRESS = "progress.json"
    LIMIT = "limit.json"
    ACK = "limit-ack.json"

    def __init__(self, ctl_dir: str, throttle_s: Optional[float] = None):
        os.makedirs(ctl_dir, exist_ok=True)
        self.dir = ctl_dir
        # Thread contract (ISSUE 15 audit): cross-WORKER coordination
        # rides exclusively in the atomically-replaced JSON files; these
        # in-process latches are only ever touched by the range worker's
        # single replay thread (checkpoint_hook), attested below and
        # enforced at runtime by @race_checked under make race.
        self.accepted: Optional[int] = None  # corelint: owned-by=main -- steal-limit accept latch, written once by checkpoint_hook on the replay thread
        self._rejected = False  # corelint: owned-by=main -- sticky reject latch, checkpoint_hook only
        if throttle_s is None:
            throttle_s = float(
                os.environ.get("STPU_CATCHUP_THROTTLE_S", "0") or 0.0)
        self.throttle_s = throttle_s

    def _write(self, name: str, doc: dict) -> None:
        _write_json_atomic(os.path.join(self.dir, name), doc)

    def _read(self, name: str) -> Optional[dict]:
        return _read_json(os.path.join(self.dir, name))

    def checkpoint_hook(self, lcl: int) -> Optional[int]:
        """CatchupWork hook: heartbeat + honor at most one steal limit.
        Returns the accepted boundary (the truncated target) or None."""
        if self.throttle_s:
            _time.sleep(self.throttle_s)
        self._write(self.PROGRESS, {"lcl": lcl})
        if self.accepted is not None or self._rejected:
            return self.accepted
        lim = self._read(self.LIMIT)
        if lim is None:
            return None
        boundary = int(lim["replay_to"])
        if boundary >= lcl:
            self.accepted = boundary
            self._write(self.ACK, {"accepted": boundary})
            eventlog.record("History", "INFO", "steal limit accepted",
                            boundary=boundary, lcl=lcl)
            return boundary
        self._rejected = True
        self._write(self.ACK, {"rejected": lcl})
        eventlog.record("History", "INFO", "steal limit rejected",
                        boundary=boundary, lcl=lcl)
        return None


def plan_parallel_ranges(target: int, workers: int) -> List[RangeSpec]:
    """Split the checkpoints covering (genesis, target] into up to
    `workers` contiguous ranges.  Every interior seam sits on a published
    checkpoint boundary so range k+1 can seed itself from the archive's
    per-checkpoint HAS; ranges are balanced to within one checkpoint."""
    if target < 2:
        raise CatchupError(f"nothing to replay to ledger {target}")
    if workers < 1:
        raise CatchupError(f"workers must be >= 1, got {workers}")
    freq = checkpoint_frequency()
    last_cp = checkpoint_containing(target)
    boundaries = list(range(freq - 1, last_cp + 1, freq))
    n = max(1, min(workers, len(boundaries)))
    base, rem = divmod(len(boundaries), n)
    specs: List[RangeSpec] = []
    seed: Optional[int] = None
    start = 0
    for k in range(n):
        size = base + (1 if k < rem else 0)
        end_cp = boundaries[start + size - 1]
        replay_to = target if k == n - 1 else min(end_cp, target)
        specs.append(RangeSpec(index=k, seed_checkpoint=seed,
                               replay_to=replay_to))
        seed = end_cp
        start += size
    return specs


# ---------------------------------------------------------------------------
# the worker body (runs inside `python -m stellar_core_tpu catchup-range`)
# ---------------------------------------------------------------------------

def run_range(archive, spec: RangeSpec, network_id: bytes, passphrase: str,
              *, accel: bool = False, accel_chunk: int = 8192,
              native: Optional[bool] = None,
              invariant_manager=None,
              bucket_dir: Optional[str] = None,
              entry_cache_size: Optional[int] = None,
              resident_levels: Optional[int] = None,
              persist_dir: Optional[str] = None,
              persist_target: Optional[int] = None,
              ctl_dir: Optional[str] = None,
              accel_profile: Optional[str] = None,
              clock=None, lookahead: int = 2) -> dict:
    """Seed + replay one range and return its stitch record.  This is the
    in-process body of the `catchup-range` worker subcommand; tests drive
    it directly too.

    With `bucket_dir`, the range's assumed/replayed state lives in its own
    BucketListDB store there (throwaway for interior ranges).  With
    `persist_dir`, the final state is durably persisted (Database +
    BucketDir) so the orchestrator can adopt the last range's ledger —
    gated on `persist_target` when given: under work stealing whichever
    worker actually ENDS at the catchup target owns the adoptable state,
    and a truncated victim must not burn time persisting a mid-chain
    snapshot.  With `ctl_dir`, the worker heartbeats progress and honors
    steal limits (RangeControl)."""
    from ..catchup.catchup import CatchupManager

    store = None
    if bucket_dir is not None:
        from ..bucket.manager import BucketListStore
        store = BucketListStore(bucket_dir)
    cm = CatchupManager(network_id, passphrase, accel=accel,
                        accel_chunk=accel_chunk, native=native,
                        invariant_manager=invariant_manager,
                        bucket_store=store,
                        entry_cache_size=entry_cache_size,
                        resident_levels=resident_levels,
                        accel_profile=accel_profile)
    control = RangeControl(ctl_dir) if ctl_dir is not None else None
    t0 = _time.perf_counter()
    mgr, seed_hash = cm.catchup_range(
        archive, spec.seed_checkpoint, spec.replay_to, clock=clock,
        lookahead=lookahead,
        checkpoint_hook=control.checkpoint_hook if control else None)
    wall = _time.perf_counter() - t0
    final_seq = mgr.last_closed_ledger_seq
    persisted = persist_dir is not None and (
        persist_target is None or final_seq == persist_target)
    if persisted:
        from ..bucket.manager import BucketDir
        from ..database import Database
        os.makedirs(persist_dir, exist_ok=True)
        db = Database(os.path.join(persist_dir, "state.db"))
        mgr.enable_persistence(db, BucketDir(
            os.path.join(persist_dir, "buckets")))
        db.close()
    n = final_seq - spec.replay_from + 1
    result = {
        "index": spec.index,
        "seed_checkpoint": spec.seed_checkpoint,
        "seed_header_hash": seed_hash.hex() if seed_hash is not None else None,
        "replay_to": spec.replay_to,
        "final_ledger_seq": final_seq,
        "final_hash": mgr.lcl_hash.hex(),
        "ledgers_replayed": n,
        "wall_s": round(wall, 3),
        "ledgers_per_s": round(n / wall, 1) if wall > 0 else 0.0,
        "sig_offload_hit_rate": round(cm.offload_hit_rate(), 3),
        "persisted": persisted,
    }
    if final_seq < spec.replay_to:
        result["truncated_to"] = final_seq   # a thief adopted the tail
    # read the pin straight from the env: importing accel.mesh would drag
    # the whole accel package (and its eager jax import) into every
    # CPU-only worker
    dev = os.environ.get("STPU_DEVICE_INDEX")
    if dev is not None and dev.isdigit():
        result["device_index"] = int(dev)
        if accel:
            # pinned accel worker: record what JAX actually sees (the
            # mesh env must have reduced it to exactly one device)
            import jax
            result["visible_devices"] = len(jax.devices())
    return result


# ---------------------------------------------------------------------------
# stitch proof
# ---------------------------------------------------------------------------

def verify_stitches(results: List[dict],
                    crash_dir: Optional[str] = None) -> int:
    """Prove the seams: range k's final ledger (seq, hash) must equal range
    k+1's seed (checkpoint, header hash).  Each verified boundary counts on
    catchup.parallel.stitch-verified; any mismatch writes a crash bundle
    naming the boundary and raises CatchupError.  Returns the number of
    boundaries verified."""
    counter = _registry().counter("catchup.parallel.stitch-verified")
    verified = 0
    for a, b in zip(results, results[1:]):
        boundary = b["seed_checkpoint"]
        detail = None
        if a["final_ledger_seq"] != boundary:
            detail = (f"range {a['index']} ended at ledger "
                      f"{a['final_ledger_seq']}, range {b['index']} seeded "
                      f"at checkpoint {boundary}")
        elif a["final_hash"] != b["seed_header_hash"]:
            detail = (f"range {a['index']} final hash {a['final_hash']} != "
                      f"range {b['index']} seed header hash "
                      f"{b['seed_header_hash']}")
        if detail is not None:
            eventlog.record("History", "ERROR", "parallel catchup stitch "
                            "FAILED", boundary=boundary, detail=detail)
            eventlog.write_crash_bundle(
                f"parallel catchup stitch mismatch at checkpoint boundary "
                f"{boundary}: {detail}", crash_dir=crash_dir)
            raise CatchupError(
                f"stitch mismatch at checkpoint boundary {boundary}: "
                f"{detail}")
        counter.inc()
        verified += 1
        eventlog.record("History", "INFO", "stitch verified",
                        boundary=boundary,
                        hash=a["final_hash"][:16])
    return verified


# ---------------------------------------------------------------------------
# orchestration
# ---------------------------------------------------------------------------

class RangeWork(BasicWork):
    """One range's subprocess, as a Work: on_run launches the worker via
    ProcessManager and WAITs; a non-zero exit or an unreadable result file
    is a FAILURE, which BasicWork retries with the single-stream catchup's
    truncated-exponential backoff (archive corruption is transient on real
    mirrors)."""

    def __init__(self, clock: VirtualClock, pm: ProcessManager,
                 cmdline: str, result_path: str, spec: RangeSpec,
                 log_path: Optional[str] = None,
                 workdir: Optional[str] = None,
                 max_retries: int = RETRY_A_FEW):
        super().__init__(clock, f"catchup-range-{spec.index}",
                         max_retries=max_retries)
        self.pm = pm
        self.cmdline = cmdline
        self.result_path = result_path
        self.log_path = log_path
        self.workdir = workdir
        self.spec = spec
        self.result: Optional[dict] = None
        self.error_detail: Optional[str] = None
        self._ev = None
        self._code: Optional[int] = None

    def on_reset(self) -> None:
        if self._ev is not None and self._ev.running:
            self.pm.cancel(self._ev)
        self._ev = None
        self._code = None

    def _on_exit(self, code: int) -> None:
        self._code = code
        self.wake_up()

    def on_run(self) -> State:
        if self._ev is None:
            if self.workdir is not None and self.retries > 0:
                # a crashed attempt can leave TORN range-private state
                # (half-written state.db, a bucketlistdb mid-adopt);
                # feeding it back in would turn a one-shot transient fault
                # into max_retries hard failures — every retry starts from
                # the pristine dir a fresh worker would get
                shutil.rmtree(self.workdir, ignore_errors=True)
                os.makedirs(self.workdir, exist_ok=True)
            try:
                os.unlink(self.result_path)   # stale result from a retry
            except FileNotFoundError:
                pass
            eventlog.record("History", "INFO", "range worker started",
                            range=self.spec.index,
                            replay_to=self.spec.replay_to,
                            attempt=self.retries + 1)
            self._ev = self.pm.run_command(self.cmdline, self._on_exit,
                                           output_path=self.log_path)
            return State.WAITING
        if self._code is None:
            return State.WAITING
        if self._code == 0:
            try:
                with open(self.result_path) as f:
                    result = json.load(f)
            except (OSError, ValueError) as e:
                self.error_detail = f"result file unreadable: {e}"
                log.warning("%s: %s", self.name, self.error_detail)
                _registry().counter("catchup.parallel.range-retry").inc()
                return State.FAILURE
            if "error" in result:
                self.error_detail = result["error"]
                log.warning("%s: worker error: %s", self.name,
                            self.error_detail)
                _registry().counter("catchup.parallel.range-retry").inc()
                return State.FAILURE
            self.result = result
            _registry().histogram("catchup.parallel.range-rate").update(
                result.get("ledgers_per_s", 0.0))
            eventlog.record("History", "INFO", "range worker finished",
                            range=self.spec.index,
                            final=result["final_ledger_seq"],
                            rate=result.get("ledgers_per_s", 0.0))
            return State.SUCCESS
        self.error_detail = f"worker exited {self._code}"
        self.error_detail += self._tail_of_log()
        log.warning("%s: %s", self.name, self.error_detail)
        _registry().counter("catchup.parallel.range-retry").inc()
        return State.FAILURE

    def _tail_of_log(self) -> str:
        if self.log_path is None:
            return ""
        try:
            with open(self.log_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                f.seek(max(0, f.tell() - 800))
                tail = f.read().decode(errors="replace").strip()
            return f"; log tail: {tail[-400:]}" if tail else ""
        except OSError:
            return ""


class ParallelCatchup:
    """Plan, fan out, stitch, adopt.

    ``run()`` splits the archive's chain into up to `workers` contiguous
    checkpoint ranges, replays them as bounded-concurrency subprocess
    workers (each seeding itself via assume-state into a throwaway
    BucketListDB dir under `workdir`), proves every boundary, and returns
    the report.  The LAST range persists its state; ``load_manager()``
    rebuilds the node's LedgerManager from it, and ``adopt_into()`` moves
    it to the node's authoritative paths — both only reachable after the
    stitch proof, so a poisoned range can never touch the real ledger."""

    def __init__(self, archive_spec: str, passphrase: str, *,
                 workers: int = 4, workdir: Optional[str] = None,
                 accel: bool = False, accel_chunk: int = 8192,
                 native: Optional[bool] = None,
                 invariant_checks: Optional[List[str]] = None,
                 in_memory: bool = False,
                 entry_cache_size: Optional[int] = None,
                 resident_levels: Optional[int] = None,
                 max_retries: int = RETRY_A_FEW,
                 keep_range_dirs: bool = False,
                 crash_dir: Optional[str] = None,
                 clock: Optional[VirtualClock] = None,
                 steal: bool = True,
                 steal_min_checkpoints: int = 4,
                 mesh_devices: int = 0,
                 mesh_platform: str = "auto",
                 accel_profile: Optional[str] = None,
                 extra_env: Optional[Dict[int, Dict[str, str]]] = None,
                 python: str = sys.executable):
        from ..crypto.sha import sha256
        self.archive_spec = archive_spec
        self.passphrase = passphrase
        self.network_id = sha256(passphrase.encode())
        self.workers = max(1, workers)
        self._own_workdir = workdir is None
        if workdir is None:
            import tempfile
            workdir = tempfile.mkdtemp(prefix="catchup-par-")
        self.workdir = workdir
        self.accel = accel
        self.accel_chunk = accel_chunk
        self.native = native
        # checkpoint-granular work stealing (module docstring): finished
        # workers adopt the later half of the slowest range's remaining
        # checkpoints; only remainders >= steal_min_checkpoints units are
        # worth a worker spawn + assume-state
        self.steal = steal
        self.steal_min_checkpoints = max(2, steal_min_checkpoints)
        # device-per-range mesh: > 0 pins worker k to device k % N via
        # env (accel/mesh.py), threaded through the subprocess cmdline
        self.mesh_devices = max(0, mesh_devices)
        self.mesh_platform = mesh_platform
        self.accel_profile = accel_profile
        # per-range-index env additions (tests inject stragglers with
        # STPU_CATCHUP_THROTTLE_S; the mesh pin composes on top)
        self.extra_env = dict(extra_env or {})
        # INVARIANT_CHECKS patterns travel to every worker — a parallel
        # catchup must honor exactly what the single-stream path would;
        # same for the node's storage knobs (IN_MEMORY_LEDGER + the
        # BucketListDB cache/residency bounds, which matter MOST when N
        # workers share the box's memory)
        self.invariant_checks = list(invariant_checks or [])
        self.in_memory = in_memory
        self.entry_cache_size = entry_cache_size
        self.resident_levels = resident_levels
        self.max_retries = max_retries
        self.keep_range_dirs = keep_range_dirs
        self.crash_dir = crash_dir
        self.clock = clock or VirtualClock(ClockMode.REAL_TIME)
        self.python = python
        self.report: Optional[dict] = None
        self._final_dir: Optional[str] = None
        self._target: Optional[int] = None

    # -- worker command ----------------------------------------------------
    def _range_dir(self, index: int) -> str:
        return os.path.join(self.workdir, f"range-{index:02d}")

    def _ctl_dir(self, index: int) -> str:
        # OUTSIDE the range dir: RangeWork wipes the range dir on retry,
        # and an accepted steal limit must survive the wipe or the fresh
        # attempt would replay past the split boundary and tear the seam
        return os.path.join(self.workdir, f"ctl-{index:02d}")

    def _worker_cmdline(self, spec: RangeSpec) -> str:
        d = self._range_dir(spec.index)
        # workers must import the SAME package the parent runs, even when
        # the parent got it via sys.path manipulation (an embedding
        # consumer) rather than cwd or an inherited PYTHONPATH — without
        # this every range worker dies on ImportError and the retry
        # backoff turns a config quirk into minutes of spin
        pkg_parent = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        inherited = os.environ.get("PYTHONPATH")
        pythonpath = pkg_parent if not inherited \
            else pkg_parent + os.pathsep + inherited
        args = [self.python, "-m", "stellar_core_tpu", "catchup-range",
                "--archive", self.archive_spec,
                "--passphrase", self.passphrase,
                "--to", str(spec.replay_to),
                "--seed-checkpoint",
                ("genesis" if spec.seed_checkpoint is None
                 else str(spec.seed_checkpoint)),
                "--workdir", d,
                "--result", os.path.join(d, "result.json")]
        args += ["--index", str(spec.index)]
        # whichever worker ends at the catchup target owns the adoptable
        # state (under stealing that may be a thief, not the planned last
        # range) — every worker gets the target and self-selects
        if self._target is not None:
            args += ["--persist-target", str(self._target)]
        # the control dir rides along even with stealing off: the
        # progress heartbeat is the orchestrator's visibility into a
        # straggling range (and the throttle seam must behave identically
        # in steal-on vs steal-off comparisons)
        args += ["--ctl-dir", self._ctl_dir(spec.index)]
        if self.accel:
            args += ["--accel", "tpu", "--accel-chunk",
                     str(self.accel_chunk)]
        if self.accel_profile is not None:
            args += ["--accel-profile", self.accel_profile]
        if self.native is not None:
            args += ["--native", "on" if self.native else "off"]
        for pattern in self.invariant_checks:
            args += ["--invariant", pattern]
        if self.in_memory:
            args.append("--in-memory")
        if self.entry_cache_size is not None:
            args += ["--entry-cache-size", str(self.entry_cache_size)]
        if self.resident_levels is not None:
            args += ["--resident-levels", str(self.resident_levels)]
        if checkpoint_frequency() != 64:
            # non-default cadence (accelerated test fleets) must reach the
            # worker process or its range plan/seam math disagrees with ours
            args += ["--checkpoint-frequency", str(checkpoint_frequency())]
        # ProcessManager runs shell-less (shlex.split + Popen), so the
        # assignments travel through `env`: PYTHONPATH, the per-worker
        # device pin (mesh), and any per-range test env
        env_pairs = {"PYTHONPATH": pythonpath}
        if self.mesh_devices > 0:
            from ..accel import mesh as _mesh
            env_pairs.update(_mesh.worker_device_env(
                spec.index % self.mesh_devices, self.mesh_devices,
                self.mesh_platform))
        env_pairs.update(self.extra_env.get(spec.index, {}))
        args = ["env"] + [f"{k}={v}" for k, v in env_pairs.items()] + args
        return " ".join(shlex.quote(a) for a in args)

    # -- work stealing -----------------------------------------------------
    def _make_work(self, pm: ProcessManager, spec: RangeSpec) -> RangeWork:
        d = self._range_dir(spec.index)
        os.makedirs(d, exist_ok=True)
        # a reused workdir may hold a PREVIOUS run's steal artifacts
        # (limit/ack from an interrupted catchup): a worker honoring a
        # stale limit would truncate with no thief to cover the tail.
        # Control state is strictly per-run; only the RETRY path within a
        # run must preserve it (RangeWork wipes the range dir, not this).
        shutil.rmtree(self._ctl_dir(spec.index), ignore_errors=True)
        return RangeWork(
            self.clock, pm, self._worker_cmdline(spec),
            os.path.join(d, "result.json"), spec,
            log_path=os.path.join(d, "worker.log"),
            workdir=d,
            max_retries=self.max_retries)

    def _read_ctl(self, index: int, name: str) -> Optional[dict]:
        return _read_json(os.path.join(self._ctl_dir(index), name))

    def _progress_of(self, w: RangeWork) -> int:
        """The victim candidate's last heartbeat LCL (its seed when no
        checkpoint has completed yet — stealable from the start)."""
        doc = self._read_ctl(w.spec.index, RangeControl.PROGRESS)
        if doc is not None and isinstance(doc.get("lcl"), int):
            return max(doc["lcl"], w.spec.seed_checkpoint or 1)
        return w.spec.seed_checkpoint or 1

    def _spawn_thief(self, pm, works, victim: RangeWork,
                     boundary: int) -> None:
        """The accepted half of the handshake: the victim WILL stop at
        `boundary`; seed a thief there covering the abandoned tail."""
        spec = RangeSpec(index=self._next_index,
                         seed_checkpoint=boundary,
                         replay_to=self._expected_to[victim])
        self._next_index += 1
        self._expected_to[victim] = boundary
        thief = self._make_work(pm, spec)
        self._expected_to[thief] = spec.replay_to
        works.append(thief)
        thief.start()
        adopted = remaining_checkpoint_units(boundary, spec.replay_to)
        self._steal_events.append({
            "victim": victim.spec.index, "thief": spec.index,
            "boundary": boundary, "checkpoints_adopted": adopted})
        _registry().counter("catchup.parallel.steal").inc()
        eventlog.record("History", "INFO", "checkpoint steal",
                        victim=victim.spec.index, thief=spec.index,
                        boundary=boundary, adopted=adopted)
        log.info("work steal: range %d adopts %d checkpoint(s) of range "
                 "%d past boundary %d", spec.index, adopted,
                 victim.spec.index, boundary)

    def _maybe_steal(self, pm, works: List[RangeWork]) -> None:
        """One crank of the steal state machine: settle the outstanding
        negotiation (spawn the thief on an accept), then — with spare
        worker capacity and no negotiation in flight — pick the slowest
        running range and write it a limit at the plan_steal boundary."""
        # settle the in-flight negotiation first (at most one at a time:
        # seams are serialized so the stitch chain stays a chain)
        if self._negotiation is not None:
            victim, boundary = self._negotiation
            ack = self._read_ctl(victim.spec.index, RangeControl.ACK)
            if ack is not None and ack.get("accepted") == boundary:
                self._negotiation = None
                self._spawn_thief(pm, works, victim, boundary)
            elif ack is not None:
                self._negotiation = None   # progress won the race
            elif victim.done:
                self._negotiation = None
                if victim.succeeded and victim.result is not None \
                        and victim.result["final_ledger_seq"] == boundary:
                    # it honored the limit but the ack read raced its exit
                    self._spawn_thief(pm, works, victim, boundary)
            return
        active = [w for w in works if not w.done]
        if not active or len(active) >= self.workers \
                or not any(w.done and w.succeeded for w in works):
            return
        candidates = []
        for w in active:
            if w in self._victimized:
                continue
            progress = self._progress_of(w)
            units = remaining_checkpoint_units(progress,
                                               self._expected_to[w])
            if units >= self.steal_min_checkpoints:
                candidates.append((units, progress, w))
        if not candidates:
            return
        units, progress, victim = max(candidates, key=lambda c: c[0])
        boundary = plan_steal(progress, self._expected_to[victim])
        if boundary is None:
            return
        ctl = self._ctl_dir(victim.spec.index)
        os.makedirs(ctl, exist_ok=True)
        _write_json_atomic(os.path.join(ctl, RangeControl.LIMIT),
                           {"replay_to": boundary})
        self._victimized.add(victim)
        self._negotiation = (victim, boundary)
        eventlog.record("History", "INFO", "steal limit offered",
                        victim=victim.spec.index, boundary=boundary,
                        remaining_units=units)

    # -- driving -----------------------------------------------------------
    def run(self, target: Optional[int] = None) -> dict:
        archive = make_archive(self.archive_spec)
        has = archive.get_state()
        if has is None:
            raise CatchupError("archive has no HAS")
        if target is None:
            target = has.current_ledger
        self._target = target
        self._specs = plan_parallel_ranges(target, self.workers)
        if len(self._specs) == 1:
            log.info("parallel catchup degenerates to a single range "
                     "(target %d)", target)
        pm = ProcessManager(self.clock, max_concurrent=self.workers)
        works: List[RangeWork] = [self._make_work(pm, spec)
                                  for spec in self._specs]
        # steal bookkeeping: each work's CURRENT end (shrinks when stolen
        # from), the outstanding limit negotiation, spawned thieves
        self._expected_to = {w: w.spec.replay_to for w in works}
        self._victimized: set = set()
        self._negotiation = None
        self._steal_events: List[dict] = []
        self._next_index = len(self._specs)
        inflight = _registry().gauge("catchup.parallel.ranges-inflight")
        inflight.set_source(lambda: sum(1 for w in works if not w.done))
        eventlog.record("History", "INFO", "parallel catchup started",
                        target=target, ranges=len(self._specs),
                        workers=self.workers, steal=self.steal)
        t0 = _time.perf_counter()
        last_steal_check = 0.0
        for w in works:
            w.start()
        try:
            while True:
                if self.steal and len(works) > 1:
                    now = _time.perf_counter()
                    # an outstanding negotiation is settled EVERY
                    # iteration: a victim that accepts and exits right
                    # before the run drains must still get its thief
                    # spawned, or the stolen tail is replayed by nobody
                    if self._negotiation is not None \
                            or now - last_steal_check >= 0.1:
                        last_steal_check = now
                        self._maybe_steal(pm, works)
                if all(w.done for w in works) \
                        and self._negotiation is None:
                    break
                if self.clock.crank() == 0:
                    # REAL_TIME + subprocesses still running: yield the
                    # host instead of spinning the poll pump
                    _time.sleep(0.02)
        finally:
            pm.shutdown()
            # drop the closure over `works` (the registry is
            # process-global; a stale source would pin every RangeWork)
            inflight.set_source(lambda: 0)
        wall = _time.perf_counter() - t0
        failed = [w for w in works if not w.succeeded]
        if failed:
            w = failed[0]
            detail = (f"range {w.spec.index} "
                      f"(replay to {w.spec.replay_to}) failed after "
                      f"{w.retries} retries: {w.error_detail or '?'}")
            eventlog.record("History", "ERROR",
                            "parallel catchup range FAILED",
                            range=w.spec.index, detail=w.error_detail or "?")
            eventlog.write_crash_bundle(
                f"parallel catchup range failure: {detail}",
                crash_dir=self.crash_dir)
            raise CatchupError(detail)
        # chain order by seed: steals splice thieves into the middle of
        # the plan, and verify_stitches proves consecutive seams
        works_by_seed = sorted(
            works, key=lambda w: (w.result["seed_checkpoint"]
                                  if w.result["seed_checkpoint"] is not None
                                  else -1))
        results = [w.result for w in works_by_seed]
        stitches = verify_stitches(results, crash_dir=self.crash_dir)
        final = results[-1]
        if final["final_ledger_seq"] != target:
            raise CatchupError(
                f"parallel catchup ended at {final['final_ledger_seq']}, "
                f"target {target}")
        if not final.get("persisted"):
            raise CatchupError(
                f"range {final['index']} reached the target but did not "
                "persist its state")
        self._final_dir = self._range_dir(final["index"])
        self._gc_range_dirs(keep_index=final["index"])
        total = sum(r["ledgers_replayed"] for r in results)
        self.report = {
            "target": target,
            "workers": self.workers,
            "ranges": results,
            "stitches_verified": stitches,
            "steals": len(self._steal_events),
            "steal_events": self._steal_events,
            "final_ledger_seq": final["final_ledger_seq"],
            "final_hash": final["final_hash"],
            "ledgers_replayed": total,
            "wall_s": round(wall, 3),
            "ledgers_per_s": round(total / wall, 1) if wall > 0 else 0.0,
        }
        if self.mesh_devices:
            self.report["mesh_devices"] = self.mesh_devices
            self.report["device_assignments"] = {
                r["index"]: r.get("device_index") for r in results}
        eventlog.record("History", "INFO", "parallel catchup finished",
                        target=target, stitches=stitches,
                        steals=len(self._steal_events),
                        wall_s=round(wall, 1))
        log.info("parallel catchup: %d ledgers over %d ranges in %.1fs "
                 "(%.0f ledgers/s), %d stitches verified, %d steal(s)",
                 total, len(results), wall, self.report["ledgers_per_s"],
                 stitches, len(self._steal_events))
        return self.report

    def _gc_range_dirs(self, keep_index: int) -> None:
        """Interior ranges' state was only ever evidence for the stitch
        proof; reclaim the disk (the dir holding the ledger that reached
        the target survives for adoption)."""
        if self.keep_range_dirs:
            return
        for i in range(self._next_index):
            shutil.rmtree(self._ctl_dir(i), ignore_errors=True)
            if i != keep_index:
                shutil.rmtree(self._range_dir(i), ignore_errors=True)

    # -- adoption ----------------------------------------------------------
    def load_manager(self, bucket_store=None,
                     entry_cache_size: Optional[int] = None,
                     resident_levels: Optional[int] = None):
        """Rebuild a LedgerManager from the last range's persisted state
        (only reachable after run() proved every stitch)."""
        if self.report is None or self._final_dir is None:
            raise CatchupError("parallel catchup has not completed")
        from ..bucket.manager import BucketDir
        from ..database import Database
        from ..ledger.manager import LedgerManager
        db = Database(os.path.join(self._final_dir, "state.db"))
        bdir = BucketDir(os.path.join(self._final_dir, "buckets"))
        return LedgerManager.load_last_known_ledger(
            self.network_id, db, bdir, bucket_store=bucket_store,
            entry_cache_size=entry_cache_size,
            resident_levels=resident_levels)

    def adopt_into(self, database_path: str, bucket_dir_path: str) -> None:
        """Move the verified final range's durable state to the node's
        authoritative paths.  Never called on a failed run — run() raised
        before _final_dir was set, so tampered archives leave the real
        ledger untouched."""
        if self.report is None or self._final_dir is None:
            raise CatchupError("parallel catchup has not completed")
        os.makedirs(os.path.dirname(database_path) or ".", exist_ok=True)
        shutil.move(os.path.join(self._final_dir, "state.db"), database_path)
        if os.path.isdir(bucket_dir_path):
            shutil.rmtree(bucket_dir_path)
        shutil.move(os.path.join(self._final_dir, "buckets"),
                    bucket_dir_path)

    def cleanup(self) -> None:
        """Drop the whole workdir (owned temp dirs only, unless forced)."""
        if self._own_workdir:
            shutil.rmtree(self.workdir, ignore_errors=True)
