"""Catchup: bootstrap/resync a node from history archives.

Reference: src/catchup/CatchupWork.cpp (the work DAG root),
VerifyLedgerChainWork.cpp (back-chained previousLedgerHash verification),
ApplyBucketsWork.cpp + BucketApplicator (state snapshot assumption),
ApplyCheckpointWork.cpp (tx replay — THE north-star hot loop, SURVEY.md §3.3),
CatchupConfiguration (CATCHUP_COMPLETE vs minimal/recent modes).

TPU offload hook: before a checkpoint replays, every (pk, sig, payload)
triple that can be paired by signature hint is batch-verified on the
accelerator and the verdicts seeded into the process verify cache, so the
SignatureChecker inside TransactionFrame.apply hits the cache instead of
calling libsodium — observable behavior identical, compute hoisted
(BASELINE.json: "batches every envelope and transaction signature from a
catchup work-unit into a single vmapped Ed25519 verify").
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

from .. import xdr as X
from ..bucket.bucket import Bucket
from ..bucket.bucket_list import NUM_LEVELS
from ..crypto import keys
from ..crypto.sha import sha256
from ..history.archive import (CATEGORY_LEDGER, CATEGORY_RESULTS,
                               CATEGORY_TRANSACTIONS, FileHistoryArchive,
                               category_path, checkpoint_containing,
                               checkpoint_frequency,
                               first_ledger_in_checkpoint)
from ..ledger.manager import LedgerManager
from ..transactions.frame import TransactionFrame
import itertools

from ..util import eventlog
from ..util import logging as slog
from ..util.logging import discard_rate_limit, rate_limited
from ..util.metrics import registry as _registry
from ..util.racetrace import race_checked

log = slog.get("History")

_LHHE = X.LedgerHeaderHistoryEntry._xdr_adapter()
_THE = X.TransactionHistoryEntry._xdr_adapter()


class CatchupError(RuntimeError):
    pass


# monotone ids for per-pipeline rate-limit keys (GIL-atomic counter)
_PIPELINE_IDS = itertools.count(1)


def verify_ledger_chain(headers: Sequence[X.LedgerHeaderHistoryEntry],
                        trusted_tail_hash: Optional[bytes] = None) -> None:
    """Each entry's hash must equal SHA256 of its header, and each header
    must chain to the previous entry's hash (reference:
    VerifyLedgerChainWork — verified back from a trusted hash)."""
    prev_hash: Optional[bytes] = None
    for entry in headers:
        if sha256(entry.header.to_xdr()) != entry.hash:
            raise CatchupError(
                f"header self-hash mismatch at ledger {entry.header.ledgerSeq}")
        if prev_hash is not None and entry.header.previousLedgerHash != prev_hash:
            raise CatchupError(
                f"hash chain broken at ledger {entry.header.ledgerSeq}")
        prev_hash = entry.hash
    if trusted_tail_hash is not None and headers \
            and headers[-1].hash != trusted_tail_hash:
        raise CatchupError("chain tail does not match trusted hash")


@race_checked
class PreverifyPipeline:
    """Double-buffered TPU signature pre-verification (SURVEY §5.8:
    dispatch checkpoint k+1's batch while the CPU applies checkpoint k;
    reference pipelining shape: src/catchup/ — DownloadApplyTxsWork).

    ``dispatch(groups, ledger_state)`` pairs every hint-pairable signature
    of one or more checkpoints and enqueues the device kernels WITHOUT
    syncing (accel verify_async); ``collect(checkpoint)`` seeds the
    verdicts of the group containing that checkpoint into the process
    verify cache.  Between the two calls the device computes while the
    host applies earlier ledgers.

    Offload profiles (ISSUE 14: the device may only ever ADD throughput):

    * ``poll`` (the default) — collect() is a NON-BLOCKING poll: every
      materialized group's verdicts are harvested and seeded on the spot,
      and a group whose verdicts have not materialized yet is simply
      skipped (the apply verifies those signatures on CPU via libsodium —
      verdicts identical, the device is never waited on).  A group that
      ripens later is still seeded at the next checkpoint's collect, so
      its remaining checkpoints hit the cache (``sigs_late_seeded``).
      The consumer's worst case is the CPU replay rate minus the (small,
      measured) pairing cost — the device cannot drag it below that.
    * ``race`` (opt-in; the pre-ISSUE-14 behavior) — collect() waits,
      bounded by what libsodium would charge for the group, and repeated
      losses/wedges disable the pipeline.  The admission pipeline keeps
      this profile: it needs the batch's verdicts to answer the submitter.
    * ``sig-only`` — like ``poll``, but the pipeline never disables
      itself: the device ships signature verdicts opportunistically for
      as long as the catchup runs and is never on the ledger-close
      critical path, however slow it is.

    Pairing candidates per signature: the tx/fee-bump/op source accounts'
    master keys, every ed25519 signer of those accounts in `ledger_state`
    (reference hint semantics: SignatureChecker::checkSignature tries every
    signer whose hint matches), plus every ed25519 signer harvested from
    SetOptions operations of ANY checkpoint dispatched so far — dispatching
    k+1 against pre-k state is exact as long as signers added between the
    state snapshot and the tx's ledger are harvested, and in-order dispatch
    guarantees that.  Hint collisions pair against every matching
    candidate; a wrong pairing just caches a negative verdict for a tuple
    nobody asks about.  Unpaired signatures fall back to on-demand CPU
    verification — verdicts never differ, only where they're computed.
    """

    # profile names (see class docstring); DEFAULT_PROFILE is a class
    # attribute so tests that need deterministic blocking collects can pin
    # the legacy profile process-wide
    PROFILE_POLL = "poll"
    PROFILE_RACE = "race"
    PROFILE_SIG_ONLY = "sig-only"
    DEFAULT_PROFILE = "poll"
    PROFILES = (PROFILE_POLL, PROFILE_RACE, PROFILE_SIG_ONLY)

    def __init__(self, network_id: bytes, chunk_size: int = 2048,
                 stats: Optional[Dict[str, int]] = None,
                 hot_threshold: int = 1 << 62,
                 verdict_sink=None, pair_extractor=None,
                 profile: Optional[str] = None):
        self.network_id = network_id
        self.chunk_size = chunk_size
        self.profile = profile if profile is not None else self.DEFAULT_PROFILE
        if self.profile not in self.PROFILES:
            raise ValueError(f"unknown offload profile {self.profile!r} "
                             f"(know: {self.PROFILES})")
        # optional second consumer of collected verdicts (the native apply
        # engine's verify cache) alongside the process verify cache
        self.verdict_sink = verdict_sink
        # optional native pairing (bridge.extract_pairs): dispatch_raw
        # pairs straight from raw records, skipping Python frame decode
        self.pair_extractor = pair_extractor
        # per-key window tables on the replay path: default OFF (the r3
        # measurement said install dispatches cost more than they saved),
        # overridable for A/B — replay key sets are small and the verifier
        # (with its installed tables) persists across every group of a
        # catchup, so the r3 conclusion deserves a re-test per rig
        self.hot_threshold = hot_threshold
        self.stats = stats if stats is not None else {}
        # The tunneled PJRT backend executes lazily: device work happens at
        # materialization (np.asarray), NOT at kernel enqueue — JAX's async
        # dispatch alone buys no overlap here (measured: a dispatched
        # kernel sat idle through 2x its runtime of host busy-work, then
        # took full device time to collect).  So ALL device interaction for
        # a group — enqueue AND materialize — runs on ONE background daemon
        # worker, which blocks in the tunnel RPC with the GIL released
        # while the main thread applies ledgers.  Keeping enqueue off the
        # main thread also serializes every tunnel call: concurrent
        # main-thread enqueue + worker materialize wedged the tunnel
        # client intermittently (observed: a bench pass frozen mid-RPC
        # with zero CPU advance).  collect() waits with a timeout and
        # falls back to on-demand CPU verification if the tunnel wedges —
        # verdicts are then computed by libsodium instead of seeded, so
        # behavior degrades to CPU speed, never to a hang; the daemon
        # worker cannot block interpreter exit.
        self._worker = None
        self._jobs = None
        self._consecutive_wedges = 0
        self._consecutive_losses = 0
        self._first_collect_done = False
        self._disabled = False
        # hint (4 bytes) -> [pk, ...] of every SetOptions-added ed25519
        # signer seen in any dispatched checkpoint (cumulative: covers
        # signers added between the pairing state snapshot and apply)
        self._harvested_hint: Dict[bytes, List[bytes]] = {}
        self._groups: Dict[int, dict] = {}   # checkpoint -> shared group
        self._counted_sigs: Dict[int, int] = {}  # raw-path per-cp totals
        # poll-profile machinery: dispatched-but-unseeded groups in
        # dispatch order, harvested (non-blocking) at every collect.
        # Thread contract (ISSUE 15 audit): the device worker touches NO
        # pipeline state (see _submit) — every poll-profile field below
        # is read and written only by the dispatch/collect caller, so
        # each carries the owned-by attestation the thread-safety lint
        # checks and @race_checked enforces at runtime under make race.
        self._live_groups: List[dict] = []  # corelint: owned-by=main -- appended at dispatch, drained at collect; the device worker only fills job boxes
        self._collects_since_harvest = 0  # corelint: owned-by=main -- poll stand-down counter, bumped only inside _collect_poll
        self._harvested_once = False  # corelint: owned-by=main -- cold-vs-warm miss budget latch, flipped only in _harvest_ready
        # auto-tuned dispatch-ahead depth (recommended_coalesce): EWMAs of
        # the measured consumer rate (host apply seconds per checkpoint)
        # vs the measured device rate (seconds per paired signature)
        self._last_collect_t: Optional[float] = None  # corelint: owned-by=main -- consumer-rate EWMA input, collect-path only
        self._apply_s_per_cp: Optional[float] = None  # corelint: owned-by=main -- consumer-rate EWMA, collect-path only
        self._device_s_per_pair: Optional[float] = None  # corelint: owned-by=main -- device-rate EWMA; device wall rides home in the job box, folded in on harvest
        self._pairs_per_cp: Optional[float] = None  # corelint: owned-by=main -- dispatch-path EWMA of pairs per checkpoint
        # per-pipeline rate-limit key, unique for process lifetime (an
        # id(self) key would recycle addresses after GC and inherit a
        # dead pipeline's count); discarded in close()
        self._fallback_warn_key = \
            f"preverify-collect-fallback-{next(_PIPELINE_IDS)}"

    # a wedged tunnel RPC must degrade to CPU-speed verification, not hang
    # the catchup; generous enough for a cold compile (~60s observed)
    COLLECT_TIMEOUT_S = 180.0
    # after this many consecutive genuine wedges the device is presumed
    # dead and the pipeline disables itself — otherwise a long catchup
    # would pay the full timeout once per group (observed: the tunnel can
    # go down for an hour+)
    MAX_CONSECUTIVE_WEDGES = 2
    # CPU-race bound per PAIRED candidate.  Deliberately tighter than the
    # host's real ~60-70us/verify: the group's pair count exceeds its
    # signature count (hint collisions/multisig pair one sig against
    # several candidates), and a device that only ever finishes JUST
    # under a generous budget still loses end-to-end (measured at 10k
    # ledgers: 58.5s of under-budget waits vs the CPU's 34s total —
    # experiments/out_replay_at_scale_r5.txt).  40us x 1.25 means the
    # device must beat ~50us/pair — clearly faster than libsodium — or
    # the pipeline stands down.
    RACE_CPU_S_PER_SIG = 40e-6
    MAX_CONSECUTIVE_LOSSES = 3
    # collect-fallback warnings are rate-limited: a drifted chip can lose
    # the CPU race on EVERY group (r5 bench logs: one warning per group),
    # and the interesting signal is the first occurrence + the trend —
    # which catchup.preverify.fallback and stats carry in full
    FALLBACK_WARN_EVERY_N = 10
    # Test seam: when set (class attribute), called as DEVICE_GATE(i)
    # inside the device worker before group i's verdicts materialize.  A
    # test that must lose the CPU race DETERMINISTICALLY blocks the gate
    # for i >= 1 instead of hoping 0.25s of wall clock beats the device
    # (the old sleep-race test flaked whenever CPU-jax finished first).
    DEVICE_GATE = None
    # poll profile: a device that NEVER ripens a group still costs pairing
    # host-prep per dispatch — after this many consecutive checkpoint
    # collects without a single harvest the pipeline stands down (the
    # sig-only profile never does).  Before the first harvest ever, the
    # budget is much larger: the first group absorbs the kernel compile
    # (~60s observed), which can span many checkpoints of misses.
    MAX_POLL_MISS_COLLECTS = 8
    MAX_POLL_MISS_COLLECTS_COLD = 64
    # auto-tuned dispatch-ahead depth bounds (recommended_coalesce)
    MIN_COALESCE = 1
    MAX_COALESCE = 8

    def dispatched(self, checkpoint: int) -> bool:
        return checkpoint in self._groups

    def job_done(self, checkpoint: int) -> bool:
        """True when `checkpoint`'s device verdicts have materialized (its
        collect would return without waiting).  Non-blocking — the
        admission pipeline polls this to keep kernel warmup off the
        submission critical path."""
        group = self._groups.get(checkpoint)
        if group is None or group.get("collected") or group.get("seeded"):
            return True
        job = group["job"]
        if job is None:
            return True
        _box, ev, _q = job
        return ev.is_set()

    def _add_sigs_total(self, n: int) -> None:
        """One accounting seam for the offload hit-rate denominator —
        mirrored into the registry so /metrics and bench agree with
        stats.  The stats dict nets retractions exactly
        (correct_total_for_fallback can pass n < 0); the registry counter
        is clamped non-negative because it renders as a Prometheus
        counter, where a decrease reads as a process restart."""
        self.stats["sigs_total"] = self.stats.get("sigs_total", 0) + n
        if n > 0:
            _registry().counter("catchup.preverify.sigs-total").inc(n)

    def _note_not_dispatched(self, n: int) -> None:
        """Watermark accounting (ISSUE 14 satellite): signatures that never
        reached the device at all — unpairable hints, parser-rejected
        records, a disabled pipeline.  Distinct from 'device lost the
        race' (dispatched but not materialized in time), which
        _collect_poll/_collect_race count on the race-lost meter; the two
        causes used to share one opaque hit-rate gap."""
        if n <= 0:
            return
        self.stats["sigs_not_dispatched"] = \
            self.stats.get("sigs_not_dispatched", 0) + n
        _registry().counter("catchup.preverify.not-dispatched").inc(n)

    def _submit(self, fn):
        """Run fn on the single daemon device-worker; returns (box, event).
        box["result"]/box["error"] is set before event fires.

        Thread contract (ISSUE 9 audit): the worker body touches NO
        pipeline instance state — only its own job tuple (box/event) and
        the generation-tagged queue, handed over through Queue's internal
        lock and Event's release ordering.  `_worker`/`_jobs` themselves
        are written only from the dispatching (main) thread, which is why
        the thread-safety reachability rule finds the worker role
        field-free."""
        import queue
        import threading
        if self._worker is None:
            jobs = queue.Queue()
            self._jobs = jobs

            def run(jobs=jobs):
                # the worker serves ONLY its own generation's queue: an
                # abandoned (wedged) worker that later un-wedges must not
                # rebind to a successor's queue — two workers draining one
                # queue would reintroduce the concurrent tunnel calls this
                # design exists to prevent
                while True:
                    item = jobs.get()
                    if item is None:
                        return
                    jfn, jbox, jev = item
                    try:
                        jbox["result"] = jfn()
                    except BaseException as e:  # corelint: disable=exception-hygiene -- verdict box re-raised at collect()
                        jbox["error"] = e
                    jev.set()

            self._worker = threading.Thread(target=run, daemon=True,
                                            name="preverify-device")
            self._worker.start()
        box: dict = {}
        ev = threading.Event()
        self._jobs.put((fn, box, ev))
        # the queue ref tags the job's worker generation: after a wedge the
        # stale queue's remaining jobs will never run, and collect() must
        # fall back immediately instead of waiting out a timeout per group
        return box, ev, self._jobs

    def dispatch(self, frames_by_checkpoint: Dict[int, Sequence[TransactionFrame]],
                 ledger_state=None) -> None:
        """Pair + enqueue one device batch covering every checkpoint in
        `frames_by_checkpoint` (ascending order).  No device sync.

        Takes DECODED frames — the same objects the apply will execute
        (decoded once at download, content_hash memoized per frame), so the
        accel pass never re-decodes the replay stream (VERDICT r3 weak #2)."""
        if self._disabled:
            # device presumed dead: pure CPU verification.  Still count
            # the signatures so offload_hit_rate() honestly reflects the
            # un-offloaded remainder instead of freezing at ~1.0, and
            # register a no-op collected group so the apply path sees
            # dispatched()==True and does not re-dispatch/double-count.
            total = 0
            for cp in frames_by_checkpoint:
                for frame in frames_by_checkpoint[cp]:
                    total += len(frame.signatures)
            self._add_sigs_total(total)
            self._note_not_dispatched(total)
            cps = sorted(frames_by_checkpoint)
            group = {"job": None, "pks": [], "sigs": [], "msgs": [],
                     "checkpoints": cps, "collected": True}
            for cp in cps:
                self._groups[cp] = group
            return
        import time as _time

        from ..accel.ed25519 import verify_batch_async
        from ..transactions.utils import account_key

        t0 = _time.perf_counter()
        cps = sorted(frames_by_checkpoint)
        signer_cache: Dict[bytes, List[bytes]] = {}

        def signers_of(acc_id_val: bytes) -> List[bytes]:
            if ledger_state is None:
                return []
            got = signer_cache.get(acc_id_val)
            if got is not None:
                return got
            entry = ledger_state.get_entry(account_key(
                X.AccountID.ed25519(acc_id_val)).to_xdr())
            out: List[bytes] = []
            if entry is not None:
                for s in entry.data.value.signers:
                    if s.key.switch == X.SignerKeyType.SIGNER_KEY_TYPE_ED25519:
                        out.append(s.key.value)
            signer_cache[acc_id_val] = out
            return out

        frames: List[TransactionFrame] = []
        for cp in cps:
            frames.extend(frames_by_checkpoint[cp])
        # harvest before pairing: a signer added late in the group still
        # pairs a tx earlier in it (superset candidates are harmless)
        harvested = self._harvested_hint
        for frame in frames:
            for op in frame.operations:
                if op.body.switch == X.OperationType.SET_OPTIONS:
                    signer = op.body.value.signer
                    if signer is not None and signer.key.switch == \
                            X.SignerKeyType.SIGNER_KEY_TYPE_ED25519:
                        pk = signer.key.value
                        lst = harvested.setdefault(pk[28:32], [])
                        if pk not in lst:
                            lst.append(pk)

        pks: List[bytes] = []
        sigs: List[bytes] = []
        msgs: List[bytes] = []
        pairs_by_cp: Dict[int, int] = {}
        for cp in cps:
            total = paired = 0
            for frame in frames_by_checkpoint[cp]:
                h = frame.content_hash()
                account_ids = [frame.source_account_id().value]
                if hasattr(frame, "inner"):
                    account_ids.append(frame.inner.source_account_id().value)
                for op in frame.operations:
                    if op.sourceAccount is not None:
                        account_ids.append(
                            X.muxed_to_account_id(op.sourceAccount).value)
                candidates = list(account_ids)
                for aid in account_ids:
                    candidates.extend(signers_of(aid))
                total += len(frame.signatures)
                for dsig in frame.signatures:
                    seen = set()
                    for pk in candidates:
                        if dsig.hint == pk[28:32] and pk not in seen:
                            seen.add(pk)
                            pks.append(pk)
                            sigs.append(dsig.signature)
                            msgs.append(h)
                    for pk in harvested.get(dsig.hint, ()):
                        if pk not in seen:
                            seen.add(pk)
                            pks.append(pk)
                            sigs.append(dsig.signature)
                            msgs.append(h)
                    if seen:
                        paired += 1
            pairs_by_cp[cp] = paired
            self._add_sigs_total(total)
            self._note_not_dispatched(total - paired)
        # sigs_shipped is counted at COLLECT time (successful seeding
        # only): a group that wedges and falls back to CPU never shipped
        self._enqueue_group(cps, pks, sigs, msgs, t0, pairs_by_cp)

    def dispatch_raw(self, recs_by_checkpoint: Dict[int, Sequence[bytes]]
                     ) -> None:
        """dispatch() for the native path: pairing runs in C straight from
        the raw transaction records (no Python frame decode)."""
        cps = sorted(recs_by_checkpoint)
        if self._disabled or self.pair_extractor is None:
            # count signatures per checkpoint (honest hit rate denominator)
            # without materializing pairs, then register a no-op group
            for cp in cps:
                n = self._count_and_record(cp, recs_by_checkpoint[cp])
                self._add_sigs_total(n)
                self._note_not_dispatched(n)
            group = {"job": None, "pks": [], "sigs": [], "msgs": [],
                     "checkpoints": cps, "collected": True}
            for cp in cps:
                self._groups[cp] = group
            return
        import time as _time
        t0 = _time.perf_counter()
        pks, sigs, msgs = [], [], []
        pairs_by_cp: Dict[int, int] = {}
        for cp in cps:
            # per-checkpoint extraction: records each checkpoint's counted
            # total so the Python-fallback apply can correct the
            # denominator for records the C parser rejected (their
            # signatures are neither paired nor counted here)
            p_, s_, m_, total = self.pair_extractor(recs_by_checkpoint[cp])
            pks.extend(p_)
            sigs.extend(s_)
            msgs.extend(m_)
            # distinct signatures paired (hint collisions pair one sig
            # against several candidates — count the sig once)
            paired = len({bytes(s) for s in s_})
            pairs_by_cp[cp] = paired
            self._counted_sigs[cp] = total
            self._add_sigs_total(total)
            self._note_not_dispatched(total - paired)
        self._enqueue_group(cps, pks, sigs, msgs, t0, pairs_by_cp)

    def _count_and_record(self, cp, recs) -> int:
        from stellar_core_tpu import _capply
        n = 0
        for r in recs:
            try:
                _, sig_count = _capply.scan_tx_record(self.network_id, r)
                n += sig_count
            except _capply.Error:
                pass
        self._counted_sigs[cp] = n
        return n

    def correct_total_for_fallback(self, checkpoint: int,
                                   python_total: int) -> None:
        """A probe-rejected checkpoint re-counts its signatures from the
        decoded frames; replace whatever partial count the raw extraction
        recorded for it (records the C parser rejected were uncounted)."""
        counted = self._counted_sigs.pop(checkpoint, None)
        if counted is None:
            return
        self._add_sigs_total(python_total - counted)
        # records the C parser rejected were never paired either — they
        # belong to the never-dispatched bucket, not the race-lost one
        self._note_not_dispatched(python_total - counted)

    def _enqueue_group(self, cps, pks, sigs, msgs, t0,
                       pairs_by_cp: Optional[Dict[int, int]] = None) -> None:
        import time as _time

        from ..accel.ed25519 import verify_batch_async
        job = None
        if pks:
            # tail_floor=chunk_size: one compiled shape per path, amortized
            # across every checkpoint of the catchup.  hot_threshold
            # selects the per-key-table path (see __init__) vs the
            # single-kernel-per-chunk generic path.
            chunk = self.chunk_size
            hot = self.hot_threshold
            gate = self.DEVICE_GATE
            group_idx = self.stats.get("dispatch_groups", 0)

            def device_job(pks=pks, sigs=sigs, msgs=msgs):
                if gate is not None:
                    gate(group_idx)
                tj = _time.perf_counter()
                verdicts = verify_batch_async(
                    pks, sigs, msgs, chunk_size=chunk,
                    tail_floor=chunk, hot_threshold=hot)()
                # device wall rides along for the dispatch-depth auto-tune
                return verdicts, _time.perf_counter() - tj

            job = self._submit(device_job)
        group = {"job": job, "pks": pks, "sigs": sigs,
                 "msgs": msgs, "checkpoints": cps,
                 "pairs_by_cp": pairs_by_cp or {},
                 "collected_cps": set()}
        if job is not None:
            self._live_groups.append(group)
        for cp in cps:
            self._groups[cp] = group
        # phase accounting (bench per-phase breakdown): host prep + enqueue
        dt = _time.perf_counter() - t0
        self.stats["dispatch_s"] = self.stats.get("dispatch_s", 0.0) + dt
        self.stats["dispatch_groups"] = \
            self.stats.get("dispatch_groups", 0) + 1
        _registry().timer("catchup.preverify.dispatch").update(dt)

    def collect(self, checkpoint: int) -> None:
        """Make `checkpoint`'s verdicts available to the apply (no-op if
        never dispatched or already collected) by seeding the verify
        cache.  Poll/sig-only profiles NEVER wait: ready groups are
        harvested on the spot, unripe ones fall back to on-demand CPU
        verification (race-lost accounting) and may still seed later
        checkpoints when they ripen.  The race profile keeps the
        pre-ISSUE-14 bounded wait.  Later checkpoints of an
        already-collected group stay registered in `_groups` so
        dispatched() keeps answering True for them — popping them all at
        first collect made the apply path re-dispatch each one
        synchronously (measured: every coalesced group was followed by N-1
        redundant singleton dispatches)."""
        if self.profile == self.PROFILE_RACE:
            self._collect_race(checkpoint)
        else:
            self._collect_poll(checkpoint)

    def _seed_group(self, group: dict, verdicts) -> None:
        """Push one materialized group's verdicts into the process verify
        cache (and the native engine's, via verdict_sink) — main thread
        only: the sink touches C engine state."""
        pks, sigs, msgs = group["pks"], group["sigs"], group["msgs"]
        keys.seed_verify_cache(
            (pks[i], sigs[i], msgs[i], bool(verdicts[i]))
            for i in range(len(pks)))
        if self.verdict_sink is not None:
            self.verdict_sink(pks, sigs, msgs, verdicts)
        self.stats["sigs_shipped"] = \
            self.stats.get("sigs_shipped", 0) + len(pks)
        _registry().counter("catchup.preverify.sigs-shipped").inc(len(pks))

    def _count_fallback(self, group: dict, why: str) -> None:
        n_fallbacks = self.stats.get("collect_fallbacks", 0) + 1
        self.stats["collect_fallbacks"] = n_fallbacks
        _registry().counter("catchup.preverify.fallback").inc()
        emit, _n = rate_limited(log, self._fallback_warn_key,
                                self.FALLBACK_WARN_EVERY_N)
        emit("preverify group %s for checkpoints %s — falling back to "
             "on-demand CPU verification (occurrence %d)",
             why, group["checkpoints"], n_fallbacks)
        if emit is not log.warning:
            eventlog.record("History", "WARNING",
                            "preverify collect fallback", why=why,
                            checkpoints=str(group["checkpoints"]),
                            occurrence=n_fallbacks)

    def _harvest_ready(self) -> None:
        """Seed every dispatched group whose device verdicts have
        materialized — a non-blocking sweep run at each collect.  A group
        that ripens after its own checkpoints started applying still seeds
        here: the group's LATER checkpoints (coalesced dispatch) then hit
        the cache instead of recomputing (counted as sigs_late_seeded)."""
        if not self._live_groups:
            return
        harvested = False
        for group in list(self._live_groups):
            box, ev, q = group["job"]
            if not ev.is_set():
                if q is not self._jobs:
                    # stale worker generation (dropped at disable): these
                    # verdicts are never coming
                    self._live_groups.remove(group)
                    group["seeded"] = True
                    self._count_fallback(group, "stranded on a dropped "
                                         "worker generation")
                continue
            self._live_groups.remove(group)
            group["seeded"] = True
            if "error" in box:
                self._count_fallback(group, f"failed: {box['error']}")
                continue
            verdicts, dur_s = box["result"]
            self._seed_group(group, verdicts)
            n_pairs = max(1, len(group["pks"]))
            self._device_s_per_pair = self._ewma(
                self._device_s_per_pair, dur_s / n_pairs)
            self._pairs_per_cp = self._ewma(
                self._pairs_per_cp,
                len(group["pks"]) / max(1, len(group["checkpoints"])))
            late = sum(group.get("pairs_by_cp", {}).get(c, 0)
                       for c in group.get("collected_cps", ()))
            if late:
                # seeded after those checkpoints' applies already began:
                # their earlier ledgers recomputed on CPU, the rest hit
                self.stats["sigs_late_seeded"] = \
                    self.stats.get("sigs_late_seeded", 0) + late
                _registry().counter("catchup.preverify.late-seeded") \
                    .inc(late)
            harvested = True
        if harvested:
            self._harvested_once = True
            self._collects_since_harvest = 0

    def _collect_poll(self, checkpoint: int) -> None:
        """Never-wait collect: harvest whatever has ripened; a miss for
        THIS checkpoint degrades to on-demand CPU verification (verdicts
        identical — only where they're computed differs) and is metered
        as a race loss.  The device can only ever ADD throughput."""
        import time as _time
        now = _time.perf_counter()
        if self._last_collect_t is not None:
            dt = now - self._last_collect_t
            if 0.0 < dt < 30.0:   # ignore boot/compile outliers
                self._apply_s_per_cp = self._ewma(self._apply_s_per_cp, dt)
        self._last_collect_t = now
        group = self._groups.pop(checkpoint, None)
        self._harvest_ready()
        if group is None or group.get("collected") or group["job"] is None:
            return
        if group.get("seeded"):
            group.setdefault("collected_cps", set()).add(checkpoint)
            return
        # the device lost the race for this checkpoint: its signatures
        # verify on CPU during the apply; the group stays live and may
        # still seed the later checkpoints it covers
        group.setdefault("collected_cps", set()).add(checkpoint)
        paired = group.get("pairs_by_cp", {}).get(checkpoint, 0)
        self.stats["sigs_race_lost"] = \
            self.stats.get("sigs_race_lost", 0) + paired
        self.stats["collect_race_misses"] = \
            self.stats.get("collect_race_misses", 0) + 1
        if paired:
            _registry().counter("catchup.preverify.race-lost").inc(paired)
        self._collects_since_harvest += 1
        budget = (self.MAX_POLL_MISS_COLLECTS if self._harvested_once
                  else self.MAX_POLL_MISS_COLLECTS_COLD)
        if self.profile != self.PROFILE_SIG_ONLY \
                and self._collects_since_harvest >= budget:
            # the device has not produced one verdict across `budget`
            # checkpoints: stop paying pairing prep for it.  The worker
            # generation is abandoned (daemon; dies with the process).
            self._disabled = True
            self._worker = None
            self._jobs = None
            log.warning(
                "preverify pipeline DISABLED after %d checkpoint collects "
                "without a single materialized device group — remaining "
                "catchup verifies on CPU", self._collects_since_harvest)

    def _collect_race(self, checkpoint: int) -> None:
        """The opt-in pre-ISSUE-14 behavior: a bounded wait for the
        group's verdicts (the admission pipeline needs them to answer the
        submitter), with wedge/race-loss disable."""
        group = self._groups.pop(checkpoint, None)
        if group is None or group.get("collected"):
            return
        group["collected"] = True
        if group in self._live_groups:
            self._live_groups.remove(group)
        job = group["job"]
        if job is None:
            return
        import time as _time
        box, ev, q = job
        t0 = _time.perf_counter()
        stale = q is not self._jobs and not ev.is_set()
        # RACE-BOUNDED wait (round 5): with the native apply engine the
        # device is the replay critical path, so waiting longer than the
        # group's CPU-verify cost LOSES outright (measured: a drifted chip
        # turned a 3s replay into 55s of collect_wait).  Bound the wait by
        # what libsodium would charge for the group; a miss skips seeding
        # (the engine recomputes on CPU — verdicts identical) without
        # abandoning the worker, and repeated losses disable the pipeline
        # for the rest of the catchup.  The FIRST collect keeps the long
        # wedge timeout: it absorbs kernel compiles and is the only probe
        # that can tell a wedged tunnel from a slow one.
        if self._first_collect_done:
            budget = min(self.COLLECT_TIMEOUT_S,
                         max(0.25, len(group["pks"])
                             * self.RACE_CPU_S_PER_SIG * 1.25))
        else:
            budget = self.COLLECT_TIMEOUT_S
        if stale:
            done = False   # stale worker generation: never going to finish
        else:
            done = ev.wait(budget)
        # sync stall: how long the apply cursor waited on the device —
        # ~0 when double-buffering hid the compute under earlier applies
        wait = _time.perf_counter() - t0
        self.stats["collect_wait_s"] = \
            self.stats.get("collect_wait_s", 0.0) + wait
        _registry().timer("catchup.preverify.collect-wait").update(wait)
        race_loss = (not done and not stale
                     and budget < self.COLLECT_TIMEOUT_S)
        first = not self._first_collect_done
        self._first_collect_done = True
        if not done or "error" in box:
            n_fallbacks = self.stats.get("collect_fallbacks", 0) + 1
            self.stats["collect_fallbacks"] = n_fallbacks
            _registry().counter("catchup.preverify.fallback").inc()
            # first occurrence + every Nth at WARNING (with the running
            # count); the rest at DEBUG — the per-group counter metric
            # above keeps the exact tally either way
            why = (("lost the CPU race" if race_loss else "timed out")
                   if not done else f"failed: {box.get('error')}")
            # keyed per pipeline: each catchup gets its own loud first
            # occurrence, and the emit cadence tracks the same count the
            # message prints (a process-wide key would let an earlier
            # catchup swallow this one's first WARNING)
            emit, _n = rate_limited(log, self._fallback_warn_key,
                                    self.FALLBACK_WARN_EVERY_N)
            emit(
                "preverify collect %s for checkpoints %s — falling back to "
                "on-demand CPU verification (occurrence %d%s)",
                why, group["checkpoints"], n_fallbacks,
                "" if n_fallbacks == 1 else
                f"; warning logged every {self.FALLBACK_WARN_EVERY_N}th")
            if emit is not log.warning:
                # quiet occurrences still land in the flight recorder
                # with structured fields; loud ones arrive via the
                # WARNING bridge — never both (duplicates would burn
                # bounded ring slots on a degraded catchup)
                eventlog.record("History", "WARNING",
                                "preverify collect fallback",
                                why=why,
                                checkpoints=str(group["checkpoints"]),
                                occurrence=n_fallbacks)
            if race_loss:
                # the device is slower than libsodium on this group; the
                # worker keeps running (its queue drains eventually) but
                # repeated losses mean the chip can't win today
                self._consecutive_losses += 1
                self.stats["race_losses"] = \
                    self.stats.get("race_losses", 0) + 1
                lost = sum(group.get("pairs_by_cp", {}).values())
                self.stats["sigs_race_lost"] = \
                    self.stats.get("sigs_race_lost", 0) + lost
                if lost:
                    _registry().counter("catchup.preverify.race-lost") \
                        .inc(lost)
                if self._consecutive_losses >= self.MAX_CONSECUTIVE_LOSSES:
                    self._disabled = True
                    log.warning(
                        "preverify pipeline DISABLED after %d consecutive "
                        "CPU-race losses — the device is slower than "
                        "libsodium on this rig right now; remaining "
                        "catchup verifies on CPU", self._consecutive_losses)
            elif not done and not stale:
                # a genuine wedge (full timeout, incl. the first-collect
                # compile grace): abandon this worker generation (the
                # daemon thread stays blocked harmlessly); a stale job's
                # current worker is healthy and keeps serving
                self._worker = None
                self._jobs = None
                self._consecutive_wedges += 1
                if first or self._consecutive_wedges >= \
                        self.MAX_CONSECUTIVE_WEDGES:
                    self._disabled = True
                    log.warning(
                        "preverify pipeline DISABLED after %d consecutive "
                        "device wedges — remaining catchup verifies on CPU",
                        self._consecutive_wedges)
            return
        self._consecutive_wedges = 0
        self._consecutive_losses = 0
        verdicts, _dur_s = box["result"]
        self._seed_group(group, verdicts)

    @staticmethod
    def _ewma(prev: Optional[float], x: float,
              alpha: float = 0.3) -> float:
        return x if prev is None else prev + alpha * (x - prev)

    def recommended_coalesce(self, current: int) -> int:
        """Dispatch-ahead depth auto-tuned against the measured consumer
        rate (poll/sig-only profiles; CatchupWork consults this before
        every dispatch sweep).  When the device's measured per-checkpoint
        verify time exceeds the host's per-checkpoint apply time the depth
        GROWS — bigger coalesced groups amortize the per-dispatch tunnel
        overhead, and in poll mode a late group costs nothing.  When the
        device is comfortably ahead the depth shrinks so seeds stay fresh
        (smaller groups materialize sooner)."""
        if self._disabled:
            return current
        if self._apply_s_per_cp is None or self._device_s_per_pair is None \
                or self._pairs_per_cp is None:
            return current
        device_s_per_cp = self._device_s_per_pair * self._pairs_per_cp
        if device_s_per_cp > self._apply_s_per_cp:
            return min(self.MAX_COALESCE, current * 2)
        if device_s_per_cp < 0.5 * self._apply_s_per_cp:
            return max(self.MIN_COALESCE, current - 1)
        return current

    def close(self) -> None:
        """Release the device worker (a pipeline is per-catchup; a node
        that resyncs repeatedly must not accumulate idle workers).  A
        healthy worker exits on the None sentinel; a wedged one is daemon
        and dies with the process."""
        if self._jobs is not None:
            self._jobs.put(None)
        self._worker = None
        self._jobs = None
        self._live_groups = []
        discard_rate_limit(self._fallback_warn_key)


def preverify_checkpoint_signatures(network_id: bytes,
                                    tx_entries: Sequence[X.TransactionHistoryEntry],
                                    chunk_size: int = 2048,
                                    ledger_state=None) -> Dict[str, int]:
    """Synchronous single-checkpoint wrapper over PreverifyPipeline
    (dispatch + immediate collect) — kept for differential tests and
    callers outside the pipelined catchup DAG."""
    frames = [TransactionFrame.make_from_wire(network_id, env)
              for entry in tx_entries for env in entry.txSet.txs]
    pipe = PreverifyPipeline(network_id, chunk_size)
    try:
        pipe.dispatch({0: frames}, ledger_state=ledger_state)
        pipe.collect(0)
    finally:
        pipe.close()
    return {"total": pipe.stats.get("sigs_total", 0),
            "shipped": pipe.stats.get("sigs_shipped", 0)}


@dataclass
class CatchupRange:
    """Partition of a catchup target into a bucket-apply point and a
    replay range (reference: src/catchup/CatchupRange.{h,cpp} — the
    `--at X --count N` / CATCHUP_RECENT planning)."""
    apply_buckets_at: Optional[int]   # checkpoint to assume; None = genesis
    replay_to: int

    @property
    def replay_from(self) -> int:
        return (self.apply_buckets_at + 1 if self.apply_buckets_at
                else 2)


def plan_catchup_range(target: int, count: Optional[int]) -> CatchupRange:
    """Choose the newest published checkpoint boundary that still leaves
    >= `count` ledgers to replay before `target` (reference:
    CatchupRange's 'replayed range covers count, buckets cover the rest').
    count=None (CATCHUP_COMPLETE) replays everything from genesis."""
    freq = checkpoint_frequency()
    first_boundary = freq - 1   # 63 at the default cadence
    if count is None or target - count < first_boundary:
        return CatchupRange(apply_buckets_at=None, replay_to=target)
    boundary = ((target - count + 1) // freq) * freq - 1
    if boundary < first_boundary:
        return CatchupRange(apply_buckets_at=None, replay_to=target)
    return CatchupRange(apply_buckets_at=boundary, replay_to=target)


def _archive_state(archive: FileHistoryArchive, checkpoint=None):
    """get_state with hostile-HAS errors localized to CatchupError."""
    try:
        return archive.get_state(checkpoint)
    except (ValueError, OSError) as e:
        raise CatchupError(f"malformed archive HAS: {e}") from e


class CatchupManager:
    """Replay/assume-state driver (reference: CatchupManagerImpl +
    CatchupWork).  `accel=True` routes checkpoint signature verification
    through the TPU batch backend."""

    def __init__(self, network_id: bytes, network_passphrase: str,
                 accel: bool = False, accel_chunk: int = 2048,
                 invariant_manager=None,
                 accel_hot_threshold: int = 1 << 62,
                 native: Optional[bool] = None,
                 bucket_store=None,
                 entry_cache_size: Optional[int] = None,
                 resident_levels: Optional[int] = None,
                 accel_profile: Optional[str] = None):
        """invariant_manager: None (default — the bench/hot replay path;
        the hash chain is the corruption *detector*) or an
        InvariantManager to also *localize* faults during replay and
        bucket apply (reference: INVARIANT_CHECKS honored in catchup).

        native: route supported checkpoints through the native C apply
        engine (native/capply.c).  Default (None) = auto: on when the
        extension is built, no invariants are requested (the invariant
        hooks live on the Python close path), and STELLAR_TPU_NO_CAPPLY
        is unset.  An EXPLICIT native=True that cannot be honored logs a
        prominent warning instead of silently degrading.  The Python
        engine remains the oracle and the fallback for unsupported tx
        shapes.

        bucket_store: a bucket.manager.BucketListStore → every
        LedgerManager this catchup builds runs in BucketListDB mode
        (`in_memory_ledger = false`): assumed/replayed state lives in
        indexed on-disk bucket files, reads go through the bounded
        `entry_cache_size` LRU, and bucket-list levels >=
        `resident_levels` (config BUCKET_RESIDENT_LEVELS) stay
        disk-resident — streaming decode-free merges, no decoded entry
        lists."""
        self.network_id = network_id
        self.network_passphrase = network_passphrase
        self.accel = accel
        self.accel_chunk = accel_chunk
        self.accel_hot_threshold = accel_hot_threshold
        # offload profile (PreverifyPipeline docstring): None = the
        # pipeline default ("poll" — the device can only add throughput)
        self.accel_profile = accel_profile
        self.invariant_manager = invariant_manager
        self.bucket_store = bucket_store
        self.entry_cache_size = entry_cache_size
        self.resident_levels = resident_levels
        from ..ledger.native_apply import native_apply_available
        self.native = (native if native is not None else True) \
            and native_apply_available() and invariant_manager is None
        if native is True and not self.native:
            # an explicit request that cannot be honored must be LOUD —
            # the Python path is ~an order of magnitude slower (ADVICE r5)
            reason = ("an invariant_manager forces the Python apply path"
                      if native_apply_available() else
                      "the _capply extension is not built "
                      "(or STELLAR_TPU_NO_CAPPLY is set)")
            log.warning(
                "native apply engine EXPLICITLY requested but unavailable "
                "(%s) — falling back to the ~10x slower Python engine",
                reason)
        # offload hit-rate accounting (VERDICT r1 weak #4)
        self.stats = {"sigs_total": 0, "sigs_shipped": 0}

    def offload_hit_rate(self) -> float:
        t = self.stats["sigs_total"]
        return self.stats["sigs_shipped"] / t if t else 0.0

    # -- archive readers ----------------------------------------------------
    def _read_headers(self, archive: FileHistoryArchive,
                      checkpoint: int) -> List[X.LedgerHeaderHistoryEntry]:
        try:
            recs = archive.get_xdr_file(
                category_path(CATEGORY_LEDGER, checkpoint))
            if recs is None:
                raise CatchupError(
                    f"missing ledger file for checkpoint {checkpoint}")
            return [_LHHE.unpack(r) for r in recs]
        except (X.XdrError, ValueError, OSError) as e:
            # hostile/corrupt stream: bad gzip, truncated record, inflate
            # cap exceeded, XDR decode failure — one localized error class
            raise CatchupError(
                f"corrupt ledger file at checkpoint {checkpoint}: {e}") from e

    # -- complete replay (from genesis) ------------------------------------
    def catchup_complete(self, archive: FileHistoryArchive,
                         to_ledger: Optional[int] = None,
                         clock=None, lookahead: int = 2,
                         checkpoint_hook=None) -> LedgerManager:
        """Replay every ledger from genesis to the target, built from the
        historywork DAG: per-checkpoint download/verify units run
        `lookahead` ahead of the sequential cooperative apply, with retry
        backoff on archive corruption (reference: CATCHUP_COMPLETE —
        CatchupWork + DownloadApplyTxsWork + ApplyCheckpointWork)."""
        from ..historywork.works import CatchupWork
        from ..util.clock import ClockMode, VirtualClock

        has = _archive_state(archive)
        if has is None:
            raise CatchupError("archive has no HAS")
        target = to_ledger if to_ledger is not None else has.current_ledger

        mgr = LedgerManager(self.network_id,
                            invariant_manager=self.invariant_manager,
                            bucket_store=self.bucket_store,
                            entry_cache_size=self.entry_cache_size,
                            resident_levels=self.resident_levels)
        mgr.start_new_ledger()
        self._run_catchup_work(mgr, archive, target, clock, lookahead,
                               checkpoint_hook)
        return mgr

    def _run_catchup_work(self, mgr: LedgerManager,
                          archive: FileHistoryArchive, target: int,
                          clock=None, lookahead: int = 2,
                          checkpoint_hook=None) -> int:
        """Crank a CatchupWork DAG from mgr's current LCL to `target`
        (shared by complete and recent modes).  `checkpoint_hook(lcl)`
        runs after every applied checkpoint; returning a lower ledger
        (a published boundary >= lcl) TRUNCATES the target — the
        work-stealing seam (catchup.parallel): a range worker that
        accepted a steal limit stops at the split boundary.  Returns the
        effective target actually replayed to."""
        from ..historywork.works import CatchupWork
        from ..util.clock import ClockMode, VirtualClock

        if clock is None:
            clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        bridge = None
        if self.native:
            from ..ledger.native_apply import NativeApplyBridge
            bridge = NativeApplyBridge(self.network_id)
            bridge.import_from(mgr)
            mgr.native_bridge = bridge
        work = CatchupWork(clock, mgr, archive, target, self.network_id,
                           accel=self.accel, accel_chunk=self.accel_chunk,
                           lookahead=lookahead, stats=self.stats,
                           accel_hot_threshold=self.accel_hot_threshold,
                           # with the native engine, BOTH apply and accel
                           # pairing parse raw records in C — Python frame
                           # decode happens only on fallback checkpoints
                           decode_txs=not self.native,
                           keep_raw=self.native,
                           accel_profile=self.accel_profile,
                           checkpoint_hook=checkpoint_hook,
                           verdict_sink=(bridge.seed_verdicts
                                         if bridge is not None and self.accel
                                         else None),
                           pair_extractor=(bridge.extract_pairs
                                           if bridge is not None and
                                           self.accel else None))
        work.start()
        try:
            while not work.done:
                if clock.crank() == 0:
                    raise CatchupError("catchup work stalled")
        finally:
            # a stalled DAG never reaches the work's finish hooks — the
            # collector thread must still be released
            work._close_pipeline()
            if bridge is not None:
                mgr.native_bridge = None
                if bridge.active:
                    bridge.export_to_manager(mgr)
                self.stats.update(
                    {f"native_{k}": v for k, v in bridge.stats().items()})
                # checkpoint outcome split (bench catchup column): how
                # many checkpoints ran native vs fell back to Python
                self.stats["native_checkpoints"] = \
                    self.stats.get("native_checkpoints", 0) \
                    + bridge.native_checkpoints
                self.stats["native_fallback_checkpoints"] = \
                    self.stats.get("native_fallback_checkpoints", 0) \
                    + bridge.fallback_checkpoints
        if not work.succeeded:
            detail = work.error_detail or "unknown failure"
            raise CatchupError(
                f"catchup ended at {mgr.last_closed_ledger_seq}, "
                f"target {target}: {detail}")
        # the hook may have truncated the target (work stealing): the
        # WORK's target is the authoritative one the replay must reach
        if mgr.last_closed_ledger_seq != work.target:
            raise CatchupError(
                f"catchup ended at {mgr.last_closed_ledger_seq}, "
                f"target {work.target}")
        return work.target

    # -- recent (assume buckets at a boundary, replay the tail) -------------
    def catchup_recent(self, archive: FileHistoryArchive, count: int,
                       to_ledger: Optional[int] = None,
                       clock=None, lookahead: int = 2) -> LedgerManager:
        """CATCHUP_RECENT / `catchup --at X --count N`: assume the bucket
        snapshot at the newest checkpoint leaving >= count ledgers to
        replay, then replay the tail to the target (reference:
        CatchupWork over a CatchupRange with both bucket-apply and replay
        segments)."""
        has = _archive_state(archive)
        if has is None:
            raise CatchupError("archive has no HAS")
        target = to_ledger if to_ledger is not None else has.current_ledger
        rng = plan_catchup_range(target, count)
        if rng.apply_buckets_at is None:
            return self.catchup_complete(archive, to_ledger=target,
                                         clock=clock, lookahead=lookahead)
        log.info("catchup range: buckets at %d, replay %d..%d",
                 rng.apply_buckets_at, rng.replay_from, rng.replay_to)
        mgr = self.catchup_minimal(archive, checkpoint=rng.apply_buckets_at)
        if mgr.last_closed_ledger_seq < target:
            self._run_catchup_work(mgr, archive, target, clock, lookahead)
        return mgr

    # -- one range of a parallel catchup ------------------------------------
    def catchup_range(self, archive: FileHistoryArchive,
                      seed_checkpoint: Optional[int], to_ledger: int,
                      clock=None, lookahead: int = 2,
                      checkpoint_hook=None):
        """Replay one contiguous checkpoint range: assume the hash-verified
        bucket snapshot at `seed_checkpoint` (None = replay from genesis),
        then replay through `to_ledger` with full verification.  Returns
        (manager, seed_header_hash) — the seed hash is the stitch evidence
        a parallel orchestrator checks against the previous range's final
        ledger hash (catchup.parallel.verify_stitches).  `checkpoint_hook`
        (see _run_catchup_work) lets a work-stealing orchestrator truncate
        the range at a later boundary mid-replay."""
        if seed_checkpoint is None:
            return (self.catchup_complete(archive, to_ledger=to_ledger,
                                          clock=clock, lookahead=lookahead,
                                          checkpoint_hook=checkpoint_hook),
                    None)
        mgr = self.catchup_minimal(archive, checkpoint=seed_checkpoint)
        seed_hash = mgr.lcl_hash
        if mgr.last_closed_ledger_seq < to_ledger:
            self._run_catchup_work(mgr, archive, to_ledger, clock,
                                   lookahead, checkpoint_hook)
        return mgr, seed_hash

    # -- minimal (assume state from buckets, no replay) ---------------------
    def catchup_minimal(self, archive: FileHistoryArchive,
                        checkpoint: Optional[int] = None) -> LedgerManager:
        """Assume a checkpoint's state snapshot from bucket files
        (reference: ApplyBucketsWork + BucketApplicator), verifying every
        bucket hash and the reassembled bucket-list hash against the
        header.  `checkpoint` targets a specific published boundary (the
        CatchupRange bucket-apply point); default = the archive tip."""
        has = _archive_state(archive, checkpoint)
        if has is None:
            raise CatchupError(
                "archive has no HAS" if checkpoint is None
                else f"archive has no HAS for checkpoint {checkpoint}")
        if checkpoint is not None and has.current_ledger != checkpoint:
            # a mirror that serves the wrong HAS here would silently skip
            # the whole replay tail of a CATCHUP_RECENT plan — fail-stop
            raise CatchupError(
                f"archive HAS for checkpoint {checkpoint} claims ledger "
                f"{has.current_ledger}")
        checkpoint = has.current_ledger
        headers = self._read_headers(archive, checkpoint)
        verify_ledger_chain(headers)
        tail = headers[-1]
        if tail.header.ledgerSeq != checkpoint:
            raise CatchupError("checkpoint tail mismatch")

        mgr = LedgerManager(self.network_id,
                            invariant_manager=self.invariant_manager,
                            bucket_store=self.bucket_store,
                            entry_cache_size=self.entry_cache_size,
                            resident_levels=self.resident_levels)
        mgr.start_new_ledger()  # scaffolding; replaced below

        hashes = has.bucket_hashes()
        if len(hashes) != NUM_LEVELS * 2:
            raise CatchupError("HAS bucket list malformed")
        empty = "0" * 64

        def source(idx: int) -> Bucket:
            hh = hashes[idx]
            if hh == empty:
                return Bucket.empty()
            try:
                b = archive.get_bucket(hh)
            except (ValueError, OSError) as e:
                # content-hash mismatch / hostile gzip: localized fail-stop
                raise CatchupError(f"corrupt bucket {hh}: {e}") from e
            if b is None:
                raise CatchupError(f"missing bucket {hh}")
            return b

        def next_source(level: int):
            try:
                return has.rehydrate_next(level, archive.get_bucket)
            except RuntimeError as e:
                raise CatchupError(str(e)) from e

        from ..ledger.manager import assume_bucket_state
        scaffold_root = mgr.root
        try:
            mgr.root = assume_bucket_state(
                mgr.bucket_list, tail.header, source, next_source,
                invariant_manager=self.invariant_manager,
                store=self.bucket_store,
                entry_cache_size=mgr.entry_cache_size)
        except RuntimeError as e:
            raise CatchupError(str(e)) from e
        if scaffold_root is not None and scaffold_root.disk_backed:
            scaffold_root.release_snapshot()  # genesis scaffolding pins
        mgr.lcl_header = tail.header
        mgr.lcl_hash = tail.hash
        log.info("assumed state at ledger %d (%d entries)",
                 checkpoint, mgr.root.entry_count())
        return mgr
