"""Catchup: bootstrap/resync a node from history archives.

Reference: src/catchup/CatchupWork.cpp (the work DAG root),
VerifyLedgerChainWork.cpp (back-chained previousLedgerHash verification),
ApplyBucketsWork.cpp + BucketApplicator (state snapshot assumption),
ApplyCheckpointWork.cpp (tx replay — THE north-star hot loop, SURVEY.md §3.3),
CatchupConfiguration (CATCHUP_COMPLETE vs minimal/recent modes).

TPU offload hook: before a checkpoint replays, every (pk, sig, payload)
triple that can be paired by signature hint is batch-verified on the
accelerator and the verdicts seeded into the process verify cache, so the
SignatureChecker inside TransactionFrame.apply hits the cache instead of
calling libsodium — observable behavior identical, compute hoisted
(BASELINE.json: "batches every envelope and transaction signature from a
catchup work-unit into a single vmapped Ed25519 verify").
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

from .. import xdr as X
from ..bucket.bucket import Bucket
from ..bucket.bucket_list import NUM_LEVELS
from ..crypto import keys
from ..crypto.sha import sha256
from ..history.archive import (CATEGORY_LEDGER, CATEGORY_RESULTS,
                               CATEGORY_TRANSACTIONS, CHECKPOINT_FREQUENCY,
                               FileHistoryArchive, category_path,
                               checkpoint_containing,
                               first_ledger_in_checkpoint)
from ..ledger.manager import LedgerManager
from ..transactions.frame import TransactionFrame
from ..util import logging as slog

log = slog.get("History")

_LHHE = X.LedgerHeaderHistoryEntry._xdr_adapter()
_THE = X.TransactionHistoryEntry._xdr_adapter()


class CatchupError(RuntimeError):
    pass


def verify_ledger_chain(headers: Sequence[X.LedgerHeaderHistoryEntry],
                        trusted_tail_hash: Optional[bytes] = None) -> None:
    """Each entry's hash must equal SHA256 of its header, and each header
    must chain to the previous entry's hash (reference:
    VerifyLedgerChainWork — verified back from a trusted hash)."""
    prev_hash: Optional[bytes] = None
    for entry in headers:
        if sha256(entry.header.to_xdr()) != entry.hash:
            raise CatchupError(
                f"header self-hash mismatch at ledger {entry.header.ledgerSeq}")
        if prev_hash is not None and entry.header.previousLedgerHash != prev_hash:
            raise CatchupError(
                f"hash chain broken at ledger {entry.header.ledgerSeq}")
        prev_hash = entry.hash
    if trusted_tail_hash is not None and headers \
            and headers[-1].hash != trusted_tail_hash:
        raise CatchupError("chain tail does not match trusted hash")


def preverify_checkpoint_signatures(network_id: bytes,
                                    tx_entries: Sequence[X.TransactionHistoryEntry],
                                    chunk_size: int = 2048) -> int:
    """Batch-verify all hint-pairable signatures of a checkpoint on the
    accelerator and seed the verify cache.  Returns number of sigs shipped.

    Pairing: a DecoratedSignature whose hint matches the tx source account's
    master key (the dominant case in replay).  Unpaired signatures simply
    fall back to on-demand CPU verification — verdicts never differ, only
    where they're computed."""
    from ..accel.ed25519 import verify_batch

    pks: List[bytes] = []
    sigs: List[bytes] = []
    msgs: List[bytes] = []
    for entry in tx_entries:
        for env in entry.txSet.txs:
            frame = TransactionFrame.make_from_wire(network_id, env)
            h = frame.content_hash()
            candidates = [frame.source_account_id().value]
            if hasattr(frame, "inner"):
                candidates.append(frame.inner.source_account_id().value)
            for op in frame.operations:
                if op.sourceAccount is not None:
                    candidates.append(
                        X.muxed_to_account_id(op.sourceAccount).value)
            for dsig in frame.signatures:
                for pk in candidates:
                    if dsig.hint == pk[28:32]:
                        pks.append(pk)
                        sigs.append(dsig.signature)
                        msgs.append(h)
                        break
    if not pks:
        return 0
    verdicts = verify_batch(pks, sigs, msgs, chunk_size=chunk_size)
    keys.seed_verify_cache(
        (pks[i], sigs[i], msgs[i], bool(verdicts[i])) for i in range(len(pks)))
    return len(pks)


class CatchupManager:
    """Replay/assume-state driver (reference: CatchupManagerImpl +
    CatchupWork).  `accel=True` routes checkpoint signature verification
    through the TPU batch backend."""

    def __init__(self, network_id: bytes, network_passphrase: str,
                 accel: bool = False, accel_chunk: int = 2048):
        self.network_id = network_id
        self.network_passphrase = network_passphrase
        self.accel = accel
        self.accel_chunk = accel_chunk

    # -- archive readers ----------------------------------------------------
    def _read_headers(self, archive: FileHistoryArchive,
                      checkpoint: int) -> List[X.LedgerHeaderHistoryEntry]:
        recs = archive.get_xdr_file(category_path(CATEGORY_LEDGER, checkpoint))
        if recs is None:
            raise CatchupError(f"missing ledger file for checkpoint {checkpoint}")
        try:
            return [_LHHE.unpack(r) for r in recs]
        except X.XdrError as e:
            raise CatchupError(
                f"corrupt ledger file at checkpoint {checkpoint}: {e}") from e

    def _read_txs(self, archive: FileHistoryArchive, checkpoint: int
                  ) -> Dict[int, X.TransactionHistoryEntry]:
        recs = archive.get_xdr_file(
            category_path(CATEGORY_TRANSACTIONS, checkpoint)) or []
        out = {}
        try:
            for r in recs:
                e = _THE.unpack(r)
                out[e.ledgerSeq] = e
        except X.XdrError as e:
            raise CatchupError(
                f"corrupt tx file at checkpoint {checkpoint}: {e}") from e
        return out

    # -- complete replay (from genesis) ------------------------------------
    def catchup_complete(self, archive: FileHistoryArchive,
                         to_ledger: Optional[int] = None) -> LedgerManager:
        """Replay every ledger from genesis to the target (reference:
        CATCHUP_COMPLETE; ApplyCheckpointWork per checkpoint)."""
        has = archive.get_state()
        if has is None:
            raise CatchupError("archive has no HAS")
        target = to_ledger if to_ledger is not None else has.current_ledger

        mgr = LedgerManager(self.network_id, invariant_manager=None)  # hot replay path: hash checks are the oracle
        mgr.start_new_ledger()
        checkpoint = checkpoint_containing(2)
        prev_tail: Optional[X.LedgerHeaderHistoryEntry] = None
        while mgr.last_closed_ledger_seq < target:
            headers = self._read_headers(archive, checkpoint)
            verify_ledger_chain(headers)
            if prev_tail is not None and headers and \
                    headers[0].header.previousLedgerHash != prev_tail.hash:
                raise CatchupError(
                    f"chain broken across checkpoint {checkpoint}")
            txs = self._read_txs(archive, checkpoint)
            if self.accel:
                n = preverify_checkpoint_signatures(
                    self.network_id, list(txs.values()), self.accel_chunk)
                log.info("checkpoint %d: %d sigs batch-verified on accel",
                         checkpoint, n)
            self._apply_checkpoint(mgr, headers, txs, target)
            if headers:
                prev_tail = headers[-1]
            checkpoint += CHECKPOINT_FREQUENCY
            if mgr.last_closed_ledger_seq >= target:
                break
            if checkpoint > checkpoint_containing(target):
                break
        if mgr.last_closed_ledger_seq != target:
            raise CatchupError(
                f"catchup ended at {mgr.last_closed_ledger_seq}, "
                f"target {target}")
        return mgr

    def _apply_checkpoint(self, mgr: LedgerManager,
                          headers: Sequence[X.LedgerHeaderHistoryEntry],
                          txs: Dict[int, X.TransactionHistoryEntry],
                          target: int) -> None:
        """Reference: ApplyCheckpointWork — per ledger: reassemble the tx
        set, check its hash against the header, apply, check the resulting
        ledger hash (fail-stop on mismatch)."""
        for entry in headers:
            seq = entry.header.ledgerSeq
            if seq <= mgr.last_closed_ledger_seq:
                continue
            if seq > target:
                return
            if seq != mgr.last_closed_ledger_seq + 1:
                raise CatchupError(f"gap in headers at {seq}")
            the = txs.get(seq)
            if the is not None:
                tx_set = the.txSet
            else:
                tx_set = X.TransactionSet(previousLedgerHash=mgr.lcl_hash,
                                          txs=[])
            if sha256(tx_set.to_xdr()) != entry.header.scpValue.txSetHash:
                raise CatchupError(f"tx set hash mismatch at ledger {seq}")
            frames = [TransactionFrame.make_from_wire(self.network_id, env)
                      for env in tx_set.txs]
            # the historical scpValue must be stored (and its upgrades
            # applied) verbatim, or the replayed header hash diverges from
            # the live close path
            mgr.close_ledger(frames, entry.header.scpValue.closeTime,
                             tx_set=tx_set,
                             expected_ledger_hash=entry.hash,
                             stellar_value=entry.header.scpValue)

    # -- minimal (assume state from buckets, no replay) ---------------------
    def catchup_minimal(self, archive: FileHistoryArchive) -> LedgerManager:
        """Assume the checkpoint state snapshot from bucket files
        (reference: ApplyBucketsWork + BucketApplicator), verifying every
        bucket hash and the reassembled bucket-list hash against the header."""
        has = archive.get_state()
        if has is None:
            raise CatchupError("archive has no HAS")
        checkpoint = has.current_ledger
        headers = self._read_headers(archive, checkpoint)
        verify_ledger_chain(headers)
        tail = headers[-1]
        if tail.header.ledgerSeq != checkpoint:
            raise CatchupError("checkpoint tail mismatch")

        mgr = LedgerManager(self.network_id, invariant_manager=None)  # hot replay path: hash checks are the oracle
        mgr.start_new_ledger()  # scaffolding; replaced below

        hashes = has.bucket_hashes()
        if len(hashes) != NUM_LEVELS * 2:
            raise CatchupError("HAS bucket list malformed")
        empty = "0" * 64

        def source(idx: int) -> Bucket:
            hh = hashes[idx]
            if hh == empty:
                return Bucket.empty()
            b = archive.get_bucket(hh)
            if b is None:
                raise CatchupError(f"missing bucket {hh}")
            return b

        from ..ledger.manager import assume_bucket_state
        try:
            mgr.root = assume_bucket_state(mgr.bucket_list, tail.header,
                                           source)
        except RuntimeError as e:
            raise CatchupError(str(e)) from e
        mgr.lcl_header = tail.header
        mgr.lcl_hash = tail.hash
        log.info("assumed state at ledger %d (%d entries)",
                 checkpoint, mgr.root.entry_count())
        return mgr
