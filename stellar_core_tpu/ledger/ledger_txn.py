"""LedgerTxn: nested transactional read/write cache over ledger entries.

Reference: src/ledger/LedgerTxn.{h,cpp} — AbstractLedgerTxn, LedgerTxn,
LedgerTxnRoot(Impl), LedgerTxnHeader.  Semantics kept: nested txns see
parent state through a copy-on-write delta; commit folds the delta into the
parent, rollback discards it; at most one active child; header mutations are
transactional alongside entries.

Deliberate divergence (TPU-first simplification, round 1): the root's
authoritative store is an in-memory dict keyed by LedgerKey XDR bytes, with
the BucketList maintained separately by the LedgerManager for hashing; the
reference backs the root with BucketListDB disk indexes + SQL.  Disk-backed
root is tracked as a capability gap in SURVEY §2 terms, not a semantics gap.
"""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional, Tuple

from ..xdr import (LedgerEntry, LedgerHeader, LedgerKey, deep_copy_value,
                   ledger_entry_key, ledger_entry_key_xdr)


class LedgerTxnError(Exception):
    pass


class AbstractLedgerTxnParent:
    def get_entry(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        raise NotImplementedError

    def get_header(self) -> LedgerHeader:
        raise NotImplementedError

    def _attach_child(self, child: "LedgerTxn") -> None:
        raise NotImplementedError

    def _detach_child(self) -> None:
        raise NotImplementedError

    def all_keys(self) -> Iterator[bytes]:
        raise NotImplementedError


class LedgerTxnRoot(AbstractLedgerTxnParent):
    """Authoritative live-entry store + last closed header."""

    def __init__(self, header: LedgerHeader):
        self._entries: Dict[bytes, LedgerEntry] = {}
        self._header = header
        self._child: Optional[LedgerTxn] = None

    # -- parent protocol ----------------------------------------------------
    def get_entry(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        return self._entries.get(key_bytes)

    def get_header(self) -> LedgerHeader:
        return self._header

    def _attach_child(self, child: "LedgerTxn") -> None:
        if self._child is not None:
            raise LedgerTxnError("LedgerTxnRoot already has an active child")
        self._child = child

    def _detach_child(self) -> None:
        self._child = None

    def all_keys(self) -> Iterator[bytes]:
        return iter(list(self._entries.keys()))

    # -- root-only ----------------------------------------------------------
    def _apply_delta(self, entries: Dict[bytes, Optional[LedgerEntry]],
                     header: Optional[LedgerHeader]) -> None:
        for k, e in entries.items():
            if e is None:
                self._entries.pop(k, None)
            else:
                self._entries[k] = e
        if header is not None:
            self._header = header

    def set_header(self, header: LedgerHeader) -> None:
        self._header = header

    def entry_count(self) -> int:
        return len(self._entries)


class LedgerTxn(AbstractLedgerTxnParent):
    """One nesting level.  Use as a context manager or call commit/rollback
    explicitly; falling out of scope without commit == rollback (matches the
    reference's destructor behavior)."""

    def __init__(self, parent: AbstractLedgerTxnParent):
        self._parent = parent
        self._delta: Dict[bytes, Optional[LedgerEntry]] = {}
        self._header: Optional[LedgerHeader] = None
        self._child: Optional[LedgerTxn] = None
        self._open = True
        parent._attach_child(self)

    # -- context manager ----------------------------------------------------
    def __enter__(self) -> "LedgerTxn":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._open:
            self.rollback()

    # -- parent protocol (for nested children; no seal check — the child
    #    delegates reads through its sealed ancestors by design) ------------
    def get_entry(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        if not self._open:
            raise LedgerTxnError("LedgerTxn is closed")
        if key_bytes in self._delta:
            return self._delta[key_bytes]
        return self._parent.get_entry(key_bytes)

    def get_header(self) -> LedgerHeader:
        if self._header is not None:
            return self._header
        return self._parent.get_header()

    def _attach_child(self, child: "LedgerTxn") -> None:
        if self._child is not None:
            raise LedgerTxnError("LedgerTxn already has an active child")
        self._child = child

    def _detach_child(self) -> None:
        self._child = None

    def all_keys(self) -> Iterator[bytes]:
        seen = set()
        for k in self._parent.all_keys():
            seen.add(k)
        for k, v in self._delta.items():
            if v is None:
                seen.discard(k)
            else:
                seen.add(k)
        return iter(seen)

    # -- entry operations ----------------------------------------------------
    def load(self, key: LedgerKey) -> Optional[LedgerEntry]:
        """Copy-out load (deep — struct .copy() is shallow); mutate the
        copy then put() it back."""
        return self.load_by_bytes(key.to_xdr())

    def load_by_bytes(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        """load() for callers that already hold the key's XDR bytes (the
        account hot path memoizes them — xdr.account_key_xdr)."""
        self._assert_open_no_child()
        e = self.get_entry(key_bytes)
        # deep_copy_value dispatches straight to the native copier,
        # skipping the per-entry Python method wrapper (hot path)
        return deep_copy_value(e) if e is not None else None

    def exists(self, key: LedgerKey) -> bool:
        self._assert_open_no_child()
        return self.get_entry(key.to_xdr()) is not None

    def create(self, entry: LedgerEntry) -> None:
        self._assert_open_no_child()
        kb = ledger_entry_key_xdr(entry)
        if self.get_entry(kb) is not None:
            raise LedgerTxnError("create: entry already exists")
        self._delta[kb] = entry

    def put(self, entry: LedgerEntry) -> None:
        """Create-or-update (reference: LedgerTxn::createWithoutLoading /
        updateWithoutLoading pair)."""
        self._assert_open_no_child()
        self._delta[ledger_entry_key_xdr(entry)] = entry

    def update(self, entry: LedgerEntry) -> None:
        self._assert_open_no_child()
        kb = ledger_entry_key_xdr(entry)
        if self.get_entry(kb) is None:
            raise LedgerTxnError("update: entry does not exist")
        self._delta[kb] = entry

    def erase(self, key: LedgerKey) -> None:
        self._assert_open_no_child()
        kb = key.to_xdr()
        if self.get_entry(kb) is None:
            raise LedgerTxnError("erase: entry does not exist")
        self._delta[kb] = None

    # -- header operations ---------------------------------------------------
    def load_header(self) -> LedgerHeader:
        """Copy-out header; mutate and commit_header() it."""
        self._assert_open_no_child()
        return self.get_header().copy()

    def commit_header(self, header: LedgerHeader) -> None:
        self._assert_open_no_child()
        self._header = header

    # -- lifecycle -----------------------------------------------------------
    def commit(self) -> None:
        self._assert_open_no_child()
        parent = self._parent
        if isinstance(parent, LedgerTxn):
            parent._delta.update(self._delta)
            if self._header is not None:
                parent._header = self._header
        else:
            parent._apply_delta(self._delta, self._header)
        self._finish()

    def rollback(self) -> None:
        if not self._open:
            return  # idempotent; must NOT detach a sibling's registration
        if self._child is not None:
            self._child.rollback()
        self._finish()

    def _finish(self) -> None:
        assert self._open, "LedgerTxn finished twice"
        self._open = False
        self._parent._detach_child()
        self._delta = {}
        self._header = None

    def _assert_open_no_child(self) -> None:
        if not self._open:
            raise LedgerTxnError("LedgerTxn is closed")
        if self._child is not None:
            raise LedgerTxnError("LedgerTxn has an active child (sealed)")

    # -- delta inspection (LedgerManager uses this to feed the bucket list
    #    and emit meta; reference: LedgerTxn::getChanges / getDelta) --------
    def delta(self) -> Dict[bytes, Optional[LedgerEntry]]:
        return dict(self._delta)
