"""LedgerTxn: nested transactional read/write cache over ledger entries.

Reference: src/ledger/LedgerTxn.{h,cpp} — AbstractLedgerTxn, LedgerTxn,
LedgerTxnRoot(Impl), LedgerTxnHeader.  Semantics kept: nested txns see
parent state through a copy-on-write delta; commit folds the delta into the
parent, rollback discards it; at most one active child; header mutations are
transactional alongside entries.

Root storage (SURVEY §2.1 row 9): since v21 the reference's bucket list IS
the ledger-entry database (BucketListDB — LedgerTxnRoot reads through
SearchableBucketListSnapshot over indexed bucket files, with a bounded
entry cache).  This root mirrors the read architecture: in BucketListDB
mode (constructed with a snapshot) every read goes through the snapshot's
on-disk indexes and a bounded LRU entry cache, so the ROOT holds at most
`entry_cache_size` decoded entries instead of one per live key.  (Phase 2:
BucketList levels >= BUCKET_RESIDENT_LEVELS are disk-resident too — their
buckets hold no decoded entries and merge via the streaming decode-free
path, see bucket/bucket.py merge_buckets_raw.)  The legacy in-memory dict
remains behind the `in_memory_ledger` config flag (the default for
tests/sims — reference analog: the deprecated in-memory SQL ledger
state).
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from ..util.cache import LRUCache
from ..util.metrics import registry as _registry
from ..xdr import (LedgerEntry, LedgerHeader, LedgerKey, deep_copy_value,
                   ledger_entry_key, ledger_entry_key_xdr)


class LedgerTxnError(Exception):
    pass


class AbstractLedgerTxnParent:
    def get_entry(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        raise NotImplementedError

    def get_header(self) -> LedgerHeader:
        raise NotImplementedError

    def _attach_child(self, child: "LedgerTxn") -> None:
        raise NotImplementedError

    def _detach_child(self) -> None:
        raise NotImplementedError

    def all_keys(self) -> Iterator[bytes]:
        raise NotImplementedError


class LedgerTxnRoot(AbstractLedgerTxnParent):
    """Authoritative live-entry store + last closed header.

    Default (in-memory) mode keeps every live entry in a dict.  In
    BucketListDB mode (``snapshot`` given) the dict is RETIRED: reads go
    through the snapshot's indexed on-disk bucket files, with a bounded
    LRU entry cache in front (negative results — "definitively absent" —
    are cached too, sparing repeated 22-bucket probe chains).  The
    LedgerManager swaps in a fresh snapshot after every bucket-list
    mutation (ledger close seal, catchup assume-state, native-engine
    export); committed deltas land in the cache, so between the snapshot
    refresh and the next one the cache carries exactly the keys the
    snapshot does not yet serve.
    """

    _MISS = object()   # cache sentinel: distinguishes a cached None

    def __init__(self, header: LedgerHeader, snapshot=None,
                 entry_cache_size: int = 4096):
        self._header = header
        self._child: Optional[LedgerTxn] = None
        self._snapshot = snapshot
        if snapshot is None:
            self._entries: Optional[Dict[bytes, LedgerEntry]] = {}
            self._cache: Optional[LRUCache] = None
        else:
            self._entries = None
            self._cache = LRUCache(entry_cache_size)
            self._bind_metrics()

    def _bind_metrics(self) -> None:
        # re-resolved on every snapshot swap: the registry can be replaced
        # wholesale (tests' reset_registry) and cached handles would feed
        # a dead object for the rest of the manager's life
        reg = _registry()
        self._cache_hit = reg.meter("bucketlistdb.cache.hit")
        self._cache_miss = reg.meter("bucketlistdb.cache.miss")
        self._prefetch_timer = reg.timer("bucketlistdb.prefetch")

    @property
    def disk_backed(self) -> bool:
        return self._snapshot is not None

    # -- parent protocol ----------------------------------------------------
    def get_entry(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        if self._snapshot is None:
            return self._entries.get(key_bytes)
        v = self._cache.get(key_bytes, self._MISS)
        if v is not self._MISS:
            self._cache_hit.mark()
            return v
        self._cache_miss.mark()
        v = self._snapshot.load(key_bytes)
        self._cache.put(key_bytes, v)
        return v

    def get_header(self) -> LedgerHeader:
        return self._header

    def _attach_child(self, child: "LedgerTxn") -> None:
        if self._child is not None:
            raise LedgerTxnError("LedgerTxnRoot already has an active child")
        self._child = child

    def _detach_child(self) -> None:
        self._child = None

    def all_keys(self) -> Iterator[bytes]:
        if self._snapshot is None:
            return iter(list(self._entries.keys()))  # corelint: disable=iteration-order -- _entries is insertion-ordered (apply order); consumers do keyed scans
        return self._snapshot.iter_live_keys()

    # -- root-only ----------------------------------------------------------
    def _apply_delta(self, entries: Dict[bytes, Optional[LedgerEntry]],
                     header: Optional[LedgerHeader]) -> None:
        if self._snapshot is None:
            for k, e in entries.items():
                if e is None:
                    self._entries.pop(k, None)
                else:
                    self._entries[k] = e
        else:
            # deletions cache as None (negative entries); the bucket list
            # is the authority and the manager has already fed it this
            # delta by the time the commit lands here
            for k, e in entries.items():
                self._cache.put(k, e)
        if header is not None:
            self._header = header

    def set_header(self, header: LedgerHeader) -> None:
        self._header = header

    def set_snapshot(self, snapshot):
        """Swap in a fresh read view after a bucket-list mutation; returns
        the previous snapshot (caller releases its pins).  The entry cache
        survives: committed deltas were applied to it, everything else is
        unchanged between consecutive views."""
        old = self._snapshot
        self._snapshot = snapshot
        self._bind_metrics()
        return old

    def release_snapshot(self) -> None:
        """Drop this root's read view + its file pins (the root is being
        replaced wholesale — genesis scaffolding, native-engine export)."""
        if self._snapshot is not None:
            self._snapshot.release()
            self._snapshot = None

    def prefetch(self, keys: Iterable[bytes]) -> int:
        """Bulk-load `keys` into the entry cache via one batched snapshot
        pass (reference: LedgerTxnRoot::prefetchClassic before tx-set
        apply).  Absent keys cache as definitive misses.  Returns the
        number of keys actually probed."""
        if self._snapshot is None:
            return 0
        cache = self._cache
        missing = [kb for kb in keys if kb not in cache]
        if not missing:
            return 0
        import time as _time
        t0 = _time.perf_counter()
        found = self._snapshot.load_keys(missing)
        for kb in missing:
            cache.put(kb, found.get(kb))
        self._prefetch_timer.update(_time.perf_counter() - t0)
        return len(missing)

    def cache_stats(self) -> Dict[str, float]:
        """Entry-cache occupancy + hit rate (bench exposure)."""
        if self._cache is None:
            return {}
        return {"size": len(self._cache), "max_size": self._cache.max_size,
                "hits": self._cache.hits, "misses": self._cache.misses,
                "hit_rate": round(self._cache.hit_rate(), 4)}

    def entry_count(self) -> int:
        if self._snapshot is None:
            return len(self._entries)
        return self._snapshot.live_entry_count()

    def export_raw_entries(self) -> List[Tuple[bytes, bytes]]:
        """(LedgerKey XDR, LedgerEntry XDR) for every live entry — the
        native-engine import seam.  Only valid on a SETTLED root (no close
        in flight): in disk mode the snapshot must already reflect every
        committed delta.  Disk mode streams raw records (no entry
        decode)."""
        if self._snapshot is None:
            # canonical key order — the disk-mode twin streams bucket
            # records, which are already key-sorted
            return [(kb, e.to_xdr())
                    for kb, e in sorted(self._entries.items())]
        return list(self._snapshot.iter_live_raw())


class LedgerTxn(AbstractLedgerTxnParent):
    """One nesting level.  Use as a context manager or call commit/rollback
    explicitly; falling out of scope without commit == rollback (matches the
    reference's destructor behavior)."""

    # Instances are thread-confined: the soroban cluster workers each build
    # a PRIVATE LedgerTxn chain, and their only reach into the shared close
    # ltx goes through _ClusterBase, which serializes on the
    # soroban.cluster-read lock (see soroban/scheduler.py).
    def __init__(self, parent: AbstractLedgerTxnParent):
        self._parent = parent
        self._delta: Dict[bytes, Optional[LedgerEntry]] = {}  # corelint: owned-by=instance-thread -- per-instance; cross-thread reads serialize at _ClusterBase
        self._header: Optional[LedgerHeader] = None  # corelint: owned-by=instance-thread -- per-instance; cross-thread reads serialize at _ClusterBase
        self._child: Optional[LedgerTxn] = None  # corelint: owned-by=instance-thread -- per-instance; cluster chains never span threads
        self._open = True  # corelint: owned-by=instance-thread -- per-instance; cluster chains never span threads
        parent._attach_child(self)

    # -- context manager ----------------------------------------------------
    def __enter__(self) -> "LedgerTxn":
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        if self._open:
            self.rollback()

    # -- parent protocol (for nested children; no seal check — the child
    #    delegates reads through its sealed ancestors by design) ------------
    def get_entry(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        if not self._open:
            raise LedgerTxnError("LedgerTxn is closed")
        if key_bytes in self._delta:
            return self._delta[key_bytes]
        return self._parent.get_entry(key_bytes)

    def get_header(self) -> LedgerHeader:
        if self._header is not None:
            return self._header
        return self._parent.get_header()

    def _attach_child(self, child: "LedgerTxn") -> None:
        if self._child is not None:
            raise LedgerTxnError("LedgerTxn already has an active child")
        self._child = child

    def _detach_child(self) -> None:
        self._child = None

    def all_keys(self) -> Iterator[bytes]:
        seen = set()
        for k in self._parent.all_keys():
            seen.add(k)
        for k, v in self._delta.items():
            if v is None:
                seen.discard(k)
            else:
                seen.add(k)
        return iter(seen)

    # -- entry operations ----------------------------------------------------
    def load(self, key: LedgerKey) -> Optional[LedgerEntry]:
        """Copy-out load (deep — struct .copy() is shallow); mutate the
        copy then put() it back."""
        return self.load_by_bytes(key.to_xdr())

    def load_by_bytes(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        """load() for callers that already hold the key's XDR bytes (the
        account hot path memoizes them — xdr.account_key_xdr)."""
        self._assert_open_no_child()
        e = self.get_entry(key_bytes)
        # deep_copy_value dispatches straight to the native copier,
        # skipping the per-entry Python method wrapper (hot path)
        return deep_copy_value(e) if e is not None else None

    def exists(self, key: LedgerKey) -> bool:
        self._assert_open_no_child()
        return self.get_entry(key.to_xdr()) is not None

    def create(self, entry: LedgerEntry) -> None:
        self._assert_open_no_child()
        kb = ledger_entry_key_xdr(entry)
        if self.get_entry(kb) is not None:
            raise LedgerTxnError("create: entry already exists")
        self._delta[kb] = entry

    def put(self, entry: LedgerEntry) -> None:
        """Create-or-update (reference: LedgerTxn::createWithoutLoading /
        updateWithoutLoading pair)."""
        self._assert_open_no_child()
        self._delta[ledger_entry_key_xdr(entry)] = entry

    def update(self, entry: LedgerEntry) -> None:
        self._assert_open_no_child()
        kb = ledger_entry_key_xdr(entry)
        if self.get_entry(kb) is None:
            raise LedgerTxnError("update: entry does not exist")
        self._delta[kb] = entry

    def erase(self, key: LedgerKey) -> None:
        self._assert_open_no_child()
        kb = key.to_xdr()
        if self.get_entry(kb) is None:
            raise LedgerTxnError("erase: entry does not exist")
        self._delta[kb] = None

    # -- header operations ---------------------------------------------------
    def load_header(self) -> LedgerHeader:
        """Copy-out header; mutate and commit_header() it."""
        self._assert_open_no_child()
        return self.get_header().copy()

    def commit_header(self, header: LedgerHeader) -> None:
        self._assert_open_no_child()
        self._header = header

    # -- lifecycle -----------------------------------------------------------
    def commit(self) -> None:
        self._assert_open_no_child()
        parent = self._parent
        if isinstance(parent, LedgerTxn):
            parent._delta.update(self._delta)
            if self._header is not None:
                parent._header = self._header
        else:
            parent._apply_delta(self._delta, self._header)
        self._finish()

    def rollback(self) -> None:
        if not self._open:
            return  # idempotent; must NOT detach a sibling's registration
        if self._child is not None:
            self._child.rollback()
        self._finish()

    def _finish(self) -> None:
        assert self._open, "LedgerTxn finished twice"
        self._open = False
        self._parent._detach_child()
        self._delta = {}
        self._header = None

    def _assert_open_no_child(self) -> None:
        if not self._open:
            raise LedgerTxnError("LedgerTxn is closed")
        if self._child is not None:
            raise LedgerTxnError("LedgerTxn has an active child (sealed)")

    # -- delta inspection (LedgerManager uses this to feed the bucket list
    #    and emit meta; reference: LedgerTxn::getChanges / getDelta) --------
    def delta(self) -> Dict[bytes, Optional[LedgerEntry]]:
        return dict(self._delta)
