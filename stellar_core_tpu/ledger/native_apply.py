"""Bridge between the Python LedgerManager state and the native apply
engine (native/capply.c).

Reference: SURVEY.md §3.3 — the catchup replay hot loop.  The native
engine owns the ledger state (entry store + bucket list + header) while a
catchup replays supported checkpoints; anything the probe rejects (fee
bumps, ops outside the native set, generalized tx sets) falls back to the
Python engine for that checkpoint after an export/import round-trip.
State transfer is exact: entries as (LedgerKey XDR, LedgerEntry XDR)
pairs, buckets as aligned (sort-key, record) lists / serialized streams,
pending merges resolved (outputs are pure functions of inputs, so eager
resolution is hash-identical to the lazy merge pipeline).

Kill switch: STELLAR_TPU_NO_CAPPLY forces the Python path everywhere.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence

from .. import xdr as X
from ..bucket.bucket import Bucket
from ..bucket.future import FutureBucket

try:
    if os.environ.get("STELLAR_TPU_NO_CAPPLY"):
        raise ImportError("capply disabled by STELLAR_TPU_NO_CAPPLY")
    # staleness guard: a shipped .so older than native/capply.c is
    # rebuilt here, or the import FAIL-STOPS (StaleNativeExtensionError)
    # — running stale native code would invalidate every differential
    # guarantee without tripping a single hash check
    from .._native_build import require_fresh
    require_fresh("_capply")
    from stellar_core_tpu import _capply  # built via `make native`
except ImportError:
    _capply = None


def native_apply_available() -> bool:
    return _capply is not None


def _bucket_tuple(bucket: Bucket):
    # raw_records: a disk-resident bucket slices its file (transient list,
    # nothing cached) — no BucketEntry decode on the import path
    return (bucket.sort_keys(), bucket.raw_records(),
            bucket.protocol_version)


class NativeApplyBridge:
    """Owns a _capply.Engine and tracks where the authoritative state
    lives (`active`: in the engine; otherwise: in the Python manager)."""

    def __init__(self, network_id: bytes):
        if _capply is None:
            raise RuntimeError("native apply engine not built")
        self.engine = _capply.Engine(network_id)
        self.active = False
        # per-checkpoint outcome accounting (historywork fills these;
        # bench's catchup section reports native vs fallback so a silent
        # fallback regression shows in the BENCH trajectory)
        self.native_checkpoints = 0
        self.fallback_checkpoints = 0

    # -- state transfer ----------------------------------------------------
    def import_from(self, mgr) -> None:
        """Python manager -> engine (authoritative state moves to C).
        Works for both root flavors: the BucketListDB root streams raw
        records straight from its indexed bucket files (no Python entry
        decode), the dict root serializes its entries."""
        entries = mgr.root.export_raw_entries()
        buckets = []
        nexts = []
        for lvl in mgr.bucket_list.levels:
            buckets.append(_bucket_tuple(lvl.curr))
            buckets.append(_bucket_tuple(lvl.snap))
            nexts.append(None if lvl.next is None
                         else _bucket_tuple(lvl.next.resolve()))
        self.engine.import_state(mgr.lcl_header.to_xdr(), mgr.lcl_hash,
                                 entries, buckets, nexts)
        self.active = True

    def export_to_manager(self, mgr) -> None:
        """Engine -> Python manager (authoritative state moves back)."""
        self._export_into(mgr)
        self.active = False

    def _export_into(self, mgr) -> None:
        """Copy the engine state into `mgr` WITHOUT transferring authority
        (the differential spot-checks of native live close build scratch
        managers this way).  The bucket list is rebuilt first and
        hash-verified; only then is the root rebound — a BucketListDB
        root is rebuilt OVER that list (ignoring the exported entry
        pairs, no decode), a dict root materializes them."""
        hdr, lcl_hash, entries, bucket_streams, next_streams = \
            self.engine.export_state()
        header = X.LedgerHeader.from_xdr(hdr)
        for i, lvl in enumerate(mgr.bucket_list.levels):
            lvl.curr = Bucket.deserialize(bucket_streams[2 * i])
            lvl.snap = Bucket.deserialize(bucket_streams[2 * i + 1])
            ns = next_streams[i]
            lvl.next = (None if ns is None
                        else FutureBucket.from_output(Bucket.deserialize(ns)))
        if mgr.bucket_list.hash() != header.bucketListHash:
            raise RuntimeError(
                "native state export diverged from the bucket list hash")
        mgr.root = mgr.build_root(header, entries)
        mgr.lcl_header = header
        mgr.lcl_hash = lcl_hash

    def sync_buckets_to(self, mgr) -> None:
        """Rebuild `mgr`'s PYTHON bucket list from the engine (authority
        stays in C) — the live-close checkpoint-boundary seam: history
        publishing and persistence read `mgr.bucket_list` directly.
        Uses the entries-free export: boundaries must not pay an
        O(all-entries) Python materialization every 64 ledgers."""
        hdr, bucket_streams, next_streams = self.engine.export_buckets()
        header = X.LedgerHeader.from_xdr(hdr)
        for i, lvl in enumerate(mgr.bucket_list.levels):
            lvl.curr = Bucket.deserialize(bucket_streams[2 * i])
            lvl.snap = Bucket.deserialize(bucket_streams[2 * i + 1])
            ns = next_streams[i]
            lvl.next = (None if ns is None
                        else FutureBucket.from_output(Bucket.deserialize(ns)))
        if mgr.bucket_list.hash() != header.bucketListHash:
            raise RuntimeError(
                "native bucket sync diverged from the bucket list hash")

    # -- live close ---------------------------------------------------------
    def close_ledger(self, tx_rec: Optional[bytes], scp_value_xdr: bytes):
        """Drive one live ledger close in C.  Returns (seq, lcl_hash,
        header_xdr, result_set_xdr, delta) — delta is the ledger's entry
        changes as (key XDR, entry XDR | None) pairs for the Python
        read-mirror.  Raises _capply.Error on probe misses / divergence;
        the engine rolls back cleanly unless `poisoned` reports
        otherwise."""
        return self.engine.close_ledger(tx_rec, scp_value_xdr)

    # -- replay ------------------------------------------------------------
    def probe(self, tx_recs: Sequence[Optional[bytes]]) -> bool:
        return bool(self.engine.probe(list(tx_recs)))

    def apply_checkpoint(self, header_recs: List[bytes],
                         tx_recs: List[Optional[bytes]],
                         max_seq: int) -> int:
        return self.engine.apply_checkpoint(header_recs, tx_recs, max_seq)

    def extract_pairs(self, tx_recs: Sequence[Optional[bytes]]):
        """Accel pairing without Python frame decode: returns (pks, sigs,
        msgs, total_sigs) for every hint-pairable signature in the raw
        records, using the engine state's account signers plus the
        cumulative SetOptions harvest (exactly the PreverifyPipeline
        pairing contract — unpaired signatures fall back to CPU verify)."""
        return self.engine.extract_pairs(list(tx_recs))

    def seed_verdicts(self, pks, sigs, msgs, verdicts) -> None:
        """TPU preverify hook: push batch-verified signature verdicts into
        the engine's verify cache (identical to the Python seam in
        crypto/keys.py — a miss just recomputes via libsodium)."""
        self.engine.seed_verdicts(list(pks), list(sigs), list(msgs),
                                  [bool(v) for v in verdicts])

    def lcl(self):
        return self.engine.lcl()

    def stats(self) -> dict:
        return self.engine.stats()
