"""Per-close cost ledger: one structured cost row per sealed ledger.

Reference shape: stellar-core's per-ledger close diagnostics (the
`ledger close` log line plus the medida timers it summarizes) — but
retained and queryable.  `LedgerManager._close_ledger` emits one
``CloseCostRecord`` at the seal edge carrying the phase split (fee /
apply / seal from the existing nested spans), the bucket merge-stall
time the close spent blocked on an unresolved background merge, the
entry-cache hit/miss deltas for this close, the snapshot-pin count and
resident-entry delta, and the GC backlog — the unit of post-mortem
analysis for "why did ledger N take 400 ms?".

The ring is bounded (``STPU_CLOSECOST_CAPACITY``, default 1024 — ~85
minutes at a 5 s close cadence) and served incrementally at
``/closecosts?since=`` with the same watermark contract as /tracespans
and /timeseries: every record gets a monotonically increasing
``export_seq`` and ``doc(since)`` returns ``next_since``.

Writers run INSIDE the detguard "ledger-close" region — nothing here
touches a guarded primitive (the close hands in durations it measured
with ``time.perf_counter``; the ring itself is pure data + a traced
lock).  Readers are admin threads and the anomaly bundle writer, which
is why the ring is ``@race_checked``.
"""

from __future__ import annotations

import os
from collections import deque
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

from ..util.lockorder import make_lock
from ..util.racetrace import race_checked

CLOSECOST_CAPACITY = int(os.environ.get("STPU_CLOSECOST_CAPACITY",
                                        "1024"))


@dataclass(frozen=True)
class CloseCostRecord:
    """The cost breakdown of one sealed ledger."""
    export_seq: int          # watermark for /closecosts?since=
    seq: int                 # ledger sequence
    txs: int                 # transactions applied
    total_s: float           # whole close, fee intake to seal
    fee_s: float             # fee-processing phase
    apply_s: float           # tx-apply phase
    seal_s: float            # seal phase (bucket add_batch + snapshot)
    merge_stall_s: float     # close blocked on unresolved merges
    cache_hits: int          # entry-cache hit delta this close
    cache_misses: int        # entry-cache miss delta this close
    pin_count: int           # live snapshot pins at seal
    resident_entries: int    # decoded bucket entries resident at seal
    resident_delta: int      # resident-entry change across the close
    gc_backlog: int          # closes since the last bucket-file GC


@race_checked
class CloseCostLedger:
    """Bounded ring of CloseCostRecords (newest kept).  Written by the
    close path (main thread / native closer fallback), read by admin
    /closecosts workers and the anomaly bundle writer — every access is
    under ``_lock``."""

    def __init__(self, capacity: Optional[int] = None) -> None:
        self._ring: deque = deque(maxlen=capacity or CLOSECOST_CAPACITY)
        self._lock = make_lock("closecost.ring")
        self._next_seq = 0

    def add(self, **fields) -> CloseCostRecord:
        with self._lock:
            self._next_seq += 1
            rec = CloseCostRecord(export_seq=self._next_seq, **fields)
            self._ring.append(rec)
        return rec

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    @property
    def next_since(self) -> int:
        with self._lock:
            return self._next_seq

    def doc(self, since: int = 0) -> dict:
        """The /closecosts document: records with export_seq > since,
        oldest first, plus the next_since watermark."""
        with self._lock:
            records = [asdict(r) for r in self._ring
                       if r.export_seq > since]
            next_since = max(since, self._next_seq)
        return {"records": records, "next_since": next_since,
                "capacity": self._ring.maxlen}

    def recent(self, n: int) -> List[dict]:
        """The newest n records, oldest first (anomaly bundles)."""
        with self._lock:
            rows = list(self._ring)[-n:]
        return [asdict(r) for r in rows]

    def window(self, lo_seq: int, hi_seq: int) -> List[dict]:
        """Records for ledger sequences in [lo_seq, hi_seq]."""
        with self._lock:
            rows = [r for r in self._ring
                    if lo_seq <= r.seq <= hi_seq]
        return [asdict(r) for r in rows]

    def latest(self) -> Optional[dict]:
        with self._lock:
            return asdict(self._ring[-1]) if self._ring else None
