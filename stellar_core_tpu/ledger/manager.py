"""LedgerManager: genesis, ledger close, header hash chain.

Reference: src/ledger/LedgerManagerImpl.{h,cpp} — startNewLedger,
valueExternalized/applyLedger (SURVEY.md §3.2 call stack), advanceLedgerPointers,
plus TxSetFrame hashing (src/herder/TxSetFrame.cpp — computeTxSetHash,
sortTxsInHashOrder).

Close pipeline per ledger (same phases as the reference):
  1. canonicalize tx set (txs sorted by content hash), hash it
  2. process fees + consume seq nums for every tx
  3. apply each tx (all-or-nothing per tx) collecting results
  4. txSetResultHash, bucket-list batch (INIT/LIVE/DEAD from the LedgerTxn
     delta), header finalize, hash = SHA256(header XDR) chains previous.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence, Tuple

from .. import xdr as X
from ..crypto.keys import SecretKey
from ..crypto.sha import sha256
from ..bucket.bucket_list import BucketList
from ..transactions.frame import TransactionFrame
from ..util import detguard
from ..util import eventlog
from ..util import logging as slog
from ..util import tracing
from ..util.assertions import release_assert
from ..util.metrics import registry as _registry
from .costs import CloseCostLedger
from .ledger_txn import LedgerTxn, LedgerTxnRoot

log = slog.get("Ledger")

GENESIS_LEDGER_SEQ = 1
TOTAL_COINS = 100_000_000_000 * 10_000_000  # 100B XLM in stroops
GENESIS_BASE_FEE = 100
GENESIS_BASE_RESERVE = 100_000_000
GENESIS_MAX_TX_SET_SIZE = 100
CURRENT_LEDGER_PROTOCOL_VERSION = 23

SKIP_INTERVALS = (50, 5000, 50000, 500000)


@dataclass
class ClosedLedgerArtifacts:
    """What history publishing needs from each close (reference: the data
    CheckpointBuilder appends — SURVEY.md §2.1 History publish)."""
    header_entry: X.LedgerHeaderHistoryEntry
    tx_entry: X.TransactionHistoryEntry
    result_entry: X.TransactionHistoryResultEntry


DEFAULT_ENTRY_CACHE_SIZE = 4096   # mirrored by config.BUCKETLISTDB_ENTRY_CACHE_SIZE


def assume_bucket_state(bucket_list, header: X.LedgerHeader,
                        bucket_source, next_source=None,
                        invariant_manager=None, store=None,
                        entry_cache_size: int = DEFAULT_ENTRY_CACHE_SIZE,
                        resident_levels: Optional[int] = None
                        ) -> LedgerTxnRoot:
    """Fill `bucket_list`'s levels from `bucket_source(hex_hash) -> Bucket`
    and build the authoritative root.  In-memory mode derives the entry
    dict newest-first (first record per key wins; DEADENTRY shadows older
    versions); BucketListDB mode (`store` given) persists + indexes the
    buckets instead — the files ARE the store, no dict is materialized.
    Verifies the reassembled list against header.bucketListHash.  Shared
    by restart (loadLastKnownLedger) and catchup state assumption
    (ApplyBucketsWork + BucketApplicator).

    next_source(level) -> Optional[FutureBucket]: the level's pending merge
    (HAS "next", reference: FutureBucket::makeLive, usually built via
    HistoryArchiveState.rehydrate_next) — restoring it is what makes
    post-restore bucket hashes identical to a node that never restarted.
    Entry derivation skips next buckets: their content is older-or-equal to
    what curr/snap already expose."""
    from ..bucket.bucket_list import NUM_LEVELS

    seen: set = set()
    root = None if store is not None else LedgerTxnRoot(header)
    for i in range(NUM_LEVELS):
        for j, attr in ((0, "curr"), (1, "snap")):
            bucket = bucket_source(i * 2 + j)
            if bucket is None:
                raise RuntimeError("missing bucket for level %d %s"
                                   % (i, attr))
            if invariant_manager is not None:
                # localize archive corruption to an entry + message
                # (reference: InvariantManagerImpl::checkOnBucketApply)
                invariant_manager.check_on_bucket_apply(
                    bucket, i, header.ledgerSeq)
            setattr(bucket_list.levels[i], attr, bucket)
            if root is None:
                continue
            for be in bucket.entries:
                if be.switch == X.BucketEntryType.DEADENTRY:
                    seen.add(be.value.to_xdr())
                else:
                    kb = X.ledger_entry_key(be.value).to_xdr()
                    if kb not in seen:
                        seen.add(kb)
                        root._apply_delta({kb: be.value}, None)
        if next_source is not None:
            bucket_list.levels[i].next = next_source(i)
    if bucket_list.hash() != header.bucketListHash:
        raise RuntimeError("assumed bucket list hash != header hash")
    if root is None:
        # BucketListDB: persist + index the assumed buckets, then drop the
        # decoded lists of levels >= the residency depth — the deep levels
        # never stay O(ledger) in memory, even right after catchup
        if bucket_list.store is None:
            from ..bucket.bucket_list import DEFAULT_RESIDENT_LEVELS
            bucket_list.configure_residency(
                store, resident_levels if resident_levels is not None
                else DEFAULT_RESIDENT_LEVELS)
        bucket_list.enforce_residency()
        snap = bucket_list.snapshot(header.ledgerSeq, store=store)
        root = LedgerTxnRoot(header, snapshot=snap,
                             entry_cache_size=entry_cache_size)
    return root


_DEFAULT_INVARIANTS = object()


class LedgerManager:
    # disk-mode GC cadence: every N closes, unreferenced+unpinned bucket
    # files are deleted (content-addressed level-0 files otherwise pile up
    # one per ledger)
    BUCKET_GC_INTERVAL = 8

    def __init__(self, network_id: bytes,
                 invariant_manager=_DEFAULT_INVARIANTS,
                 merge_executor=None, bucket_store=None,
                 entry_cache_size: Optional[int] = None,
                 resident_levels: Optional[int] = None):
        """invariant_manager: an InvariantManager, None to disable, or
        default = all invariants enabled (reference ships them off by
        default; this framework inverts that — fail-stop by default, opt
        out on the hot replay path).

        merge_executor: thread pool for background bucket merges
        (reference: WORKER_THREADS-driven FutureBucket merges).

        bucket_store: a bucket.manager.BucketListStore → BucketListDB mode
        (`in_memory_ledger = false`): the root reads through indexed
        on-disk bucket files with an LRU entry cache of
        `entry_cache_size` entries; None → legacy in-memory dict root.

        resident_levels: BucketListDB residency depth (config
        BUCKET_RESIDENT_LEVELS): levels >= it hold no decoded entries —
        their buckets are served from indexed files and merged by the
        streaming decode-free path."""
        self.network_id = network_id
        self.bucket_list = BucketList(executor=merge_executor)
        self.bucket_store = bucket_store
        self.entry_cache_size = entry_cache_size or DEFAULT_ENTRY_CACHE_SIZE
        if bucket_store is not None:
            from ..bucket.bucket_list import DEFAULT_RESIDENT_LEVELS
            self.bucket_list.configure_residency(
                bucket_store, resident_levels if resident_levels is not None
                else DEFAULT_RESIDENT_LEVELS)
        self.root: Optional[LedgerTxnRoot] = None
        self.lcl_header: Optional[X.LedgerHeader] = None
        self.lcl_hash: Optional[bytes] = None
        if invariant_manager is _DEFAULT_INVARIANTS:
            from ..invariant import InvariantManager
            invariant_manager = InvariantManager()
        self.invariants = invariant_manager
        self.db = None           # database.Database when persistence is on
        self.bucket_dir = None   # bucket.manager.BucketDir
        # observability (reference: METADATA_OUTPUT_STREAM + medida timers)
        self.meta_stream = None  # callable(LedgerCloseMeta) or file-like
        # catchup's native bridge (historywork probes it per checkpoint)
        self.native_bridge = None
        # native live close (ledger/native_close.py): when attached,
        # close_ledger routes through the C engine with differential
        # spot-checks; None = classic Python close
        self.native_closer = None
        # Soroban (ISSUE 17): footprint-parallel apply of the Soroban
        # phase (serial fallback stays byte-identical); the TTL expiry
        # index drives archival/eviction at close.  None = unknown (state
        # was loaded, rebuild lazily on first Soroban activity); {} =
        # known-empty (fresh chain).
        self.soroban_parallel_apply = True
        self._ttl_expiry: Optional[dict] = {}
        # per-close cost ledger (ISSUE 20): one CloseCostRecord per
        # sealed ledger, served at /closecosts.  The Python close fills
        # every field; native closes record seq/txs/total and the
        # bucket-side fields (phase splits and cache traffic are engine-
        # internal there and read as 0).
        self.close_costs = CloseCostLedger()
        _registry().weak_gauge("closecost.records.retained",
                               self.close_costs, len)
        self._last_gc_seq = 0
        # injected-regression seam (ISSUE 20 anomaly proof): > 0 spins
        # the close for this many extra seconds.  Spins on perf_counter,
        # which detguard leaves unpatched, so the throttle is legal
        # inside the guarded close region.
        self.debug_close_throttle_s = 0.0  # corelint: disable=float-discipline -- test-only throttle knob, never ledger state

    # -- genesis ------------------------------------------------------------
    def start_new_ledger(self,
                         protocol_version: int = CURRENT_LEDGER_PROTOCOL_VERSION
                         ) -> None:
        """Create ledger 1 with the network root account (reference:
        LedgerManagerImpl::startNewLedger — root seed is the network id)."""
        root_key = SecretKey(self.network_id)
        root_acc = X.AccountEntry(
            accountID=X.AccountID.ed25519(root_key.public_key.ed25519),
            balance=TOTAL_COINS,
            seqNum=GENESIS_LEDGER_SEQ << 32)
        root_entry = X.LedgerEntry(
            lastModifiedLedgerSeq=GENESIS_LEDGER_SEQ,
            data=X.LedgerEntryData.account(root_acc))

        self.bucket_list.add_batch(GENESIS_LEDGER_SEQ, protocol_version,
                                   [root_entry], [], [])
        header = X.LedgerHeader(
            ledgerVersion=protocol_version,
            previousLedgerHash=b"\x00" * 32,
            scpValue=X.StellarValue(txSetHash=b"\x00" * 32, closeTime=0),
            txSetResultHash=sha256(X.TransactionResultSet(results=[]).to_xdr()),
            bucketListHash=self.bucket_list.hash(),
            ledgerSeq=GENESIS_LEDGER_SEQ,
            totalCoins=TOTAL_COINS, feePool=0, inflationSeq=0, idPool=0,
            baseFee=GENESIS_BASE_FEE, baseReserve=GENESIS_BASE_RESERVE,
            maxTxSetSize=GENESIS_MAX_TX_SET_SIZE,
            skipList=[b"\x00" * 32] * 4)
        if self.bucket_store is not None:
            # BucketListDB: the bucket list (just fed the root account)
            # IS the store — no dict to seed
            self.root = self._make_disk_root(header)
        else:
            self.root = LedgerTxnRoot(header)
            with LedgerTxn(self.root) as ltx:
                ltx.create(root_entry)
                ltx.commit()
        self.lcl_header = header
        self.lcl_hash = sha256(header.to_xdr())
        log.info("genesis ledger 1 closed, root=%s",
                 root_key.public_key.to_strkey())

    def root_account_secret(self) -> SecretKey:
        return SecretKey(self.network_id)

    # -- BucketListDB snapshot management -----------------------------------
    def _make_disk_root(self, header: X.LedgerHeader) -> LedgerTxnRoot:
        """Fresh disk-backed root over the CURRENT bucket list (genesis /
        native-engine export / rebuilds).  Replaces any previous root's
        snapshot pins.  Deep levels that (re)entered decoded — the native
        export path deserializes every bucket — drop their entry lists
        first."""
        self.bucket_list.enforce_residency()
        snap = self.bucket_list.snapshot(header.ledgerSeq,
                                         store=self.bucket_store)
        if self.root is not None and self.root.disk_backed:
            self.root.release_snapshot()
        return LedgerTxnRoot(header, snapshot=snap,
                             entry_cache_size=self.entry_cache_size)

    def _refresh_snapshot(self, ledger_seq: int) -> None:
        """Swap the root onto a fresh read view after a bucket-list
        mutation (every close's seal phase); the superseded view's file
        pins are released so GC can reclaim its buckets."""
        snap = self.bucket_list.snapshot(ledger_seq, store=self.bucket_store)
        old = self.root.set_snapshot(snap)
        if old is not None:
            old.release()

    def _maybe_gc_buckets(self, ledger_seq: int) -> None:
        """Periodic bucket-file GC (reference: forgetUnreferencedBuckets
        after each close): referenced = the live list's curr/snap/pending
        hashes; snapshot-pinned files survive regardless."""
        if ledger_seq % self.BUCKET_GC_INTERVAL == 0:
            self.bucket_store.gc(self.bucket_list.referenced_hashes())
            self._last_gc_seq = ledger_seq

    def build_root(self, header: X.LedgerHeader,
                   raw_entries) -> LedgerTxnRoot:
        """Root over `header` + the current bucket list, from the native
        engine's exported state.  Disk mode ignores `raw_entries` (the
        just-rebuilt bucket list is the authority — no decode); in-memory
        mode materializes the dict from the (key XDR, entry XDR) pairs."""
        if self.bucket_store is not None:
            return self._make_disk_root(header)
        root = LedgerTxnRoot(header)
        root._entries = {kb: X.LedgerEntry.from_xdr(rec)
                         for kb, rec in raw_entries}
        return root

    # -- tx set canonicalization -------------------------------------------
    def make_tx_set(self, frames: Sequence[TransactionFrame]
                    ) -> Tuple[X.TransactionSet, bytes, List[TransactionFrame]]:
        """Sort txs into hash order, build the XDR set and its hash
        (reference: TxSetUtils::sortTxsInHashOrder + computeTxSetHash)."""
        ordered = sorted(frames, key=lambda f: f.content_hash())
        tx_set = X.TransactionSet(
            previousLedgerHash=self.lcl_hash,
            txs=[f.envelope for f in ordered])
        return tx_set, sha256(tx_set.to_xdr()), ordered

    def make_tx_set_any(self, frames: Sequence[TransactionFrame]):
        """make_tx_set, upgraded: a set containing Soroban txs becomes a
        GeneralizedTransactionSet (classic phase + Soroban phase); a
        pure-classic set keeps the legacy shape (and hash) byte-for-byte."""
        soroban = [f for f in frames if f.is_soroban()]
        if not soroban:
            return self.make_tx_set(frames)
        from ..soroban.txset import build_generalized_tx_set
        classic = [f for f in frames if not f.is_soroban()]
        gts, h = build_generalized_tx_set(self.lcl_hash, classic, soroban)
        ordered = sorted(classic, key=lambda f: f.content_hash()) \
            + sorted(soroban, key=lambda f: f.content_hash())
        return gts, h, ordered

    @staticmethod
    def apply_order(frames: Sequence[TransactionFrame]
                    ) -> List[TransactionFrame]:
        """Deterministic APPLY order (reference: TxSetFrame::
        getTxsInApplyOrder / ApplyTxSorter): each source account's txs in
        sequence-number order — hash order alone would seq-fail all but
        the lowest-seq tx of a multi-tx source — interleaved across
        sources by picking the queue head with the smallest content hash.
        Consensus-critical: live close and catchup replay must agree."""
        import heapq
        by_src: dict = {}
        for f in frames:
            by_src.setdefault(f.source_account_id().value, []).append(f)
        for q in by_src.values():
            q.sort(key=lambda f: f.seq_num)
        # sorted list is a valid heap; content hashes are unique so the
        # pop order is total regardless of dict insertion order
        heads = sorted((q[0].content_hash(), src)
                       for src, q in by_src.items())
        out: List[TransactionFrame] = []
        while heads:
            _, src = heapq.heappop(heads)
            q = by_src[src]
            out.append(q.pop(0))
            if q:
                heapq.heappush(heads, (q[0].content_hash(), src))
        return out

    # -- close --------------------------------------------------------------
    def close_ledger(self, frames: Sequence[TransactionFrame],
                     close_time: int,
                     tx_set: Optional[X.TransactionSet] = None,
                     expected_ledger_hash: Optional[bytes] = None,
                     stellar_value: Optional[X.StellarValue] = None
                     ) -> ClosedLedgerArtifacts:
        """Apply one ledger.  `frames` may arrive unsorted; the canonical
        order is derived.  If expected_ledger_hash is given (catchup replay),
        a mismatch raises — fail-stop, like the reference's hash checks.
        `stellar_value` is the externalized consensus value (carries voted
        upgrades, applied after the tx phase — reference:
        LedgerManagerImpl::applyLedger → Upgrades::applyTo)."""
        release_assert(self.root is not None,
                       "start_new_ledger/load first")
        with detguard.region("ledger-close"):
            if self.native_closer is not None \
                    and expected_ledger_hash is None:
                # live close through the C engine (catchup replay keeps
                # its own bridge: expected_ledger_hash marks that path).
                # The closer owns the ledger.close span — its fallback
                # paths run _close_ledger_python, which opens its own
                return self.native_closer.close_ledger(
                    frames, close_time, tx_set, stellar_value)
            return self._close_ledger_python(
                frames, close_time, tx_set, expected_ledger_hash,
                stellar_value)

    def _close_ledger_python(self, frames: Sequence[TransactionFrame],
                             close_time: int,
                             tx_set: Optional[X.TransactionSet] = None,
                             expected_ledger_hash: Optional[bytes] = None,
                             stellar_value: Optional[X.StellarValue] = None
                             ) -> ClosedLedgerArtifacts:
        """The classic pure-Python close (the oracle the native close
        differentially checks against, and its fallback)."""
        release_assert(self.root is not None,
                       "start_new_ledger/load first")
        with tracing.span("ledger.close",
                          seq=self.lcl_header.ledgerSeq + 1,
                          txs=len(frames)):
            try:
                return self._close_ledger(frames, close_time, tx_set,
                                          expected_ledger_hash, stellar_value)
            except BaseException:
                # a close that dies mid-flight (fail-stop invariant, a bug
                # surfaced by fuzzing) must not leave its LedgerTxn attached
                # to the root — the manager would refuse every later close
                # with "already has an active child" instead of reporting
                # the real error
                child = getattr(self.root, "_child", None)
                if child is not None and getattr(child, "_open", False):
                    child.rollback()
                raise

    # -- native live close ---------------------------------------------------
    def attach_native_close(self, differential: Optional[int] = None
                            ) -> bool:
        """Route live closes through the C engine (ledger/native_close.py).
        Returns False (and stays on Python) when unavailable."""
        from .native_close import NativeLedgerCloser, native_close_available
        if not native_close_available(self):
            return False
        self.native_closer = NativeLedgerCloser(self, differential)
        self.native_closer.activate()
        return True

    def detach_native_close(self) -> None:
        """Move authority back to Python and drop the native closer."""
        if self.native_closer is not None:
            self.native_closer.deactivate()
            self.native_closer = None

    def _close_ledger(self, frames: Sequence[TransactionFrame],
                      close_time: int,
                      tx_set: Optional[X.TransactionSet],
                      expected_ledger_hash: Optional[bytes],
                      stellar_value: Optional[X.StellarValue]
                      ) -> ClosedLedgerArtifacts:
        _t0 = time.perf_counter()
        if tx_set is None:
            tx_set, tx_set_hash, ordered = self.make_tx_set_any(frames)
        else:
            tx_set_hash = sha256(tx_set.to_xdr())
        # phase split: classic applies first, then the Soroban phase —
        # for a pure-classic set this is exactly the legacy apply order
        soroban_frames = [f for f in frames if f.is_soroban()]
        if soroban_frames:
            classic_frames = [f for f in frames if not f.is_soroban()]
            ordered = self.apply_order(classic_frames) \
                + self.apply_order(soroban_frames)
        else:
            ordered = self.apply_order(frames)
        if stellar_value is not None:
            if stellar_value.txSetHash != tx_set_hash:
                # fail-stop: committing a header that names a tx set other
                # than the one applied would corrupt the hash chain
                raise RuntimeError(
                    "externalized value names a different tx set")
            close_time = stellar_value.closeTime

        seq = self.lcl_header.ledgerSeq + 1
        # cost-ledger baselines (ISSUE 20): entry-cache traffic and
        # resident footprint are reported as per-close deltas; taken
        # before the prefetch so its cache fills count toward this close
        _hits0 = _registry().meter("bucketlistdb.cache.hit").count
        _miss0 = _registry().meter("bucketlistdb.cache.miss").count
        _resident0 = self.bucket_list.decoded_entry_count()
        if self.root.disk_backed and ordered:
            # bulk prefetch the tx set's account entries into the entry
            # cache: one batched, file-order snapshot pass instead of a
            # per-load probe chain each (reference: prefetchClassic
            # before apply)
            keys = set()
            for f in ordered:
                keys.add(X.account_key_xdr(f.source_account_id().value))
                inner = getattr(f, "inner", None)
                if inner is not None:
                    keys.add(X.account_key_xdr(
                        inner.source_account_id().value))
                for op in f.operations:
                    if op.sourceAccount is not None:
                        keys.add(X.account_key_xdr(
                            X.muxed_to_account_id(op.sourceAccount).value))
            self.root.prefetch(keys)
        ltx = LedgerTxn(self.root)
        header = ltx.load_header()
        header.ledgerSeq = seq
        header.previousLedgerHash = self.lcl_hash
        header.scpValue = stellar_value if stellar_value is not None else \
            X.StellarValue(txSetHash=tx_set_hash, closeTime=close_time)
        ltx.commit_header(header)

        # phase 1: fees + seq nums for every tx, before any applies
        _fee_t0 = time.perf_counter()
        with tracing.span("ledger.fee-process"), \
                _registry().timer("ledger.fee.process").time():
            for f in ordered:
                with LedgerTxn(ltx) as fee_ltx:
                    f.process_fee_seq_num(fee_ltx)
                    fee_ltx.commit()
        _fee_s = time.perf_counter() - _fee_t0

        # phase 2: apply — classic serially, then the Soroban phase
        # (footprint-clustered, optionally parallel)
        result_pairs: List[X.TransactionResultPair] = []
        split = len(ordered) - len(soroban_frames) if soroban_frames \
            else len(ordered)
        _apply_t0 = time.perf_counter()
        with tracing.span("ledger.tx-apply"):
            for f in ordered[:split]:
                with tracing.span("tx.apply"):
                    res = f.apply(ltx, close_time)
                result_pairs.append(X.TransactionResultPair(
                    transactionHash=f.content_hash(), result=res))
            if split < len(ordered):
                for f, res in self._apply_soroban_phase(
                        ltx, ordered[split:], close_time, seq):
                    result_pairs.append(X.TransactionResultPair(
                        transactionHash=f.content_hash(), result=res))
        _apply_s = time.perf_counter() - _apply_t0

        # state archival: expired TTLs evict at the close edge (before
        # the delta is split for the bucket list)
        self._evict_expired_ttl(ltx, seq)

        result_set = X.TransactionResultSet(results=result_pairs)
        header = ltx.load_header()
        header.txSetResultHash = sha256(result_set.to_xdr())

        # voted upgrades apply after the tx phase (reference: applyLedger →
        # Upgrades::applyTo, which re-validates and skips-with-log rather
        # than crashing mid-close; skipping is deterministic so live close
        # and catchup replay stay hash-identical)
        if stellar_value is not None and stellar_value.upgrades:
            from ..herder.upgrades import Upgrades
            for up in stellar_value.upgrades:
                Upgrades.apply_to_checked(up, header)
        ltx.commit_header(header)

        # split delta into INIT/LIVE/DEAD vs the pre-close state; stamp
        # lastModified at top-level commit time (reference: LedgerTxn
        # shouldUpdateLastModified at the root commit)
        delta = ltx.delta()
        self._note_soroban_delta(delta)
        pre_entries = {kb: self.root.get_entry(kb) for kb in delta}
        init_entries, live_entries, dead_keys = [], [], []
        for kb, entry in delta.items():  # corelint: disable=iteration-order -- delta is insertion-ordered: serial-equivalent first-write order, load-bearing
            pre = pre_entries[kb]
            if entry is None:
                if pre is not None:
                    dead_keys.append(X.LedgerKey.from_xdr(kb))
            elif pre is None:
                entry.lastModifiedLedgerSeq = seq
                init_entries.append(entry)
            else:
                entry.lastModifiedLedgerSeq = seq
                live_entries.append(entry)

        # pre-bucket invariant phase: a violation here fail-stops with the
        # manager un-torn (neither root store nor bucket list advanced)
        inv_ctx = None
        if self.invariants is not None:
            from ..invariant import LedgerCloseContext
            inv_ctx = LedgerCloseContext(
                pre=pre_entries, post=delta,
                pre_header=self.lcl_header, post_header=ltx.get_header(),
                root_get=self.root.get_entry,
                all_keys=lambda: list(self.root.all_keys()),
                bucket_list=self.bucket_list)
            self.invariants.check_on_ledger_close(inv_ctx,
                                                  needs_buckets=False)

        _seal_t0 = time.perf_counter()
        with tracing.span("ledger.seal"):
            self.bucket_list.add_batch(seq, header.ledgerVersion,
                                       init_entries, live_entries, dead_keys)
            if self.root.disk_backed:
                # the list just mutated: persist+index the changed buckets
                # and swap the root onto the new view; deep levels drop any
                # decoded entry lists (streaming-merge outputs already are
                # disk-resident), then GC reclaims files only old
                # (released) snapshots referenced
                with tracing.span("bucket.snapshot"):
                    self._refresh_snapshot(seq)
                self.bucket_list.enforce_residency()
                self._maybe_gc_buckets(seq)
            header = ltx.load_header()
            header.bucketListHash = self.bucket_list.hash()
            self._update_skip_list(header)
            ltx.commit_header(header)
        _seal_s = time.perf_counter() - _seal_t0

        if inv_ctx is not None:
            # post-bucket phase: a violation means the bucket list is
            # corrupt; the manager must be discarded
            self.invariants.check_on_ledger_close(inv_ctx, needs_buckets=True)
        ltx.commit()

        self.lcl_header = self.root.get_header()
        self.lcl_hash = sha256(self.lcl_header.to_xdr())
        if expected_ledger_hash is not None \
                and self.lcl_hash != expected_ledger_hash:
            raise RuntimeError(
                f"ledger {seq} hash mismatch: got {self.lcl_hash.hex()} "
                f"expected {expected_ledger_hash.hex()}")

        if self.db is not None:
            self._persist_lcl()

        header_entry = X.LedgerHeaderHistoryEntry(
            hash=self.lcl_hash, header=self.lcl_header)
        meta_tx_set = tx_set
        from ..soroban.txset import is_generalized
        if is_generalized(tx_set):
            # history entry: generalized sets ride in ext v1; the legacy
            # txSet field carries an empty classic set (reference:
            # TransactionHistoryEntry.ext.generalizedTxSet)
            meta_tx_set = X.TransactionSet(
                previousLedgerHash=tx_set.value.previousLedgerHash, txs=[])
            tx_entry = X.TransactionHistoryEntry(
                ledgerSeq=seq, txSet=meta_tx_set,
                ext=X.TransactionHistoryEntryExt.generalizedTxSet(tx_set))
        else:
            tx_entry = X.TransactionHistoryEntry(ledgerSeq=seq, txSet=tx_set)
        result_entry = X.TransactionHistoryResultEntry(
            ledgerSeq=seq, txResultSet=result_set)

        if self.debug_close_throttle_s > 0.0:  # corelint: disable=float-discipline -- test-only throttle knob, never ledger state
            # injected-regression seam: spin out the close so the
            # anomaly detector has a real sustained departure to catch
            _spin_until = time.perf_counter() + self.debug_close_throttle_s
            while time.perf_counter() < _spin_until:
                pass

        # registry lookups are NOT cached across the close: /clearmetrics
        # resets metrics in place, but reset_registry() (tests) swaps the
        # whole registry — a cached reference would feed a dead object
        dur_s = time.perf_counter() - _t0
        _registry().timer("ledger.ledger.close").update(dur_s)
        # flight event at the seal edge: the last thing a post-mortem sees
        # from a healthy node is the close it finished
        eventlog.record("Ledger", "INFO", "ledger close sealed",
                        seq=seq, txs=len(ordered),
                        dur_ms=round(dur_s * 1e3, 3),
                        hash=self.lcl_hash.hex()[:16])
        tracing.mark_phase("close-seal", seq, txs=len(ordered),
                           dur_ms=round(dur_s * 1e3, 3))
        _registry().meter("ledger.transaction.apply").mark(len(ordered))
        # per-close cost row (ISSUE 20): the post-mortem unit /closecosts
        # serves and anomaly bundles ship.  Deltas close over the whole
        # close (prefetch, apply loads and seal-phase snapshot churn all
        # land in the cache counters).
        _resident1 = self.bucket_list.decoded_entry_count()
        self.close_costs.add(
            seq=seq, txs=len(ordered), total_s=dur_s,
            fee_s=_fee_s, apply_s=_apply_s, seal_s=_seal_s,
            merge_stall_s=self.bucket_list.last_add_stall_s,
            cache_hits=_registry().meter(
                "bucketlistdb.cache.hit").count - _hits0,
            cache_misses=_registry().meter(
                "bucketlistdb.cache.miss").count - _miss0,
            pin_count=self.bucket_store.pin_count()
            if self.bucket_store is not None else 0,
            resident_entries=_resident1,
            resident_delta=_resident1 - _resident0,
            gc_backlog=seq - self._last_gc_seq
            if self.bucket_store is not None else 0)
        if self.meta_stream is not None:
            self._emit_close_meta(header_entry, meta_tx_set, result_pairs)
        return ClosedLedgerArtifacts(header_entry, tx_entry, result_entry)

    # -- Soroban phase (ISSUE 17) -------------------------------------------
    def _apply_soroban_phase(self, ltx: LedgerTxn, soroban_ordered,
                             close_time: int, seq: int):
        """Apply the Soroban phase: partition into disjoint write-set
        clusters, apply clusters as parallel batches (serial when the
        partition is a single cluster or parallel apply is off), and
        return (frame, result) pairs in canonical order.  Serial and
        parallel runs are byte-identical — asserted end-to-end by
        tests/test_soroban.py and the loadgen campaign."""
        from ..soroban.scheduler import (apply_clusters_parallel,
                                         cluster_footprints)
        t0 = time.perf_counter()
        with detguard.region("soroban-apply"):
            clusters = cluster_footprints(soroban_ordered)
            _registry().histogram("soroban.apply.clusters").update(
                len(clusters))
            if not self.soroban_parallel_apply or len(clusters) <= 1:
                out = []
                for f in soroban_ordered:
                    with tracing.span("tx.apply"):
                        out.append((f, f.apply(ltx, close_time)))
            else:
                positions = {id(f): i for i, f in enumerate(soroban_ordered)}
                with tracing.span("soroban.parallel-apply",
                                  clusters=len(clusters)):
                    res_map = apply_clusters_parallel(
                        ltx, clusters,
                        lambda fr, cltx: fr.apply(cltx, close_time),
                        positions)
                out = [(f, res_map[id(f)]) for f in soroban_ordered]
        dur_s = time.perf_counter() - t0
        _registry().timer("soroban.apply.phase").update(dur_s)
        _registry().meter("soroban.transaction.apply").mark(
            len(soroban_ordered))
        eventlog.record("Ledger", "INFO", "soroban phase applied",
                        seq=seq, txs=len(soroban_ordered),
                        clusters=len(clusters),
                        parallel=bool(self.soroban_parallel_apply
                                      and len(clusters) > 1),
                        dur_ms=round(dur_s * 1e3, 3))
        tracing.mark_phase("soroban-apply", seq, txs=len(soroban_ordered),
                           clusters=len(clusters))
        return out

    _TTL_KEY_PREFIX = (9).to_bytes(4, "big")
    _CONTRACT_KEY_PREFIXES = ((6).to_bytes(4, "big"), (7).to_bytes(4, "big"))

    def _rebuild_ttl_index(self) -> dict:
        """Full scan rebuild of keyHash → [liveUntil, dataKeyXdr,
        durability] (loaded/assumed state arrives without one).  Only
        CONTRACT_DATA/CONTRACT_CODE/TTL keys are decoded — sniffed by
        the 4-byte LedgerEntryType prefix, so classic-only state pays
        one pass of byte compares and zero decodes."""
        idx: dict = {}
        for kb in self.root.all_keys():
            prefix = bytes(kb[:4])
            if prefix in self._CONTRACT_KEY_PREFIXES:
                key = X.LedgerKey.from_xdr(kb)
                dur = (key.value.durability
                       if key.switch == X.LedgerEntryType.CONTRACT_DATA
                       else X.ContractDataDurability.PERSISTENT)
                rec = idx.setdefault(sha256(kb), [0, None, None])
                rec[1], rec[2] = kb, dur
            elif prefix == self._TTL_KEY_PREFIX:
                entry = self.root.get_entry(kb)
                if entry is not None:
                    kh = bytes(entry.data.value.keyHash)
                    rec = idx.setdefault(kh, [0, None, None])
                    rec[0] = int(entry.data.value.liveUntilLedgerSeq)
        self._ttl_expiry = idx
        return idx

    def _note_soroban_delta(self, delta) -> None:
        """Fold one close's delta into the TTL expiry index (no-op for
        classic-only deltas; index is rebuilt lazily when None)."""
        idx = self._ttl_expiry
        if idx is None:
            return
        for kb, entry in delta.items():  # corelint: disable=iteration-order -- per-key hashing into a keyed index; order immaterial
            prefix = bytes(kb[:4])
            if prefix in self._CONTRACT_KEY_PREFIXES:
                kh = sha256(kb)
                if entry is None:
                    idx.pop(kh, None)
                else:
                    d = entry.data
                    dur = (d.value.durability
                           if d.switch == X.LedgerEntryType.CONTRACT_DATA
                           else X.ContractDataDurability.PERSISTENT)
                    rec = idx.setdefault(kh, [0, None, None])
                    rec[1], rec[2] = kb, dur
            elif prefix == self._TTL_KEY_PREFIX:
                if entry is not None:
                    kh = bytes(entry.data.value.keyHash)
                    rec = idx.setdefault(kh, [0, None, None])
                    rec[0] = int(entry.data.value.liveUntilLedgerSeq)

    def _evict_expired_ttl(self, ltx: LedgerTxn, seq: int) -> int:
        """State archival at the close edge: expired TEMPORARY entries
        (and their TTL entries) are erased; expired PERSISTENT entries
        stay put — they read as ENTRY_ARCHIVED until RestoreFootprint.
        Deterministic: expiry candidates walk in sorted keyHash order."""
        idx = self._ttl_expiry
        if idx is None:
            idx = self._rebuild_ttl_index()
        if not idx:
            return 0
        evicted = 0
        for kh in sorted(idx):
            live_until, data_kb, durability = idx[kh]
            if data_kb is None or live_until >= seq:
                continue
            if durability != X.ContractDataDurability.TEMPORARY:
                continue
            if ltx.get_entry(data_kb) is not None:
                ltx.erase(X.LedgerKey.from_xdr(data_kb))
                evicted += 1
            ttl_kb = X.LedgerKey.ttl(X.LedgerKeyTtl(keyHash=kh)).to_xdr()
            if ltx.get_entry(ttl_kb) is not None:
                ltx.erase(X.LedgerKey.from_xdr(ttl_kb))
        if evicted:
            _registry().meter("soroban.ttl.evicted").mark(evicted)
        return evicted

    def close_ledger_synthetic(self, init_entries: Sequence[X.LedgerEntry],
                               close_time: int) -> None:
        """Advance one ledger injecting `init_entries` directly — no txs,
        no fees, no signatures, no invariants (reference shape:
        BucketApplicator seeding state during ApplyBucketsWork, repurposed
        as the load-campaign seam for synthesizing account universes at
        millions-of-entries scale without replaying millions of
        CreateAccount ops).  Entries must be NEW keys (they land as INIT
        in the bucket list); the header advances and hashes exactly like
        a real close of an empty tx set over the mutated bucket list."""
        release_assert(self.root is not None,
                       "start_new_ledger/load first")
        # synthetic closes mutate the Python state directly: round-trip
        # the engine state so the two views cannot diverge.  Only when
        # the engine actually HOLDS authority — a degraded or
        # mid-catchup-deactivated closer must not overwrite newer Python
        # state with its stale export (nor be silently re-armed below)
        nc = self.native_closer
        nc_roundtrip = (nc is not None and nc.bridge.active
                        and nc.degraded is None)
        if nc_roundtrip:
            nc.bridge.export_to_manager(self)
        seq = self.lcl_header.ledgerSeq + 1
        entries = list(init_entries)
        for e in entries:
            e.lastModifiedLedgerSeq = seq
        # synthetic injections bypass _close_ledger: keep the TTL expiry
        # index honest for any contract/TTL entries seeded this way
        self._note_soroban_delta(
            {X.ledger_entry_key_xdr(e): e for e in entries})
        self.bucket_list.add_batch(seq, self.lcl_header.ledgerVersion,
                                   entries, [], [])
        if self.root.disk_backed:
            with tracing.span("bucket.snapshot"):
                self._refresh_snapshot(seq)
            self.bucket_list.enforce_residency()
            self._maybe_gc_buckets(seq)
        ltx = LedgerTxn(self.root)
        header = ltx.load_header()
        header.ledgerSeq = seq
        header.previousLedgerHash = self.lcl_hash
        header.scpValue = X.StellarValue(txSetHash=b"\x00" * 32,
                                         closeTime=close_time)
        header.txSetResultHash = sha256(
            X.TransactionResultSet(results=[]).to_xdr())
        header.bucketListHash = self.bucket_list.hash()
        self._update_skip_list(header)
        ltx.commit_header(header)
        if not self.root.disk_backed:
            for e in entries:
                ltx.create(e)
        ltx.commit()
        self.lcl_header = self.root.get_header()
        self.lcl_hash = sha256(self.lcl_header.to_xdr())
        if self.db is not None:
            self._persist_lcl()
        if nc_roundtrip:
            nc.bridge.import_from(self)

    def _emit_close_meta(self, header_entry, tx_set, result_pairs) -> None:
        """Emit LedgerCloseMeta v0 (reference: METADATA_OUTPUT_STREAM —
        one length-prefixed XDR frame per close)."""
        meta = X.LedgerCloseMeta.v0(X.LedgerCloseMetaV0(
            ledgerHeader=header_entry,
            txSet=tx_set,
            txProcessing=[X.TransactionResultMeta(
                result=p, feeProcessing=b"", txApplyProcessing=b"")
                for p in result_pairs],
            upgradesProcessing=[],
            scpInfo=[]))
        out = self.meta_stream
        if callable(out):
            out(meta)
        else:
            raw = meta.to_xdr()
            out.write(len(raw).to_bytes(4, "big") + raw)
            out.flush()

    # -- durable persistence -------------------------------------------------
    def enable_persistence(self, database, bucket_dir) -> None:
        """Attach a Database + BucketDir; every close (and the current LCL,
        immediately) is then durably recorded.  Reference: the implicit
        persistence of LedgerManagerImpl's SQL store + BucketManager."""
        self.db = database
        self.bucket_dir = bucket_dir
        if self.lcl_header is not None:
            self._persist_lcl()

    def _persist_lcl(self) -> None:
        """Bucket files first (content-addressed, idempotent), then the
        header row + storestate pointers in one sqlite transaction — a crash
        between the two leaves only orphaned bucket files, never a DB that
        references missing buckets.  Pending merges persist without
        blocking: resolved ones as their output, running ones as their
        inputs.

        The HAS is serialized ONCE and the level loop saves exactly the
        buckets that serialization recorded — serializing twice would race
        a background merge completing in between, and a HAS naming a
        state-1 output that was never written bricks restart."""
        from ..database import PersistentState
        from ..history.archive import HistoryArchiveState
        has = HistoryArchiveState.from_bucket_list(
            self.last_closed_ledger_seq, self.network_id.hex(),
            self.bucket_list, resolve=False)
        for lvl, lh in zip(self.bucket_list.levels, has.level_hashes):
            self.bucket_dir.save(lvl.curr)
            self.bucket_dir.save(lvl.snap)
            nxt = lh["next"]
            if nxt is None:
                continue
            if nxt["state"] == 1:
                # recorded as output ⇒ the merge was done at serialize
                # time; resolve() returns that same output instantly
                self.bucket_dir.save(lvl.next.resolve())
            else:
                curr_in, snap_in, _, _ = lvl.next.inputs
                self.bucket_dir.save(curr_in)
                self.bucket_dir.save(snap_in)
        self.db.store_header(self.lcl_hash, self.lcl_header)
        self.db.set_state(PersistentState.LAST_CLOSED_LEDGER,
                          self.lcl_hash.hex())
        self.db.set_state(PersistentState.HISTORY_ARCHIVE_STATE,
                          has.to_json())
        self.db.set_state(PersistentState.NETWORK_PASSPHRASE,
                          self.network_id.hex())
        self.db.commit()

    @classmethod
    def load_last_known_ledger(cls, network_id: bytes, database, bucket_dir,
                               invariant_manager=_DEFAULT_INVARIANTS,
                               bucket_store=None,
                               entry_cache_size: Optional[int] = None,
                               resident_levels: Optional[int] = None
                               ) -> "LedgerManager":
        """Rebuild a manager from durable state (reference:
        LedgerManagerImpl::loadLastKnownLedger): header from the DB, bucket
        list from on-disk bucket files named by the stored HAS, entry store
        re-derived newest-first from the bucket list, everything
        hash-verified against the stored header."""
        from ..database import PersistentState
        from ..history.archive import HistoryArchiveState
        from ..bucket.bucket_list import NUM_LEVELS

        # integrity audit BEFORE any on-disk state is trusted: every
        # manifest-listed bucket file must be present and every file must
        # hash to its name — a corrupted or vanished file (even one only a
        # pinned snapshot or publish queue still needs) fail-stops with a
        # diagnostic here instead of serving wrong ledger state later
        verified = bucket_dir.audit()
        log.info("bucket dir audit: %d files hash-verified", verified)

        lcl_hex = database.get_state(PersistentState.LAST_CLOSED_LEDGER)
        if lcl_hex is None:
            raise RuntimeError("database has no last closed ledger")
        stored_net = database.get_state(PersistentState.NETWORK_PASSPHRASE)
        if stored_net is not None and stored_net != network_id.hex():
            raise RuntimeError("database belongs to a different network")
        header = database.load_header_by_hash(bytes.fromhex(lcl_hex))
        if header is None:
            raise RuntimeError("stored LCL header missing")
        if sha256(header.to_xdr()) != bytes.fromhex(lcl_hex):
            raise RuntimeError("stored LCL header fails hash check")
        has_json = database.get_state(PersistentState.HISTORY_ARCHIVE_STATE)
        if has_json is None:
            raise RuntimeError("database has no archive state")
        has = HistoryArchiveState.from_json(has_json)

        mgr = cls(network_id, invariant_manager=invariant_manager,
                  bucket_store=bucket_store,
                  entry_cache_size=entry_cache_size,
                  resident_levels=resident_levels)
        hashes = has.bucket_hashes()
        if len(hashes) != NUM_LEVELS * 2:
            raise RuntimeError("stored HAS malformed")

        def source(idx: int):
            bucket = bucket_dir.load(hashes[idx])
            if bucket is None:
                raise RuntimeError(f"missing bucket {hashes[idx]}")
            return bucket

        def next_source(level: int):
            return has.rehydrate_next(level, bucket_dir.load)

        mgr.root = assume_bucket_state(mgr.bucket_list, header, source,
                                       next_source,
                                       store=bucket_store,
                                       entry_cache_size=mgr.entry_cache_size)
        mgr.lcl_header = header
        mgr.lcl_hash = bytes.fromhex(lcl_hex)
        mgr.db = database
        mgr.bucket_dir = bucket_dir
        mgr._ttl_expiry = None   # loaded state: rebuild index lazily
        log.info("resumed at ledger %d (%d entries)",
                 header.ledgerSeq, mgr.root.entry_count())
        return mgr

    def _update_skip_list(self, header: X.LedgerHeader) -> None:
        """Rotate the 4 skip hashes at their intervals (reference:
        LedgerHeaderUtils / updateSkipList in LedgerManagerImpl)."""
        sl = list(header.skipList)
        for i, interval in enumerate(SKIP_INTERVALS):
            if header.ledgerSeq % interval == 0:
                sl[i] = header.previousLedgerHash
        header.skipList = sl

    # -- accessors -----------------------------------------------------------
    @property
    def last_closed_ledger_seq(self) -> int:
        return self.lcl_header.ledgerSeq

    def make_frame(self, envelope: X.TransactionEnvelope) -> TransactionFrame:
        return TransactionFrame.make_from_wire(self.network_id, envelope)
