"""Native live close: LedgerManager.close_ledger driven by the C engine.

Reference: the reference node's single native apply path serves BOTH
catchup replay and live close (LedgerManagerImpl::applyLedger); round 12
gives this framework the same property.  A NativeLedgerCloser owns a
NativeApplyBridge whose engine holds the authoritative ledger state;
every externalized tx set is serialized once and applied in C
(`Engine.close_ledger`), and the engine returns the new header, the
TransactionResultSet and the ledger's entry delta so the Python manager
mirrors its read view (tx-queue/admission sequence checks, /info, HTTP
endpoints keep working against `mgr.root`).

Differential guard subsystem:

- ``NATIVE_CLOSE_DIFFERENTIAL=N`` (config key or environment): every Nth
  close ALSO runs the pure-Python close on a scratch manager built from
  the engine's exported state and fail-stops with a crash bundle on any
  divergence in per-tx results, fees, header hash or bucket hashes.  A
  divergence is a consensus-critical engine bug: the node must not keep
  closing ledgers with it.
- probe miss (a live tx set with non-classic content): that one close
  runs in Python after an export round-trip, then the engine re-imports
  — mirrored by the ``ledger.native.fallbacks`` meter.
- engine error: the engine rolls back the failed close, state is
  exported back to Python, the closer DEGRADES permanently (flight event
  + ``on_degrade`` status hook) and every later close runs in Python.

Durability while active: the Python bucket list is stale between
checkpoint boundaries; the closer rebuilds it (and persists, when a
database is attached) at every boundary and on deactivate, so history
publishing always sees fresh buckets.  A crash between boundaries is
re-covered by the same archive-rejoin path the fleet harness exercises.

Kill switch: STELLAR_TPU_NO_CAPPLY disables the whole subsystem.
"""

from __future__ import annotations

import os
import time
from typing import Optional, Sequence

from .. import xdr as X
from ..crypto.sha import sha256
from ..util import eventlog
from ..util import logging as slog
from ..util import tracing
from ..util.metrics import registry as _registry
from .native_apply import NativeApplyBridge, native_apply_available

log = slog.get("Ledger")

ENV_DIFFERENTIAL = "NATIVE_CLOSE_DIFFERENTIAL"


def native_close_available(mgr) -> bool:
    """The native close path needs the extension, an in-memory root (the
    BucketListDB root reads bucket files the engine does not maintain
    per close) and no invariant manager (the invariant hooks live on the
    Python close path)."""
    return (native_apply_available()
            and mgr.bucket_store is None
            and mgr.invariants is None)


class NativeCloseDivergence(RuntimeError):
    """A differential spot-check caught the C engine disagreeing with the
    Python oracle — consensus-critical, always fail-stop."""


class NativeLedgerCloser:
    """Owns the engine that closes this manager's ledgers natively."""

    def __init__(self, mgr, differential: Optional[int] = None):
        if not native_close_available(mgr):
            raise RuntimeError("native close unavailable (extension not "
                               "built, disk root, or invariants enabled)")
        self.mgr = mgr
        if differential is None:
            differential = int(os.environ.get(ENV_DIFFERENTIAL, "0") or 0)
        self.differential = max(0, int(differential))
        self.bridge = NativeApplyBridge(mgr.network_id)
        self.degraded: Optional[str] = None
        self.closes = 0
        self.fallbacks = 0
        self.differential_checks = 0
        # wiring hooks (Application: status line + flight recorder)
        self.on_degrade = None          # callable(reason: str)
        # test seam: mutate the native result tuple before the
        # differential compare (forces a divergence end to end)
        self._corrupt_native_result_for_test = None

    # -- lifecycle ----------------------------------------------------------
    def activate(self) -> None:
        if not self.bridge.active:
            self.bridge.import_from(self.mgr)
        log.info("native live close active (differential=%d)",
                 self.differential)

    def deactivate(self) -> None:
        """Move authority back to Python (bucket list + root rebuilt)."""
        if self.bridge.active:
            self.bridge.export_to_manager(self.mgr)
            if self.mgr.db is not None:
                self.mgr._persist_lcl()

    # -- close --------------------------------------------------------------
    def close_ledger(self, frames: Sequence, close_time: int,
                     tx_set=None, stellar_value=None):
        """The LedgerManager.close_ledger native path.  Returns the same
        ClosedLedgerArtifacts as the Python close, or falls back to it
        (probe miss / degraded)."""
        mgr = self.mgr
        if self.degraded is not None or not self.bridge.active:
            return mgr._close_ledger_python(frames, close_time, tx_set,
                                            None, stellar_value)
        # Soroban content (generalized tx set / soroban frames / pending
        # TTL archival) is Python-only: the C engine neither hosts the
        # built-in table nor evicts expired entries — fall back EARLY,
        # before any TransactionHistoryEntry is built from a set shape
        # the legacy record cannot carry
        from ..soroban.txset import is_generalized
        if (tx_set is not None and is_generalized(tx_set)) \
                or any(f.is_soroban() for f in frames) \
                or mgr._ttl_expiry is None or mgr._ttl_expiry:
            return self._fallback_close(frames, close_time, tx_set,
                                        stellar_value,
                                        why="soroban content in the tx set")
        _t0 = time.perf_counter()
        if tx_set is None:
            tx_set, tx_set_hash, _ = mgr.make_tx_set(frames)
        else:
            tx_set_hash = sha256(tx_set.to_xdr())
        if stellar_value is None:
            stellar_value = X.StellarValue(txSetHash=tx_set_hash,
                                           closeTime=close_time)
        seq = mgr.lcl_header.ledgerSeq + 1
        tx_rec = X.TransactionHistoryEntry(ledgerSeq=seq,
                                           txSet=tx_set).to_xdr()
        if not self.bridge.probe([tx_rec]):
            # non-classic content in a LIVE tx set: close this one in
            # Python after an export round-trip, then resume native
            return self._fallback_close(frames, close_time, tx_set,
                                        stellar_value,
                                        why="probe rejected the tx set")
        scratch = None
        if self.differential and (self.closes + 1) % self.differential == 0:
            scratch = self._scratch_manager()
        # the ledger.close span covers ONLY the genuinely-native close;
        # every fallback route runs _close_ledger_python, which opens its
        # own span — nesting two ledger.close spans for one ledger would
        # double trace-derived close counts
        err = None
        with tracing.span("ledger.close",
                          seq=mgr.lcl_header.ledgerSeq + 1,
                          txs=len(frames)):
            try:
                # the whole tx phase runs in C: one batched tx.apply span
                # stands in for the Python path's per-tx spans
                with tracing.span("ledger.tx-apply"), \
                        tracing.span("tx.apply", txs=len(frames),
                                     engine="native"):
                    result = self.bridge.close_ledger(
                        tx_rec, self._scp_value_xdr(stellar_value))
            except Exception as e:  # corelint: disable=exception-hygiene -- any engine error degrades to the Python close (logged + flight event)
                err = e
            if err is None:
                if scratch is not None:
                    if self._corrupt_native_result_for_test is not None:
                        result = self._corrupt_native_result_for_test(result)
                    self._differential_check(scratch, frames, close_time,
                                             tx_set, stellar_value, result)
                return self._finish(result, tx_set, _t0)
        return self._degrade_close(frames, close_time, tx_set,
                                   stellar_value, err)

    # -- internals ----------------------------------------------------------
    @staticmethod
    def _scp_value_xdr(stellar_value) -> bytes:
        return stellar_value.to_xdr()

    def _finish(self, result, tx_set, t0: float):
        from .manager import ClosedLedgerArtifacts
        mgr = self.mgr
        seq, lcl_hash, header_xdr, results_xdr, delta = result
        header = X.LedgerHeader.from_xdr(header_xdr)
        result_set = X.TransactionResultSet.from_xdr(results_xdr)
        # mirror the ledger delta into the Python read view
        entries = {kb: (None if rec is None else X.LedgerEntry.from_xdr(rec))
                   for kb, rec in delta}
        mgr.root._apply_delta(entries, header)
        mgr.lcl_header = header
        mgr.lcl_hash = lcl_hash
        if mgr.db is not None:
            # per-ledger header rows feed checkpoint publishing; the
            # bucket files + durable LCL pointer follow at boundaries
            mgr.db.store_header(lcl_hash, header)
            mgr.db.commit()
        self.closes += 1
        reg = _registry()
        reg.meter("ledger.native.closes").mark()
        reg.meter("ledger.transaction.apply").mark(len(result_set.results))
        # injected-regression seam (ISSUE 20): same spin as the Python
        # close so anomaly proof tests work regardless of engine
        if mgr.debug_close_throttle_s > 0.0:  # corelint: disable=float-discipline -- test-only throttle knob, never ledger state
            _spin_until = time.perf_counter() + mgr.debug_close_throttle_s
            while time.perf_counter() < _spin_until:
                pass
        dur_s = time.perf_counter() - t0
        reg.timer("ledger.ledger.close").update(dur_s)
        # close cost record (ISSUE 20): phase splits and entry-cache
        # traffic are engine-internal on this path — 0 marks "not
        # attributable", total_s still carries the close cost
        mgr.close_costs.add(
            seq=seq, txs=len(result_set.results), total_s=dur_s,
            fee_s=0.0, apply_s=0.0, seal_s=0.0,  # corelint: disable=float-discipline -- cost-record "not attributable" sentinels, monitoring-only
            merge_stall_s=mgr.bucket_list.last_add_stall_s,
            cache_hits=0, cache_misses=0,
            pin_count=(mgr.bucket_store.pin_count()
                       if mgr.bucket_store is not None else 0),
            resident_entries=mgr.bucket_list.decoded_entry_count(),
            resident_delta=0,
            gc_backlog=(seq - mgr._last_gc_seq
                        if mgr.bucket_store is not None else 0))
        # same flight-event name as the Python close (post-mortem greps
        # key on it); the engine field tells the paths apart
        eventlog.record("Ledger", "INFO", "ledger close sealed",
                        seq=seq, txs=len(result_set.results),
                        dur_ms=round(dur_s * 1e3, 3),
                        hash=lcl_hash.hex()[:16], engine="native")
        tracing.mark_phase("close-seal", seq,
                           txs=len(result_set.results),
                           dur_ms=round(dur_s * 1e3, 3), engine="native")
        if self._at_boundary(seq):
            self._sync_boundary()
        if mgr.meta_stream is not None:
            mgr._emit_close_meta(
                X.LedgerHeaderHistoryEntry(hash=lcl_hash, header=header),
                tx_set, list(result_set.results))
        return ClosedLedgerArtifacts(
            header_entry=X.LedgerHeaderHistoryEntry(hash=lcl_hash,
                                                    header=header),
            tx_entry=X.TransactionHistoryEntry(ledgerSeq=seq, txSet=tx_set),
            result_entry=X.TransactionHistoryResultEntry(
                ledgerSeq=seq, txResultSet=result_set))

    def _at_boundary(self, seq: int) -> bool:
        from ..history.archive import is_checkpoint_boundary
        return is_checkpoint_boundary(seq)

    def _sync_boundary(self) -> None:
        """Checkpoint boundary: history publishing (and persistence) read
        the PYTHON bucket list — rebuild it from the engine."""
        self.bridge.sync_buckets_to(self.mgr)
        if self.mgr.db is not None:
            self.mgr._persist_lcl()

    def _scratch_manager(self):
        """A throwaway manager carrying a full copy of the engine state —
        the Python oracle closes on it during a differential check."""
        from .manager import LedgerManager
        scratch = LedgerManager(self.mgr.network_id, invariant_manager=None)
        scratch.start_new_ledger()
        self.bridge._export_into(scratch)
        return scratch

    def _differential_check(self, scratch, frames, close_time, tx_set,
                            stellar_value, result) -> None:
        seq, lcl_hash, header_xdr, results_xdr, _delta = result
        self.differential_checks += 1
        _registry().meter("ledger.native.differential-checks").mark()
        arts = scratch.close_ledger(frames, close_time, tx_set=tx_set,
                                    stellar_value=stellar_value)
        py_results = arts.result_entry.txResultSet
        ok = (scratch.lcl_hash == lcl_hash
              and scratch.lcl_header.to_xdr() == header_xdr
              and py_results.to_xdr() == results_xdr)
        if ok:
            return
        detail = self._divergence_detail(seq, py_results, results_xdr,
                                         scratch, lcl_hash)
        eventlog.write_crash_bundle(f"NativeCloseDivergence: {detail}")
        raise NativeCloseDivergence(detail)

    @staticmethod
    def _divergence_detail(seq, py_results, results_xdr, scratch,
                           lcl_hash) -> str:
        """Name the first diverging tx (and fee) so the crash bundle says
        which op went wrong, not just that hashes differ."""
        try:
            c_results = X.TransactionResultSet.from_xdr(results_xdr)
        except Exception:  # corelint: disable=exception-hygiene -- undecodable native bytes ARE the divergence being reported
            return (f"ledger {seq}: native result set undecodable; python "
                    f"lcl {scratch.lcl_hash.hex()[:16]} vs native "
                    f"{lcl_hash.hex()[:16]}")
        for i, (pp, cp) in enumerate(zip(py_results.results,
                                         c_results.results)):
            if pp.to_xdr() != cp.to_xdr():
                fee = (pp.result.feeCharged, cp.result.feeCharged)
                return (f"ledger {seq} tx #{i} "
                        f"{pp.transactionHash.hex()[:16]}: python result "
                        f"code {pp.result.result.switch} fee {fee[0]} vs "
                        f"native code {cp.result.result.switch} fee "
                        f"{fee[1]}")
        if len(py_results.results) != len(c_results.results):
            return (f"ledger {seq}: result counts diverge "
                    f"({len(py_results.results)} python vs "
                    f"{len(c_results.results)} native)")
        return (f"ledger {seq}: results identical but header/bucket state "
                f"diverged (python lcl {scratch.lcl_hash.hex()[:16]} vs "
                f"native {lcl_hash.hex()[:16]})")

    def _fallback_close(self, frames, close_time, tx_set, stellar_value,
                        why: str):
        """One Python close with a full export/import round-trip (probe
        miss on a live set — rare: Soroban or generalized-set content)."""
        mgr = self.mgr
        self.fallbacks += 1
        _registry().meter("ledger.native.fallbacks").mark()
        eventlog.record("Ledger", "WARNING", "native close fallback",
                        seq=mgr.lcl_header.ledgerSeq + 1, why=why)
        self.bridge.export_to_manager(mgr)
        arts = mgr._close_ledger_python(frames, close_time, tx_set, None,
                                        stellar_value)
        self.bridge.import_from(mgr)
        return arts

    def _degrade_close(self, frames, close_time, tx_set, stellar_value,
                       exc: Exception):
        """Engine error: permanent degrade to the Python engine.  The
        engine rolled the failed close back (or reports itself poisoned,
        in which case there is no state to recover — fail-stop)."""
        mgr = self.mgr
        reason = f"native close error at ledger " \
                 f"{mgr.lcl_header.ledgerSeq + 1}: {exc}"
        self.degraded = reason
        self.fallbacks += 1
        _registry().meter("ledger.native.fallbacks").mark()
        eventlog.record("Ledger", "ERROR", "native close DEGRADED",
                        reason=str(exc))
        log.error("native live close degraded to Python: %s", exc)
        if self.on_degrade is not None:
            try:
                self.on_degrade(reason)
            except Exception:  # corelint: disable=exception-hygiene -- status-line wiring is best-effort during a degrade
                pass
        try:
            self.bridge.export_to_manager(mgr)
        except Exception as export_exc:
            eventlog.write_crash_bundle(
                f"native close degrade failed: engine state unrecoverable "
                f"({export_exc}) after {exc}")
            raise
        return mgr._close_ledger_python(frames, close_time, tx_set, None,
                                        stellar_value)
