"""SearchableBucketListSnapshot: immutable point-in-time ledger-state reads.

Reference: src/bucket/SearchableBucketListSnapshot* + BucketSnapshotManager —
the reference hands read-only bucket-list snapshots to threads that must not
see (or block) the main thread's mutations: the HTTP query server
(`getledgerentry`), background tx-validation pre-flight, and parallel apply.
Since v21 the snapshot is also the AUTHORITATIVE read path: BucketListDB
serves every ledger-entry load from indexed bucket files.

Two view flavors compose a snapshot, one per non-empty bucket in
newest-first order (level 0 curr, level 0 snap, level 1 curr, ...):

* resident — the in-memory ``Bucket`` object (tests/sims, and any bucket
  the store has not persisted);
* disk — a ``DiskBucketIndex`` + the content-addressed file, so a lookup
  seeks to one record instead of holding decoded entries in memory.

When built against a ``BucketListStore`` the snapshot PINS its files for
its lifetime, so bucket GC after later closes never unlinks a file this
view still reads (release() drops the pins).  Buckets are immutable, so
later ``add_batch`` calls on the live list never mutate what this object
sees either way.
"""

from __future__ import annotations

import time
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from ..util.lockorder import make_lock
from ..util.metrics import registry as _registry
from ..util.racetrace import race_checked
from ..xdr import LedgerEntry
from .bucket import _BE, Bucket, _is_dead

# probe() result: None = absent; (True, None) = tombstone; (False, entry)
_Probe = Optional[Tuple[bool, Optional[LedgerEntry]]]


class _ResidentView:
    """Read view over an in-memory bucket."""

    __slots__ = ("bucket",)

    def __init__(self, bucket: Bucket):
        self.bucket = bucket

    def maybe_contains(self, key_bytes: bytes) -> bool:
        return self.bucket.index().maybe_contains(key_bytes)

    def probe(self, key_bytes: bytes) -> _Probe:
        be = self.bucket.find(key_bytes)
        if be is None:
            return None
        return (True, None) if _is_dead(be) else (False, be.value)

    def probe_many(self, sorted_keys: List[bytes]) -> Dict[bytes, _Probe]:
        out: Dict[bytes, _Probe] = {}
        for kb in sorted_keys:
            hit = self.probe(kb)
            if hit is not None:
                out[kb] = hit
        return out

    def iter_keys(self) -> Iterator[Tuple[bytes, bool]]:
        for kb, be in zip(self.bucket.sort_keys(), self.bucket.entries):
            yield kb, _is_dead(be)

    def iter_entries(self) -> Iterator[Tuple[bytes, bool, Optional[LedgerEntry]]]:
        for kb, be in zip(self.bucket.sort_keys(), self.bucket.entries):
            dead = _is_dead(be)
            yield kb, dead, (None if dead else be.value)

    def iter_live_raw(self) -> Iterator[Tuple[bytes, bytes]]:
        packed = self.bucket.packed_entries()
        for kb, be, rec in zip(self.bucket.sort_keys(), self.bucket.entries,
                               packed):
            if not _is_dead(be):
                yield kb, rec[4:]   # strip the BucketEntry type tag


@race_checked
class _DiskView:
    """Read view over an on-disk bucket file via its DiskBucketIndex.
    One persistent file handle per view; reads are lock-serialized (the
    admin HTTP thread may share a snapshot with the main thread)."""

    __slots__ = ("index", "_f", "_lock", "_race_fields_")

    def __init__(self, index):
        self.index = index
        self._f = None
        self._lock = make_lock("bucket.snapshot-file")

    def _read(self, off: int, end: int) -> bytes:
        with self._lock:
            if self._f is None:
                self._f = open(self.index.path, "rb")
            self._f.seek(off)
            return self._f.read(end - off)

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None

    def maybe_contains(self, key_bytes: bytes) -> bool:
        return self.index.maybe_contains(key_bytes)

    def _decode(self, rec: bytes, off: int) -> LedgerEntry:
        try:
            be, _ = _BE.unpack_from_fast(rec, 0)
        except Exception as exc:
            raise RuntimeError(
                f"bucket file {self.index.path} has a corrupt record at "
                f"byte {off}: {exc}") from exc
        return be.value

    def probe(self, key_bytes: bytes) -> _Probe:
        hit = self.index.find(key_bytes)
        if hit is None:
            return None
        off, end, dead = hit
        if dead:
            return True, None          # tombstone: no file read needed
        return False, self._decode(self._read(off, end), off)

    def probe_many(self, sorted_keys: List[bytes]) -> Dict[bytes, _Probe]:
        """Batched point loads: resolve offsets first, then read in file
        order (one seek chain instead of key-order scatter)."""
        out: Dict[bytes, _Probe] = {}
        reads: List[Tuple[int, int, bytes]] = []
        for kb in sorted_keys:
            hit = self.index.find(kb)
            if hit is None:
                continue
            off, end, dead = hit
            if dead:
                out[kb] = (True, None)
            else:
                reads.append((off, end, kb))
        reads.sort()
        for off, end, kb in reads:
            out[kb] = (False, self._decode(self._read(off, end), off))
        return out

    def iter_keys(self) -> Iterator[Tuple[bytes, bool]]:
        idx = self.index
        for i, kb in enumerate(idx.keys()):
            yield kb, idx.is_dead(i)

    def iter_entries(self) -> Iterator[Tuple[bytes, bool, Optional[LedgerEntry]]]:
        for kb, dead, rec in self._iter_records():
            yield kb, dead, (None if dead else self._decode(rec, -1))

    def iter_live_raw(self) -> Iterator[Tuple[bytes, bytes]]:
        for kb, dead, rec in self._iter_records():
            if not dead:
                yield kb, rec[4:]

    def _iter_records(self) -> Iterator[Tuple[bytes, bool, bytes]]:
        idx = self.index
        keys = idx.keys()
        with open(idx.path, "rb") as f:
            for i, kb in enumerate(keys):
                off, end, dead = idx._record_bounds(i)
                f.seek(off)
                yield kb, dead, f.read(end - off)


@race_checked
class SearchableBucketListSnapshot:
    __slots__ = ("ledger_seq", "_views", "_store", "_pinned", "_load_timer",
                 "_probe_counters", "_live_count", "_pin_t0", "_pin_timer",
                 "_read_meter", "_race_fields_")

    def __init__(self, bucket_list, ledger_seq: int = 0, store=None):
        self.ledger_seq = ledger_seq
        self._store = store
        self._pinned: List[str] = []
        self._live_count: Optional[int] = None
        # newest-first: level 0 curr, level 0 snap, level 1 curr, ...
        self._views: List[Tuple[int, object]] = []
        for pos, bucket in enumerate(bucket_list.buckets()):
            if bucket.is_empty():
                continue
            level = pos // 2
            if store is not None:
                # a disk-resident bucket already carries its index
                # (streaming-merge output / residency pass) — unify the
                # seams: one _DiskView either way, no re-ensure scan
                idx = (bucket.disk_index() if bucket.is_disk_resident()
                       else store.ensure(bucket))
                self._views.append((level, _DiskView(idx)))
                self._pinned.append(bucket.hash().hex())
            elif bucket.is_disk_resident():
                # storeless view over a disk-resident bucket (tests,
                # tools): serve from the file rather than rehydrating
                self._views.append((level, _DiskView(bucket.disk_index())))
            else:
                self._views.append((level, _ResidentView(bucket)))
        if store is not None:
            store.pin(self._pinned)
        # metric handles resolved once per snapshot (a snapshot lives one
        # close; per-call registry lookups were measurable on the load path)
        reg = _registry()
        self._load_timer = reg.timer("bucketlistdb.load")
        self._probe_counters = {
            level: reg.counter(f"bucketlistdb.probe.level-{level}")
            for level in {lv for lv, _ in self._views}}
        # contention observability (ISSUE 20): how long readers hold GC
        # pins (recorded at release) and bulk-read key volume — the two
        # series the close-p99-vs-read-QPS curve correlates against
        self._pin_t0 = time.perf_counter()
        self._pin_timer = reg.timer("bucketlistdb.pin.held")
        self._read_meter = reg.meter("bucketlistdb.read.keys")

    # -- lifecycle -----------------------------------------------------------
    def release(self) -> None:
        """Drop file pins + handles; idempotent.  The LedgerManager calls
        this when a newer snapshot replaces this one — files this view
        referenced become GC-eligible again."""
        if self._store is not None and self._pinned:
            self._store.unpin(self._pinned)
            self._pinned = []
            # reader-held pin time: init (pin) to release (unpin); only
            # recorded for snapshots that actually held store pins
            self._pin_timer.update(time.perf_counter() - self._pin_t0)
        for _, view in self._views:
            if isinstance(view, _DiskView):
                view.close()

    def __del__(self):  # best-effort: a leaked snapshot must not leak pins
        try:
            self.release()
        except Exception:  # corelint: disable=exception-hygiene -- destructor cleanup must never raise
            pass

    # -- point reads ---------------------------------------------------------
    def load(self, key) -> Optional[LedgerEntry]:
        """Newest live version of a LedgerKey (or raw key bytes); None if
        absent or dead."""
        key_bytes = key if isinstance(key, bytes) else key.to_xdr()
        t0 = time.perf_counter()
        try:
            for level, view in self._views:
                if not view.maybe_contains(key_bytes):
                    continue
                self._probe_counters[level].inc()
                hit = view.probe(key_bytes)
                if hit is not None:
                    dead, entry = hit
                    return None if dead else entry
            return None
        finally:
            self._load_timer.update(time.perf_counter() - t0)

    def load_keys(self, keys: Iterable) -> Dict[bytes, LedgerEntry]:
        """Batched point loads (reference: loadKeysWithLimits); returns only
        the keys that exist, keyed by their XDR bytes.  Probes run
        level-major so disk views read each file in offset order — the bulk
        prefetch path for whole tx sets."""
        remaining = {key if isinstance(key, bytes) else key.to_xdr()
                     for key in keys}
        if remaining:
            self._read_meter.mark(len(remaining))
        out: Dict[bytes, LedgerEntry] = {}
        for level, view in self._views:
            if not remaining:
                break
            cand = sorted(kb for kb in remaining
                          if view.maybe_contains(kb))
            if not cand:
                continue
            self._probe_counters[level].inc(len(cand))
            hits = view.probe_many(cand)
            for kb, (dead, entry) in hits.items():
                remaining.discard(kb)
                if not dead:
                    out[kb] = entry
        return out

    # -- iteration -----------------------------------------------------------
    def iter_live_keys(self) -> Iterator[bytes]:
        """Every live LedgerKey (XDR bytes), newest record per key winning
        — no entry decode for disk views (index keys only)."""
        seen: set = set()
        for _, view in self._views:
            for kb, dead in view.iter_keys():
                if kb in seen:
                    continue
                seen.add(kb)
                if not dead:
                    yield kb

    def live_entry_count(self) -> int:
        """Number of live entries in this view (computed once per
        snapshot; key-only scan)."""
        if self._live_count is None:
            self._live_count = sum(1 for _ in self.iter_live_keys())
        return self._live_count

    def scan(self) -> Iterable[LedgerEntry]:
        """All live entries, newest version per key (reference: the
        in-order full-list scans used by dump-ledger / invariants)."""
        seen: set = set()
        for _, view in self._views:
            for kb, dead, entry in view.iter_entries():
                if kb in seen:
                    continue
                seen.add(kb)
                if not dead:
                    yield entry

    def iter_live_raw(self) -> Iterator[Tuple[bytes, bytes]]:
        """(key XDR, LedgerEntry XDR) for every live entry, newest record
        per key winning (tombstones shadow like everywhere else) — the
        native-engine import path, no Python entry decode."""
        seen: set = set()
        for _, view in self._views:
            for kb, rec in view.iter_live_raw():
                if kb not in seen:
                    yield kb, rec
            # ALL of this view's keys (incl. tombstones) shadow older views
            seen.update(kb for kb, _ in view.iter_keys())
