"""SearchableBucketListSnapshot: immutable point-in-time ledger-state reads.

Reference: src/bucket/SearchableBucketListSnapshot* + BucketSnapshotManager —
the reference hands read-only bucket-list snapshots to threads that must not
see (or block) the main thread's mutations: the HTTP query server
(`getledgerentry`), background tx-validation pre-flight, and parallel apply.

Buckets are immutable here, so a snapshot is just the ordered (newest-first)
bucket references captured at construction; later ``add_batch`` calls on the
live list never mutate what this object sees.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

from ..xdr import LedgerEntry, LedgerKey
from .bucket import Bucket, _is_dead, entry_sort_key


class SearchableBucketListSnapshot:
    __slots__ = ("ledger_seq", "_buckets")

    def __init__(self, bucket_list, ledger_seq: int = 0):
        self.ledger_seq = ledger_seq
        # newest-first: level 0 curr, level 0 snap, level 1 curr, ...
        self._buckets: List[Bucket] = [b for b in bucket_list.buckets()
                                       if not b.is_empty()]

    def load(self, key) -> Optional[LedgerEntry]:
        """Newest live version of a LedgerKey (or raw key bytes); None if
        absent or dead."""
        key_bytes = key if isinstance(key, bytes) else key.to_xdr()
        for bucket in self._buckets:
            be = bucket.find(key_bytes)
            if be is not None:
                return None if _is_dead(be) else be.value
        return None

    def load_keys(self, keys: Iterable) -> Dict[bytes, LedgerEntry]:
        """Batched point loads (reference: loadKeysWithLimits); returns only
        the keys that exist, keyed by their XDR bytes."""
        out: Dict[bytes, LedgerEntry] = {}
        for key in keys:
            key_bytes = key if isinstance(key, bytes) else key.to_xdr()
            entry = self.load(key_bytes)
            if entry is not None:
                out[key_bytes] = entry
        return out

    def scan(self) -> Iterable[LedgerEntry]:
        """All live entries, newest version per key (reference: the
        in-order full-list scans used by dump-ledger / invariants)."""
        seen: set = set()
        for bucket in self._buckets:
            for be in bucket.entries:
                kb = entry_sort_key(be)
                if kb in seen:
                    continue
                seen.add(kb)
                if not _is_dead(be):
                    yield be.value
