from .bucket import (Bucket, entry_sort_key, merge_buckets,
                     merge_buckets_raw)
from .bucket_list import (DEFAULT_RESIDENT_LEVELS, NUM_LEVELS, BucketLevel,
                          BucketList, keep_tombstone_entries, level_half,
                          level_should_spill, level_size)
from .future import FutureBucket
from .index import BucketIndex, DiskBucketIndex
from .manager import BucketDir, BucketListStore, BucketStreamWriter
from .snapshot import SearchableBucketListSnapshot

__all__ = [
    "Bucket", "BucketDir", "BucketIndex", "BucketLevel", "BucketList",
    "BucketListStore", "BucketStreamWriter", "DEFAULT_RESIDENT_LEVELS",
    "DiskBucketIndex", "FutureBucket", "NUM_LEVELS",
    "SearchableBucketListSnapshot",
    "entry_sort_key", "keep_tombstone_entries", "level_half",
    "level_should_spill", "level_size", "merge_buckets",
    "merge_buckets_raw",
]
