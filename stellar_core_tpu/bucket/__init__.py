from .bucket import Bucket, entry_sort_key, merge_buckets
from .bucket_list import (NUM_LEVELS, BucketLevel, BucketList,
                          keep_tombstone_entries, level_half,
                          level_should_spill, level_size)
from .future import FutureBucket
from .index import BucketIndex
from .manager import BucketDir
from .snapshot import SearchableBucketListSnapshot

__all__ = [
    "Bucket", "BucketDir", "BucketIndex", "BucketLevel", "BucketList",
    "FutureBucket", "NUM_LEVELS", "SearchableBucketListSnapshot",
    "entry_sort_key", "keep_tombstone_entries", "level_half",
    "level_should_spill", "level_size", "merge_buckets",
]
