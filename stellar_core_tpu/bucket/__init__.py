from .bucket import Bucket, entry_sort_key, merge_buckets
from .bucket_list import (NUM_LEVELS, BucketLevel, BucketList,
                          keep_tombstone_entries, level_half,
                          level_should_spill, level_size)
from .future import FutureBucket
from .index import BucketIndex, DiskBucketIndex
from .manager import BucketDir, BucketListStore
from .snapshot import SearchableBucketListSnapshot

__all__ = [
    "Bucket", "BucketDir", "BucketIndex", "BucketLevel", "BucketList",
    "BucketListStore", "DiskBucketIndex", "FutureBucket", "NUM_LEVELS",
    "SearchableBucketListSnapshot",
    "entry_sort_key", "keep_tombstone_entries", "level_half",
    "level_should_spill", "level_size", "merge_buckets",
]
