"""BucketIndex: per-bucket point-lookup acceleration.

Reference: src/bucket/BucketIndexImpl.{h,cpp} — the reference keeps, per
bucket file, (a) a sorted key→offset index (individual or page-ranged) and
(b) a binary-fuse membership filter so that the common case — "this bucket
does not contain the key" — is answered without touching the file at all.

Two index flavors live here:

* ``BucketIndex`` — over an in-memory bucket's sorted entry list: the
  sorted LedgerKey-bytes array for bisection plus a set of 64-bit key
  fingerprints (CPython's SipHash via ``hash()``) as the membership filter.
* ``DiskBucketIndex`` — over an on-disk bucket FILE (the BucketListDB
  authority, reference: BucketIndexImpl over bucket-<hash>.xdr): the same
  sorted keys + filter, but each key maps to the byte range of its
  serialized BucketEntry record so a lookup SEEKS into the file instead of
  requiring the decoded entries resident in memory.

A ``lookup_latest`` over the 11-level list probes up to 22 buckets, of
which at most a handful contain the key — the filter turns the other ~20
probes into one set lookup each instead of an O(log n) bisection.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import List, Optional, Tuple

from ..crypto.sha import SHA256


class BucketIndex:
    """Immutable index over one bucket's (sorted) entries."""

    __slots__ = ("_keys", "_filter")

    def __init__(self, sort_keys: List[bytes]):
        self._keys = sort_keys
        self._filter = frozenset(map(hash, sort_keys))

    def __len__(self) -> int:
        return len(self._keys)

    def maybe_contains(self, key_bytes: bytes) -> bool:
        """False ⇒ definitely absent (the fast negative path); True ⇒ must
        bisect (no false negatives, same contract as the fuse filter)."""
        return hash(key_bytes) in self._filter  # corelint: disable=hash-order -- process-local membership filter; fingerprints never serialized

    def find(self, key_bytes: bytes) -> Optional[int]:
        """Position of the entry with this exact LedgerKey, or None."""
        if hash(key_bytes) not in self._filter:  # corelint: disable=hash-order -- process-local membership filter; fingerprints never serialized
            return None
        i = bisect_left(self._keys, key_bytes)
        if i < len(self._keys) and self._keys[i] == key_bytes:
            return i
        return None

    def lower_bound(self, key_bytes: bytes) -> int:
        """First position with sort key >= key_bytes (range scans: the
        reference's page-index getOffsetBounds analog)."""
        return bisect_left(self._keys, key_bytes)


class DiskBucketIndex:
    """Sorted key→(offset, end) table over one on-disk bucket file, plus
    the fingerprint membership filter and a per-entry tombstone flag.

    Built either from the in-memory bucket at save time (``from_bucket`` —
    no file re-read, offsets derived from the packed records the save just
    wrote) or by a hash-verified scan of an existing file (``build`` — the
    restart/catchup path; a corrupt file FAIL-STOPS here, it never serves
    lookups).  Record i spans [offsets[i], offsets[i+1]) with the final
    bound at end-of-file; record bytes start with the 4-byte BucketEntry
    type tag, so deadness is known without decoding.
    """

    __slots__ = ("path", "protocol_version", "_keys", "_offsets",
                 "_file_size", "_dead", "_filter")

    def __init__(self, path: str, protocol_version: int, keys: List[bytes],
                 offsets: List[int], file_size: int, dead: bytes):
        self.path = path
        self.protocol_version = protocol_version
        self._keys = keys
        self._offsets = offsets
        self._file_size = file_size
        self._dead = dead                      # aligned 0/1 per entry
        self._filter = frozenset(map(hash, keys))

    # -- construction --------------------------------------------------------
    @classmethod
    def from_bucket(cls, bucket, path: str) -> "DiskBucketIndex":
        """Index the file just written for `bucket` using its cached sort
        keys and packed records (the hot path: one ledger close saves level
        0 curr every ledger — no re-read, no re-decode)."""
        from .bucket import _BE, BucketEntry, BucketEntryType, BucketMetadata
        meta_len = len(_BE.pack(BucketEntry.metaEntry(
            BucketMetadata(ledgerVersion=bucket.protocol_version))))
        keys = bucket.sort_keys()
        offsets: List[int] = []
        off = meta_len
        for rec in bucket.packed_entries():
            offsets.append(off)
            off += len(rec)
        dead = bytes(1 if e.switch == BucketEntryType.DEADENTRY else 0
                     for e in bucket.entries)
        return cls(path, bucket.protocol_version, keys, offsets, off, dead)

    @classmethod
    def build(cls, path: str, expected_hex_hash: Optional[str] = None
              ) -> "DiskBucketIndex":
        """Scan + index an existing bucket file, verifying its content hash
        (when given) and key ordering.  Corruption raises RuntimeError —
        silently indexing a damaged file would serve wrong ledger state."""
        from .bucket import _BE, BucketEntryType, entry_sort_key
        with open(path, "rb") as f:
            data = f.read()
        if expected_hex_hash is not None:
            got = SHA256().add(data).finish().hex() if data else "0" * 64
            if got != expected_hex_hash:
                raise RuntimeError(
                    f"bucket file {path} fails hash check while indexing "
                    f"(got {got[:16]}..., want {expected_hex_hash[:16]}...)")
        keys: List[bytes] = []
        offsets: List[int] = []
        dead = bytearray()
        protocol = 0
        off = 0
        prev_key: Optional[bytes] = None
        while off < len(data):
            start = off
            try:
                e, off = _BE.unpack_from_fast(data, off)
            except Exception as exc:
                raise RuntimeError(
                    f"bucket file {path} has a corrupt record at byte "
                    f"{start}: {exc}") from exc
            if e.switch == BucketEntryType.METAENTRY:
                protocol = e.value.ledgerVersion
                continue
            kb = entry_sort_key(e)
            if prev_key is not None and kb <= prev_key:
                raise RuntimeError(
                    f"bucket file {path} keys out of order at byte {start}")
            prev_key = kb
            keys.append(kb)
            offsets.append(start)
            dead.append(1 if e.switch == BucketEntryType.DEADENTRY else 0)
        return cls(path, protocol, keys, offsets, len(data), bytes(dead))

    # -- lookups -------------------------------------------------------------
    def __len__(self) -> int:
        return len(self._keys)

    def maybe_contains(self, key_bytes: bytes) -> bool:
        return hash(key_bytes) in self._filter  # corelint: disable=hash-order -- process-local membership filter; fingerprints never serialized

    def find(self, key_bytes: bytes) -> Optional[Tuple[int, int, bool]]:
        """(offset, end, is_dead) of the record with this exact LedgerKey,
        or None — the reference's getOffsetBounds point-lookup."""
        if hash(key_bytes) not in self._filter:  # corelint: disable=hash-order -- process-local membership filter; fingerprints never serialized
            return None
        i = bisect_left(self._keys, key_bytes)
        if i < len(self._keys) and self._keys[i] == key_bytes:
            return self._record_bounds(i)
        return None

    def _record_bounds(self, i: int) -> Tuple[int, int, bool]:
        end = self._offsets[i + 1] if i + 1 < len(self._offsets) \
            else self._file_size
        return self._offsets[i], end, bool(self._dead[i])

    def keys(self) -> List[bytes]:
        """The sorted key array (aligned with is_dead) — snapshot key
        iteration reads this without touching the file."""
        return self._keys

    def is_dead(self, i: int) -> bool:
        return bool(self._dead[i])
