"""BucketIndex: per-bucket point-lookup acceleration.

Reference: src/bucket/BucketIndexImpl.{h,cpp} — the reference keeps, per
bucket file, (a) a sorted key→offset index (individual or page-ranged) and
(b) a binary-fuse membership filter so that the common case — "this bucket
does not contain the key" — is answered without touching the file at all.

Here buckets are in-memory sequences, so the analog is (a) the sorted
LedgerKey-bytes array for bisection and (b) a set of 64-bit key fingerprints
(CPython's SipHash via ``hash()``) as the membership filter.  A
``lookup_latest`` over the 11-level list probes up to 22 buckets, of which
at most a handful contain the key — the filter turns the other ~20 probes
into one set lookup each instead of an O(log n) bisection over bytes keys.
"""

from __future__ import annotations

from bisect import bisect_left
from typing import List, Optional


class BucketIndex:
    """Immutable index over one bucket's (sorted) entries."""

    __slots__ = ("_keys", "_filter")

    def __init__(self, sort_keys: List[bytes]):
        self._keys = sort_keys
        self._filter = frozenset(map(hash, sort_keys))

    def __len__(self) -> int:
        return len(self._keys)

    def maybe_contains(self, key_bytes: bytes) -> bool:
        """False ⇒ definitely absent (the fast negative path); True ⇒ must
        bisect (no false negatives, same contract as the fuse filter)."""
        return hash(key_bytes) in self._filter

    def find(self, key_bytes: bytes) -> Optional[int]:
        """Position of the entry with this exact LedgerKey, or None."""
        if hash(key_bytes) not in self._filter:
            return None
        i = bisect_left(self._keys, key_bytes)
        if i < len(self._keys) and self._keys[i] == key_bytes:
            return i
        return None

    def lower_bound(self, key_bytes: bytes) -> int:
        """First position with sort key >= key_bytes (range scans: the
        reference's page-index getOffsetBounds analog)."""
        return bisect_left(self._keys, key_bytes)
