"""The 11-level log-structured BucketList (the ledger state's hash structure).

Reference: src/bucket/BucketListBase.{h,cpp} / LiveBucketList — levels of
(curr, snap) buckets, spill cadence in powers of 4, levelShouldSpill /
levelHalf / levelSize, getHash = tree of SHA-256s.

Merge scheduling follows the reference's commit/prepare pipeline exactly
(BucketLevel::commit / prepare / snap + FutureBucket): when level i−1 spills,
level i first *commits* the merge prepared at the previous spill (which had a
whole spill interval to run in the background) and then *prepares* a new
future merge of its curr with the incoming snap.  Between spills the pending
merge is invisible to the level hash — the spilled data remains visible as
level i−1's snap — so the hash evolution is deterministic regardless of
whether merges run synchronously (executor=None) or on a thread pool.
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..crypto.sha import SHA256
from ..util.assertions import release_assert
from ..util.metrics import registry as _registry
from ..xdr import LedgerEntry, LedgerKey
from .bucket import Bucket, merge_buckets
from .future import FutureBucket

NUM_LEVELS = 11


def level_size(level: int) -> int:
    return 4 ** (level + 1)


def level_half(level: int) -> int:
    return level_size(level) // 2


def level_should_spill(ledger: int, level: int) -> bool:
    """Does `level` spill its curr on this ledger? (reference:
    BucketListBase::levelShouldSpill — at multiples of levelHalf)."""
    if level == NUM_LEVELS - 1:
        return False
    return ledger == (ledger // level_half(level)) * level_half(level)


def keep_tombstone_entries(level: int) -> bool:
    return level < NUM_LEVELS - 1


class BucketLevel:
    __slots__ = ("curr", "snap", "next")

    def __init__(self) -> None:
        self.curr = Bucket.empty()
        self.snap = Bucket.empty()
        self.next: Optional[FutureBucket] = None

    def snap_curr(self) -> Bucket:
        """curr → snap (reference: BucketLevel::snap; the pending future is
        untouched — it is committed by the level below's spill handling)."""
        self.snap = self.curr
        self.curr = Bucket.empty()
        return self.snap

    def commit(self) -> None:
        """Resolve the pending merge into curr (reference:
        BucketLevel::commit)."""
        if self.next is not None:
            self.curr = self.next.resolve()
            self.next = None

    def prepare(self, spill: Bucket, keep_tombstones: bool,
                protocol_version: int, executor=None) -> None:
        """Start merging curr with the incoming spill (reference:
        BucketLevel::prepare → FutureBucket ctor on a worker thread)."""
        release_assert(self.next is None,
                       "prepare() without a prior commit()")
        self.next = FutureBucket(self.curr, spill, keep_tombstones,
                                 protocol_version, executor)

    def hash(self) -> bytes:
        return SHA256().add(self.curr.hash()).add(self.snap.hash()).finish()


class BucketList:
    def __init__(self, executor=None) -> None:
        """executor: a concurrent.futures.Executor to run level merges in
        the background (reference: worker-thread FutureBucket merges), or
        None for synchronous merges — the outputs are identical either way."""
        self.levels: List[BucketLevel] = [BucketLevel() for _ in range(NUM_LEVELS)]
        self.executor = executor

    def add_batch(self, ledger_seq: int, protocol_version: int,
                  init_entries: Iterable[LedgerEntry],
                  live_entries: Iterable[LedgerEntry],
                  dead_keys: Iterable[LedgerKey]) -> None:
        """One ledger's changes enter level 0; spill boundaries snap the
        level above, commit the previously prepared merge and prepare the
        next one (reference: BucketListBase::addBatch)."""
        release_assert(ledger_seq > 0, "ledger_seq must be positive")
        with _registry().timer("bucket.batch.addtime").time():
            for i in range(NUM_LEVELS - 1, 0, -1):
                if level_should_spill(ledger_seq, i - 1):
                    spill = self.levels[i - 1].snap_curr()
                    self.levels[i].commit()
                    self.levels[i].prepare(spill, keep_tombstone_entries(i),
                                           protocol_version, self.executor)
            fresh = Bucket.fresh(protocol_version, init_entries,
                                 live_entries, dead_keys)
            # level 0 merges synchronously every ledger (reference:
            # prepare + immediate commit — the batch is small and needed
            # for this ledger's hash)
            self.levels[0].prepare(fresh, True, protocol_version, None)
            self.levels[0].commit()

    def hash(self) -> bytes:
        """bucketListHash in the ledger header: SHA-256 over level hashes
        (each SHA-256(curr.hash || snap.hash)); pending merges excluded."""
        h = SHA256()
        for lvl in self.levels:
            h.add(lvl.hash())
        return h.finish()

    def resolve_all_merges(self) -> None:
        """Block until every pending merge has an output (publish/persist
        barrier — the HAS serializes next as a resolved output hash)."""
        for lvl in self.levels:
            if lvl.next is not None:
                lvl.next.resolve()

    def referenced_hashes(self) -> List[str]:
        """Hex hashes of every bucket restart depends on — curr, snap and
        pending-merge outputs or inputs (reference:
        BucketManager::getAllReferencedBuckets feeding
        forgetUnreferencedBuckets).  Never blocks on a running merge."""
        out = []
        for lvl in self.levels:
            out.append(lvl.curr.hash().hex())
            out.append(lvl.snap.hash().hex())
            if lvl.next is not None:
                if lvl.next.done:
                    out.append(lvl.next.resolve().hash().hex())
                else:
                    curr_in, snap_in, _, _ = lvl.next.inputs
                    out.append(curr_in.hash().hex())
                    out.append(snap_in.hash().hex())
        return out

    def buckets(self) -> List[Bucket]:
        out = []
        for lvl in self.levels:
            out.append(lvl.curr)
            out.append(lvl.snap)
        return out

    def snapshot(self, ledger_seq: int = 0, store=None):
        """Immutable point-in-time view (reference:
        SearchableBucketListSnapshot via BucketSnapshotManager).  With a
        ``BucketListStore``, the view reads through on-disk bucket indexes
        and pins its files against GC (BucketListDB mode)."""
        from .snapshot import SearchableBucketListSnapshot
        return SearchableBucketListSnapshot(self, ledger_seq, store=store)

    def lookup_latest(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        """Newest version of a key across the list, or None if the newest
        record is a tombstone / the key is absent (reference:
        SearchableBucketListSnapshot::load — level 0 curr is newest)."""
        from .bucket import _is_dead
        for bucket in self.buckets():
            be = bucket.find(key_bytes)
            if be is not None:
                return None if _is_dead(be) else be.value
        return None
