"""The 11-level log-structured BucketList (the ledger state's hash structure).

Reference: src/bucket/BucketListBase.{h,cpp} / LiveBucketList — levels of
(curr, snap) buckets, spill cadence in powers of 4, levelShouldSpill /
levelHalf / levelSize, getHash = tree of SHA-256s.

Merge scheduling follows the reference's commit/prepare pipeline exactly
(BucketLevel::commit / prepare / snap + FutureBucket): when level i−1 spills,
level i first *commits* the merge prepared at the previous spill (which had a
whole spill interval to run in the background) and then *prepares* a new
future merge of its curr with the incoming snap.  Between spills the pending
merge is invisible to the level hash — the spilled data remains visible as
level i−1's snap — so the hash evolution is deterministic regardless of
whether merges run synchronously (executor=None) or on a thread pool.
"""

from __future__ import annotations

import time
from typing import Iterable, List, Optional

from ..crypto.sha import SHA256
from ..util.assertions import release_assert
from ..util.metrics import registry as _registry
from ..xdr import LedgerEntry, LedgerKey
from .bucket import Bucket, merge_buckets
from .future import FutureBucket

NUM_LEVELS = 11

# Residency depth default: levels < DEFAULT_RESIDENT_LEVELS keep decoded
# entries (they churn every few ledgers and feed the synchronous level-0
# merge); levels >= it are disk-resident once persisted+indexed, and their
# merges stream file-to-file (BUCKET_RESIDENT_LEVELS in config).
DEFAULT_RESIDENT_LEVELS = 2


def level_size(level: int) -> int:
    return 4 ** (level + 1)


def level_half(level: int) -> int:
    return level_size(level) // 2


def level_should_spill(ledger: int, level: int) -> bool:
    """Does `level` spill its curr on this ledger? (reference:
    BucketListBase::levelShouldSpill — at multiples of levelHalf)."""
    if level == NUM_LEVELS - 1:
        return False
    return ledger == (ledger // level_half(level)) * level_half(level)


def keep_tombstone_entries(level: int) -> bool:
    return level < NUM_LEVELS - 1


class BucketLevel:
    __slots__ = ("curr", "snap", "next")

    def __init__(self) -> None:
        self.curr = Bucket.empty()
        self.snap = Bucket.empty()
        self.next: Optional[FutureBucket] = None

    def snap_curr(self) -> Bucket:
        """curr → snap (reference: BucketLevel::snap; the pending future is
        untouched — it is committed by the level below's spill handling)."""
        self.snap = self.curr
        self.curr = Bucket.empty()
        return self.snap

    def commit(self) -> None:
        """Resolve the pending merge into curr (reference:
        BucketLevel::commit).  A streaming merge's output is now
        referenced by the level, so its adoption-time GC pin drops."""
        if self.next is not None:
            self.curr = self.next.resolve()
            self.next.release_output_pin()
            self.next = None

    def prepare(self, spill: Bucket, keep_tombstones: bool,
                protocol_version: int, executor=None,
                raw_store=None) -> None:
        """Start merging curr with the incoming spill (reference:
        BucketLevel::prepare → FutureBucket ctor on a worker thread).
        With `raw_store` the merge streams records file-to-file
        (merge_buckets_raw) and resolves to a disk-resident bucket."""
        release_assert(self.next is None,
                       "prepare() without a prior commit()")
        self.next = FutureBucket(self.curr, spill, keep_tombstones,
                                 protocol_version, executor,
                                 raw_store=raw_store)

    def hash(self) -> bytes:
        return SHA256().add(self.curr.hash()).add(self.snap.hash()).finish()


class BucketList:
    def __init__(self, executor=None) -> None:
        """executor: a concurrent.futures.Executor to run level merges in
        the background (reference: worker-thread FutureBucket merges), or
        None for synchronous merges — the outputs are identical either way."""
        self.levels: List[BucketLevel] = [BucketLevel() for _ in range(NUM_LEVELS)]
        self.executor = executor
        # residency (BucketListDB phase 2): unset = every bucket decoded
        self.store = None
        self.resident_levels = NUM_LEVELS
        self.peak_decoded_entries = 0
        # close-blocked-on-merge seconds for the most recent add_batch
        # (ISSUE 20 contention observability: read by the close path's
        # CloseCostRecord; 0.0 when every spill commit found its merge
        # already resolved)
        self.last_add_stall_s = 0.0  # corelint: disable=float-discipline -- contention telemetry, never ledger state

    # -- residency (BucketListDB phase 2) ------------------------------------
    def configure_residency(self, store, resident_levels: int) -> None:
        """Run levels >= `resident_levels` disk-resident against `store`
        (a BucketListStore): their merges stream file-to-file and their
        decoded entry lists drop after each close's enforce_residency().
        Level 0 must stay resident (its merge runs synchronously inside
        every close), so the floor is 1."""
        release_assert(store is not None, "residency needs a store")
        self.store = store
        self.resident_levels = max(1, min(int(resident_levels), NUM_LEVELS))
        _registry().gauge("bucket.resident.entries").set_source(
            self.decoded_entry_count)

    def decoded_entry_count(self) -> int:
        """Decoded BucketEntry objects currently held across the list
        (curr/snap plus already-materialized merge outputs; merge inputs
        alias curr/snap so they are not double-counted).  This is the
        memory story phase 2 bounds: O(working set + top levels) instead
        of O(ledger)."""
        total = 0
        for lvl in self.levels:
            total += lvl.curr.resident_entry_count()
            total += lvl.snap.resident_entry_count()
            if lvl.next is not None:
                out = lvl.next.peek()
                if out is not None:
                    total += out.resident_entry_count()
        return total

    def _note_decoded_peak(self) -> None:
        n = self.decoded_entry_count()
        if n > self.peak_decoded_entries:
            self.peak_decoded_entries = n

    def enforce_residency(self) -> None:
        """Drop decoded entries from levels >= resident_levels: persist +
        index each such bucket in the store (content addressing makes the
        repeat calls free) and flip it disk-resident.  Resolved pending
        merges convert too — a streaming merge's output already is, so in
        steady state this only catches buckets that entered decoded
        (restart, catchup assume, native export)."""
        if self.store is None:
            return
        self._note_decoded_peak()
        for i in range(self.resident_levels, NUM_LEVELS):
            lvl = self.levels[i]
            for b in (lvl.curr, lvl.snap):
                if not b.is_empty() and not b.is_disk_resident():
                    b.make_disk_resident(self.store.ensure(b))
            if lvl.next is not None:
                out = lvl.next.peek()
                if out is not None and not out.is_empty() \
                        and not out.is_disk_resident():
                    out.make_disk_resident(self.store.ensure(out))

    def add_batch(self, ledger_seq: int, protocol_version: int,
                  init_entries: Iterable[LedgerEntry],
                  live_entries: Iterable[LedgerEntry],
                  dead_keys: Iterable[LedgerKey]) -> None:
        """One ledger's changes enter level 0; spill boundaries snap the
        level above, commit the previously prepared merge and prepare the
        next one (reference: BucketListBase::addBatch)."""
        release_assert(ledger_seq > 0, "ledger_seq must be positive")
        stall_s = 0.0  # corelint: disable=float-discipline -- contention telemetry, never ledger state
        with _registry().timer("bucket.batch.addtime").time():
            for i in range(NUM_LEVELS - 1, 0, -1):
                if level_should_spill(ledger_seq, i - 1):
                    spill = self.levels[i - 1].snap_curr()
                    # contention seam (ISSUE 20): a spill commit whose
                    # background merge is still running blocks the close
                    # right here — time exactly that wait
                    nxt = self.levels[i].next
                    blocked = nxt is not None and not nxt.done
                    t0 = time.perf_counter() if blocked else 0.0  # corelint: disable=float-discipline -- contention telemetry, never ledger state
                    self.levels[i].commit()
                    if blocked:
                        stall_s += time.perf_counter() - t0
                    # deep levels merge decode-free, file-to-file
                    raw = self.store if (self.store is not None
                                         and i >= self.resident_levels) \
                        else None
                    self.levels[i].prepare(spill, keep_tombstone_entries(i),
                                           protocol_version, self.executor,
                                           raw_store=raw)
            fresh = Bucket.fresh(protocol_version, init_entries,
                                 live_entries, dead_keys)
            # level 0 merges synchronously every ledger (reference:
            # prepare + immediate commit — the batch is small and needed
            # for this ledger's hash)
            self.levels[0].prepare(fresh, True, protocol_version, None)
            self.levels[0].commit()
            if self.store is not None:
                self._note_decoded_peak()
        # recorded every batch (0.0 included): the merge-stall series
        # must baseline at "no stall" so the anomaly detector sees a
        # stall APPEARING, not only stalls getting worse
        self.last_add_stall_s = stall_s
        _registry().timer("bucket.merge.stall").update(stall_s)

    def hash(self) -> bytes:
        """bucketListHash in the ledger header: SHA-256 over level hashes
        (each SHA-256(curr.hash || snap.hash)); pending merges excluded."""
        h = SHA256()
        for lvl in self.levels:
            h.add(lvl.hash())
        return h.finish()

    def resolve_all_merges(self) -> None:
        """Block until every pending merge has an output (publish/persist
        barrier — the HAS serializes next as a resolved output hash)."""
        for lvl in self.levels:
            if lvl.next is not None:
                lvl.next.resolve()

    def referenced_hashes(self) -> List[str]:
        """Hex hashes of every bucket restart depends on — curr, snap and
        pending-merge outputs or inputs (reference:
        BucketManager::getAllReferencedBuckets feeding
        forgetUnreferencedBuckets).  Never blocks on a running merge."""
        out = []
        for lvl in self.levels:
            out.append(lvl.curr.hash().hex())
            out.append(lvl.snap.hash().hex())
            if lvl.next is not None:
                if lvl.next.done:
                    out.append(lvl.next.resolve().hash().hex())
                else:
                    curr_in, snap_in, _, _ = lvl.next.inputs
                    out.append(curr_in.hash().hex())
                    out.append(snap_in.hash().hex())
        return out

    def buckets(self) -> List[Bucket]:
        out = []
        for lvl in self.levels:
            out.append(lvl.curr)
            out.append(lvl.snap)
        return out

    def snapshot(self, ledger_seq: int = 0, store=None):
        """Immutable point-in-time view (reference:
        SearchableBucketListSnapshot via BucketSnapshotManager).  With a
        ``BucketListStore``, the view reads through on-disk bucket indexes
        and pins its files against GC (BucketListDB mode)."""
        from .snapshot import SearchableBucketListSnapshot
        return SearchableBucketListSnapshot(self, ledger_seq, store=store)

    def lookup_latest(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        """Newest version of a key across the list, or None if the newest
        record is a tombstone / the key is absent (reference:
        SearchableBucketListSnapshot::load — level 0 curr is newest)."""
        from .bucket import _is_dead
        for bucket in self.buckets():
            be = bucket.find(key_bytes)
            if be is not None:
                return None if _is_dead(be) else be.value
        return None
