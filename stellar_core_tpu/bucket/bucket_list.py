"""The 11-level log-structured BucketList (the ledger state's hash structure).

Reference: src/bucket/BucketListBase.{h,cpp} / LiveBucketList — levels of
(curr, snap) buckets, spill cadence in powers of 4, levelShouldSpill /
levelHalf / levelSize, getHash = tree of SHA-256s.  Merges that the reference
runs asynchronously (FutureBucket on worker threads) are synchronous here;
the observable bucket contents and hashes are the same (flagged as a perf
item, not a semantics item).
"""

from __future__ import annotations

from typing import Iterable, List, Optional

from ..crypto.sha import SHA256
from ..xdr import LedgerEntry, LedgerKey
from .bucket import Bucket, merge_buckets

NUM_LEVELS = 11


def level_size(level: int) -> int:
    return 4 ** (level + 1)


def level_half(level: int) -> int:
    return level_size(level) // 2


def level_should_spill(ledger: int, level: int) -> bool:
    """Does `level` spill its curr on this ledger? (reference:
    BucketListBase::levelShouldSpill — at multiples of levelHalf)."""
    if level == NUM_LEVELS - 1:
        return False
    return ledger == (ledger // level_half(level)) * level_half(level)


def keep_tombstone_entries(level: int) -> bool:
    return level < NUM_LEVELS - 1


class BucketLevel:
    __slots__ = ("curr", "snap")

    def __init__(self) -> None:
        self.curr = Bucket.empty()
        self.snap = Bucket.empty()

    def snap_curr(self) -> Bucket:
        self.snap = self.curr
        self.curr = Bucket.empty()
        return self.snap

    def hash(self) -> bytes:
        return SHA256().add(self.curr.hash()).add(self.snap.hash()).finish()


class BucketList:
    def __init__(self) -> None:
        self.levels: List[BucketLevel] = [BucketLevel() for _ in range(NUM_LEVELS)]

    def add_batch(self, ledger_seq: int, protocol_version: int,
                  init_entries: Iterable[LedgerEntry],
                  live_entries: Iterable[LedgerEntry],
                  dead_keys: Iterable[LedgerKey]) -> None:
        """One ledger's changes enter level 0; spill boundaries cascade
        older halves downward (reference: BucketListBase::addBatch)."""
        assert ledger_seq > 0
        for i in range(NUM_LEVELS - 1, 0, -1):
            if level_should_spill(ledger_seq, i - 1):
                spill = self.levels[i - 1].snap_curr()
                self.levels[i].curr = merge_buckets(
                    self.levels[i].curr, spill,
                    keep_tombstones=keep_tombstone_entries(i),
                    protocol_version=protocol_version)
        fresh = Bucket.fresh(protocol_version, init_entries, live_entries,
                             dead_keys)
        self.levels[0].curr = merge_buckets(
            self.levels[0].curr, fresh, keep_tombstones=True,
            protocol_version=protocol_version)

    def hash(self) -> bytes:
        """bucketListHash in the ledger header: SHA-256 over level hashes
        (each SHA-256(curr.hash || snap.hash))."""
        h = SHA256()
        for lvl in self.levels:
            h.add(lvl.hash())
        return h.finish()

    def buckets(self) -> List[Bucket]:
        out = []
        for lvl in self.levels:
            out.append(lvl.curr)
            out.append(lvl.snap)
        return out

    def lookup_latest(self, key_bytes: bytes) -> Optional[LedgerEntry]:
        """Newest version of a key across the list, or None if the newest
        record is a tombstone / the key is absent (reference:
        SearchableBucketListSnapshot::load — level 0 curr is newest)."""
        from .bucket import _is_dead
        for bucket in self.buckets():
            be = bucket.find(key_bytes)
            if be is not None:
                return None if _is_dead(be) else be.value
        return None
