"""FutureBucket: a handle on an in-progress (or finished) bucket merge.

Reference: src/bucket/FutureBucket.{h,cpp} — the reference runs level merges
on worker threads and resolves the future lazily at the next spill boundary
("commit"), so merge compute overlaps ledger closes.  The HAS serializes a
level's pending merge as ``next``: ``{"state": 0}`` when clear or
``{"state": 1, "output": <hex>}`` once resolved (FB_HASH_OUTPUT), which is
how a restarted / catching-up node reconstructs the exact same future bucket
lineage and therefore the exact same subsequent bucket-list hashes.

Merges are pure functions of their inputs, so resolution order/threading
never changes the output — sync mode (no executor) and threaded mode are
bit-identical, which the test suite asserts.  With a ``raw_store`` the
merge runs as ``merge_buckets_raw``: records stream file-to-file without
decoding (reference: BucketBase::merge between Bucket*Iterators) and the
output is a disk-resident bucket — same hash, O(1) merge memory.
"""

from __future__ import annotations

from typing import Optional

from ..util import perf
from ..util.metrics import registry as _registry
from .bucket import Bucket, merge_buckets, merge_buckets_raw

# deep-level merges run minutes on big ledgers, by design, in the
# background — only a pathological merge deserves a slow-scope warning
perf.set_slow_threshold("bucket.merge.time", 120.0)
perf.set_slow_threshold("bucket.merge.stream", 120.0)


def _timed_merge(curr: Bucket, snap: Bucket, keep_tombstones: bool,
                 protocol_version: int) -> Bucket:
    """merge_buckets with the bucket.merge.time timer (reference: the
    "bucket.merge" medida timers in BucketManagerImpl) — runs on whichever
    thread executes the merge, so background merges are timed too."""
    with perf.scoped_timer("bucket.merge.time"):
        return merge_buckets(curr, snap, keep_tombstones, protocol_version)


def _timed_merge_raw(curr: Bucket, snap: Bucket, keep_tombstones: bool,
                     protocol_version: int, store) -> Bucket:
    """merge_buckets_raw under the bucket.merge.stream timer, with the
    output volume marked on the bucket.merge.bytes meter (merged-bytes/s
    is the streaming pipeline's throughput signal)."""
    with perf.scoped_timer("bucket.merge.stream"):
        out = merge_buckets_raw(curr, snap, keep_tombstones,
                                protocol_version, store)
    idx = out.disk_index()
    if idx is not None:
        _registry().meter("bucket.merge.bytes").mark(idx._file_size)
    return out


class FutureBucket:
    """Either a running merge (executor future) or a resolved output.

    Inputs are retained until resolution so an unresolved merge can be
    serialized as FB_HASH_INPUTS (curr/snap hashes + merge params) in the
    node's durable HAS — restart then re-runs the merge from inputs instead
    of the close path having to block on resolve() every ledger."""

    __slots__ = ("_future", "_output", "inputs", "_raw_store")

    def __init__(self, curr: Bucket, snap: Bucket, keep_tombstones: bool,
                 protocol_version: int, executor=None, raw_store=None):
        self._output: Optional[Bucket] = None
        self._future = None
        self.inputs = (curr, snap, keep_tombstones, protocol_version)
        self._raw_store = raw_store
        if raw_store is not None:
            fn, args = _timed_merge_raw, (curr, snap, keep_tombstones,
                                          protocol_version, raw_store)
        else:
            fn, args = _timed_merge, (curr, snap, keep_tombstones,
                                      protocol_version)
        if executor is not None:
            self._future = executor.submit(fn, *args)
        else:
            self._output = fn(*args)

    @staticmethod
    def from_output(bucket: Bucket) -> "FutureBucket":
        """Rehydrate a future from its serialized output hash (HAS state 1,
        reference: FutureBucket::makeLive on the FB_HASH_OUTPUT path)."""
        fb = FutureBucket.__new__(FutureBucket)
        fb._future = None
        fb._output = bucket
        fb.inputs = None
        fb._raw_store = None
        return fb

    @property
    def done(self) -> bool:
        return self._output is not None or self._future.done()

    def resolve(self) -> Bucket:
        """Block until the merge output is available and return it."""
        if self._output is None:
            self._output = self._future.result()
            self._future = None
        return self._output

    def peek(self) -> Optional[Bucket]:
        """The output if already materialized on THIS handle, else None —
        never blocks (resident-entry accounting must not sync a running
        background merge)."""
        return self._output

    def release_output_pin(self) -> None:
        """Drop the streaming-merge output's GC pin (taken by the store at
        stream adoption).  Called by BucketLevel.commit once the output is
        referenced as the level's curr; no-op for in-memory merges."""
        if self._raw_store is not None and self._output is not None:
            store, self._raw_store = self._raw_store, None
            if not self._output.is_empty():
                store.unpin([self._output.hash().hex()])

    def serialize(self) -> dict:
        """The HAS `next` form (reference: FutureBucket::save): output hash
        when already resolved, inputs otherwise — never blocks."""
        if self.done:
            return {"state": 1, "output": self.resolve().hash().hex()}
        curr, snap, keep, proto = self.inputs
        return {"state": 2, "curr": curr.hash().hex(),
                "snap": snap.hash().hex(), "keepTombstones": keep,
                "outputProtocol": proto}
