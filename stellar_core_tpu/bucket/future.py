"""FutureBucket: a handle on an in-progress (or finished) bucket merge.

Reference: src/bucket/FutureBucket.{h,cpp} — the reference runs level merges
on worker threads and resolves the future lazily at the next spill boundary
("commit"), so merge compute overlaps ledger closes.  The HAS serializes a
level's pending merge as ``next``: ``{"state": 0}`` when clear or
``{"state": 1, "output": <hex>}`` once resolved (FB_HASH_OUTPUT), which is
how a restarted / catching-up node reconstructs the exact same future bucket
lineage and therefore the exact same subsequent bucket-list hashes.

Merges are pure functions of their inputs, so resolution order/threading
never changes the output — sync mode (no executor) and threaded mode are
bit-identical, which the test suite asserts.
"""

from __future__ import annotations

from typing import Optional

from ..util import perf
from .bucket import Bucket, merge_buckets

# deep-level merges run minutes on big ledgers, by design, in the
# background — only a pathological merge deserves a slow-scope warning
perf.set_slow_threshold("bucket.merge.time", 120.0)


def _timed_merge(curr: Bucket, snap: Bucket, keep_tombstones: bool,
                 protocol_version: int) -> Bucket:
    """merge_buckets with the bucket.merge.time timer (reference: the
    "bucket.merge" medida timers in BucketManagerImpl) — runs on whichever
    thread executes the merge, so background merges are timed too."""
    with perf.scoped_timer("bucket.merge.time"):
        return merge_buckets(curr, snap, keep_tombstones, protocol_version)


class FutureBucket:
    """Either a running merge (executor future) or a resolved output.

    Inputs are retained until resolution so an unresolved merge can be
    serialized as FB_HASH_INPUTS (curr/snap hashes + merge params) in the
    node's durable HAS — restart then re-runs the merge from inputs instead
    of the close path having to block on resolve() every ledger."""

    __slots__ = ("_future", "_output", "inputs")

    def __init__(self, curr: Bucket, snap: Bucket, keep_tombstones: bool,
                 protocol_version: int, executor=None):
        self._output: Optional[Bucket] = None
        self._future = None
        self.inputs = (curr, snap, keep_tombstones, protocol_version)
        if executor is not None:
            self._future = executor.submit(
                _timed_merge, curr, snap, keep_tombstones, protocol_version)
        else:
            self._output = _timed_merge(curr, snap, keep_tombstones,
                                        protocol_version)

    @staticmethod
    def from_output(bucket: Bucket) -> "FutureBucket":
        """Rehydrate a future from its serialized output hash (HAS state 1,
        reference: FutureBucket::makeLive on the FB_HASH_OUTPUT path)."""
        fb = FutureBucket.__new__(FutureBucket)
        fb._future = None
        fb._output = bucket
        fb.inputs = None
        return fb

    @property
    def done(self) -> bool:
        return self._output is not None or self._future.done()

    def resolve(self) -> Bucket:
        """Block until the merge output is available and return it."""
        if self._output is None:
            self._output = self._future.result()
            self._future = None
        return self._output

    def serialize(self) -> dict:
        """The HAS `next` form (reference: FutureBucket::save): output hash
        when already resolved, inputs otherwise — never blocks."""
        if self.done:
            return {"state": 1, "output": self.resolve().hash().hex()}
        curr, snap, keep, proto = self.inputs
        return {"state": 2, "curr": curr.hash().hex(),
                "snap": snap.hash().hex(), "keepTombstones": keep,
                "outputProtocol": proto}
