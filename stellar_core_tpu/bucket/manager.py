"""BucketDir: content-addressed on-disk bucket files.

Reference: src/bucket/BucketManager.{h,cpp} — adoptFileAsBucket /
getBucketByHash over `buckets/bucket-<hex>.xdr`, plus forgetUnreferenced
garbage collection.  Files are immutable once written (content-addressed by
SHA-256 of the serialized stream), written atomically via tmp+rename, and
verified against their name hash on load.
"""

from __future__ import annotations

import os
from typing import Iterable, Optional, Set

from .bucket import Bucket

_EMPTY_HEX = "0" * 64


class BucketDir:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _file_for(self, hex_hash: str) -> str:
        return os.path.join(self.path, f"bucket-{hex_hash}.xdr")

    def save(self, bucket: Bucket) -> str:
        """Persist a bucket; returns its hex hash.  Existing files are
        trusted (content addressing makes rewrites pointless)."""
        hh = bucket.hash().hex()
        if bucket.is_empty():
            return _EMPTY_HEX
        target = self._file_for(hh)
        if os.path.exists(target):
            return hh
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(bucket.serialize())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
        # fsync the directory so the rename itself survives power loss —
        # the DB that points at this bucket commits after us
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return hh

    def load(self, hex_hash: str) -> Optional[Bucket]:
        """Load and hash-verify a bucket; None when absent.  A corrupt file
        raises — silently returning None would let catchup treat corruption
        as absence."""
        if hex_hash == _EMPTY_HEX:
            return Bucket.empty()
        target = self._file_for(hex_hash)
        if not os.path.exists(target):
            return None
        with open(target, "rb") as f:
            bucket = Bucket.deserialize(f.read())
        if bucket.hash().hex() != hex_hash:
            raise RuntimeError(f"bucket file {target} fails hash check")
        return bucket

    def exists(self, hex_hash: str) -> bool:
        return hex_hash == _EMPTY_HEX or os.path.exists(self._file_for(hex_hash))

    def gc(self, referenced: Iterable[str]) -> int:
        """Delete bucket files not in `referenced` (reference:
        BucketManager::forgetUnreferencedBuckets).  Returns removed count."""
        keep: Set[str] = set(referenced)
        removed = 0
        for name in os.listdir(self.path):
            if not (name.startswith("bucket-") and name.endswith(".xdr")):
                continue
            hh = name[len("bucket-"):-len(".xdr")]
            if hh not in keep:
                os.unlink(os.path.join(self.path, name))
                removed += 1
        return removed
