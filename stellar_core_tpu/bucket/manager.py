"""BucketDir: content-addressed on-disk bucket files.

Reference: src/bucket/BucketManager.{h,cpp} — adoptFileAsBucket /
getBucketByHash over `buckets/bucket-<hex>.xdr`, plus forgetUnreferenced
garbage collection.  Files are immutable once written (content-addressed by
SHA-256 of the serialized stream), written atomically via tmp+rename, and
verified against their name hash on load.

``BucketListStore`` layers the BucketListDB authority on top: every saved
file carries a ``DiskBucketIndex`` so point lookups seek into the file,
and live snapshots PIN the files they reference so GC never deletes a
bucket out from under an open read view (reference: BucketManager's
shared-ptr liveness feeding forgetUnreferencedBuckets).
"""

from __future__ import annotations

import itertools
import os
from typing import Dict, Iterable, List, Optional, Set

from ..crypto.sha import SHA256
from ..util import eventlog
from ..util.lockorder import make_rlock
from ..util.metrics import registry as _registry
from ..util.racetrace import race_checked
from .bucket import DEAD_TAG, Bucket, pack_meta
from .index import DiskBucketIndex

_EMPTY_HEX = "0" * 64

# tmp merge outputs need uniqueness, not unpredictability: pid + a
# process-local sequence keeps the name deterministic (rng-discipline)
_MERGE_SEQ = itertools.count()


class BucketDir:
    # every bucket file this directory is supposed to hold, one hex hash
    # per line — written alongside the files so a startup audit can tell
    # "this file was deleted/lost" apart from "this file was never ours".
    # Content addressing alone cannot: a vanished file leaves no trace.
    MANIFEST_NAME = "MANIFEST"

    def __init__(self, path: str):
        self.path = path
        self._manifest_cache: Optional[Set[str]] = None
        os.makedirs(path, exist_ok=True)

    def _file_for(self, hex_hash: str) -> str:
        return os.path.join(self.path, f"bucket-{hex_hash}.xdr")

    # -- manifest ------------------------------------------------------------
    @property
    def _manifest_path(self) -> str:
        return os.path.join(self.path, self.MANIFEST_NAME)

    def _manifest_read(self) -> Set[str]:
        """Well-formed entries only (64 hex chars): a torn tail line from
        a crash mid-append must read as absent — the full-file hash scan
        in audit() still covers whatever file the lost entry named — not
        as a permanently unstartable 'missing bucket <garbage>'.

        Cached in memory after the first read (this instance is the only
        writer for its directory — Application aliases bucket_dir to the
        store): save() membership-checks on every ledger close, and
        re-reading the file each time puts O(manifest) disk reads on the
        persistence hot path.  Only a missing file means 'legacy dir';
        a real I/O error must surface — swallowing EIO here would
        silently disable the missing-bucket half of the startup audit
        exactly when the disk is in trouble."""
        if self._manifest_cache is None:
            try:
                with open(self._manifest_path) as f:
                    self._manifest_cache = {
                        line.strip() for line in f
                        if len(line.strip()) == 64
                        and all(c in "0123456789abcdef"
                                for c in line.strip())}
            except FileNotFoundError:
                self._manifest_cache = set()   # pre-manifest legacy dir
        return set(self._manifest_cache)

    def _manifest_write(self, hashes: Set[str]) -> None:
        tmp = self._manifest_path + ".tmp"
        with open(tmp, "w") as f:
            f.write("".join(hh + "\n" for hh in sorted(hashes)))
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._manifest_path)
        self._manifest_cache = set(hashes)

    def _manifest_add(self, hex_hash: str) -> None:
        # O_APPEND one-line write: atomic enough for concurrent adopters;
        # duplicates are harmless (the reader is a set).  If a crash left
        # a torn tail line (no trailing newline), lead with one so this
        # entry does not glue onto the fragment and invalidate both —
        # blank lines are filtered by the reader.
        lead = ""
        try:
            with open(self._manifest_path, "rb") as f:
                f.seek(0, os.SEEK_END)
                if f.tell() > 0:
                    f.seek(-1, os.SEEK_END)
                    if f.read(1) != b"\n":
                        lead = "\n"
        except FileNotFoundError:
            pass
        with open(self._manifest_path, "a") as f:
            f.write(lead + hex_hash + "\n")
        if self._manifest_cache is not None:
            self._manifest_cache.add(hex_hash)

    def _manifest_has(self, hex_hash: str) -> bool:
        # membership against the cache directly — _manifest_read()'s
        # defensive copy is O(manifest) and this runs per ledger close
        if self._manifest_cache is None:
            self._manifest_read()
        return hex_hash in self._manifest_cache

    def _manifest_readopt(self, hex_hash: str) -> None:
        """Re-adopt a file left untracked by a crash between its durable
        rename and the manifest append — otherwise it can never become
        manifest-tracked and its later loss escapes audit()."""
        if not self._manifest_has(hex_hash):
            self._manifest_add(hex_hash)

    def _fsync_dir(self) -> None:
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)

    def audit(self) -> int:
        """Fail-stop integrity scan, run before on-disk state is trusted
        (reference shape: BucketManager::assumeState verifying buckets
        against the HAS): every manifest-listed bucket file must exist,
        and every bucket file present must hash to its own name — a
        flipped byte or a vanished file raises RuntimeError with the
        offending path instead of surfacing later as wrong ledger state.
        Returns the number of files verified."""
        on_disk: Set[str] = set()
        for name in os.listdir(self.path):
            if name.startswith("bucket-") and name.endswith(".xdr"):
                on_disk.add(name[len("bucket-"):-len(".xdr")])
        for hh in sorted(self._manifest_read()):
            if hh != _EMPTY_HEX and hh not in on_disk:
                raise RuntimeError(f"missing bucket {hh} "
                                   f"(manifest-listed, not on disk)")
        verified = 0
        for hh in sorted(on_disk):
            sha = SHA256()
            with open(self._file_for(hh), "rb") as f:
                while True:
                    chunk = f.read(1 << 20)
                    if not chunk:
                        break
                    sha.add(chunk)
            if sha.finish().hex() != hh:
                raise RuntimeError(
                    f"bucket file {self._file_for(hh)} fails hash check")
            verified += 1
        return verified

    def save(self, bucket: Bucket) -> str:
        """Persist a bucket; returns its hex hash.  Existing files are
        trusted (content addressing makes rewrites pointless)."""
        hh = bucket.hash().hex()
        if bucket.is_empty():
            return _EMPTY_HEX
        target = self._file_for(hh)
        if os.path.exists(target):
            self._manifest_readopt(hh)
            return hh
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(bucket.serialize())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
        # fsync the directory so the rename itself survives power loss —
        # the DB that points at this bucket commits after us
        self._fsync_dir()
        # manifest entry only AFTER the rename is durable: a crash in
        # between leaves an untracked-but-intact file (harmless), never a
        # durable manifest entry whose file rename was lost (a false
        # missing-bucket fail-stop at the next startup audit)
        self._manifest_add(hh)
        return hh

    def load(self, hex_hash: str) -> Optional[Bucket]:
        """Load and hash-verify a bucket; None when absent.  A corrupt file
        raises — silently returning None would let catchup treat corruption
        as absence."""
        if hex_hash == _EMPTY_HEX:
            return Bucket.empty()
        target = self._file_for(hex_hash)
        if not os.path.exists(target):
            return None
        with open(target, "rb") as f:
            bucket = Bucket.deserialize(f.read())
        if bucket.hash().hex() != hex_hash:
            raise RuntimeError(f"bucket file {target} fails hash check")
        return bucket

    def exists(self, hex_hash: str) -> bool:
        return hex_hash == _EMPTY_HEX or os.path.exists(self._file_for(hex_hash))

    def gc(self, referenced: Iterable[str]) -> int:
        """Delete bucket files not in `referenced` (reference:
        BucketManager::forgetUnreferencedBuckets).  Returns removed count.
        The manifest is rewritten BEFORE any unlink: a crash in between
        leaves an untracked-but-intact file (re-collected next pass), never
        a manifest entry whose file is gone (a false missing-bucket
        fail-stop at the next startup audit)."""
        keep: Set[str] = set(referenced)
        keep.update(self._protected_hashes())
        victims: List[str] = []
        for name in os.listdir(self.path):
            if not (name.startswith("bucket-") and name.endswith(".xdr")):
                continue
            hh = name[len("bucket-"):-len(".xdr")]
            if hh not in keep:
                victims.append(hh)
        if victims:
            self._manifest_write(self._manifest_read() - set(victims))
            # the rewrite must be durable BEFORE any unlink: a crash that
            # persists the unlinks but loses the manifest rename would
            # leave durable entries for vanished files — the very false
            # fail-stop this ordering exists to prevent
            self._fsync_dir()
        removed = 0
        for hh in victims:
            os.unlink(self._file_for(hh))
            self._on_removed(hh)
            removed += 1
        return removed

    def _protected_hashes(self) -> Set[str]:
        """Hashes GC must keep beyond the referenced set (BucketListStore
        adds snapshot pins)."""
        return set()

    def _on_removed(self, hex_hash: str) -> None:
        pass


class BucketStreamWriter:
    """Streaming bucket output (reference: BucketOutputIterator): records
    append to a temp file while the content hash and the DiskBucketIndex
    grow incrementally, so a whole merge never holds more than one record
    in memory.  finalize() renames the file to its content address and
    registers the index with the store; content addressing makes a
    collision with an existing file a free dedup.

    Records must arrive in strictly ascending key order — the same
    contract the in-memory merge guarantees — and are NOT inspected
    beyond their leading 4-byte discriminant (the tombstone flag)."""

    __slots__ = ("_store", "_proto", "_tmp", "_f", "_sha", "_off",
                 "_keys", "_offsets", "_dead", "bytes_written")

    def __init__(self, store: "BucketListStore", protocol_version: int):
        self._store = store
        self._proto = protocol_version
        self._tmp = os.path.join(
            store.path,
            f".merge-{os.getpid()}-{next(_MERGE_SEQ)}.tmp")
        self._f = open(self._tmp, "wb", buffering=1 << 16)
        meta = pack_meta(protocol_version)
        self._f.write(meta)
        self._sha = SHA256().add(meta)
        self._off = len(meta)
        self._keys: List[bytes] = []
        self._offsets: List[int] = []
        self._dead = bytearray()
        self.bytes_written = len(meta)

    def write(self, key: bytes, rec: bytes) -> None:
        self._f.write(rec)
        self._sha.add(rec)
        self._keys.append(key)
        self._offsets.append(self._off)
        self._dead.append(1 if rec[:4] == DEAD_TAG else 0)
        self._off += len(rec)
        self.bytes_written += len(rec)

    def finalize(self) -> Bucket:
        """Close + content-address the stream; returns the disk-resident
        Bucket (or a plain empty bucket carrying the output protocol when
        nothing was written — the all-annihilated merge)."""
        if not self._keys:
            self.abort()
            return Bucket([], self._proto)
        self._f.flush()
        os.fsync(self._f.fileno())
        self._f.close()
        self._f = None
        hh = self._sha.finish()
        bucket = self._store._adopt_stream(
            self._tmp, hh, DiskBucketIndex(
                "", self._proto, self._keys, self._offsets, self._off,
                bytes(self._dead)))
        self._tmp = None
        return bucket

    def abort(self) -> None:
        """Discard the stream (merge raised / empty output); idempotent."""
        if self._f is not None:
            self._f.close()
            self._f = None
        if self._tmp is not None:
            try:
                os.unlink(self._tmp)
            except OSError:
                pass
            self._tmp = None


@race_checked
class BucketListStore(BucketDir):
    """BucketDir + per-file ``DiskBucketIndex`` cache + snapshot pinning —
    the storage half of BucketListDB (reference: BucketManager +
    BucketIndexImpl since v21, where the indexed bucket files ARE the
    ledger-entry database and SQL holds no entry tables).

    Indexes are built once per content hash: at save time from the
    in-memory bucket (free — reuses its cached sort keys / packed records)
    or, for files adopted from a previous run, by a hash-verified scan.
    Pins are refcounts held by open ``SearchableBucketListSnapshot``s; GC
    keeps ``referenced ∪ pinned`` so an old snapshot keeps serving a
    consistent view while the live list moves on.
    """

    def __init__(self, path: str):
        super().__init__(path)
        self._indexes: Dict[str, DiskBucketIndex] = {}
        self._pins: Dict[str, int] = {}
        # background streaming merges register outputs from worker threads
        # while the close path reads/pins/GCs on the main thread; reentrant
        # because gc() holds it across the scan and _protected_hashes()
        # re-acquires
        self._lock = make_rlock("bucket.store")
        # weak source: a torn-down store reads as null, never pins the
        # store graph in the process-global registry
        _registry().weak_gauge("bucketlistdb.pin.active", self,
                               BucketListStore.pin_count)

    # -- streaming merge output ----------------------------------------------
    def stream_writer(self, protocol_version: int) -> BucketStreamWriter:
        """Open a streaming bucket output (merge_buckets_raw's sink)."""
        return BucketStreamWriter(self, protocol_version)

    def _adopt_stream(self, tmp_path: str, hash_bytes: bytes,
                      idx: DiskBucketIndex) -> Bucket:
        """Content-address a finished stream file and register its index.
        The output hash is PINNED (released by FutureBucket at commit):
        a background merge can finish between the close path computing
        referenced_hashes and GC unlinking — without the pin that window
        would delete a file the about-to-commit level points at.  Pin,
        register and rename happen under the store lock, and gc() holds
        the same lock across its whole scan, so the file can never become
        visible-but-unpinned mid-collection."""
        hh = hash_bytes.hex()
        target = self._file_for(hh)
        idx.path = target
        with self._lock:
            self._pins[hh] = self._pins.get(hh, 0) + 1
            self._indexes.setdefault(hh, idx)
            idx = self._indexes[hh]
            if os.path.exists(target):
                deduped = True
                os.unlink(tmp_path)  # dedup: identical content already stored
                self._manifest_readopt(hh)
            else:
                deduped = False
                os.replace(tmp_path, target)
                self._fsync_dir()
                # after the rename is durable — same ordering as save()
                self._manifest_add(hh)
        # recorded OUTSIDE the store lock: the event-log lock is a leaf
        eventlog.record("Bucket", "INFO", "stream merge output adopted",
                        hash=hh[:16], entries=len(idx._keys),
                        bytes=idx._file_size, deduped=deduped)
        return Bucket.from_disk(idx, hash_bytes)

    def gc(self, referenced: Iterable[str]) -> int:
        # one atomic scan vs concurrent stream adoptions (see
        # _adopt_stream) — the lock is reentrant for _protected_hashes
        with self._lock:
            removed = super().gc(referenced)
        if removed:
            eventlog.record("Bucket", "INFO", "bucket GC pass",
                            removed=removed)
        return removed

    # -- save + index --------------------------------------------------------
    def ensure(self, bucket: Bucket) -> Optional[DiskBucketIndex]:
        """Persist `bucket` and return its index; None for the empty
        bucket (nothing to look up).  A file this process just wrote is
        indexed for free from the in-memory bucket; a PRE-EXISTING file
        (restart — content addressing trusts names, but the bytes about
        to serve authoritative reads must prove themselves) is adopted
        through the hash-verified scan, so on-disk corruption fail-stops
        here instead of surfacing as wrong ledger state."""
        if bucket.is_empty():
            return None
        attached = bucket.disk_index()
        if attached is not None:
            # a disk-resident bucket (streaming-merge output / prior
            # residency pass) carries its index; adopt it if unseen
            with self._lock:
                return self._indexes.setdefault(bucket.hash().hex(),
                                                attached)
        hh = bucket.hash().hex()
        with self._lock:
            idx = self._indexes.get(hh)
        if idx is not None:
            return idx
        if os.path.exists(self._file_for(hh)):
            self._manifest_readopt(hh)
            return self.index_for(hh)
        self.save(bucket)
        idx = DiskBucketIndex.from_bucket(bucket, self._file_for(hh))
        with self._lock:
            idx = self._indexes.setdefault(hh, idx)
        return idx

    def index_for(self, hex_hash: str) -> Optional[DiskBucketIndex]:
        """Index of an already-on-disk bucket (restart/assume-state path);
        builds via a hash-verified file scan on first use.  None for the
        empty hash; missing files raise (the caller named a bucket the
        store must have)."""
        if hex_hash == _EMPTY_HEX:
            return None
        with self._lock:
            idx = self._indexes.get(hex_hash)
        if idx is None:
            target = self._file_for(hex_hash)
            if not os.path.exists(target):
                raise RuntimeError(f"missing bucket file for {hex_hash}")
            idx = DiskBucketIndex.build(target, expected_hex_hash=hex_hash)
            with self._lock:
                idx = self._indexes.setdefault(hex_hash, idx)
        return idx

    # -- snapshot pinning ----------------------------------------------------
    def pin_count(self) -> int:
        """Distinct bucket files currently pinned (snapshot readers +
        in-flight merge outputs) — the bucketlistdb.pin.active gauge and
        the CloseCostRecord pin column."""
        with self._lock:
            return len(self._pins)

    def pin(self, hex_hashes: Iterable[str]) -> None:
        with self._lock:
            for hh in hex_hashes:
                self._pins[hh] = self._pins.get(hh, 0) + 1

    def unpin(self, hex_hashes: Iterable[str]) -> None:
        with self._lock:
            for hh in hex_hashes:
                n = self._pins.get(hh, 0) - 1
                if n <= 0:
                    self._pins.pop(hh, None)
                else:
                    self._pins[hh] = n

    def _protected_hashes(self) -> Set[str]:
        with self._lock:
            return set(self._pins)

    def _on_removed(self, hex_hash: str) -> None:
        with self._lock:
            self._indexes.pop(hex_hash, None)
