"""BucketDir: content-addressed on-disk bucket files.

Reference: src/bucket/BucketManager.{h,cpp} — adoptFileAsBucket /
getBucketByHash over `buckets/bucket-<hex>.xdr`, plus forgetUnreferenced
garbage collection.  Files are immutable once written (content-addressed by
SHA-256 of the serialized stream), written atomically via tmp+rename, and
verified against their name hash on load.

``BucketListStore`` layers the BucketListDB authority on top: every saved
file carries a ``DiskBucketIndex`` so point lookups seek into the file,
and live snapshots PIN the files they reference so GC never deletes a
bucket out from under an open read view (reference: BucketManager's
shared-ptr liveness feeding forgetUnreferencedBuckets).
"""

from __future__ import annotations

import os
from typing import Dict, Iterable, Optional, Set

from .bucket import Bucket
from .index import DiskBucketIndex

_EMPTY_HEX = "0" * 64


class BucketDir:
    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _file_for(self, hex_hash: str) -> str:
        return os.path.join(self.path, f"bucket-{hex_hash}.xdr")

    def save(self, bucket: Bucket) -> str:
        """Persist a bucket; returns its hex hash.  Existing files are
        trusted (content addressing makes rewrites pointless)."""
        hh = bucket.hash().hex()
        if bucket.is_empty():
            return _EMPTY_HEX
        target = self._file_for(hh)
        if os.path.exists(target):
            return hh
        tmp = target + ".tmp"
        with open(tmp, "wb") as f:
            f.write(bucket.serialize())
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, target)
        # fsync the directory so the rename itself survives power loss —
        # the DB that points at this bucket commits after us
        dfd = os.open(self.path, os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
        return hh

    def load(self, hex_hash: str) -> Optional[Bucket]:
        """Load and hash-verify a bucket; None when absent.  A corrupt file
        raises — silently returning None would let catchup treat corruption
        as absence."""
        if hex_hash == _EMPTY_HEX:
            return Bucket.empty()
        target = self._file_for(hex_hash)
        if not os.path.exists(target):
            return None
        with open(target, "rb") as f:
            bucket = Bucket.deserialize(f.read())
        if bucket.hash().hex() != hex_hash:
            raise RuntimeError(f"bucket file {target} fails hash check")
        return bucket

    def exists(self, hex_hash: str) -> bool:
        return hex_hash == _EMPTY_HEX or os.path.exists(self._file_for(hex_hash))

    def gc(self, referenced: Iterable[str]) -> int:
        """Delete bucket files not in `referenced` (reference:
        BucketManager::forgetUnreferencedBuckets).  Returns removed count."""
        keep: Set[str] = set(referenced)
        keep.update(self._protected_hashes())
        removed = 0
        for name in os.listdir(self.path):
            if not (name.startswith("bucket-") and name.endswith(".xdr")):
                continue
            hh = name[len("bucket-"):-len(".xdr")]
            if hh not in keep:
                os.unlink(os.path.join(self.path, name))
                self._on_removed(hh)
                removed += 1
        return removed

    def _protected_hashes(self) -> Set[str]:
        """Hashes GC must keep beyond the referenced set (BucketListStore
        adds snapshot pins)."""
        return set()

    def _on_removed(self, hex_hash: str) -> None:
        pass


class BucketListStore(BucketDir):
    """BucketDir + per-file ``DiskBucketIndex`` cache + snapshot pinning —
    the storage half of BucketListDB (reference: BucketManager +
    BucketIndexImpl since v21, where the indexed bucket files ARE the
    ledger-entry database and SQL holds no entry tables).

    Indexes are built once per content hash: at save time from the
    in-memory bucket (free — reuses its cached sort keys / packed records)
    or, for files adopted from a previous run, by a hash-verified scan.
    Pins are refcounts held by open ``SearchableBucketListSnapshot``s; GC
    keeps ``referenced ∪ pinned`` so an old snapshot keeps serving a
    consistent view while the live list moves on.
    """

    def __init__(self, path: str):
        super().__init__(path)
        self._indexes: Dict[str, DiskBucketIndex] = {}
        self._pins: Dict[str, int] = {}

    # -- save + index --------------------------------------------------------
    def ensure(self, bucket: Bucket) -> Optional[DiskBucketIndex]:
        """Persist `bucket` and return its index; None for the empty
        bucket (nothing to look up).  A file this process just wrote is
        indexed for free from the in-memory bucket; a PRE-EXISTING file
        (restart — content addressing trusts names, but the bytes about
        to serve authoritative reads must prove themselves) is adopted
        through the hash-verified scan, so on-disk corruption fail-stops
        here instead of surfacing as wrong ledger state."""
        if bucket.is_empty():
            return None
        hh = bucket.hash().hex()
        idx = self._indexes.get(hh)
        if idx is not None:
            return idx
        if os.path.exists(self._file_for(hh)):
            return self.index_for(hh)
        self.save(bucket)
        idx = DiskBucketIndex.from_bucket(bucket, self._file_for(hh))
        self._indexes[hh] = idx
        return idx

    def index_for(self, hex_hash: str) -> Optional[DiskBucketIndex]:
        """Index of an already-on-disk bucket (restart/assume-state path);
        builds via a hash-verified file scan on first use.  None for the
        empty hash; missing files raise (the caller named a bucket the
        store must have)."""
        if hex_hash == _EMPTY_HEX:
            return None
        idx = self._indexes.get(hex_hash)
        if idx is None:
            target = self._file_for(hex_hash)
            if not os.path.exists(target):
                raise RuntimeError(f"missing bucket file for {hex_hash}")
            idx = DiskBucketIndex.build(target, expected_hex_hash=hex_hash)
            self._indexes[hex_hash] = idx
        return idx

    # -- snapshot pinning ----------------------------------------------------
    def pin(self, hex_hashes: Iterable[str]) -> None:
        for hh in hex_hashes:
            self._pins[hh] = self._pins.get(hh, 0) + 1

    def unpin(self, hex_hashes: Iterable[str]) -> None:
        for hh in hex_hashes:
            n = self._pins.get(hh, 0) - 1
            if n <= 0:
                self._pins.pop(hh, None)
            else:
                self._pins[hh] = n

    def _protected_hashes(self) -> Set[str]:
        return set(self._pins)

    def _on_removed(self, hex_hash: str) -> None:
        self._indexes.pop(hex_hash, None)
