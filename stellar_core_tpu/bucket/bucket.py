"""Content-addressed buckets and the exact-semantics merge.

Reference: src/bucket/Bucket.{h,cpp} (LiveBucket), BucketInputIterator /
BucketOutputIterator, and the CAP-20 INIT/LIVE/DEAD merge logic in
src/bucket/BucketBase.cpp — merge (modern protocol >= 12 semantics, no
shadow buckets).

A bucket is an immutable, key-sorted sequence of BucketEntry XDR records,
headed by a METAENTRY carrying the protocol version; its identity is the
SHA-256 of the serialized stream (content addressing, same scheme the
reference uses for bucket files).

Two residency modes (BucketListDB phase 2):

* decoded — the classic in-memory form: a ``List[BucketEntry]`` plus
  cached sort keys / packed records.
* disk-resident — the bucket is backed by its content-addressed file and
  ``DiskBucketIndex`` only; no decoded entries are held.  ``find`` seeks
  one record, iteration streams the file, and ``entries`` rehydrates
  lazily (counted by the ``bucket.rehydrate`` metrics) only when a
  consumer truly needs decoded objects.  ``merge_buckets_raw`` merges two
  buckets in either mode file-to-file without constructing BucketEntry
  objects (reference: BucketBase::merge streaming XDR records between
  BucketInputIterator and BucketOutputIterator).
"""

from __future__ import annotations

from typing import Dict, Iterable, Iterator, List, Optional, Tuple

from ..crypto.sha import SHA256
from ..xdr import (BucketEntry, BucketEntryType, BucketMetadata, LedgerEntry,
                   LedgerKey, ledger_entry_key)

_BE = BucketEntry._xdr_adapter()

# BucketEntry XDR discriminants as wire bytes (big-endian int32) — the raw
# merge decides CAP-20 pair rules from these without decoding records
LIVE_TAG = (BucketEntryType.LIVEENTRY).to_bytes(4, "big")
DEAD_TAG = (BucketEntryType.DEADENTRY).to_bytes(4, "big")
INIT_TAG = (BucketEntryType.INITENTRY).to_bytes(4, "big")


def pack_meta(protocol_version: int) -> bytes:
    """The METAENTRY record heading every serialized bucket stream."""
    return _BE.pack(BucketEntry.metaEntry(
        BucketMetadata(ledgerVersion=protocol_version)))


def _key_bytes(key: LedgerKey) -> bytes:
    """Sort key: LedgerKey XDR bytes.  Type discriminant leads, then the
    per-type fields in declaration order — matches the reference's
    LedgerEntryIdCmp grouping (src/bucket/LedgerCmp.h) for classic types."""
    return key.to_xdr()


def entry_sort_key(be: BucketEntry) -> bytes:
    if be.switch == BucketEntryType.DEADENTRY:
        return _key_bytes(be.value)
    if be.switch == BucketEntryType.METAENTRY:
        return b""  # meta sorts first
    return _key_bytes(ledger_entry_key(be.value))


class Bucket:
    """Immutable sorted bucket. entries EXCLUDE the meta entry; protocol
    version is carried separately and re-serialized as METAENTRY."""

    __slots__ = ("_entries", "protocol_version", "_hash", "_index", "_keys",
                 "_packed", "_disk")

    def __init__(self, entries: List[BucketEntry], protocol_version: int,
                 keys: Optional[List[bytes]] = None,
                 packed: Optional[List[Optional[bytes]]] = None):
        self._entries: Optional[List[BucketEntry]] = entries
        self.protocol_version = protocol_version
        self._hash: Optional[bytes] = None
        self._index = None
        self._keys = keys  # cached sort keys, aligned with entries
        # cached BucketEntry XDR bytes, aligned with entries (None holes
        # fill lazily).  Entries are shared objects across a merge chain,
        # so propagating the packed bytes through merges means each entry
        # is packed ONCE per lifetime instead of once per bucket hash —
        # bucket.hash() re-packs were the top pack call site in the replay
        # profile (25k packs / 191 ledgers).  Memory: the ~150-300 B XDR
        # slice per entry is a fraction of the decoded entry's Python
        # object graph, and the bytes are SHARED across the merge chain
        # (not one copy per bucket).
        self._packed = packed
        # DiskBucketIndex backing a disk-resident bucket (entries dropped)
        self._disk = None

    # -- disk residency ------------------------------------------------------
    @staticmethod
    def from_disk(index, hash_bytes: bytes) -> "Bucket":
        """A bucket whose authoritative form is its on-disk file + index —
        no decoded entries are materialized (the streaming-merge output
        path and the deep-level residency path)."""
        b = Bucket.__new__(Bucket)
        b._entries = None
        b.protocol_version = index.protocol_version
        b._hash = hash_bytes
        b._index = None
        b._keys = index.keys()      # shared with the index, not a copy
        b._packed = None
        b._disk = index
        return b

    def disk_index(self):
        """The backing DiskBucketIndex, or None for a purely in-memory
        bucket."""
        return self._disk

    def is_disk_resident(self) -> bool:
        """True when no decoded entry list is held (reads go through the
        file + index)."""
        return self._disk is not None and self._entries is None

    def make_disk_resident(self, index) -> None:
        """Drop the decoded entry list; the bucket is served from `index`
        + its file from now on.  The content hash is pinned first (it is
        the bucket's identity and must not require a file re-read)."""
        if index is None:
            return  # the empty bucket has no file
        self.hash()
        self._disk = index
        self._entries = None
        self._packed = None
        self._keys = index.keys()
        self._index = None

    def resident_entry_count(self) -> int:
        """Decoded entries currently held (0 for disk-resident) — the
        bucket.resident.entries gauge sums this across the list."""
        return len(self._entries) if self._entries is not None else 0

    def _rehydrate(self) -> List[BucketEntry]:
        """Decode the backing file into entries (the escape hatch for
        consumers that truly need objects — dump tooling, invariants).
        Counted so regressions that silently re-decode deep levels show
        up in bucket.rehydrate.* metrics."""
        from ..util.metrics import registry as _registry
        with open(self._disk.path, "rb") as f:
            data = f.read()
        entries: List[BucketEntry] = []
        packed: List[Optional[bytes]] = []
        off = 0
        while off < len(data):
            start = off
            e, off = _BE.unpack_from_fast(data, off)
            if e.switch != BucketEntryType.METAENTRY:
                entries.append(e)
                packed.append(data[start:off])
        self._entries = entries
        self._packed = packed
        reg = _registry()
        reg.counter("bucket.rehydrate").inc()
        reg.counter("bucket.rehydrate.entries").inc(len(entries))
        return entries

    @property
    def entries(self) -> List[BucketEntry]:
        if self._entries is None:
            self._rehydrate()
        return self._entries

    def __len__(self) -> int:
        if self._entries is not None:
            return len(self._entries)
        return len(self._disk) if self._disk is not None else 0

    # -- caches --------------------------------------------------------------
    def sort_keys(self) -> List[bytes]:
        """Per-entry sort keys, computed once per immutable bucket (the
        merge path walks every level's keys each spill — recomputing the
        key XDR per merge was a top replay cost).  Disk-resident buckets
        share the index's key array."""
        if self._keys is None:
            if self._entries is None and self._disk is not None:
                self._keys = self._disk.keys()
            else:
                self._keys = [entry_sort_key(e) for e in self.entries]
        return self._keys

    def packed_entries(self) -> List[bytes]:
        """Per-entry serialized XDR, computed once per entry lifetime
        (propagated through merges; deserialize captures wire slices)."""
        if self._packed is None:
            if self._entries is None and self._disk is not None:
                # disk mode: slice the file without decoding and WITHOUT
                # caching — callers that need the records transiently
                # (native import) must not re-pin O(bucket) bytes
                return [rec for _, rec in self.iter_raw()]
            self._packed = [_BE.pack(e) for e in self.entries]
        else:
            pk = self._packed
            for i, p in enumerate(pk):
                if p is None:
                    pk[i] = _BE.pack(self._entries[i])
        return self._packed

    def raw_records(self) -> List[bytes]:
        """The packed BucketEntry records (no meta) — the native bridge's
        raw-record seam; disk-resident buckets slice their file without
        any decode."""
        return self.packed_entries()

    def iter_raw(self) -> Iterator[Tuple[bytes, bytes]]:
        """Stream (sort_key, packed_record) pairs without constructing
        BucketEntry objects — the merge_buckets_raw input contract.
        Decoded buckets zip their caches; disk-resident buckets read the
        file sequentially (one buffered pass, no decode)."""
        if self._entries is not None or self._disk is None:
            yield from zip(self.sort_keys(), self.packed_entries())
            return
        idx = self._disk
        keys = idx.keys()
        if not keys:
            return
        # records are contiguous (file = meta + concatenated records):
        # read strictly sequentially so the OS buffer does the batching —
        # a seek per record measured ~3x slower on the merge path
        with open(idx.path, "rb", buffering=1 << 16) as f:
            off0, _, _ = idx._record_bounds(0)
            f.seek(off0)
            for i, kb in enumerate(keys):
                off, end, _ = idx._record_bounds(i)
                yield kb, f.read(end - off)

    def index(self):
        """The bucket's point-lookup index, built lazily once per immutable
        bucket (reference: BucketManager::maybeBuildIndex)."""
        if self._index is None:
            from .index import BucketIndex
            self._index = BucketIndex(self.sort_keys())
        return self._index

    def find(self, key_bytes: bytes) -> Optional[BucketEntry]:
        """Indexed lookup by LedgerKey XDR bytes (entries are sorted by
        exactly this).  Disk-resident: one seek + one-record decode."""
        if self._entries is None and self._disk is not None:
            hit = self._disk.find(key_bytes)
            if hit is None:
                return None
            off, end, _ = hit
            with open(self._disk.path, "rb") as f:
                f.seek(off)
                rec = f.read(end - off)
            be, _ = _BE.unpack_from_fast(rec, 0)
            return be
        i = self.index().find(key_bytes)
        return self._entries[i] if i is not None else None

    @staticmethod
    def empty() -> "Bucket":
        return Bucket([], 0)

    def is_empty(self) -> bool:
        return len(self) == 0

    def hash(self) -> bytes:
        """SHA-256 over the serialized stream (meta + entries); empty bucket
        hashes to 32 zero bytes (reference: Bucket::getHash of empty)."""
        if self._hash is None:
            if self.is_empty():
                self._hash = b"\x00" * 32
            else:
                h = SHA256()
                h.add(pack_meta(self.protocol_version))
                for p in self.packed_entries():
                    h.add(p)
                self._hash = h.finish()
        return self._hash

    def serialize(self) -> bytes:
        if self.is_empty():
            return b""
        if self._entries is None and self._disk is not None:
            with open(self._disk.path, "rb") as f:
                return f.read()
        return pack_meta(self.protocol_version) + b"".join(
            self.packed_entries())

    @staticmethod
    def deserialize(data: bytes) -> "Bucket":
        entries: List[BucketEntry] = []
        packed: List[Optional[bytes]] = []
        off = 0
        protocol = 0
        while off < len(data):
            start = off
            e, off = _BE.unpack_from_fast(data, off)
            if e.switch == BucketEntryType.METAENTRY:
                protocol = e.value.ledgerVersion
            else:
                entries.append(e)
                packed.append(data[start:off])   # wire slice: free cache
        return Bucket(entries, protocol, packed=packed)

    @staticmethod
    def fresh(protocol_version: int, init_entries: Iterable[LedgerEntry],
              live_entries: Iterable[LedgerEntry],
              dead_keys: Iterable[LedgerKey]) -> "Bucket":
        """One ledger's output as a bucket (reference: LiveBucket::fresh).
        Within a single batch a key appears at most once per class; the
        LedgerManager guarantees init/live/dead disjointness."""
        tagged: List[Tuple[bytes, BucketEntry]] = []
        for e in init_entries:
            be = BucketEntry.initEntry(e)
            tagged.append((entry_sort_key(be), be))
        for e in live_entries:
            be = BucketEntry.liveEntry(e)
            tagged.append((entry_sort_key(be), be))
        for k in dead_keys:
            be = BucketEntry.deadEntry(k)
            tagged.append((entry_sort_key(be), be))
        tagged.sort(key=lambda t: t[0])
        return Bucket([e for _, e in tagged], protocol_version,
                      keys=[k for k, _ in tagged])

    def __iter__(self):
        return iter(self.entries)


def _is_init(be: BucketEntry) -> bool:
    return be.switch == BucketEntryType.INITENTRY


def _is_live(be: BucketEntry) -> bool:
    return be.switch == BucketEntryType.LIVEENTRY


def _is_dead(be: BucketEntry) -> bool:
    return be.switch == BucketEntryType.DEADENTRY


def merge_buckets(old: Bucket, new: Bucket, keep_tombstones: bool = True,
                  protocol_version: Optional[int] = None) -> Bucket:
    """Merge two key-sorted buckets, new entries shadowing old.

    CAP-20 pair rules (reference: BucketBase::merge + mergeCasesWithEqualKeys,
    protocol >= 12 semantics):
      (INIT, LIVE) -> INIT carrying the live value
      (INIT, DEAD) -> annihilate (both dropped)
      (DEAD, INIT) -> LIVE carrying the init value
      (LIVE, DEAD) -> DEAD tombstone
      otherwise    -> the newer entry
    keep_tombstones=False (bottom level): DEADs are dropped and INITs decay
    to LIVE (no deeper state left to annihilate against).
    """
    proto = protocol_version if protocol_version is not None else max(
        old.protocol_version, new.protocol_version)
    out: List[BucketEntry] = []
    out_keys: List[bytes] = []
    out_packed: List[Optional[bytes]] = []

    def emit(be: BucketEntry, key: bytes, pb: Optional[bytes] = None):
        """pb: the entry's cached XDR bytes when it passes through
        UNCHANGED from an input bucket (None for merge-constructed
        entries — packed lazily if/when the output is hashed)."""
        if _is_dead(be):
            if keep_tombstones:
                out.append(be)
                out_keys.append(key)
                out_packed.append(pb)
        elif _is_init(be) and not keep_tombstones:
            out.append(BucketEntry.liveEntry(be.value))
            out_keys.append(key)
            out_packed.append(None)   # re-tagged: bytes differ
        else:
            out.append(be)
            out_keys.append(key)
            out_packed.append(pb)

    i = j = 0
    o, n = old.entries, new.entries
    o_keys = old.sort_keys()
    n_keys = new.sort_keys()
    o_pk = old._packed
    n_pk = new._packed

    def opb(i):
        return o_pk[i] if o_pk is not None else None

    def npb(j):
        return n_pk[j] if n_pk is not None else None

    while i < len(o) or j < len(n):
        if j >= len(n):
            emit(o[i], o_keys[i], opb(i)); i += 1
            continue
        if i >= len(o):
            emit(n[j], n_keys[j], npb(j)); j += 1
            continue
        ko, kn = o_keys[i], n_keys[j]
        if ko < kn:
            emit(o[i], ko, opb(i)); i += 1
        elif kn < ko:
            emit(n[j], kn, npb(j)); j += 1
        else:
            oe, ne = o[i], n[j]
            pb = npb(j)
            i += 1
            j += 1
            if _is_init(oe) and _is_live(ne):
                emit(BucketEntry.initEntry(ne.value), kn)
            elif _is_init(oe) and _is_dead(ne):
                pass  # annihilated
            elif _is_dead(oe) and _is_init(ne):
                emit(BucketEntry.liveEntry(ne.value), kn)
            else:
                emit(ne, kn, pb)
    return Bucket(out, proto, keys=out_keys, packed=out_packed)


def merge_buckets_raw(old: Bucket, new: Bucket, keep_tombstones: bool,
                      protocol_version: Optional[int], store) -> Bucket:
    """The streaming flavor of merge_buckets: identical CAP-20 semantics
    decided from the 4-byte XDR discriminant of each packed record — no
    BucketEntry is constructed for any record, pass-through or merged
    (reference: BucketBase::merge pumping BucketInputIterators into a
    BucketOutputIterator file-to-file).  Output records and an incremental
    DiskBucketIndex stream straight into `store` (a BucketListStore); the
    result is a disk-resident Bucket whose hash is bit-identical to the
    in-memory merge's.  Memory: the two input cursors plus the output
    index — no decoded entries, O(1) records in flight.

    Pair-rule/tag mapping (body = record minus its 4-byte tag; the merged
    value's wire bytes ARE the newer record's body, so re-tagging is a
    4-byte splice):
      (INIT, LIVE) -> INIT_TAG + live body
      (INIT, DEAD) -> nothing
      (DEAD, INIT) -> LIVE_TAG + init body
      otherwise    -> the newer record verbatim
    keep_tombstones=False: DEAD dropped, INIT re-tagged LIVE.
    """
    proto = protocol_version if protocol_version is not None else max(
        old.protocol_version, new.protocol_version)
    if old.is_empty() and new.is_empty():
        return Bucket([], proto)
    writer = store.stream_writer(proto)
    try:
        w = writer.write

        def emit(key: bytes, rec: bytes) -> None:
            tag = rec[:4]
            if tag == DEAD_TAG:
                if keep_tombstones:
                    w(key, rec)
            elif tag == INIT_TAG and not keep_tombstones:
                w(key, LIVE_TAG + rec[4:])
            else:
                w(key, rec)

        _SENT = (None, None)
        oit = old.iter_raw()
        nit = new.iter_raw()
        ok, orec = next(oit, _SENT)
        nk, nrec = next(nit, _SENT)
        while ok is not None or nk is not None:
            if nk is None or (ok is not None and ok < nk):
                emit(ok, orec)
                ok, orec = next(oit, _SENT)
            elif ok is None or nk < ok:
                emit(nk, nrec)
                nk, nrec = next(nit, _SENT)
            else:
                ot, nt = orec[:4], nrec[:4]
                if ot == INIT_TAG and nt == LIVE_TAG:
                    emit(nk, INIT_TAG + nrec[4:])
                elif ot == INIT_TAG and nt == DEAD_TAG:
                    pass  # annihilated
                elif ot == DEAD_TAG and nt == INIT_TAG:
                    emit(nk, LIVE_TAG + nrec[4:])
                else:
                    emit(nk, nrec)
                ok, orec = next(oit, _SENT)
                nk, nrec = next(nit, _SENT)
        return writer.finalize()
    except BaseException:
        writer.abort()
        raise
