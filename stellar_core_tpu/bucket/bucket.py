"""Content-addressed buckets and the exact-semantics merge.

Reference: src/bucket/Bucket.{h,cpp} (LiveBucket), BucketInputIterator /
BucketOutputIterator, and the CAP-20 INIT/LIVE/DEAD merge logic in
src/bucket/BucketBase.cpp — merge (modern protocol >= 12 semantics, no
shadow buckets).

A bucket is an immutable, key-sorted sequence of BucketEntry XDR records,
headed by a METAENTRY carrying the protocol version; its identity is the
SHA-256 of the serialized stream (content addressing, same scheme the
reference uses for bucket files).
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional, Tuple

from ..crypto.sha import SHA256
from ..xdr import (BucketEntry, BucketEntryType, BucketMetadata, LedgerEntry,
                   LedgerKey, ledger_entry_key)

_BE = BucketEntry._xdr_adapter()


def _key_bytes(key: LedgerKey) -> bytes:
    """Sort key: LedgerKey XDR bytes.  Type discriminant leads, then the
    per-type fields in declaration order — matches the reference's
    LedgerEntryIdCmp grouping (src/bucket/LedgerCmp.h) for classic types."""
    return key.to_xdr()


def entry_sort_key(be: BucketEntry) -> bytes:
    if be.switch == BucketEntryType.DEADENTRY:
        return _key_bytes(be.value)
    if be.switch == BucketEntryType.METAENTRY:
        return b""  # meta sorts first
    return _key_bytes(ledger_entry_key(be.value))


class Bucket:
    """Immutable sorted bucket. entries EXCLUDE the meta entry; protocol
    version is carried separately and re-serialized as METAENTRY."""

    __slots__ = ("entries", "protocol_version", "_hash", "_index", "_keys",
                 "_packed")

    def __init__(self, entries: List[BucketEntry], protocol_version: int,
                 keys: Optional[List[bytes]] = None,
                 packed: Optional[List[Optional[bytes]]] = None):
        self.entries = entries
        self.protocol_version = protocol_version
        self._hash: Optional[bytes] = None
        self._index = None
        self._keys = keys  # cached sort keys, aligned with entries
        # cached BucketEntry XDR bytes, aligned with entries (None holes
        # fill lazily).  Entries are shared objects across a merge chain,
        # so propagating the packed bytes through merges means each entry
        # is packed ONCE per lifetime instead of once per bucket hash —
        # bucket.hash() re-packs were the top pack call site in the replay
        # profile (25k packs / 191 ledgers).  Memory: the ~150-300 B XDR
        # slice per entry is a fraction of the decoded entry's Python
        # object graph, and the bytes are SHARED across the merge chain
        # (not one copy per bucket).
        self._packed = packed

    def sort_keys(self) -> List[bytes]:
        """Per-entry sort keys, computed once per immutable bucket (the
        merge path walks every level's keys each spill — recomputing the
        key XDR per merge was a top replay cost)."""
        if self._keys is None:
            self._keys = [entry_sort_key(e) for e in self.entries]
        return self._keys

    def packed_entries(self) -> List[bytes]:
        """Per-entry serialized XDR, computed once per entry lifetime
        (propagated through merges; deserialize captures wire slices)."""
        if self._packed is None:
            self._packed = [_BE.pack(e) for e in self.entries]
        else:
            pk = self._packed
            for i, p in enumerate(pk):
                if p is None:
                    pk[i] = _BE.pack(self.entries[i])
        return self._packed

    def index(self):
        """The bucket's point-lookup index, built lazily once per immutable
        bucket (reference: BucketManager::maybeBuildIndex)."""
        if self._index is None:
            from .index import BucketIndex
            self._index = BucketIndex(self.sort_keys())
        return self._index

    def find(self, key_bytes: bytes) -> Optional[BucketEntry]:
        """Indexed lookup by LedgerKey XDR bytes (entries are sorted by
        exactly this)."""
        i = self.index().find(key_bytes)
        return self.entries[i] if i is not None else None

    @staticmethod
    def empty() -> "Bucket":
        return Bucket([], 0)

    def is_empty(self) -> bool:
        return not self.entries

    def hash(self) -> bytes:
        """SHA-256 over the serialized stream (meta + entries); empty bucket
        hashes to 32 zero bytes (reference: Bucket::getHash of empty)."""
        if self._hash is None:
            if not self.entries:
                self._hash = b"\x00" * 32
            else:
                h = SHA256()
                h.add(_BE.pack(BucketEntry.metaEntry(
                    BucketMetadata(ledgerVersion=self.protocol_version))))
                for p in self.packed_entries():
                    h.add(p)
                self._hash = h.finish()
        return self._hash

    def serialize(self) -> bytes:
        if not self.entries:
            return b""
        meta = _BE.pack(BucketEntry.metaEntry(
            BucketMetadata(ledgerVersion=self.protocol_version)))
        return meta + b"".join(self.packed_entries())

    @staticmethod
    def deserialize(data: bytes) -> "Bucket":
        entries: List[BucketEntry] = []
        packed: List[Optional[bytes]] = []
        off = 0
        protocol = 0
        while off < len(data):
            start = off
            e, off = _BE.unpack_from_fast(data, off)
            if e.switch == BucketEntryType.METAENTRY:
                protocol = e.value.ledgerVersion
            else:
                entries.append(e)
                packed.append(data[start:off])   # wire slice: free cache
        return Bucket(entries, protocol, packed=packed)

    @staticmethod
    def fresh(protocol_version: int, init_entries: Iterable[LedgerEntry],
              live_entries: Iterable[LedgerEntry],
              dead_keys: Iterable[LedgerKey]) -> "Bucket":
        """One ledger's output as a bucket (reference: LiveBucket::fresh).
        Within a single batch a key appears at most once per class; the
        LedgerManager guarantees init/live/dead disjointness."""
        tagged: List[Tuple[bytes, BucketEntry]] = []
        for e in init_entries:
            be = BucketEntry.initEntry(e)
            tagged.append((entry_sort_key(be), be))
        for e in live_entries:
            be = BucketEntry.liveEntry(e)
            tagged.append((entry_sort_key(be), be))
        for k in dead_keys:
            be = BucketEntry.deadEntry(k)
            tagged.append((entry_sort_key(be), be))
        tagged.sort(key=lambda t: t[0])
        return Bucket([e for _, e in tagged], protocol_version,
                      keys=[k for k, _ in tagged])

    def __iter__(self):
        return iter(self.entries)


def _is_init(be: BucketEntry) -> bool:
    return be.switch == BucketEntryType.INITENTRY


def _is_live(be: BucketEntry) -> bool:
    return be.switch == BucketEntryType.LIVEENTRY


def _is_dead(be: BucketEntry) -> bool:
    return be.switch == BucketEntryType.DEADENTRY


def merge_buckets(old: Bucket, new: Bucket, keep_tombstones: bool = True,
                  protocol_version: Optional[int] = None) -> Bucket:
    """Merge two key-sorted buckets, new entries shadowing old.

    CAP-20 pair rules (reference: BucketBase::merge + mergeCasesWithEqualKeys,
    protocol >= 12 semantics):
      (INIT, LIVE) -> INIT carrying the live value
      (INIT, DEAD) -> annihilate (both dropped)
      (DEAD, INIT) -> LIVE carrying the init value
      (LIVE, DEAD) -> DEAD tombstone
      otherwise    -> the newer entry
    keep_tombstones=False (bottom level): DEADs are dropped and INITs decay
    to LIVE (no deeper state left to annihilate against).
    """
    proto = protocol_version if protocol_version is not None else max(
        old.protocol_version, new.protocol_version)
    out: List[BucketEntry] = []
    out_keys: List[bytes] = []
    out_packed: List[Optional[bytes]] = []

    def emit(be: BucketEntry, key: bytes, pb: Optional[bytes] = None):
        """pb: the entry's cached XDR bytes when it passes through
        UNCHANGED from an input bucket (None for merge-constructed
        entries — packed lazily if/when the output is hashed)."""
        if _is_dead(be):
            if keep_tombstones:
                out.append(be)
                out_keys.append(key)
                out_packed.append(pb)
        elif _is_init(be) and not keep_tombstones:
            out.append(BucketEntry.liveEntry(be.value))
            out_keys.append(key)
            out_packed.append(None)   # re-tagged: bytes differ
        else:
            out.append(be)
            out_keys.append(key)
            out_packed.append(pb)

    i = j = 0
    o, n = old.entries, new.entries
    o_keys = old.sort_keys()
    n_keys = new.sort_keys()
    o_pk = old._packed
    n_pk = new._packed

    def opb(i):
        return o_pk[i] if o_pk is not None else None

    def npb(j):
        return n_pk[j] if n_pk is not None else None

    while i < len(o) or j < len(n):
        if j >= len(n):
            emit(o[i], o_keys[i], opb(i)); i += 1
            continue
        if i >= len(o):
            emit(n[j], n_keys[j], npb(j)); j += 1
            continue
        ko, kn = o_keys[i], n_keys[j]
        if ko < kn:
            emit(o[i], ko, opb(i)); i += 1
        elif kn < ko:
            emit(n[j], kn, npb(j)); j += 1
        else:
            oe, ne = o[i], n[j]
            pb = npb(j)
            i += 1
            j += 1
            if _is_init(oe) and _is_live(ne):
                emit(BucketEntry.initEntry(ne.value), kn)
            elif _is_init(oe) and _is_dead(ne):
                pass  # annihilated
            elif _is_dead(oe) and _is_init(ne):
                emit(BucketEntry.liveEntry(ne.value), kn)
            else:
                emit(ne, kn, pb)
    return Bucket(out, proto, keys=out_keys, packed=out_packed)
