"""SignatureChecker — THE TPU offload seam (BASELINE.json north star #1).

Reference: src/transactions/SignatureChecker.{h,cpp} — given a tx's signature
payload hash and decorated signatures, answer per-source-account threshold
checks (checkSignature) with hint matching and used-signature tracking, then
checkAllSignaturesUsed.

Ed25519 verdicts flow through crypto.keys.verify_sig, which consults the
process verify cache first; the TPU batch path (accel) pre-verifies a whole
work unit and seeds that cache, so this class is oblivious to where the
verdict was computed — exactly the seam the reference comment promises
("call sites stay byte-identical").
"""

from __future__ import annotations

from typing import List, Sequence

from .. import xdr as X
from ..crypto import keys
from ..crypto.sha import sha256


class SignatureChecker:
    def __init__(self, protocol_version: int, content_hash: bytes,
                 signatures: Sequence[X.DecoratedSignature]):
        self.protocol_version = protocol_version
        self.content_hash = content_hash
        self.signatures = list(signatures)
        self.used = [False] * len(self.signatures)

    def check_signature(self, signers: List[X.Signer],
                        needed_weight: int) -> bool:
        """Accumulate weight from signatures matching `signers` until
        needed_weight is met.  Marks used signatures.  Signer kinds:
        ed25519 (verify against content hash), preAuthTx (sig ignored; the
        signer key IS the tx hash), hashX (sha256(sig) == key)."""
        total = 0
        # preAuthTx signers contribute without consuming a signature
        for signer in signers:
            if signer.key.switch == X.SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX:
                if signer.key.value == self.content_hash:
                    total += signer.weight
                    if total > 0 and total >= needed_weight:
                        return True
        # NOTE: a used signature is NOT consumed — one signature may satisfy
        # the tx-level check and several ops.  `used` only records that it
        # was useful at least once (checkAllSignaturesUsed's question).
        for i, dsig in enumerate(self.signatures):
            for signer in signers:
                kt = signer.key.switch
                if kt == X.SignerKeyType.SIGNER_KEY_TYPE_ED25519:
                    pk_bytes = signer.key.value
                    if dsig.hint != pk_bytes[28:32]:
                        continue
                    if not keys.verify_sig(keys.PublicKey(pk_bytes),
                                           dsig.signature, self.content_hash):
                        continue
                elif kt == X.SignerKeyType.SIGNER_KEY_TYPE_HASH_X:
                    if dsig.hint != signer.key.value[28:32]:
                        continue
                    if sha256(dsig.signature) != signer.key.value:
                        continue
                else:
                    continue
                self.used[i] = True
                total += signer.weight
                break
            if total > 0 and total >= needed_weight:
                return True
        # Zero accumulated weight never authorizes — a threshold of 0 (the
        # default account thresholds are [1,0,0,0]) still demands at least
        # one valid signature; otherwise unsigned txs would pass and
        # master-weight-0 "locked" accounts would unlock.
        return False

    def check_all_signatures_used(self) -> bool:
        return all(self.used)
