"""TransactionFrame: validation, fee/seq processing, and apply.

Reference: src/transactions/TransactionFrame.{h,cpp},
FeeBumpTransactionFrame.{h,cpp}, TransactionFrameBase::makeTransactionFromWire.
Protocol level: current (23) classic semantics with version gates at the
reference's introduction boundaries (muxed accounts + fee bumps v13,
CAP-21 preconditions v19; per-op gates via MIN_PROTOCOL_VERSION in
operations.py) — exercised by the for_all_versions test sweep.

Apply pipeline (mirrors §3.2 of SURVEY.md):
  process_fee_seq_num()  — charge fee, consume seqNum (before any op runs)
  apply()                — signature checks, per-op checkValid+doApply inside
                           a nested LedgerTxn, all-or-nothing rollback
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from .. import xdr as X
from ..crypto import keys
from ..crypto.sha import sha256
from ..ledger.ledger_txn import LedgerTxn
from .signature_checker import SignatureChecker
from . import utils
from .utils import (THRESHOLD_HIGH, THRESHOLD_LOW, THRESHOLD_MED,
                    load_account)

MAX_SEQ_NUM = 2 ** 63 - 1

SOROBAN_PROTOCOL_VERSION = 20

_SOROBAN_OP_TYPES = frozenset((
    X.OperationType.INVOKE_HOST_FUNCTION,
    X.OperationType.EXTEND_FOOTPRINT_TTL,
    X.OperationType.RESTORE_FOOTPRINT,
))


class TransactionFrame:
    """Wraps a TransactionEnvelope (v0 normalized to v1 view)."""

    def __init__(self, network_id: bytes, envelope: X.TransactionEnvelope):
        if envelope.switch == X.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
            raise ValueError("use FeeBumpTransactionFrame")
        self.network_id = network_id
        self.envelope = envelope
        self._hash: Optional[bytes] = None
        self._src_id: Optional[X.AccountID] = None

    # -- wire/creation ------------------------------------------------------
    @staticmethod
    def make_from_wire(network_id: bytes,
                       envelope: X.TransactionEnvelope) -> "TransactionFrameBase":
        if envelope.switch == X.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
            return FeeBumpTransactionFrame(network_id, envelope)
        return TransactionFrame(network_id, envelope)

    # -- views over v0/v1 ---------------------------------------------------
    @property
    def is_v0(self) -> bool:
        return self.envelope.switch == X.EnvelopeType.ENVELOPE_TYPE_TX_V0

    @property
    def tx(self):
        return self.envelope.value.tx

    @property
    def signatures(self) -> List[X.DecoratedSignature]:
        return self.envelope.value.signatures

    def source_account_id(self) -> X.AccountID:
        # memoized like _hash: called several times per apply (fee, seq,
        # signature, op phases) and the envelope is immutable once framed
        if self._src_id is None:
            if self.is_v0:
                self._src_id = X.AccountID.ed25519(
                    self.tx.sourceAccountEd25519)
            else:
                self._src_id = X.muxed_to_account_id(self.tx.sourceAccount)
        return self._src_id

    @property
    def operations(self) -> List[X.Operation]:
        return self.tx.operations

    @property
    def fee_bid(self) -> int:
        return self.tx.fee

    @property
    def seq_num(self) -> int:
        return self.tx.seqNum

    def time_bounds(self) -> Optional[X.TimeBounds]:
        if self.is_v0:
            return self.tx.timeBounds
        cond = self.tx.cond
        if cond.switch == X.PreconditionType.PRECOND_TIME:
            return cond.value
        if cond.switch == X.PreconditionType.PRECOND_V2:
            return cond.value.timeBounds
        return None

    # -- hashing ------------------------------------------------------------
    def _v1_tx(self) -> X.Transaction:
        """v0 envelopes hash/sign as the equivalent v1 Transaction
        (reference: TransactionFrame::getSignaturePayload builds the TX
        tagged union for both)."""
        if not self.is_v0:
            return self.tx
        t = self.tx
        return X.Transaction(
            sourceAccount=X.MuxedAccount.ed25519(t.sourceAccountEd25519),
            fee=t.fee, seqNum=t.seqNum,
            cond=(X.Preconditions.timeBounds(t.timeBounds)
                  if t.timeBounds is not None else X.Preconditions.none()),
            memo=t.memo, operations=t.operations)

    def signature_payload(self) -> bytes:
        payload = X.TransactionSignaturePayload(
            networkId=self.network_id,
            taggedTransaction=X.TransactionSignaturePayloadTaggedTransaction.tx(self._v1_tx()))
        return payload.to_xdr()

    def content_hash(self) -> bytes:
        """The transaction hash (ids history entries, preauth signers)."""
        if self._hash is None:
            self._hash = sha256(self.signature_payload())
        return self._hash

    # -- fees ---------------------------------------------------------------
    def num_operations(self) -> int:
        return len(self.operations)

    def min_fee(self, header: X.LedgerHeader) -> int:
        return self.num_operations() * header.baseFee

    def fee_charged(self, header: X.LedgerHeader) -> int:
        """min(bid, numOps*baseFee) — flat per-op pricing.  A Soroban tx
        additionally pays its declared resourceFee in full (this repo's
        model has no refundable-fee split: the declared fee IS the
        charge, reference's non-refundable portion)."""
        fee = min(self.fee_bid, self.min_fee(header))
        sd = self.soroban_data()
        if sd is not None and self.is_soroban():
            fee = min(self.fee_bid, self.min_fee(header) + int(sd.resourceFee))
        return fee

    # -- Soroban views ------------------------------------------------------
    def soroban_data(self) -> Optional[X.SorobanTransactionData]:
        """The tx ext's SorobanTransactionData, or None for classic txs."""
        if self.is_v0:
            return None
        ext = self.tx.ext
        return ext.value if ext.switch == 1 else None

    def is_soroban(self) -> bool:
        return any(op.body.switch in _SOROBAN_OP_TYPES
                   for op in self.operations)

    def _soroban_valid(self, header: X.LedgerHeader
                       ) -> Optional[X.TransactionResultCode]:
        """Soroban envelope shape + declared-resource validation
        (reference: TransactionFrame::XDRProvidesValidFee +
        checkSorobanResourceAndSetError)."""
        C = X.TransactionResultCode
        sd = self.soroban_data()
        if not self.is_soroban():
            # sorobanData on a classic tx is malformed shape
            return C.txMALFORMED if sd is not None else None
        if header.ledgerVersion < SOROBAN_PROTOCOL_VERSION:
            return C.txNOT_SUPPORTED
        if self.num_operations() != 1:
            return C.txMALFORMED      # Soroban txs carry exactly one op
        if sd is None:
            return C.txMALFORMED
        from ..soroban.config import network_config
        net = network_config()
        res = sd.resources
        fp = res.footprint
        ro = [k.to_xdr() for k in fp.readOnly]
        rw = [k.to_xdr() for k in fp.readWrite]
        if len(set(ro)) != len(ro) or len(set(rw)) != len(rw) \
                or set(ro) & set(rw):
            return C.txSOROBAN_INVALID
        if len(ro) + len(rw) > net.tx_max_read_entries \
                or len(rw) > net.tx_max_write_entries:
            return C.txSOROBAN_INVALID
        if int(res.instructions) > net.tx_max_instructions \
                or int(res.readBytes) > net.tx_max_read_bytes \
                or int(res.writeBytes) > net.tx_max_write_bytes:
            return C.txSOROBAN_INVALID
        if int(sd.resourceFee) < net.min_resource_fee(res):
            return C.txSOROBAN_INVALID
        if self.fee_bid < self.min_fee(header) + int(sd.resourceFee):
            return C.txINSUFFICIENT_FEE
        return None

    # -- validation ---------------------------------------------------------
    def _common_valid(self, ltx: LedgerTxn, close_time: int,
                      check_seq: bool) -> Optional[X.TransactionResultCode]:
        C = X.TransactionResultCode
        if self.num_operations() == 0:
            return C.txMISSING_OPERATION
        if self.num_operations() > X.MAX_OPS_PER_TX:
            return C.txMALFORMED
        header = ltx.get_header()
        # version gates run BEFORE validity windows (reference:
        # commonValidPreSeqNum's txNOT_SUPPORTED checks come first)
        cond = self._cond()
        if cond is not None and cond.switch == X.PreconditionType.PRECOND_V2 \
                and header.ledgerVersion < 19:
            return C.txNOT_SUPPORTED  # CAP-21 preconditions are v19+
        if header.ledgerVersion < 13 and self._has_muxed_account():
            return C.txNOT_SUPPORTED  # M-strkeys (CAP-27) are v13+
        tb = self.time_bounds()
        if tb is not None:
            if tb.minTime and close_time < tb.minTime:
                return C.txTOO_EARLY
            if tb.maxTime and close_time > tb.maxTime:
                return C.txTOO_LATE
        if self.fee_bid < self.min_fee(header):
            return C.txINSUFFICIENT_FEE
        soroban_code = self._soroban_valid(header)
        if soroban_code is not None:
            return soroban_code
        if self.seq_num < 0 or self.seq_num > MAX_SEQ_NUM:
            return C.txBAD_SEQ
        acc_entry = ltx.get_entry(
            X.account_key_xdr(self.source_account_id().value))
        if acc_entry is None:
            return C.txNO_ACCOUNT
        acc = acc_entry.data.value
        if check_seq and acc.seqNum + 1 != self.seq_num:
            return C.txBAD_SEQ
        if acc.balance < self.fee_charged(header):
            return C.txINSUFFICIENT_BALANCE
        return None

    def check_valid(self, ltx: LedgerTxn, close_time: int) -> X.TransactionResult:
        """Full validity check without state writes (reference:
        TransactionFrame::checkValid — the tx-queue admission path)."""
        code = self._common_valid(ltx, close_time, check_seq=True)
        if code is None:
            checker = SignatureChecker(
                ltx.get_header().ledgerVersion, self.content_hash(),
                self.signatures)
            ops = self._make_op_frames()
            op_results = []
            ok = True
            for op in ops:
                res = op.check_valid(checker, ltx)
                op_results.append(res)
                if not _op_ok(res):
                    ok = False
            if ok and not self._check_extra_signers(checker):
                code = X.TransactionResultCode.txBAD_AUTH_EXTRA
            elif ok and not checker.check_all_signatures_used():
                code = X.TransactionResultCode.txBAD_AUTH_EXTRA
            elif not ok:
                return _tx_result(self.fee_charged(ltx.get_header()),
                                  X.TransactionResultCode.txFAILED, op_results)
        if code is not None:
            return _tx_result(self.fee_charged(ltx.get_header()), code)
        return _tx_result(self.fee_charged(ltx.get_header()),
                          X.TransactionResultCode.txSUCCESS, None)

    def _cond(self):
        return None if self.is_v0 else self.tx.cond

    def _has_muxed_account(self) -> bool:
        """Any med25519 MuxedAccount in the envelope (reference:
        hasMuxedAccount over tx source, op sources and op muxed
        destinations)."""
        MUX = X.CryptoKeyType.KEY_TYPE_MUXED_ED25519

        def muxed(acct) -> bool:
            return acct is not None and acct.switch == MUX

        if muxed(self.tx.sourceAccount):
            return True
        for op in self.tx.operations:
            if muxed(op.sourceAccount):
                return True
            b = op.body.value
            for attr in ("destination", "from_"):
                v = getattr(b, attr, None)
                if v is not None and hasattr(v, "switch") and muxed(v):
                    return True
        return False

    def _check_extra_signers(self, checker: SignatureChecker) -> bool:
        cond = self._cond()
        if cond is not None and cond.switch == X.PreconditionType.PRECOND_V2:
            for sk in cond.value.extraSigners:
                if not checker.check_signature(
                        [X.Signer(key=sk, weight=1)], 1):
                    return False
        return True

    # -- fee & sequence processing (phase 1 of ledger close) ---------------
    def process_fee_seq_num(self, ltx: LedgerTxn) -> int:
        """Charge the fee into feePool and consume the sequence number.
        Runs for every tx in the set, in set order, before any tx applies
        (reference: LedgerManager::processFeesSeqNums).  A tx whose seqNum
        doesn't chain gets its fee charged but the seq NOT consumed, and
        will report txBAD_SEQ at apply (how bad-seq results appear in
        history).  Returns fee charged."""
        header = ltx.load_header()
        acc_e = load_account(ltx, self.source_account_id())
        if acc_e is None:
            self._bad_seq = True
            return 0
        acc = acc_e.data.value
        fee = min(self.fee_charged(header), max(acc.balance, 0))
        acc.balance -= fee
        if acc.seqNum + 1 == self.seq_num:
            acc.seqNum = self.seq_num
            self._bad_seq = False
        else:
            self._bad_seq = True
        header.feePool += fee
        acc_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(acc_e)
        ltx.commit_header(header)
        return fee

    # -- apply (phase 2) ----------------------------------------------------
    def process_signatures(self, checker: SignatureChecker,
                           ltx: LedgerTxn) -> bool:
        """Tx-level (low-threshold) auth of the fee source."""
        acc_e = load_account(ltx, self.source_account_id())
        if acc_e is None:
            return False
        acc = acc_e.data.value
        return check_account_signature(checker, acc, THRESHOLD_LOW)

    def apply(self, ltx: LedgerTxn, close_time: int) -> X.TransactionResult:
        """All-or-nothing apply in a nested LedgerTxn; fee was already
        charged and stays regardless of outcome."""
        header = ltx.get_header()
        checker = SignatureChecker(header.ledgerVersion, self.content_hash(),
                                   self.signatures)
        fee = self.fee_charged(header)
        C = X.TransactionResultCode

        if getattr(self, "_bad_seq", False):
            return _tx_result(fee, C.txBAD_SEQ)
        self._sponsorship_ctx = {}   # fresh Begin/End sandwich state per apply
        inner = LedgerTxn(ltx)
        try:
            code = self._common_valid(inner, close_time, check_seq=False)
            if code is not None and code != C.txBAD_SEQ:
                inner.rollback()
                return _tx_result(fee, code)
            if not self.process_signatures(checker, inner):
                inner.rollback()
                return _tx_result(fee, C.txBAD_AUTH)
            op_results: List[X.OperationResult] = []
            ok = True
            for op in self._make_op_frames():
                res_check = op.check_valid(checker, inner)
                if not _op_ok(res_check):
                    op_results.append(res_check)
                    ok = False
                    continue
                # each op applies in its OWN nested LedgerTxn, rolled back
                # on op failure (reference: applyOperations' per-op ltxOp)
                # — a mutate-then-fail path (RevokeSponsorship transfer,
                # sponsored CreateAccount UNDERFUNDED) must leave no
                # counter mutations for later ops of the same tx to see
                with LedgerTxn(inner) as op_ltx:
                    res = op.do_apply(op_ltx)
                    if _op_ok(res):
                        op_ltx.commit()
                op_results.append(res)
                if not _op_ok(res):
                    ok = False
            if ok and self._sponsorship_ctx:
                # a BeginSponsoringFutureReserves left unclosed at tx end
                # fails the whole tx (reference: TransactionFrame apply —
                # processPostApply sponsorship check, txBAD_SPONSORSHIP)
                inner.rollback()
                return _tx_result(fee, C.txBAD_SPONSORSHIP)
            if ok and not self._check_extra_signers(checker):
                inner.rollback()
                return _tx_result(fee, C.txBAD_AUTH_EXTRA)
            if ok and not checker.check_all_signatures_used():
                inner.rollback()
                return _tx_result(fee, C.txBAD_AUTH_EXTRA)
            if not ok:
                inner.rollback()
                return _tx_result(fee, C.txFAILED, op_results)
            self._remove_used_one_time_signers(inner)
            inner.commit()
            return _tx_result(fee, C.txSUCCESS, op_results)
        except Exception:
            if inner._open:
                inner.rollback()
            raise

    def _remove_used_one_time_signers(self, ltx: LedgerTxn) -> None:
        """Drop preauth-tx signers matching this tx's hash from every source
        account (reference: removeOneTimeSignerFromAllSourceAccounts),
        releasing the sponsor and keeping signerSponsoringIDs aligned when
        a removed signer was sponsored."""
        from .sponsorship import (record_signer_remove,
                                  release_signer_sponsorship, signer_sponsor)
        ids = {self.source_account_id().value: self.source_account_id()}
        for op in self.operations:
            if op.sourceAccount is not None:
                a = X.muxed_to_account_id(op.sourceAccount)
                ids[a.value] = a
        header = ltx.get_header()
        for acc_id in ids.values():
            acc_e = load_account(ltx, acc_id)
            if acc_e is None:
                continue
            acc = acc_e.data.value
            changed = False
            i = 0
            while i < len(acc.signers):
                s = acc.signers[i]
                if s.key.switch == X.SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX \
                        and s.key.value == self.content_hash():
                    sponsor_id = signer_sponsor(acc, i)
                    acc.signers = acc.signers[:i] + acc.signers[i + 1:]
                    record_signer_remove(acc, i)
                    if sponsor_id is not None:
                        release_signer_sponsorship(ltx, header, sponsor_id,
                                                   acc_e)
                    acc.numSubEntries -= 1
                    changed = True
                else:
                    i += 1
            if changed:
                ltx.update(acc_e)

    def _make_op_frames(self):
        from .operations import make_op_frame
        return [make_op_frame(self, i, op)
                for i, op in enumerate(self.operations)]


TransactionFrameBase = TransactionFrame  # alias; FeeBump subclasses below


class FeeBumpTransactionFrame(TransactionFrame):
    """Reference: src/transactions/FeeBumpTransactionFrame.{h,cpp}.
    Outer envelope charges the fee; the inner v1 tx applies with its own
    signatures.  Result wraps the inner result in txFEE_BUMP_INNER_*."""

    def __init__(self, network_id: bytes, envelope: X.TransactionEnvelope):
        assert envelope.switch == X.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP
        self.network_id = network_id
        self.envelope = envelope
        self._hash = None
        self.inner = TransactionFrame(
            network_id,
            X.TransactionEnvelope.v1(envelope.value.tx.innerTx.value))

    @property
    def tx(self):
        return self.envelope.value.tx  # FeeBumpTransaction

    @property
    def signatures(self):
        return self.envelope.value.signatures

    def source_account_id(self) -> X.AccountID:
        return X.muxed_to_account_id(self.tx.feeSource)

    @property
    def operations(self):
        return self.inner.operations

    @property
    def fee_bid(self) -> int:
        return self.tx.fee

    @property
    def seq_num(self) -> int:
        return self.inner.seq_num

    def time_bounds(self):
        return self.inner.time_bounds()

    def soroban_data(self) -> Optional[X.SorobanTransactionData]:
        return self.inner.soroban_data()

    def signature_payload(self) -> bytes:
        payload = X.TransactionSignaturePayload(
            networkId=self.network_id,
            taggedTransaction=X.TransactionSignaturePayloadTaggedTransaction.feeBump(self.tx))
        return payload.to_xdr()

    def num_operations(self) -> int:
        return self.inner.num_operations() + 1

    def process_fee_seq_num(self, ltx: LedgerTxn) -> int:
        """Fee from the fee source; seqNum consumed on the INNER source."""
        header = ltx.load_header()
        fee_acc_e = load_account(ltx, self.source_account_id())
        if fee_acc_e is None:
            return 0
        fee_acc = fee_acc_e.data.value
        fee = min(self.fee_charged(header), max(fee_acc.balance, 0))
        fee_acc.balance -= fee
        header.feePool += fee
        fee_acc_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(fee_acc_e)
        inner_acc_e = load_account(ltx, self.inner.source_account_id())
        if inner_acc_e is not None:
            inner_acc_e.data.value.seqNum = self.inner.seq_num
            inner_acc_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(inner_acc_e)
        ltx.commit_header(header)
        return fee

    def check_valid(self, ltx: LedgerTxn, close_time: int) -> X.TransactionResult:
        C = X.TransactionResultCode
        header = ltx.get_header()
        fee = self.fee_charged(header)
        if header.ledgerVersion < 13:
            # fee bumps arrived in protocol 13 (CAP-15)
            return _tx_result(fee, C.txNOT_SUPPORTED)
        if self.fee_bid < self.min_fee(header):
            return _tx_result(fee, C.txINSUFFICIENT_FEE)
        acc_entry = ltx.get_entry(
            X.account_key_xdr(self.source_account_id().value))
        if acc_entry is None:
            return _tx_result(fee, C.txNO_ACCOUNT)
        checker = SignatureChecker(header.ledgerVersion, self.content_hash(),
                                   self.signatures)
        if not check_account_signature(
                checker, acc_entry.data.value, THRESHOLD_LOW) \
                or not checker.check_all_signatures_used():
            return _tx_result(fee, C.txBAD_AUTH)
        inner_res = self.inner.check_valid(ltx, close_time)
        code = (C.txFEE_BUMP_INNER_SUCCESS
                if inner_res.result.switch == C.txSUCCESS
                else C.txFEE_BUMP_INNER_FAILED)
        return _fee_bump_result(fee, code, self.inner.content_hash(), inner_res)

    def apply(self, ltx: LedgerTxn, close_time: int) -> X.TransactionResult:
        C = X.TransactionResultCode
        header = ltx.get_header()
        fee = self.fee_charged(header)
        if header.ledgerVersion < 13:
            return _tx_result(fee, C.txNOT_SUPPORTED)  # CAP-15 is v13+
        checker = SignatureChecker(header.ledgerVersion, self.content_hash(),
                                   self.signatures)
        acc_e = load_account(ltx, self.source_account_id())
        if acc_e is None or not check_account_signature(
                checker, acc_e.data.value, THRESHOLD_LOW) \
                or not checker.check_all_signatures_used():
            return _fee_bump_result(
                fee, C.txFEE_BUMP_INNER_FAILED, self.inner.content_hash(),
                _tx_result(0, C.txBAD_AUTH))
        inner_res = self.inner.apply(ltx, close_time)
        code = (C.txFEE_BUMP_INNER_SUCCESS
                if inner_res.result.switch == C.txSUCCESS
                else C.txFEE_BUMP_INNER_FAILED)
        return _fee_bump_result(fee, code, self.inner.content_hash(), inner_res)


# -- helpers ---------------------------------------------------------------

def check_account_signature(checker: SignatureChecker, acc: X.AccountEntry,
                            threshold_level: int) -> bool:
    """Master key + signers against the account's threshold at `level`."""
    needed = utils.threshold_level_value(acc, threshold_level)
    signers = list(acc.signers)
    master_weight = utils.threshold_level_value(acc, utils.THRESHOLD_MASTER_WEIGHT)
    if master_weight > 0:
        signers.append(X.Signer(
            key=X.SignerKey.ed25519(acc.accountID.value), weight=master_weight))
    return checker.check_signature(signers, needed)


def _op_ok(res: X.OperationResult) -> bool:
    if res.switch != X.OperationResultCode.opINNER:
        return False
    return res.value.value.switch == 0  # per-op SUCCESS code is always 0


def _tx_result(fee: int, code: X.TransactionResultCode,
               op_results: Optional[List[X.OperationResult]] = None
               ) -> X.TransactionResult:
    C = X.TransactionResultCode
    if code == C.txSUCCESS:
        rr = X.TransactionResultResult.results(op_results or [])
    elif code == C.txFAILED:
        rr = X.TransactionResultResult(C.txFAILED, op_results or [])
    else:
        rr = X.TransactionResultResult(code)
    return X.TransactionResult(feeCharged=fee, result=rr)


def _fee_bump_result(fee: int, code: X.TransactionResultCode,
                     inner_hash: bytes,
                     inner: X.TransactionResult) -> X.TransactionResult:
    inner_result = X.InnerTransactionResult(
        feeCharged=inner.feeCharged,
        result=X.InnerTransactionResultResult(
            inner.result.switch, inner.result.value))
    pair = X.InnerTransactionResultPair(
        transactionHash=inner_hash, result=inner_result)
    return X.TransactionResult(
        feeCharged=fee,
        result=X.TransactionResultResult(code, pair))
