"""SponsorshipUtils — CAP-33 sponsored-reserve bookkeeping.

Reference: src/transactions/SponsorshipUtils.{h,cpp} —
createEntryWithPossibleSponsorship / removeEntryWithPossibleSponsorship /
createSignerWithPossibleSponsorship / removeSignerWithPossibleSponsorship,
computeMultiplier, canEstablishEntrySponsorship, and the
establish/transfer/remove primitives the RevokeSponsorship op builds on.

A transaction-scoped sandwich (BeginSponsoringFutureReserves(A) by S ...
EndSponsoringFutureReserves by A) makes S the sponsor of every reserve
created FOR account A while it is active: new ledger entries owned by A
carry ``ext.v1.sponsoringID = S`` and new signers of A record S in the
account's ``signerSponsoringIDs`` slot aligned with the signer list.
Counts: S.numSponsoring += mult, A.numSponsored += mult, where mult is 2
for an account entry (its two base reserves), #claimants for a claimable
balance and 1 otherwise.  The owner's minimum balance
(2 + numSubEntries + numSponsoring - numSponsored) * baseReserve is then
unchanged by the new subentry — the sponsor's is what grows.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .. import xdr as X
from . import utils
from .utils import (_ensure_acc_ext_v2, account_liabilities, load_account,
                    num_sponsored, num_sponsoring)

UINT32_MAX = 2 ** 32 - 1

# SponsorshipResult (reference: SponsorshipUtils.h enum class SponsorshipResult)
SUCCESS = 0
LOW_RESERVE = 1
TOO_MANY_SPONSORING = 2
TOO_MANY_SPONSORED = 3


def compute_multiplier(entry: X.LedgerEntry) -> int:
    """Reserve units an entry pins (reference: computeMultiplier): 2 for
    an account (its two base reserves), #claimants for a claimable
    balance, 2 for a pool-share trustline (CAP-38 double subentry), else 1."""
    t = entry.data.switch
    if t == X.LedgerEntryType.ACCOUNT:
        return 2
    if t == X.LedgerEntryType.CLAIMABLE_BALANCE:
        return len(entry.data.value.claimants)
    if t == X.LedgerEntryType.TRUSTLINE and \
            entry.data.value.asset.switch == X.AssetType.ASSET_TYPE_POOL_SHARE:
        return 2
    return 1


def active_sponsor(tx_frame, account_id: X.AccountID) -> Optional[X.AccountID]:
    """The account sponsoring future reserves of `account_id` in this tx,
    if a Begin/End sandwich is currently open for it."""
    ctx = getattr(tx_frame, "_sponsorship_ctx", None)
    if not ctx:
        return None
    sponsor_xdr = ctx.get(account_id.to_xdr())
    if sponsor_xdr is None:
        return None
    return X.AccountID.from_xdr(sponsor_xdr)


def _sponsor_can_take(header: X.LedgerHeader, sponsor: X.AccountEntry,
                      mult: int) -> int:
    """Can `sponsor` take on `mult` more sponsored reserve units?
    (reference: canEstablishEntrySponsorship sponsor-side checks)."""
    if num_sponsoring(sponsor) > UINT32_MAX - mult:
        return TOO_MANY_SPONSORING
    need = (2 + sponsor.numSubEntries + num_sponsoring(sponsor) + mult
            - num_sponsored(sponsor)) * header.baseReserve
    _, selling = account_liabilities(sponsor)
    if sponsor.balance < need + selling:
        return LOW_RESERVE
    return SUCCESS


def _sponsored_can_take(acc: Optional[X.AccountEntry], mult: int) -> int:
    if acc is not None and num_sponsored(acc) > UINT32_MAX - mult:
        return TOO_MANY_SPONSORED
    return SUCCESS


def establish_sponsorship(ltx, header: X.LedgerHeader,
                          sponsor_id: X.AccountID,
                          owner_entry: Optional[X.LedgerEntry],
                          mult: int) -> int:
    """Core counter move: sponsor takes `mult` reserve units (reserve +
    overflow checks), the owner — when there is one — records them as
    sponsored.  The sponsor account is loaded/updated HERE (callers must
    not hold a copy of it); `owner_entry` is mutated in place and updated
    by the caller."""
    sp_e = load_account(ltx, sponsor_id)
    if sp_e is None:
        # unreachable: AccountMerge rejects IS_SPONSOR for any party to an
        # open sandwich (v14+), and merge is the only way an account
        # leaves the ledger — a missing sponsor here means corrupt state
        raise RuntimeError("sandwich sponsor missing from the ledger")
    sponsor = sp_e.data.value
    code = _sponsor_can_take(header, sponsor, mult)
    if code != SUCCESS:
        return code
    owner = owner_entry.data.value if owner_entry is not None else None
    code = _sponsored_can_take(owner, mult)
    if code != SUCCESS:
        return code
    _ensure_acc_ext_v2(sponsor).numSponsoring = num_sponsoring(sponsor) + mult
    sp_e.lastModifiedLedgerSeq = header.ledgerSeq
    ltx.update(sp_e)
    if owner is not None:
        _ensure_acc_ext_v2(owner).numSponsored = num_sponsored(owner) + mult
    return SUCCESS


def establish_entry_sponsorship(ltx, header: X.LedgerHeader,
                                entry: X.LedgerEntry,
                                sponsor_id: X.AccountID,
                                owner_entry: Optional[X.LedgerEntry]) -> int:
    """Record sponsor_id as the sponsor of `entry` and bump the counters.
    `owner_entry` is the (loaded, to-be-updated-by-caller) account that owns
    the reserve, or None for claimable balances."""
    code = establish_sponsorship(ltx, header, sponsor_id, owner_entry,
                                 compute_multiplier(entry))
    if code == SUCCESS:
        entry.ext = X.LedgerEntryExt.v1(X.LedgerEntryExtensionV1(
            sponsoringID=sponsor_id))
    return code


def entry_sponsor(entry: X.LedgerEntry) -> Optional[X.AccountID]:
    if entry.ext.switch == 1:
        return entry.ext.value.sponsoringID
    return None


def release_entry_sponsorship(ltx, header: X.LedgerHeader,
                              entry: X.LedgerEntry,
                              owner_entry: Optional[X.LedgerEntry]) -> None:
    """Undo establish_entry_sponsorship when a sponsored entry leaves the
    ledger (reference: removeEntryWithPossibleSponsorship).  No reserve
    check — releasing only ever frees balance.  The caller updates
    owner_entry; the sponsor is updated here (no-op when unsponsored)."""
    sponsor_id = entry_sponsor(entry)
    if sponsor_id is None:
        return
    mult = compute_multiplier(entry)
    sp_e = load_account(ltx, sponsor_id)
    if sp_e is not None:
        sponsor = sp_e.data.value
        if num_sponsoring(sponsor) < mult:
            raise RuntimeError("sponsoring count underflow")
        _ensure_acc_ext_v2(sponsor).numSponsoring = \
            num_sponsoring(sponsor) - mult
        sp_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(sp_e)
    owner = owner_entry.data.value if owner_entry is not None else None
    if owner is not None:
        if num_sponsored(owner) < mult:
            raise RuntimeError("sponsored count underflow")
        _ensure_acc_ext_v2(owner).numSponsored = num_sponsored(owner) - mult


def create_entry_with_possible_sponsorship(
        ltx, header: X.LedgerHeader, tx_frame, entry: X.LedgerEntry,
        owner_entry: Optional[X.LedgerEntry],
        owner_id: Optional[X.AccountID]) -> Tuple[int, bool]:
    """If a sandwich is active for `owner_id`, sponsor the new entry.
    Returns (SponsorshipResult, sponsored?).  Count/reserve changes for the
    OWNER's numSubEntries are the caller's business (they differ per op);
    this handles only the sponsorship side."""
    if owner_id is None:
        return SUCCESS, False
    sponsor_id = active_sponsor(tx_frame, owner_id)
    if sponsor_id is None:
        return SUCCESS, False
    code = establish_entry_sponsorship(ltx, header, entry, sponsor_id,
                                       owner_entry)
    return code, code == SUCCESS


# --- signer sponsorship ----------------------------------------------------
#
# Signers have no LedgerEntry of their own: the sponsor is recorded in the
# owning account's AccountEntryExtensionV2.signerSponsoringIDs, the list
# kept aligned index-for-index with `signers` (reference: the
# signerSponsoringIDs invariants in AccountEntry).


def signer_sponsoring_ids(acc: X.AccountEntry) -> Optional[list]:
    v2 = utils._acc_ext_v2(acc)
    return v2.signerSponsoringIDs if v2 is not None else None


def _aligned_sponsoring_ids(acc: X.AccountEntry) -> list:
    """The account's signerSponsoringIDs, materialized (ext upgraded to v2)
    and padded to len(signers) with None."""
    v2 = _ensure_acc_ext_v2(acc)
    ids = list(v2.signerSponsoringIDs)
    while len(ids) < len(acc.signers):
        ids.append(None)
    return ids


def record_signer_insert(acc: X.AccountEntry, index: int,
                         sponsor_id: Optional[X.AccountID]) -> None:
    """Keep signerSponsoringIDs aligned after inserting a signer at
    `index`.  Only materializes the v2 extension when there is something to
    record — an unsponsored insert on a v0/v1 account stays v0/v1, so
    pre-sponsorship ledger hashes are unchanged."""
    if sponsor_id is None and utils._acc_ext_v2(acc) is None:
        return
    ids = _aligned_sponsoring_ids(acc)
    ids.insert(index, sponsor_id)
    # the new signer was already inserted into acc.signers by the caller
    del ids[len(acc.signers):]
    utils._acc_ext_v2(acc).signerSponsoringIDs = ids


def record_signer_remove(acc: X.AccountEntry, index: int) -> None:
    """Drop the sponsoring slot of the signer removed at `index` (the
    caller already removed it from acc.signers)."""
    v2 = utils._acc_ext_v2(acc)
    if v2 is None:
        return
    ids = list(v2.signerSponsoringIDs)
    if index < len(ids):
        del ids[index]
    v2.signerSponsoringIDs = ids


def signer_sponsor(acc: X.AccountEntry, index: int) -> Optional[X.AccountID]:
    v2 = utils._acc_ext_v2(acc)
    if v2 is None or index >= len(v2.signerSponsoringIDs):
        return None
    return v2.signerSponsoringIDs[index]


def establish_signer_sponsorship(ltx, header: X.LedgerHeader,
                                 sponsor_id: X.AccountID,
                                 owner_entry: X.LedgerEntry) -> int:
    """Sponsor-side + owner-side counters for one signer (mult=1); the
    sponsoring slot itself is recorded by the caller (record_signer_insert
    or the revoke op's slot write)."""
    return establish_sponsorship(ltx, header, sponsor_id, owner_entry, 1)


def release_signer_sponsorship(ltx, header: X.LedgerHeader,
                               sponsor_id: X.AccountID,
                               owner_entry: X.LedgerEntry) -> None:
    sp_e = load_account(ltx, sponsor_id)
    if sp_e is not None:
        sponsor = sp_e.data.value
        if num_sponsoring(sponsor) < 1:
            raise RuntimeError("sponsoring count underflow (signer)")
        _ensure_acc_ext_v2(sponsor).numSponsoring = num_sponsoring(sponsor) - 1
        sp_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(sp_e)
    owner = owner_entry.data.value
    if num_sponsored(owner) < 1:
        raise RuntimeError("sponsored count underflow (signer)")
    _ensure_acc_ext_v2(owner).numSponsored = num_sponsored(owner) - 1


def owner_can_afford(header: X.LedgerHeader, acc: X.AccountEntry,
                     mult: int) -> bool:
    """After taking back `mult` reserve units (numSponsored -= mult), does
    the owner's balance still cover its minimum?  (reference: the
    LOW_RESERVE arm of removeSponsorship in RevokeSponsorshipOpFrame)."""
    need = (2 + acc.numSubEntries + num_sponsoring(acc)
            - (num_sponsored(acc) - mult)) * header.baseReserve
    _, selling = account_liabilities(acc)
    return acc.balance >= need + selling
