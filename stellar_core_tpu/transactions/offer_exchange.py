"""OfferExchange: the order-book crossing engine + liquidity-pool swaps.

Reference: src/transactions/OfferExchange.{h,cpp} — exchangeV10,
adjustOffer, crossOfferV10, convertWithOffersAndPools, getPoolExchange —
and src/transactions/ManageOfferOpFrameBase.cpp liabilities handling.

Terminology follows the reference: for a resting (maker) offer, **wheat**
is the asset the offer sells and **sheep** the asset it buys; its Price is
sheep-per-wheat as the rational n/d.  The taker receives wheat and sends
sheep.  All amount math is exact integer arithmetic (python ints stand in
for the reference's uint128 bigMultiply/bigDivide).

Price-error thresholds (reference: OfferExchange.cpp —
applyPriceErrorThresholds + checkPriceErrorBound): integer rounding can
make the realized price sheepSend/wheatReceive deviate from the offer
price n/d; near dust scale the relative error can be large enough to
betray one side.  The reference cancels an exchange whose relative price
error exceeds 1%, with the upper bound waived when favoring the resting
(wheat) offer cannot betray anyone (path strict-receive, where the path's
sendMax bounds the sender's cost); path strict-send keeps the sent amount
exact and is guarded by the path-level destMin check instead of a
per-exchange bound.  Implemented exactly in integers in
`check_price_error_bound` / `apply_price_error_thresholds` below
(adversarially tested near dust in tests/test_offer_exchange.py).
"""

from __future__ import annotations

from dataclasses import dataclass, field as dc_field
from typing import Callable, List, Optional, Tuple

from .. import xdr as X
from ..ledger.ledger_txn import LedgerTxn
from . import utils
from .utils import (INT64_MAX, account_key, account_liabilities, add_balance,
                    add_num_entries, asset_to_trustline_asset,
                    available_balance, is_authorized,
                    is_authorized_to_maintain_liabilities, is_issuer,
                    load_account, load_trustline, minimum_balance,
                    trustline_key, trustline_liabilities)

ROUND_NORMAL = 0
ROUND_PATH_STRICT_RECEIVE = 1
ROUND_PATH_STRICT_SEND = 2

# constant-product pool fee: 30 basis points (reference: CAP-38,
# LiquidityPoolConstantProduct maxFee — getPoolFeeBps)
POOL_FEE_BPS = 30


# --------------------------------------------------------------------------
# exact rational helpers

def price_valid(p: X.Price) -> bool:
    return p.n > 0 and p.d > 0


def price_cmp(a: X.Price, b: X.Price) -> int:
    """sign(a - b) by exact cross-multiplication (reference compares prices
    as int128 products; float math is forbidden in consensus code)."""
    lhs = a.n * b.d
    rhs = b.n * a.d
    return (lhs > rhs) - (lhs < rhs)


def _div_round(num: int, den: int, round_up: bool) -> int:
    assert den > 0
    q, r = divmod(num, den)
    if round_up and r:
        q += 1
    return q


@dataclass
class ExchangeResultV10:
    """Reference: OfferExchange.h — ExchangeResultV10."""
    wheat_stays: bool
    num_wheat_received: int
    num_sheep_send: int


def check_price_error_bound(price: X.Price, wheat_receive: int,
                            sheep_send: int, can_favor_wheat: bool) -> bool:
    """Relative price error of the realized exchange vs the offer price
    must be within 1% (reference: OfferExchange.cpp —
    checkPriceErrorBound, exact int128 cross-multiplication there, exact
    python ints here).

    With k = wheatReceive * n and v = sheepSend * d, the realized price
    sheepSend/wheatReceive relative to n/d is v/k, so the bound
    |v - k| <= k/100 is checked as 99*k <= 100*v <= 101*k.
    can_favor_wheat waives the upper bound: overpaying the resting offer
    betrays nobody when the caller bounds total cost elsewhere (path
    strict-receive's sendMax)."""
    k = wheat_receive * price.n
    v = sheep_send * price.d
    if 100 * v < 99 * k:
        return False
    if not can_favor_wheat and 100 * v > 101 * k:
        return False
    return True


def apply_price_error_thresholds(price: X.Price, wheat_receive: int,
                                 sheep_send: int, wheat_stays: bool,
                                 rounding: int) -> ExchangeResultV10:
    """Cancel an exchange whose realized price deviates beyond the error
    bound, and never let one leg round to zero while the other pays
    (reference: OfferExchange.cpp — applyPriceErrorThresholds).  Path
    strict-send has no per-exchange bound: sheepSend is exact and the
    path-level destMin check is the guard."""
    if wheat_receive > 0 and sheep_send > 0:
        if rounding == ROUND_NORMAL and not check_price_error_bound(
                price, wheat_receive, sheep_send, can_favor_wheat=False):
            wheat_receive = sheep_send = 0
        elif rounding == ROUND_PATH_STRICT_RECEIVE and \
                not check_price_error_bound(price, wheat_receive, sheep_send,
                                            can_favor_wheat=True):
            wheat_receive = sheep_send = 0
    if wheat_receive == 0 or sheep_send == 0:
        wheat_receive = sheep_send = 0
    return ExchangeResultV10(wheat_stays, wheat_receive, sheep_send)


def exchange_v10(price: X.Price, max_wheat_send: int, max_wheat_receive: int,
                 max_sheep_send: int, max_sheep_receive: int,
                 rounding: int) -> ExchangeResultV10:
    """Exact crossing amounts for one offer (reference: exchangeV10).

    price: the resting offer's price (sheep per wheat, n/d).
    max_wheat_send: wheat the offer owner can part with (offer amount
        clamped by balance/liabilities).
    max_wheat_receive: wheat the taker can accept (trustline capacity).
    max_sheep_send: sheep the taker can pay.
    max_sheep_receive: sheep the owner can accept.

    Rounding always favors the resting offer (the "wheat stays" side keeps
    the rounding remainder); path-payment strict-send keeps the sent amount
    exact instead of re-deriving it from the floored receive amount.

    wheat_stays compares the *offer side's* executable value
    min(maxWheatSend*n, maxSheepReceive*d) against the *demand side's*
    min(maxSheepSend*d, maxWheatReceive*n) — both in d-scaled sheep units —
    so a taker-capped partial fill never deletes the resting offer.
    """
    # offer side: limited by what it can part with AND what it can accept
    wheat_value = min(max_wheat_send * price.n, max_sheep_receive * price.d)
    # demand side: limited by what the taker can pay AND can accept
    sheep_value = min(max_sheep_send * price.d, max_wheat_receive * price.n)
    if wheat_value <= 0 or sheep_value <= 0:
        return ExchangeResultV10(wheat_value > 0, 0, 0)
    wheat_stays = wheat_value > sheep_value

    if wheat_stays:
        # the demand side is the binding constraint
        wheat_receive = _div_round(sheep_value, price.n, round_up=False)
        if rounding == ROUND_PATH_STRICT_SEND:
            sheep_send = max_sheep_send
        else:
            sheep_send = _div_round(wheat_receive * price.n, price.d,
                                    round_up=True)
    else:
        # the offer is fully consumed
        wheat_receive = _div_round(wheat_value, price.n, round_up=False)
        sheep_send = _div_round(wheat_value, price.d, round_up=True)

    assert wheat_receive <= min(max_wheat_send, max_wheat_receive)
    assert sheep_send <= max_sheep_send
    return apply_price_error_thresholds(price, wheat_receive, sheep_send,
                                        wheat_stays, rounding)


def adjust_offer(price: X.Price, max_wheat_send: int,
                 max_sheep_receive: int) -> int:
    """Reduce a resting offer's amount to what could actually be exchanged
    against an unbounded taker (reference: adjustOffer) — keeps the book
    free of offers that would cross to zero."""
    res = exchange_v10(price, max_wheat_send, INT64_MAX, INT64_MAX,
                       max_sheep_receive, ROUND_NORMAL)
    return res.num_wheat_received


def offer_selling_liabilities(price: X.Price, amount: int) -> int:
    """Reference: getOfferSellingLiabilities."""
    return adjust_offer(price, amount, INT64_MAX)


def offer_buying_liabilities(price: X.Price, amount: int) -> int:
    """Reference: getOfferBuyingLiabilities — the sheep the owner would
    receive if the adjusted offer were fully crossed."""
    res = exchange_v10(price, amount, INT64_MAX, INT64_MAX, INT64_MAX,
                       ROUND_NORMAL)
    return res.num_sheep_send


# --------------------------------------------------------------------------
# liabilities bookkeeping on accounts / trustlines

def _add_liab(entry_mut, asset: X.Asset, d_buying: int, d_selling: int,
              ltx: LedgerTxn) -> bool:
    """Adjust (buying, selling) liabilities for one asset of one account,
    mutating the loaded entry in the ltx.  Native -> AccountEntry ext v1;
    credit -> TrustLineEntry ext v1.  Returns False if the adjustment would
    violate balance/limit constraints (reference: addSellingLiabilities /
    addBuyingLiabilities)."""
    header = ltx.get_header()
    if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
        acc = entry_mut.data.value
        buying, selling = account_liabilities(acc)
        nb, ns = buying + d_buying, selling + d_selling
        if nb < 0 or ns < 0:
            return False
        if ns > acc.balance - minimum_balance(header, acc):
            return False
        if nb > INT64_MAX - acc.balance:
            return False
        _set_account_liab(acc, nb, ns)
        return True
    tl = entry_mut.data.value
    buying, selling = trustline_liabilities(tl)
    nb, ns = buying + d_buying, selling + d_selling
    if nb < 0 or ns < 0:
        return False
    if ns > tl.balance:
        return False
    if nb > tl.limit - tl.balance:
        return False
    _set_trustline_liab(tl, nb, ns)
    return True


def _set_account_liab(acc: X.AccountEntry, buying: int, selling: int) -> None:
    if acc.ext.switch == 0:
        acc.ext = X.AccountEntryExt.v1(X.AccountEntryExtensionV1(
            liabilities=X.Liabilities(buying=buying, selling=selling)))
    else:
        acc.ext.value.liabilities = X.Liabilities(buying=buying,
                                                  selling=selling)


def _set_trustline_liab(tl: X.TrustLineEntry, buying: int,
                        selling: int) -> None:
    if tl.ext.switch == 0:
        tl.ext = X.TrustLineEntryExt.v1(X.TrustLineEntryV1(
            liabilities=X.Liabilities(buying=buying, selling=selling)))
    else:
        tl.ext.value.liabilities = X.Liabilities(buying=buying,
                                                 selling=selling)


def acquire_or_release_offer_liabilities(
        ltx: LedgerTxn, offer: X.OfferEntry, acquire: bool) -> bool:
    """Add (acquire) or remove (release) an offer's liabilities on its
    owner's account/trustlines (reference: acquireLiabilities /
    releaseLiabilities in ManageOfferOpFrameBase)."""
    sign = 1 if acquire else -1
    selling_liab = offer_selling_liabilities(offer.price, offer.amount)
    buying_liab = offer_buying_liabilities(offer.price, offer.amount)
    sid = offer.sellerID

    def entry_for(asset):
        if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            return load_account(ltx, sid)
        if is_issuer(sid, asset):
            return None  # issuers carry no liabilities in their own asset
        return load_trustline(ltx, sid, asset)

    e_sell = entry_for(offer.selling)
    if offer.selling.switch != X.AssetType.ASSET_TYPE_NATIVE \
            and not is_issuer(sid, offer.selling) and e_sell is None:
        return False
    if e_sell is not None:
        if not _add_liab(e_sell, offer.selling, 0, sign * selling_liab, ltx):
            return False
        ltx.update(e_sell)
    e_buy = entry_for(offer.buying)
    if offer.buying.switch != X.AssetType.ASSET_TYPE_NATIVE \
            and not is_issuer(sid, offer.buying) and e_buy is None:
        return False
    if e_buy is not None:
        if not _add_liab(e_buy, offer.buying, sign * buying_liab, 0, ltx):
            return False
        ltx.update(e_buy)
    return True


# --------------------------------------------------------------------------
# book scan

# LedgerKey XDR starts with the 4-byte big-endian union discriminant; match
# on it before paying for a full decode (the book scan sees every key)
_OFFER_TAG = int(X.LedgerEntryType.OFFER).to_bytes(4, "big")


def _iter_offers(ltx: LedgerTxn, selling: X.Asset, buying: X.Asset):
    """All offers selling `selling` for `buying`, decoded."""
    out = []
    for kb in ltx.all_keys():
        if not kb.startswith(_OFFER_TAG):
            continue
        entry = ltx.get_entry(kb)
        if entry is None:
            continue
        offer = entry.data.value
        if offer.selling == selling and offer.buying == buying:
            out.append(offer)
    return out


def load_best_offers(ltx: LedgerTxn, selling: X.Asset,
                     buying: X.Asset) -> List[X.OfferEntry]:
    """Book side sorted by (price ascending, offerID ascending) — the
    reference's loadBestOffer order (LedgerTxnRoot best-offer query).  A
    sorted snapshot is safe during crossing: crossing only mutates/erases
    offers already visited and never inserts new ones."""
    offers = _iter_offers(ltx, selling, buying)
    import functools
    offers.sort(key=functools.cmp_to_key(
        lambda a, b: price_cmp(a.price, b.price) or
        ((a.offerID > b.offerID) - (a.offerID < b.offerID))))
    return offers


def _can_sell_at_most(ltx: LedgerTxn, account_id, asset: X.Asset,
                      header) -> int:
    if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
        acc_e = load_account(ltx, account_id)
        if acc_e is None:
            return 0
        return max(0, available_balance(header, acc_e.data.value))
    if is_issuer(account_id, asset):
        return INT64_MAX
    tl_e = load_trustline(ltx, account_id, asset)
    if tl_e is None or not is_authorized(tl_e.data.value):
        return 0
    tl = tl_e.data.value
    _, selling = trustline_liabilities(tl)
    return max(0, tl.balance - selling)


def _can_buy_at_most(ltx: LedgerTxn, account_id, asset: X.Asset,
                     header) -> int:
    if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
        acc_e = load_account(ltx, account_id)
        if acc_e is None:
            return 0
        acc = acc_e.data.value
        buying, _ = account_liabilities(acc)
        return max(0, INT64_MAX - acc.balance - buying)
    if is_issuer(account_id, asset):
        return INT64_MAX
    tl_e = load_trustline(ltx, account_id, asset)
    if tl_e is None or not is_authorized(tl_e.data.value):
        return 0
    tl = tl_e.data.value
    buying, _ = trustline_liabilities(tl)
    return max(0, tl.limit - tl.balance - buying)


def _transfer(ltx: LedgerTxn, account_id, asset: X.Asset, delta: int,
              header) -> bool:
    """Move `delta` of `asset` into (delta>0) or out of (delta<0) an
    account's balance/trustline; issuers mint/burn (no-op)."""
    if asset.switch != X.AssetType.ASSET_TYPE_NATIVE \
            and is_issuer(account_id, asset):
        return True
    if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
        e = load_account(ltx, account_id)
        if e is None:
            return False
        if not add_balance(e.data.value, delta, header):
            return False
        ltx.update(e)
        return True
    e = load_trustline(ltx, account_id, asset)
    if e is None:
        return False
    if not utils.add_trustline_balance(e.data.value, delta):
        return False
    ltx.update(e)
    return True


# crossing outcomes (reference: OfferExchange.h — ConvertResult /
# CrossOfferResult)
CONVERT_OK = 0
CONVERT_PARTIAL = 1          # book exhausted before the target was reached
CONVERT_FILTER_STOP = 2      # stopped by the price bound / self-cross


@dataclass
class CrossResult:
    """Outcome of a full conversion sweep over one book side."""
    result: int
    wheat_received: int = 0
    sheep_sent: int = 0
    offers_claimed: List[X.ClaimAtom] = dc_field(default_factory=list)
    self_cross: bool = False


def convert_with_offers(
        ltx: LedgerTxn, sheep: X.Asset, wheat: X.Asset,
        max_wheat_receive: int, max_sheep_send: int,
        taker_id, rounding: int,
        price_bound: Optional[Callable[[X.Price], bool]] = None,
) -> CrossResult:
    """Cross the (wheat-selling) book until max_wheat_receive wheat has been
    bought or max_sheep_send sheep spent (reference: convertWithOffers).

    price_bound(maker_price) -> False stops the sweep (used by manage-offer
    crossing to stop at the taker's own price).  Crossing the taker's own
    offer aborts with self_cross (opCROSS_SELF semantics)."""
    header = ltx.get_header()
    res = CrossResult(CONVERT_OK)
    need_wheat = max_wheat_receive
    have_sheep = max_sheep_send

    for offer in load_best_offers(ltx, wheat, sheep):
        if need_wheat <= 0 or have_sheep <= 0:
            break
        if price_bound is not None and not price_bound(offer.price):
            res.result = CONVERT_FILTER_STOP
            break
        if offer.sellerID == taker_id:
            res.self_cross = True
            res.result = CONVERT_FILTER_STOP
            break

        owner = offer.sellerID
        # release the maker's liabilities while the offer is off the book
        if not acquire_or_release_offer_liabilities(ltx, offer, acquire=False):
            # inconsistent offer (should not happen) — skip defensively
            continue
        max_wheat_send = min(offer.amount,
                             _can_sell_at_most(ltx, owner, wheat, header))
        max_sheep_recv = _can_buy_at_most(ltx, owner, sheep, header)
        ex = exchange_v10(offer.price, max_wheat_send, need_wheat,
                          have_sheep, max_sheep_recv, rounding)

        if ex.num_wheat_received > 0:
            assert _transfer(ltx, owner, wheat, -ex.num_wheat_received, header)
            assert _transfer(ltx, owner, sheep, ex.num_sheep_send, header)
            res.offers_claimed.append(X.ClaimAtom.orderBook(X.ClaimOfferAtom(
                sellerID=owner, offerID=offer.offerID,
                assetSold=wheat, amountSold=ex.num_wheat_received,
                assetBought=sheep, amountBought=ex.num_sheep_send)))
            res.wheat_received += ex.num_wheat_received
            res.sheep_sent += ex.num_sheep_send
            need_wheat -= ex.num_wheat_received
            have_sheep -= ex.num_sheep_send

        offer_key = X.LedgerKey.offer(X.LedgerKeyOffer(
            sellerID=owner, offerID=offer.offerID))
        if ex.wheat_stays:
            # offer remains: shrink to the executable remainder and put its
            # liabilities back.  NB: `offer` is a snapshot that may alias the
            # backing store — mutate only a load()ed copy.
            new_amount = adjust_offer(
                offer.price,
                min(offer.amount - ex.num_wheat_received,
                    _can_sell_at_most(ltx, owner, wheat, header)),
                _can_buy_at_most(ltx, owner, sheep, header))
            if new_amount > 0:
                e = ltx.load(offer_key)
                e.data.value.amount = new_amount
                ltx.update(e)
                assert acquire_or_release_offer_liabilities(
                    ltx, e.data.value, acquire=True)
            else:
                _erase_offer(ltx, offer_key, owner, header)
            break  # taker exhausted
        else:
            _erase_offer(ltx, offer_key, owner, header)

    if need_wheat > 0 and have_sheep > 0 and res.result == CONVERT_OK:
        res.result = CONVERT_PARTIAL
    return res


def _erase_offer(ltx: LedgerTxn, offer_key: X.LedgerKey, owner, header):
    """Remove an offer entry and its subentry count, releasing the
    sponsor's reserve when the offer was sponsored (reference:
    removeEntryWithPossibleSponsorship on the crossing path)."""
    from . import sponsorship
    entry = ltx.load(offer_key)
    ltx.erase(offer_key)
    acc_e = load_account(ltx, owner)
    acc = acc_e.data.value
    if entry is not None and sponsorship.entry_sponsor(entry) is not None:
        sponsorship.release_entry_sponsorship(ltx, header, entry, acc_e)
    acc.numSubEntries -= 1
    ltx.update(acc_e)


# --------------------------------------------------------------------------
# liquidity pool swaps (CAP-38 constant product)

def pool_id_for(asset_a: X.Asset, asset_b: X.Asset, fee: int = POOL_FEE_BPS):
    """PoolID = SHA256(xdr(LiquidityPoolParameters)) with assets in
    canonical order (reference: getPoolID)."""
    from ..crypto.sha import sha256
    params = X.LiquidityPoolParameters.constantProduct(
        X.LiquidityPoolConstantProductParameters(
            assetA=asset_a, assetB=asset_b, fee=fee))
    return sha256(params.to_xdr())


def asset_order(a: X.Asset, b: X.Asset) -> int:
    """Canonical asset ordering for pool parameter construction
    (reference: assetA < assetB required)."""
    ka, kb = a.to_xdr(), b.to_xdr()
    return (ka > kb) - (ka < kb)


def pool_swap_out_given_in(reserves_in: int, reserves_out: int,
                           amount_in: int) -> int:
    """Strict-send through a constant-product pool: floor of the CAP-38
    disbursement y = (Y * x * (1-F)) / (X + x * (1-F)), computed exactly in
    basis points."""
    num = reserves_out * amount_in * (10000 - POOL_FEE_BPS)
    den = reserves_in * 10000 + amount_in * (10000 - POOL_FEE_BPS)
    if den <= 0:
        return 0
    return num // den


def pool_swap_in_given_out(reserves_in: int, reserves_out: int,
                           amount_out: int) -> Optional[int]:
    """Strict-receive through a constant-product pool: ceil of
    x = (X * y) / ((Y - y) * (1-F)); None if the pool cannot disburse
    amount_out."""
    if amount_out >= reserves_out:
        return None
    num = reserves_in * amount_out * 10000
    den = (reserves_out - amount_out) * (10000 - POOL_FEE_BPS)
    x = _div_round(num, den, round_up=True)
    if x > INT64_MAX:
        return None
    return x
