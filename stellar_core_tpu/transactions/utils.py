"""Transaction-layer ledger helpers.

Reference: src/transactions/TransactionUtils.{h,cpp} — loadAccount/
loadTrustLine accessors, addBalance, getAvailableBalance, minimum-balance
(reserve) logic; src/ledger/LedgerTxnHeader reserve math.
Protocol level: current (23) semantics; earlier version gates are collapsed
and documented where behavior differs.
"""

from __future__ import annotations

from typing import Optional, Tuple

from .. import xdr as X
from ..ledger.ledger_txn import LedgerTxn

INT64_MAX = 2 ** 63 - 1

# thresholds indices into AccountEntry.thresholds
THRESHOLD_MASTER_WEIGHT = 0
THRESHOLD_LOW = 1
THRESHOLD_MED = 2
THRESHOLD_HIGH = 3


def account_key(account_id: X.AccountID) -> X.LedgerKey:
    return X.LedgerKey.account(X.LedgerKeyAccount(accountID=account_id))


def trustline_key(account_id: X.AccountID, asset: X.TrustLineAsset) -> X.LedgerKey:
    return X.LedgerKey.trustLine(X.LedgerKeyTrustLine(accountID=account_id, asset=asset))


def data_key(account_id: X.AccountID, name: bytes) -> X.LedgerKey:
    return X.LedgerKey.data(X.LedgerKeyData(accountID=account_id, dataName=name))


def cb_key(balance_id: X.ClaimableBalanceID) -> X.LedgerKey:
    return X.LedgerKey.claimableBalance(X.LedgerKeyClaimableBalance(balanceID=balance_id))


def asset_to_trustline_asset(asset: X.Asset) -> X.TrustLineAsset:
    return X.TrustLineAsset(asset.switch, asset.value)


def load_account(ltx: LedgerTxn, account_id: X.AccountID) -> Optional[X.LedgerEntry]:
    return ltx.load_by_bytes(X.account_key_xdr(account_id.value))


def load_trustline(ltx: LedgerTxn, account_id: X.AccountID,
                   asset: X.Asset) -> Optional[X.LedgerEntry]:
    return ltx.load(trustline_key(account_id, asset_to_trustline_asset(asset)))


def num_sponsoring(acc: X.AccountEntry) -> int:
    v2 = _acc_ext_v2(acc)
    return v2.numSponsoring if v2 else 0


def num_sponsored(acc: X.AccountEntry) -> int:
    v2 = _acc_ext_v2(acc)
    return v2.numSponsored if v2 else 0


def _acc_ext_v1(acc: X.AccountEntry) -> Optional[X.AccountEntryExtensionV1]:
    return acc.ext.value if acc.ext.switch == 1 else None


def _acc_ext_v2(acc: X.AccountEntry):
    v1 = _acc_ext_v1(acc)
    if v1 is not None and v1.ext.switch == 2:
        return v1.ext.value
    return None


def _ensure_acc_ext_v2(acc: X.AccountEntry) -> X.AccountEntryExtensionV2:
    """Materialize the v1+v2 extension chain (reference: prepareAccountEntry
    extension upgrade on first sponsorship use).  signerSponsoringIDs is
    padded to the signer count on materialization so the invariant
    len(signerSponsoringIDs) == len(signers) holds from the first
    sponsorship touch (reference: AccountEntry extension constraints)."""
    if acc.ext.switch == 0:
        acc.ext = X.AccountEntryExt.v1(X.AccountEntryExtensionV1(
            liabilities=X.Liabilities(buying=0, selling=0)))
    v1 = acc.ext.value
    if v1.ext.switch != 2:
        v1.ext = X.AccountEntryExtensionV1Ext.v2(X.AccountEntryExtensionV2(
            signerSponsoringIDs=[None] * len(acc.signers)))
    return v1.ext.value


def add_num_sponsoring(header: X.LedgerHeader, acc: X.AccountEntry,
                       delta: int) -> bool:
    """Adjust numSponsoring with a reserve check on increase (reference:
    createSponsoredEntry path — the sponsor's balance must cover the
    enlarged minimum balance)."""
    current = num_sponsoring(acc)
    new_count = current + delta
    if new_count < 0:
        return False
    if delta > 0:
        need = (2 + acc.numSubEntries + new_count - num_sponsored(acc)) \
            * header.baseReserve
        _, selling = account_liabilities(acc)
        if acc.balance < need + selling:
            return False
    _ensure_acc_ext_v2(acc).numSponsoring = new_count
    return True


def account_liabilities(acc: X.AccountEntry) -> Tuple[int, int]:
    """(buying, selling)."""
    v1 = _acc_ext_v1(acc)
    if v1 is None:
        return 0, 0
    return v1.liabilities.buying, v1.liabilities.selling


def trustline_liabilities(tl: X.TrustLineEntry) -> Tuple[int, int]:
    if tl.ext.switch != 1:
        return 0, 0
    li = tl.ext.value.liabilities
    return li.buying, li.selling


def minimum_balance(header: X.LedgerHeader, acc: X.AccountEntry) -> int:
    """(2 + numSubEntries + numSponsoring - numSponsored) * baseReserve
    (reference: getMinBalance, protocol >= 14 sponsorship form)."""
    count = 2 + acc.numSubEntries + num_sponsoring(acc) - num_sponsored(acc)
    return count * header.baseReserve


def available_balance(header: X.LedgerHeader, acc: X.AccountEntry) -> int:
    """Spendable native balance: balance - minBalance - selling liabilities."""
    _, selling = account_liabilities(acc)
    return acc.balance - minimum_balance(header, acc) - selling


def available_limit(acc_or_tl, limit: int, balance: int, buying: int) -> int:
    return limit - balance - buying


def add_balance(acc: X.AccountEntry, delta: int,
                header: Optional[X.LedgerHeader] = None) -> bool:
    """In-place native balance adjustment with reserve/liability floors and
    int64 ceiling (reference: addBalance + addBalanceSkipAuthorization)."""
    new = acc.balance + delta
    if new < 0 or new > INT64_MAX:
        return False
    if delta < 0:
        floor = 0
        if header is not None:
            _, selling = account_liabilities(acc)
            floor = minimum_balance(header, acc) + selling
        if new < floor:
            return False
    else:
        buying, _ = account_liabilities(acc)
        if new > INT64_MAX - buying:
            return False
    acc.balance = new
    return True


def add_trustline_balance(tl: X.TrustLineEntry, delta: int) -> bool:
    new = tl.balance + delta
    if new < 0 or new > tl.limit:
        return False
    buying, selling = trustline_liabilities(tl)
    if delta < 0 and new < selling:
        return False
    if delta > 0 and new > tl.limit - buying:
        return False
    tl.balance = new
    return True


def threshold_level_value(acc: X.AccountEntry, level: int) -> int:
    return acc.thresholds[level]


def is_authorized(tl: X.TrustLineEntry) -> bool:
    return bool(tl.flags & X.TrustLineFlags.AUTHORIZED_FLAG)


def is_authorized_to_maintain_liabilities(tl: X.TrustLineEntry) -> bool:
    return bool(tl.flags & (X.TrustLineFlags.AUTHORIZED_FLAG
                            | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG))


def is_issuer(account_id: X.AccountID, asset: X.Asset) -> bool:
    if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
        return False
    return asset.value.issuer == account_id


def asset_valid(asset: X.Asset) -> bool:
    """Asset code constraints (reference: isAssetValid): alnum, no embedded
    NULs before padding, nonempty."""
    if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
        return True
    code = asset.value.assetCode
    trimmed = code.rstrip(b"\x00")
    if not trimmed or b"\x00" in trimmed:
        return False
    if asset.switch == X.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4:
        if len(trimmed) > 4:
            return False
    else:
        if len(trimmed) < 5:
            return False
    return all(0x30 <= c <= 0x39 or 0x41 <= c <= 0x5A or 0x61 <= c <= 0x7A
               for c in trimmed)


def add_num_entries(header: X.LedgerHeader, acc: X.AccountEntry,
                    delta: int) -> bool:
    """Adjust numSubEntries with reserve check on increase (reference:
    addNumEntries). Balance floor must cover the new reserve."""
    new_count = acc.numSubEntries + delta
    if new_count < 0:
        return False
    if delta > 0:
        need = (2 + new_count + num_sponsoring(acc) - num_sponsored(acc)) \
            * header.baseReserve
        _, selling = account_liabilities(acc)
        if acc.balance < need + selling:
            return False
    acc.numSubEntries = new_count
    return True
