"""Offer, path-payment and liquidity-pool operation frames.

Reference: src/transactions/ManageOfferOpFrameBase.cpp,
ManageSellOfferOpFrame.cpp, ManageBuyOfferOpFrame.cpp,
CreatePassiveSellOfferOpFrame.cpp, PathPaymentOpFrameBase.cpp,
PathPaymentStrictReceiveOpFrame.cpp, PathPaymentStrictSendOpFrame.cpp,
LiquidityPoolDepositOpFrame.cpp, LiquidityPoolWithdrawOpFrame.cpp.

The crossing engine itself lives in offer_exchange.py.
"""

from __future__ import annotations

import math
from typing import List, Optional

from .. import xdr as X
from . import sponsorship, utils
from .offer_exchange import (CONVERT_FILTER_STOP, CONVERT_OK, CONVERT_PARTIAL,
                             ROUND_NORMAL, ROUND_PATH_STRICT_RECEIVE,
                             ROUND_PATH_STRICT_SEND, _can_buy_at_most,
                             _can_sell_at_most, _div_round, _transfer,
                             adjust_offer,
                             acquire_or_release_offer_liabilities,
                             convert_with_offers, pool_id_for, price_valid,
                             pool_swap_in_given_out, pool_swap_out_given_in)
from .operations import OperationFrame, register_op_class
from .utils import (INT64_MAX, account_key, asset_to_trustline_asset,
                    asset_valid, is_authorized, is_issuer, load_account,
                    load_trustline, trustline_key)

OT = X.OperationType
EFF = X.ManageOfferEffect


def _generate_offer_id(ltx) -> int:
    """Reference: LedgerTxnHeader generateID (idPool counter in the ledger
    header)."""
    header = ltx.load_header()
    header.idPool += 1
    ltx.commit_header(header)
    return header.idPool


class _ManageOfferBase(OperationFrame):
    """Shared create/update/delete + crossing logic
    (reference: ManageOfferOpFrameBase)."""

    PASSIVE = False

    # subclasses provide: _params() -> (selling, buying, price, offer_id)
    # and amount semantics via _sheep_budget / _wheat_target

    def _check_offer_valid(self, ltx):
        """Trustline/auth preconditions (reference: checkOfferValid)."""
        C = self.C
        src = self.source_account_id()
        selling, buying = self._selling(), self._buying()
        if selling.switch != X.AssetType.ASSET_TYPE_NATIVE \
                and not is_issuer(src, selling):
            tl = load_trustline(ltx, src, selling)
            if tl is None:
                return self.result(C("SELL_NO_TRUST"))
            if not is_authorized(tl.data.value):
                return self.result(C("SELL_NOT_AUTHORIZED"))
        if buying.switch != X.AssetType.ASSET_TYPE_NATIVE \
                and not is_issuer(src, buying):
            tl = load_trustline(ltx, src, buying)
            if tl is None:
                return self.result(C("BUY_NO_TRUST"))
            if not is_authorized(tl.data.value):
                return self.result(C("BUY_NOT_AUTHORIZED"))
        return None

    def _load_own_offer(self, ltx, offer_id: int):
        key = X.LedgerKey.offer(X.LedgerKeyOffer(
            sellerID=self.source_account_id(), offerID=offer_id))
        return key, ltx.load(key)

    def _apply_manage(self, ltx, selling: X.Asset, buying: X.Asset,
                      price: X.Price, offer_id: int,
                      sell_amount: int) -> X.OperationResult:
        """Create/update/delete + cross.  sell_amount is the desired amount
        in selling-asset units (already converted for buy offers)."""
        C = self.C
        header = ltx.get_header()
        src = self.source_account_id()

        bad = self._check_offer_valid(ltx)
        if bad is not None:
            return bad

        creating = offer_id == 0
        old_flags = 0
        old_ext = None   # preserved across the erase/recreate update path
        if not creating:
            key, existing = self._load_own_offer(ltx, offer_id)
            if existing is None:
                return self.result(C("NOT_FOUND"))
            old = existing.data.value
            old_ext = existing.ext
            # take the old offer off the book (liabilities + entry); it is
            # recreated below if a residual remains
            assert acquire_or_release_offer_liabilities(
                ltx, old, acquire=False)
            ltx.erase(key)
            if sell_amount == 0:
                acc_e = load_account(ltx, src)
                if sponsorship.entry_sponsor(existing) is not None:
                    sponsorship.release_entry_sponsorship(
                        ltx, header, existing, acc_e)
                acc_e.data.value.numSubEntries -= 1
                ltx.update(acc_e)
                return self.success(X.ManageOfferSuccessResult(
                    offersClaimed=[],
                    offer=X.ManageOfferSuccessResultOffer(EFF.MANAGE_OFFER_DELETED)))
        # crossing: we are the taker — we sell `selling` (their sheep), we
        # receive `buying` (their wheat) from offers selling `buying`
        def crossable(maker_price: X.Price) -> bool:
            # maker sells `buying` for `selling` at maker_price; we cross
            # while maker.n * price.n <= maker.d * price.d (maker's ask does
            # not exceed our bid); passive offers skip exact-price makers
            lhs = maker_price.n * price.n
            rhs = maker_price.d * price.d
            return lhs < rhs or (lhs == rhs and not self.PASSIVE)

        max_sheep = min(sell_amount,
                        _can_sell_at_most(ltx, src, selling, header))
        max_wheat = self._wheat_target(ltx, price, sell_amount, header)
        cross = convert_with_offers(
            ltx, selling, buying, max_wheat, max_sheep, src, ROUND_NORMAL,
            price_bound=crossable)
        if cross.self_cross:
            return self.result(C("CROSS_SELF"))
        if not _transfer(ltx, src, selling, -cross.sheep_sent, header):
            return self.result(C("UNDERFUNDED"))
        if not _transfer(ltx, src, buying, cross.wheat_received, header):
            return self.result(C("LINE_FULL"))

        residual = self._residual_sell_amount(
            ltx, price, sell_amount, cross.sheep_sent, cross.wheat_received,
            header)
        effect = EFF.MANAGE_OFFER_CREATED if creating else EFF.MANAGE_OFFER_UPDATED
        new_amount = adjust_offer(
            price, min(residual, _can_sell_at_most(ltx, src, selling, header)),
            _can_buy_at_most(ltx, src, buying, header))
        if new_amount <= 0:
            # fully crossed (or dust): nothing rests on the book
            if not creating:
                acc_e = load_account(ltx, src)
                if sponsorship.entry_sponsor(existing) is not None:
                    sponsorship.release_entry_sponsorship(
                        ltx, header, existing, acc_e)
                acc_e.data.value.numSubEntries -= 1
                ltx.update(acc_e)
            return self.success(X.ManageOfferSuccessResult(
                offersClaimed=cross.offers_claimed,
                offer=X.ManageOfferSuccessResultOffer(EFF.MANAGE_OFFER_DELETED)))

        offer = X.OfferEntry(
            sellerID=src, offerID=offer_id, selling=selling, buying=buying,
            amount=new_amount, price=price,
            flags=X.OfferEntryFlags.PASSIVE_FLAG if self.PASSIVE else 0)
        new_ledger_entry = X.LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=X.LedgerEntryData.offer(offer))
        if creating:
            acc_e = load_account(ltx, src)
            code, sponsored = sponsorship.create_entry_with_possible_sponsorship(
                ltx, header, self.tx, new_ledger_entry, acc_e,
                src if header.ledgerVersion >= 14 else None)
            bad = self.sponsorship_error(code, C("LOW_RESERVE"))
            if bad is not None:
                return bad
            if sponsored:
                acc_e.data.value.numSubEntries += 1
            elif not utils.add_num_entries(header, acc_e.data.value, 1):
                return self.result(C("LOW_RESERVE"))
            ltx.update(acc_e)
            offer_id = _generate_offer_id(ltx)
            offer.offerID = offer_id
        elif old_ext is not None:
            # update path (erase + recreate with the same id): the entry's
            # sponsorship, if any, carries over unchanged
            new_ledger_entry.ext = old_ext
        ltx.create(new_ledger_entry)
        if not acquire_or_release_offer_liabilities(ltx, offer, acquire=True):
            return self.result(C("LINE_FULL"))
        return self.success(X.ManageOfferSuccessResult(
            offersClaimed=cross.offers_claimed,
            offer=X.ManageOfferSuccessResultOffer(effect, offer)))

    # -- amount-semantics hooks (sell vs buy offers) ---------------------
    def _wheat_target(self, ltx, price, sell_amount, header) -> int:
        """How much `buying` the taker is willing to receive during
        crossing — unbounded for sell offers (bounded by sheep budget)."""
        return _can_buy_at_most(ltx, self.source_account_id(),
                                self._buying(), header)

    def _residual_sell_amount(self, ltx, price, sell_amount, sheep_sent,
                              wheat_received, header) -> int:
        return sell_amount - sheep_sent


class ManageSellOfferOpFrame(_ManageOfferBase):
    """Reference: src/transactions/ManageSellOfferOpFrame.cpp."""
    OP_TYPE = OT.MANAGE_SELL_OFFER
    RESULT_CLS = X.ManageSellOfferResult

    def C(self, name):
        return getattr(X.ManageSellOfferResultCode,
                       "MANAGE_SELL_OFFER_" + name)

    def _selling(self):
        return self.body.selling

    def _buying(self):
        return self.body.buying

    def do_check_valid(self, ltx):
        b = self.body
        if (b.amount < 0 or not price_valid(b.price)
                or not asset_valid(b.selling) or not asset_valid(b.buying)
                or b.selling == b.buying or b.offerID < 0
                or (b.amount == 0 and b.offerID == 0)):
            return self.result(self.C("MALFORMED"))
        return self.success()

    def do_apply(self, ltx):
        b = self.body
        return self._apply_manage(ltx, b.selling, b.buying, b.price,
                                  b.offerID, b.amount)


class CreatePassiveSellOfferOpFrame(_ManageOfferBase):
    """Reference: src/transactions/CreatePassiveSellOfferOpFrame.cpp —
    a sell offer that does not cross offers at exactly its own price."""
    OP_TYPE = OT.CREATE_PASSIVE_SELL_OFFER
    RESULT_CLS = X.ManageSellOfferResult
    PASSIVE = True

    def C(self, name):
        return getattr(X.ManageSellOfferResultCode,
                       "MANAGE_SELL_OFFER_" + name)

    def _selling(self):
        return self.body.selling

    def _buying(self):
        return self.body.buying

    def do_check_valid(self, ltx):
        b = self.body
        if (b.amount <= 0 or not price_valid(b.price)
                or not asset_valid(b.selling) or not asset_valid(b.buying)
                or b.selling == b.buying):
            return self.result(self.C("MALFORMED"))
        return self.success()

    def do_apply(self, ltx):
        b = self.body
        return self._apply_manage(ltx, b.selling, b.buying, b.price, 0,
                                  b.amount)


class ManageBuyOfferOpFrame(_ManageOfferBase):
    """Reference: src/transactions/ManageBuyOfferOpFrame.cpp (CAP-0006).

    The op specifies buyAmount in buying-asset units and buyingPrice as
    buying-per-selling... precisely: price of the thing being bought in
    terms of what is being sold.  Stored as a sell offer with the price
    inverted and amount = ceil(buyAmount * price.n / price.d) selling
    units; crossing caps wheat received at buyAmount so the buyer never
    over-buys."""
    MIN_PROTOCOL_VERSION = 11
    OP_TYPE = OT.MANAGE_BUY_OFFER
    RESULT_CLS = X.ManageBuyOfferResult

    def C(self, name):
        return getattr(X.ManageBuyOfferResultCode, "MANAGE_BUY_OFFER_" + name)

    def _selling(self):
        return self.body.selling

    def _buying(self):
        return self.body.buying

    def do_check_valid(self, ltx):
        b = self.body
        if (b.buyAmount < 0 or not price_valid(b.price)
                or not asset_valid(b.selling) or not asset_valid(b.buying)
                or b.selling == b.buying or b.offerID < 0
                or (b.buyAmount == 0 and b.offerID == 0)):
            return self.result(self.C("MALFORMED"))
        return self.success()

    def _sell_price(self) -> X.Price:
        return X.Price(n=self.body.price.d, d=self.body.price.n)

    def do_apply(self, ltx):
        b = self.body
        if b.buyAmount == 0:
            sell_amount = 0
        else:
            # selling units needed to buy buyAmount at price (round up so
            # the resting offer can always fill the requested buy amount)
            sell_amount = _div_round(b.buyAmount * b.price.n, b.price.d,
                                     round_up=True)
            if sell_amount > INT64_MAX:
                return self.result(self.C("MALFORMED"))
        return self._apply_manage(ltx, b.selling, b.buying,
                                  self._sell_price(), b.offerID, sell_amount)

    def _wheat_target(self, ltx, price, sell_amount, header) -> int:
        cap = _can_buy_at_most(ltx, self.source_account_id(),
                               self._buying(), header)
        return min(self.body.buyAmount, cap)

    def _residual_sell_amount(self, ltx, price, sell_amount, sheep_sent,
                              wheat_received, header) -> int:
        # residual is driven by the un-bought amount, reconverted to
        # selling units at the op's buy price (NOT the inverted stored one)
        left = self.body.buyAmount - wheat_received
        if left <= 0:
            return 0
        return _div_round(left * self.body.price.n, self.body.price.d,
                          round_up=True)


# --------------------------------------------------------------------------
# path payments

class _PathPaymentBase(OperationFrame):
    """Reference: src/transactions/PathPaymentOpFrameBase.cpp."""

    def _dest_id(self):
        return X.muxed_to_account_id(self.body.destination)

    def _check_common(self):
        C = self.C
        b = self.body
        assets = [b.sendAsset, *b.path, b.destAsset]
        for a in assets:
            if not asset_valid(a):
                return self.result(C("MALFORMED"))
        if len(b.path) > 5:
            return self.result(C("MALFORMED"))
        return None

    def _credit_dest(self, ltx, amount: int) -> Optional[X.OperationResult]:
        """Credit destAsset to the destination, with the reference's result
        codes for missing account/trustline/auth/limit."""
        C = self.C
        header = ltx.get_header()
        dest = self._dest_id()
        asset = self.body.destAsset
        if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            e = load_account(ltx, dest)
            if e is None:
                return self.result(C("NO_DESTINATION"))
            if not utils.add_balance(e.data.value, amount, header):
                return self.result(C("LINE_FULL"))
            ltx.update(e)
            return None
        if is_issuer(dest, asset):
            if ltx.get_entry(account_key(dest).to_xdr()) is None:
                return self.result(C("NO_DESTINATION"))
            return None  # burning at the issuer
        if ltx.get_entry(account_key(dest).to_xdr()) is None:
            return self.result(C("NO_DESTINATION"))
        tl = load_trustline(ltx, dest, asset)
        if tl is None:
            return self.result(C("NO_TRUST"))
        if not is_authorized(tl.data.value):
            return self.result(C("NOT_AUTHORIZED"))
        if not utils.add_trustline_balance(tl.data.value, amount):
            return self.result(C("LINE_FULL"))
        ltx.update(tl)
        return None

    def _debit_source(self, ltx, amount: int) -> Optional[X.OperationResult]:
        C = self.C
        header = ltx.get_header()
        src = self.source_account_id()
        asset = self.body.sendAsset
        if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            e = load_account(ltx, src)
            if not utils.add_balance(e.data.value, -amount, header):
                return self.result(C("UNDERFUNDED"))
            ltx.update(e)
            return None
        if is_issuer(src, asset):
            return None  # minting from the issuer
        tl = load_trustline(ltx, src, asset)
        if tl is None:
            return self.result(C("SRC_NO_TRUST"))
        if not is_authorized(tl.data.value):
            return self.result(C("SRC_NOT_AUTHORIZED"))
        if not utils.add_trustline_balance(tl.data.value, -amount):
            return self.result(C("UNDERFUNDED"))
        ltx.update(tl)
        return None

    def _convert_hop(self, ltx, from_asset: X.Asset, to_asset: X.Asset,
                     wheat_target: int, sheep_budget: int, rounding: int):
        """One hop: cross the book selling `to_asset` for `from_asset`,
        choosing the order book or the constant-product pool — whichever
        converts at the better rate (reference:
        convertWithOffersAndPoolsStrictReceive/Send, CAP-38).  Returns
        (result_or_None, wheat_received, sheep_sent, claims)."""
        from ..ledger.ledger_txn import LedgerTxn

        # order-book attempt in a child txn so the loser can be rolled back
        book_txn = LedgerTxn(ltx)
        book = convert_with_offers(
            book_txn, from_asset, to_asset, wheat_target, sheep_budget,
            self.source_account_id(), rounding)
        if book.self_cross:
            book_txn.rollback()
            return self.result(self.C("OFFER_CROSS_SELF")), 0, 0, []

        pool = self._pool_quote(ltx, from_asset, to_asset, wheat_target,
                                sheep_budget, rounding)

        book_filled = (rounding == ROUND_PATH_STRICT_RECEIVE
                       and book.wheat_received >= wheat_target) or \
                      (rounding == ROUND_PATH_STRICT_SEND
                       and book.sheep_sent >= sheep_budget)

        def use_pool():
            book_txn.rollback()
            pid, in_amt, out_amt, ra, rb, flip = pool
            pool_key = X.LedgerKey.liquidityPool(
                X.LedgerKeyLiquidityPool(liquidityPoolID=pid))
            pe = ltx.load(pool_key)
            cp = pe.data.value.body.value
            if flip:
                cp.reserveB += in_amt
                cp.reserveA -= out_amt
            else:
                cp.reserveA += in_amt
                cp.reserveB -= out_amt
            ltx.update(pe)
            claim = X.ClaimAtom.liquidityPool(X.ClaimLiquidityAtom(
                liquidityPoolID=pid, assetSold=to_asset, amountSold=out_amt,
                assetBought=from_asset, amountBought=in_amt))
            return None, out_amt, in_amt, [claim]

        if pool is not None:
            if rounding == ROUND_PATH_STRICT_RECEIVE:
                # pool can deliver the full target; better price == less in
                if pool[2] >= wheat_target and (
                        not book_filled or pool[1] < book.sheep_sent):
                    return use_pool()
            else:
                if pool[1] <= sheep_budget and pool[2] > book.wheat_received:
                    return use_pool()

        book_txn.commit()
        if rounding == ROUND_PATH_STRICT_RECEIVE \
                and book.wheat_received < wheat_target:
            return self.result(self.C("TOO_FEW_OFFERS")), 0, 0, []
        if rounding == ROUND_PATH_STRICT_SEND and book.sheep_sent < sheep_budget:
            return self.result(self.C("TOO_FEW_OFFERS")), 0, 0, []
        return None, book.wheat_received, book.sheep_sent, book.offers_claimed

    def _pool_quote(self, ltx, from_asset, to_asset, wheat_target,
                    sheep_budget, rounding):
        """(pool_id, amount_in, amount_out, reserve_in, reserve_out, flip)
        or None if no usable pool exists for the pair."""
        from .offer_exchange import asset_order
        a, b = ((from_asset, to_asset)
                if asset_order(from_asset, to_asset) < 0
                else (to_asset, from_asset))
        pid = pool_id_for(a, b)
        pe = ltx.get_entry(X.LedgerKey.liquidityPool(
            X.LedgerKeyLiquidityPool(liquidityPoolID=pid)).to_xdr())
        if pe is None:
            return None
        cp = pe.data.value.body.value
        flip = from_asset == cp.params.assetB
        r_in = cp.reserveB if flip else cp.reserveA
        r_out = cp.reserveA if flip else cp.reserveB
        if r_in <= 0 or r_out <= 0:
            return None
        if rounding == ROUND_PATH_STRICT_RECEIVE:
            amount_out = wheat_target
            amount_in = pool_swap_in_given_out(r_in, r_out, amount_out)
            if amount_in is None:
                return None
        else:
            amount_in = sheep_budget
            amount_out = pool_swap_out_given_in(r_in, r_out, amount_in)
            if amount_out <= 0:
                return None
        # reference getPoolExchange: skip the pool rather than overflow its
        # post-swap reserve
        if r_in + amount_in > INT64_MAX:
            return None
        return pid, amount_in, amount_out, r_in, r_out, flip


class PathPaymentStrictReceiveOpFrame(_PathPaymentBase):
    """Reference: src/transactions/PathPaymentStrictReceiveOpFrame.cpp —
    fixed destAmount, bounded sendMax, path walked destination-first."""
    OP_TYPE = OT.PATH_PAYMENT_STRICT_RECEIVE
    RESULT_CLS = X.PathPaymentStrictReceiveResult

    def C(self, name):
        return getattr(X.PathPaymentStrictReceiveResultCode,
                       "PATH_PAYMENT_STRICT_RECEIVE_" + name)

    def do_check_valid(self, ltx):
        b = self.body
        if b.destAmount <= 0 or b.sendMax <= 0:
            return self.result(self.C("MALFORMED"))
        bad = self._check_common()
        return bad if bad is not None else self.success()

    def do_apply(self, ltx):
        b = self.body
        bad = self._credit_dest(ltx, b.destAmount)
        if bad is not None:
            return bad
        chain = [b.sendAsset, *b.path, b.destAsset]
        claims: List[X.ClaimAtom] = []
        need = b.destAmount
        # walk back from the destination: each hop buys `need` of the next
        # asset with the previous one
        for i in range(len(chain) - 1, 0, -1):
            to_asset, from_asset = chain[i], chain[i - 1]
            if to_asset == from_asset:
                continue
            bad, wheat, sheep, hop_claims = self._convert_hop(
                ltx, from_asset, to_asset, need, INT64_MAX,
                ROUND_PATH_STRICT_RECEIVE)
            if bad is not None:
                return bad
            claims = hop_claims + claims
            need = sheep
        if need > b.sendMax:
            return self.result(self.C("OVER_SENDMAX"))
        bad = self._debit_source(ltx, need)
        if bad is not None:
            return bad
        last = X.SimplePaymentResult(
            destination=self._dest_id(), asset=b.destAsset,
            amount=b.destAmount)
        return self.success(X.PathPaymentStrictReceiveResultSuccess(
            offers=claims, last=last))


class PathPaymentStrictSendOpFrame(_PathPaymentBase):
    """Reference: src/transactions/PathPaymentStrictSendOpFrame.cpp —
    fixed sendAmount, bounded destMin, path walked source-first."""
    MIN_PROTOCOL_VERSION = 12
    OP_TYPE = OT.PATH_PAYMENT_STRICT_SEND
    RESULT_CLS = X.PathPaymentStrictSendResult

    def C(self, name):
        return getattr(X.PathPaymentStrictSendResultCode,
                       "PATH_PAYMENT_STRICT_SEND_" + name)

    def do_check_valid(self, ltx):
        b = self.body
        if b.sendAmount <= 0 or b.destMin <= 0:
            return self.result(self.C("MALFORMED"))
        bad = self._check_common()
        return bad if bad is not None else self.success()

    def do_apply(self, ltx):
        b = self.body
        bad = self._debit_source(ltx, b.sendAmount)
        if bad is not None:
            return bad
        chain = [b.sendAsset, *b.path, b.destAsset]
        claims: List[X.ClaimAtom] = []
        have = b.sendAmount
        for i in range(len(chain) - 1):
            from_asset, to_asset = chain[i], chain[i + 1]
            if from_asset == to_asset:
                continue
            bad, wheat, sheep, hop_claims = self._convert_hop(
                ltx, from_asset, to_asset, INT64_MAX, have,
                ROUND_PATH_STRICT_SEND)
            if bad is not None:
                return bad
            claims.extend(hop_claims)
            have = wheat
        if have < b.destMin:
            return self.result(self.C("UNDER_DESTMIN"))
        bad = self._credit_dest(ltx, have)
        if bad is not None:
            return bad
        last = X.SimplePaymentResult(
            destination=self._dest_id(), asset=b.destAsset, amount=have)
        return self.success(X.PathPaymentStrictSendResultSuccess(
            offers=claims, last=last))


# --------------------------------------------------------------------------
# liquidity pools

def _isqrt(n: int) -> int:
    return math.isqrt(n)


def _pool_trustline(ltx, account_id, pool_id):
    key = trustline_key(account_id,
                        X.TrustLineAsset.liquidityPoolID(pool_id))
    return key, ltx.load(key)


class LiquidityPoolDepositOpFrame(OperationFrame):
    """Reference: src/transactions/LiquidityPoolDepositOpFrame.cpp."""
    MIN_PROTOCOL_VERSION = 18
    OP_TYPE = OT.LIQUIDITY_POOL_DEPOSIT
    RESULT_CLS = X.LiquidityPoolDepositResult

    def C(self, name):
        return getattr(X.LiquidityPoolDepositResultCode,
                       "LIQUIDITY_POOL_DEPOSIT_" + name)

    def do_check_valid(self, ltx):
        b = self.body
        if (b.maxAmountA <= 0 or b.maxAmountB <= 0
                or not price_valid(b.minPrice) or not price_valid(b.maxPrice)
                or b.minPrice.n * b.maxPrice.d > b.maxPrice.n * b.minPrice.d):
            return self.result(self.C("MALFORMED"))
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        b = self.body
        header = ltx.get_header()
        src = self.source_account_id()
        _, tl_e = _pool_trustline(ltx, src, b.liquidityPoolID)
        if tl_e is None:
            return self.result(C("NO_TRUST"))
        pool_key = X.LedgerKey.liquidityPool(
            X.LedgerKeyLiquidityPool(liquidityPoolID=b.liquidityPoolID))
        pe = ltx.load(pool_key)
        if pe is None:
            return self.result(C("NO_TRUST"))
        cp = pe.data.value.body.value
        asset_a, asset_b = cp.params.assetA, cp.params.assetB

        if cp.totalPoolShares == 0:
            amount_a, amount_b = b.maxAmountA, b.maxAmountB
            # deposit price = a/b must lie within [minPrice, maxPrice]
            if (amount_a * b.minPrice.d < amount_b * b.minPrice.n
                    or amount_a * b.maxPrice.d > amount_b * b.maxPrice.n):
                return self.result(C("BAD_PRICE"))
            shares = _isqrt(amount_a * amount_b)
        else:
            # maximal deposit at the pool price within the sender's bounds
            shares_a = cp.totalPoolShares * b.maxAmountA // cp.reserveA
            shares_b = cp.totalPoolShares * b.maxAmountB // cp.reserveB
            shares = min(shares_a, shares_b)
            amount_a = -(-shares * cp.reserveA // cp.totalPoolShares)
            amount_b = -(-shares * cp.reserveB // cp.totalPoolShares)
            if amount_a > b.maxAmountA or amount_b > b.maxAmountB:
                shares -= 1
                amount_a = -(-shares * cp.reserveA // cp.totalPoolShares)
                amount_b = -(-shares * cp.reserveB // cp.totalPoolShares)
            if shares <= 0 or amount_a <= 0 or amount_b <= 0:
                return self.result(C("UNDERFUNDED"))
            # pool price must lie within bounds
            if (cp.reserveA * b.minPrice.d < cp.reserveB * b.minPrice.n
                    or cp.reserveA * b.maxPrice.d > cp.reserveB * b.maxPrice.n):
                return self.result(C("BAD_PRICE"))

        if cp.totalPoolShares > INT64_MAX - shares \
                or cp.reserveA > INT64_MAX - amount_a \
                or cp.reserveB > INT64_MAX - amount_b:
            return self.result(C("POOL_FULL"))
        # move the deposits in
        if not self._spend(ltx, src, asset_a, amount_a, header):
            return self.result(C("UNDERFUNDED"))
        if not self._spend(ltx, src, asset_b, amount_b, header):
            return self.result(C("UNDERFUNDED"))
        tl = tl_e.data.value
        if not utils.add_trustline_balance(tl, shares):
            return self.result(C("LINE_FULL"))
        ltx.update(tl_e)
        cp.reserveA += amount_a
        cp.reserveB += amount_b
        cp.totalPoolShares += shares
        ltx.update(pe)
        return self.success()

    @staticmethod
    def _spend(ltx, src, asset, amount, header) -> bool:
        if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            e = load_account(ltx, src)
            if e is None or not utils.add_balance(e.data.value, -amount,
                                                  header):
                return False
            ltx.update(e)
            return True
        if is_issuer(src, asset):
            return True
        tl = load_trustline(ltx, src, asset)
        if tl is None or not is_authorized(tl.data.value) \
                or not utils.add_trustline_balance(tl.data.value, -amount):
            return False
        ltx.update(tl)
        return True


class LiquidityPoolWithdrawOpFrame(OperationFrame):
    """Reference: src/transactions/LiquidityPoolWithdrawOpFrame.cpp."""
    MIN_PROTOCOL_VERSION = 18
    OP_TYPE = OT.LIQUIDITY_POOL_WITHDRAW
    RESULT_CLS = X.LiquidityPoolWithdrawResult

    def C(self, name):
        return getattr(X.LiquidityPoolWithdrawResultCode,
                       "LIQUIDITY_POOL_WITHDRAW_" + name)

    def do_check_valid(self, ltx):
        b = self.body
        if b.amount <= 0 or b.minAmountA < 0 or b.minAmountB < 0:
            return self.result(self.C("MALFORMED"))
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        b = self.body
        header = ltx.get_header()
        src = self.source_account_id()
        _, tl_e = _pool_trustline(ltx, src, b.liquidityPoolID)
        if tl_e is None:
            return self.result(C("NO_TRUST"))
        tl = tl_e.data.value
        if tl.balance < b.amount:
            return self.result(C("UNDERFUNDED"))
        pool_key = X.LedgerKey.liquidityPool(
            X.LedgerKeyLiquidityPool(liquidityPoolID=b.liquidityPoolID))
        pe = ltx.load(pool_key)
        cp = pe.data.value.body.value
        amount_a = b.amount * cp.reserveA // cp.totalPoolShares
        amount_b = b.amount * cp.reserveB // cp.totalPoolShares
        if amount_a < b.minAmountA or amount_b < b.minAmountB:
            return self.result(C("UNDER_MINIMUM"))
        if not self._receive(ltx, src, cp.params.assetA, amount_a, header):
            return self.result(C("LINE_FULL"))
        if not self._receive(ltx, src, cp.params.assetB, amount_b, header):
            return self.result(C("LINE_FULL"))
        assert utils.add_trustline_balance(tl, -b.amount)
        ltx.update(tl_e)
        cp.reserveA -= amount_a
        cp.reserveB -= amount_b
        cp.totalPoolShares -= b.amount
        ltx.update(pe)
        return self.success()

    @staticmethod
    def _receive(ltx, src, asset, amount, header) -> bool:
        if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            e = load_account(ltx, src)
            if e is None or not utils.add_balance(e.data.value, amount,
                                                  header):
                return False
            ltx.update(e)
            return True
        if is_issuer(src, asset):
            return True
        tl = load_trustline(ltx, src, asset)
        if tl is None or not utils.add_trustline_balance(tl.data.value,
                                                         amount):
            return False
        ltx.update(tl)
        return True


for _cls in (ManageSellOfferOpFrame, ManageBuyOfferOpFrame,
             CreatePassiveSellOfferOpFrame, PathPaymentStrictReceiveOpFrame,
             PathPaymentStrictSendOpFrame, LiquidityPoolDepositOpFrame,
             LiquidityPoolWithdrawOpFrame):
    register_op_class(_cls.OP_TYPE, _cls)
