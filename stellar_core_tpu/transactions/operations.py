"""Operation frames: per-op checkValid + doApply.

Reference: src/transactions/OperationFrame.{h,cpp} (dispatch, thresholds,
source auth) and one <Name>OpFrame.{h,cpp} per operation (SURVEY.md §2.2).
Protocol level: current classic semantics.

Implemented here: CreateAccount, Payment, ManageData, BumpSequence,
SetOptions, ChangeTrust, AllowTrust, AccountMerge, Inflation,
CreateClaimableBalance, ClaimClaimableBalance, Clawback,
ClawbackClaimableBalance, SetTrustLineFlags, and the full CAP-33
sponsorship set (Begin/End/RevokeSponsorship with per-entry and per-signer
reserve bookkeeping — see sponsorship.py).  Offers, path payments and
liquidity pools live in offer_exchange.py; the Soroban trio
(InvokeHostFunction / ExtendFootprintTTL / RestoreFootprint) lives in
soroban/ops.py against the bounded built-in host (no wasm, SURVEY §2.4).
"""

from __future__ import annotations

from typing import Optional

from .. import xdr as X
from ..crypto.sha import sha256
from ..ledger.ledger_txn import LedgerTxn
from . import sponsorship, utils
from .signature_checker import SignatureChecker
from .utils import (INT64_MAX, THRESHOLD_HIGH, THRESHOLD_LOW, THRESHOLD_MED,
                    add_balance, add_num_entries,
                    add_trustline_balance, asset_to_trustline_asset,
                    asset_valid, cb_key, data_key, is_authorized,
                    is_authorized_to_maintain_liabilities, is_issuer,
                    load_account, load_trustline, trustline_key)

OT = X.OperationType
ORC = X.OperationResultCode


def make_op_frame(tx_frame, index: int, op: X.Operation) -> "OperationFrame":
    cls = _OP_CLASSES.get(op.body.switch, UnsupportedOpFrame)
    return cls(tx_frame, index, op)


def _inner(op_type: OT, result_union_cls, code, value=None) -> X.OperationResult:
    res = result_union_cls(code, value)
    return X.OperationResult.tr(X.OperationResultTr(op_type, res))


class OperationFrame:
    OP_TYPE: OT = None
    RESULT_CLS = None
    # ledgerVersion that introduced this operation (reference: each op
    # frame's isVersionSupported / isOpSupported gate); 0 = always
    MIN_PROTOCOL_VERSION = 0

    def __init__(self, tx_frame, index: int, op: X.Operation):
        self.tx = tx_frame
        self.index = index
        self.op = op
        self.body = op.body.value

    # -- source & auth ------------------------------------------------------
    def source_account_id(self) -> X.AccountID:
        if self.op.sourceAccount is not None:
            return X.muxed_to_account_id(self.op.sourceAccount)
        return self.tx.source_account_id()

    def threshold_level(self) -> int:
        return THRESHOLD_MED

    def check_signatures(self, checker: SignatureChecker,
                         ltx: LedgerTxn) -> Optional[X.OperationResult]:
        acc_entry = ltx.get_entry(
            X.account_key_xdr(self.source_account_id().value))
        if acc_entry is None:
            return X.OperationResult(ORC.opNO_ACCOUNT)
        from .frame import check_account_signature
        if not check_account_signature(checker, acc_entry.data.value,
                                       self.threshold_level()):
            return X.OperationResult(ORC.opBAD_AUTH)
        return None

    # -- protocol -----------------------------------------------------------
    def check_valid(self, checker: SignatureChecker,
                    ltx: LedgerTxn) -> X.OperationResult:
        if ltx.get_header().ledgerVersion < self.MIN_PROTOCOL_VERSION:
            return X.OperationResult(ORC.opNOT_SUPPORTED)
        bad = self.check_signatures(checker, ltx)
        if bad is not None:
            return bad
        return self.do_check_valid(ltx)

    def do_check_valid(self, ltx: LedgerTxn) -> X.OperationResult:
        return self.success()

    def do_apply(self, ltx: LedgerTxn) -> X.OperationResult:
        raise NotImplementedError

    # -- result helpers ------------------------------------------------------
    def result(self, code, value=None) -> X.OperationResult:
        return _inner(self.OP_TYPE, self.RESULT_CLS, code, value)

    def success(self, value=None) -> X.OperationResult:
        return self.result(self.RESULT_CLS._switch_type.enum_cls(0), value)

    def sponsorship_error(self, code: int,
                          low_reserve_code) -> Optional[X.OperationResult]:
        """Map a SponsorshipResult to this op's result: None on SUCCESS,
        the op-specific LOW_RESERVE on reserve failure, the outer
        opTOO_MANY_SPONSORING on counter overflow (the real XDR has no
        opTOO_MANY_SPONSORED; the reference maps both overflows here)."""
        if code == sponsorship.SUCCESS:
            return None
        if code == sponsorship.LOW_RESERVE:
            return self.result(low_reserve_code)
        return X.OperationResult(ORC.opTOO_MANY_SPONSORING)


class UnsupportedOpFrame(OperationFrame):
    def check_valid(self, checker, ltx):
        bad = self.check_signatures(checker, ltx)
        if bad is not None:
            return bad
        return X.OperationResult(ORC.opNOT_SUPPORTED)

    def do_apply(self, ltx):
        return X.OperationResult(ORC.opNOT_SUPPORTED)


# --------------------------------------------------------------------------

class CreateAccountOpFrame(OperationFrame):
    """Reference: src/transactions/CreateAccountOpFrame.cpp."""
    OP_TYPE = OT.CREATE_ACCOUNT
    RESULT_CLS = X.CreateAccountResult
    C = X.CreateAccountResultCode

    def do_check_valid(self, ltx):
        # v14+ (CAP-33) allows startingBalance == 0 — a sponsored account
        # needs no balance of its own; pre-v14 requires > 0
        min_ok = (self.body.startingBalance >= 0
                  if ltx.get_header().ledgerVersion >= 14
                  else self.body.startingBalance > 0)
        if not min_ok:
            return self.result(self.C.CREATE_ACCOUNT_MALFORMED)
        if self.body.destination == self.source_account_id():
            return self.result(self.C.CREATE_ACCOUNT_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        header = ltx.get_header()
        dest_key = utils.account_key(self.body.destination)
        if ltx.exists(dest_key):
            return self.result(self.C.CREATE_ACCOUNT_ALREADY_EXIST)
        new_acc = X.AccountEntry(
            accountID=self.body.destination,
            balance=self.body.startingBalance,
            seqNum=starting_sequence_number(header))
        new_entry = X.LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=X.LedgerEntryData.account(new_acc))
        sponsor_id = (sponsorship.active_sponsor(self.tx, self.body.destination)
                      if header.ledgerVersion >= 14 else None)
        if sponsor_id is not None:
            # sponsored create: the sponsor's reserve covers the new
            # account's 2 base reserves (mult=2); checked BEFORE the source
            # pays the starting balance, so the sponsor check sees the
            # pre-transfer state.  The source is loaded only afterwards —
            # the sandwich sponsor may BE the op source, and a copy held
            # across establish would clobber its numSponsoring update.
            code = sponsorship.establish_entry_sponsorship(
                ltx, header, new_entry, sponsor_id, new_entry)
            bad = self.sponsorship_error(
                code, self.C.CREATE_ACCOUNT_LOW_RESERVE)
            if bad is not None:
                return bad
        elif self.body.startingBalance < 2 * header.baseReserve:
            return self.result(self.C.CREATE_ACCOUNT_LOW_RESERVE)
        src_e = load_account(ltx, self.source_account_id())
        src = src_e.data.value
        if not add_balance(src, -self.body.startingBalance, header):
            return self.result(self.C.CREATE_ACCOUNT_UNDERFUNDED)
        ltx.update(src_e)
        ltx.create(new_entry)
        return self.success()


def starting_sequence_number(header: X.LedgerHeader) -> int:
    """ledgerSeq << 32 (reference: getStartingSequenceNumber)."""
    return header.ledgerSeq << 32


class PaymentOpFrame(OperationFrame):
    """Reference: src/transactions/PaymentOpFrame.cpp (native + credit)."""
    OP_TYPE = OT.PAYMENT
    RESULT_CLS = X.PaymentResult
    C = X.PaymentResultCode

    def do_check_valid(self, ltx):
        if self.body.amount <= 0:
            return self.result(self.C.PAYMENT_MALFORMED)
        if not asset_valid(self.body.asset):
            return self.result(self.C.PAYMENT_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        header = ltx.get_header()
        asset = self.body.asset
        amount = self.body.amount
        src_id = self.source_account_id()
        dest_id = X.muxed_to_account_id(self.body.destination)

        dest_e = load_account(ltx, dest_id)
        if dest_e is None:
            return self.result(self.C.PAYMENT_NO_DESTINATION)

        if asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            src_e = load_account(ltx, src_id)
            src = src_e.data.value
            if src_id == dest_id:
                return self.success()
            if not add_balance(src, -amount, header):
                return self.result(self.C.PAYMENT_UNDERFUNDED)
            dest = dest_e.data.value
            if not add_balance(dest, amount):
                return self.result(self.C.PAYMENT_LINE_FULL)
            src_e.lastModifiedLedgerSeq = header.ledgerSeq
            dest_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(src_e)
            ltx.update(dest_e)
            return self.success()

        issuer = asset.value.issuer
        # source side
        if not is_issuer(src_id, asset):
            src_tl_e = load_trustline(ltx, src_id, asset)
            if src_tl_e is None:
                return self.result(self.C.PAYMENT_SRC_NO_TRUST)
            src_tl = src_tl_e.data.value
            if not is_authorized(src_tl):
                return self.result(self.C.PAYMENT_SRC_NOT_AUTHORIZED)
            if not add_trustline_balance(src_tl, -amount):
                return self.result(self.C.PAYMENT_UNDERFUNDED)
            src_tl_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(src_tl_e)
        # destination side
        if not is_issuer(dest_id, asset):
            dest_tl_e = load_trustline(ltx, dest_id, asset)
            if dest_tl_e is None:
                return self.result(self.C.PAYMENT_NO_TRUST)
            dest_tl = dest_tl_e.data.value
            if not is_authorized(dest_tl):
                return self.result(self.C.PAYMENT_NOT_AUTHORIZED)
            if not add_trustline_balance(dest_tl, amount):
                return self.result(self.C.PAYMENT_LINE_FULL)
            dest_tl_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(dest_tl_e)
        return self.success()


class ManageDataOpFrame(OperationFrame):
    """Reference: src/transactions/ManageDataOpFrame.cpp."""
    OP_TYPE = OT.MANAGE_DATA
    RESULT_CLS = X.ManageDataResult
    C = X.ManageDataResultCode

    def do_check_valid(self, ltx):
        name = self.body.dataName
        if not name or len(name) > 64:
            return self.result(self.C.MANAGE_DATA_INVALID_NAME)
        try:
            name.decode("ascii")
        except UnicodeDecodeError:
            return self.result(self.C.MANAGE_DATA_INVALID_NAME)
        return self.success()

    def do_apply(self, ltx):
        header = ltx.get_header()
        src_id = self.source_account_id()
        key = data_key(src_id, self.body.dataName)
        existing = ltx.load(key)
        src_e = load_account(ltx, src_id)
        src = src_e.data.value
        if self.body.dataValue is None:
            if existing is None:
                return self.result(self.C.MANAGE_DATA_NAME_NOT_FOUND)
            ltx.erase(key)
            if sponsorship.entry_sponsor(existing) is not None:
                sponsorship.release_entry_sponsorship(
                    ltx, header, existing, src_e)
                src.numSubEntries -= 1
            else:
                add_num_entries(header, src, -1)
            ltx.update(src_e)
            return self.success()
        if existing is None:
            new_entry = X.LedgerEntry(
                lastModifiedLedgerSeq=header.ledgerSeq,
                data=X.LedgerEntryData.data(X.DataEntry(
                    accountID=src_id, dataName=self.body.dataName,
                    dataValue=self.body.dataValue)))
            code, sponsored = sponsorship.create_entry_with_possible_sponsorship(
                ltx, header, self.tx, new_entry, src_e,
                src_id if header.ledgerVersion >= 14 else None)
            bad = self.sponsorship_error(
                code, self.C.MANAGE_DATA_LOW_RESERVE)
            if bad is not None:
                return bad
            if sponsored:
                src.numSubEntries += 1
            elif not add_num_entries(header, src, 1):
                return self.result(self.C.MANAGE_DATA_LOW_RESERVE)
            ltx.update(src_e)
            ltx.create(new_entry)
        else:
            existing.data.value.dataValue = self.body.dataValue
            existing.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(existing)
        return self.success()


class BumpSequenceOpFrame(OperationFrame):
    """Reference: src/transactions/BumpSequenceOpFrame.cpp.  LOW threshold."""
    MIN_PROTOCOL_VERSION = 10
    OP_TYPE = OT.BUMP_SEQUENCE
    RESULT_CLS = X.BumpSequenceResult
    C = X.BumpSequenceResultCode

    def threshold_level(self):
        return THRESHOLD_LOW

    def do_check_valid(self, ltx):
        if self.body.bumpTo < 0:
            return self.result(self.C.BUMP_SEQUENCE_BAD_SEQ)
        return self.success()

    def do_apply(self, ltx):
        header = ltx.get_header()
        src_e = load_account(ltx, self.source_account_id())
        src = src_e.data.value
        max_seq = (header.ledgerSeq + 1) << 32
        if self.body.bumpTo > (2 ** 63 - 1):
            return self.result(self.C.BUMP_SEQUENCE_BAD_SEQ)
        if self.body.bumpTo > src.seqNum:
            src.seqNum = self.body.bumpTo
            src_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(src_e)
        return self.success()


class SetOptionsOpFrame(OperationFrame):
    """Reference: src/transactions/SetOptionsOpFrame.cpp.  HIGH threshold."""
    OP_TYPE = OT.SET_OPTIONS
    RESULT_CLS = X.SetOptionsResult
    C = X.SetOptionsResultCode

    def threshold_level(self):
        return THRESHOLD_HIGH

    def do_check_valid(self, ltx):
        b = self.body
        C = self.C
        for t in (b.masterWeight, b.lowThreshold, b.medThreshold, b.highThreshold):
            if t is not None and t > 255:
                return self.result(C.SET_OPTIONS_THRESHOLD_OUT_OF_RANGE)
        if b.setFlags is not None and b.clearFlags is not None \
                and (b.setFlags & b.clearFlags) != 0:
            return self.result(C.SET_OPTIONS_BAD_FLAGS)
        mask = X.MASK_ACCOUNT_FLAGS_V17
        if (b.setFlags is not None and b.setFlags & ~mask) or \
                (b.clearFlags is not None and b.clearFlags & ~mask):
            return self.result(C.SET_OPTIONS_UNKNOWN_FLAG)
        if b.homeDomain is not None:
            try:
                b.homeDomain.decode("ascii")
            except UnicodeDecodeError:
                return self.result(C.SET_OPTIONS_INVALID_HOME_DOMAIN)
        if b.signer is not None:
            if b.signer.key == X.SignerKey.ed25519(
                    self.source_account_id().value):
                return self.result(C.SET_OPTIONS_BAD_SIGNER)
            if b.signer.weight > 255:
                return self.result(C.SET_OPTIONS_BAD_SIGNER)
        return self.success()

    def do_apply(self, ltx):
        b = self.body
        C = self.C
        header = ltx.get_header()
        src_e = load_account(ltx, self.source_account_id())
        src = src_e.data.value
        if b.inflationDest is not None:
            if not ltx.exists(utils.account_key(b.inflationDest)):
                return self.result(C.SET_OPTIONS_INVALID_INFLATION)
            src.inflationDest = b.inflationDest
        if b.clearFlags is not None:
            if (src.flags & X.AccountFlags.AUTH_IMMUTABLE_FLAG):
                return self.result(C.SET_OPTIONS_CANT_CHANGE)
            src.flags &= ~b.clearFlags
        if b.setFlags is not None:
            if (src.flags & X.AccountFlags.AUTH_IMMUTABLE_FLAG):
                return self.result(C.SET_OPTIONS_CANT_CHANGE)
            src.flags |= b.setFlags
        th = bytearray(src.thresholds)
        if b.masterWeight is not None:
            th[0] = b.masterWeight
        if b.lowThreshold is not None:
            th[1] = b.lowThreshold
        if b.medThreshold is not None:
            th[2] = b.medThreshold
        if b.highThreshold is not None:
            th[3] = b.highThreshold
        src.thresholds = bytes(th)
        if b.homeDomain is not None:
            src.homeDomain = b.homeDomain
        if b.signer is not None:
            signers = list(src.signers)
            idx = next((i for i, s in enumerate(signers)
                        if s.key == b.signer.key), None)
            if b.signer.weight == 0:
                if idx is not None:
                    sponsor_id = sponsorship.signer_sponsor(src, idx)
                    signers.pop(idx)
                    src.signers = signers
                    sponsorship.record_signer_remove(src, idx)
                    if sponsor_id is not None:
                        # sponsored signer: release the sponsor's reserve,
                        # no reserve movement on the owner
                        sponsorship.release_signer_sponsorship(
                            ltx, header, sponsor_id, src_e)
                        src.numSubEntries -= 1
                    elif not add_num_entries(header, src, -1):
                        return self.result(C.SET_OPTIONS_LOW_RESERVE)
            elif idx is not None:
                # weight update: sponsorship (if any) is untouched
                signers[idx] = b.signer
                src.signers = signers
            else:
                if len(signers) >= X.MAX_SIGNERS:
                    return self.result(C.SET_OPTIONS_TOO_MANY_SIGNERS)
                sponsor_id = (sponsorship.active_sponsor(
                    self.tx, self.source_account_id())
                    if header.ledgerVersion >= 14 else None)
                if sponsor_id is not None:
                    code = sponsorship.establish_signer_sponsorship(
                        ltx, header, sponsor_id, src_e)
                    bad = self.sponsorship_error(
                        code, C.SET_OPTIONS_LOW_RESERVE)
                    if bad is not None:
                        return bad
                    src.numSubEntries += 1
                elif not add_num_entries(header, src, 1):
                    return self.result(C.SET_OPTIONS_LOW_RESERVE)
                # sorted insert keeps signerSponsoringIDs index-aligned
                key = b.signer.key.to_xdr()
                pos = next((i for i, s in enumerate(signers)
                            if s.key.to_xdr() > key), len(signers))
                signers.insert(pos, b.signer)
                src.signers = signers
                sponsorship.record_signer_insert(src, pos, sponsor_id)
        src_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(src_e)
        return self.success()


class ChangeTrustOpFrame(OperationFrame):
    """Reference: src/transactions/ChangeTrustOpFrame.cpp (classic assets
    and CAP-38 pool-share trustlines)."""
    OP_TYPE = OT.CHANGE_TRUST
    RESULT_CLS = X.ChangeTrustResult
    C = X.ChangeTrustResultCode

    def do_check_valid(self, ltx):
        line = self.body.line
        if line.switch == X.AssetType.ASSET_TYPE_POOL_SHARE:
            params = line.value.value  # LiquidityPoolParameters.constantProduct
            from .offer_exchange import POOL_FEE_BPS, asset_order
            if (not asset_valid(params.assetA) or not asset_valid(params.assetB)
                    or asset_order(params.assetA, params.assetB) >= 0
                    or params.fee != POOL_FEE_BPS or self.body.limit < 0):
                return self.result(self.C.CHANGE_TRUST_MALFORMED)
            return self.success()
        if line.switch == X.AssetType.ASSET_TYPE_NATIVE:
            return self.result(self.C.CHANGE_TRUST_MALFORMED)
        asset = X.Asset(line.switch, line.value)
        if not asset_valid(asset):
            return self.result(self.C.CHANGE_TRUST_MALFORMED)
        if self.body.limit < 0:
            return self.result(self.C.CHANGE_TRUST_MALFORMED)
        if is_issuer(self.source_account_id(), asset):
            return self.result(self.C.CHANGE_TRUST_SELF_NOT_ALLOWED)
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        if self.body.line.switch == X.AssetType.ASSET_TYPE_POOL_SHARE:
            return self._apply_pool_share(ltx)
        header = ltx.get_header()
        src_id = self.source_account_id()
        asset = X.Asset(self.body.line.switch, self.body.line.value)
        key = trustline_key(src_id, asset_to_trustline_asset(asset))
        existing = ltx.load(key)
        src_e = load_account(ltx, src_id)
        src = src_e.data.value
        if existing is None:
            if self.body.limit == 0:
                return self.result(C.CHANGE_TRUST_INVALID_LIMIT)
            issuer_e = ltx.get_entry(
                utils.account_key(asset.value.issuer).to_xdr())
            if issuer_e is None:
                return self.result(C.CHANGE_TRUST_NO_ISSUER)
            flags = 0
            issuer = issuer_e.data.value
            if not (issuer.flags & X.AccountFlags.AUTH_REQUIRED_FLAG):
                flags |= X.TrustLineFlags.AUTHORIZED_FLAG
            if issuer.flags & X.AccountFlags.AUTH_CLAWBACK_ENABLED_FLAG:
                flags |= X.TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG
            new_entry = X.LedgerEntry(
                lastModifiedLedgerSeq=header.ledgerSeq,
                data=X.LedgerEntryData.trustLine(X.TrustLineEntry(
                    accountID=src_id,
                    asset=asset_to_trustline_asset(asset),
                    balance=0, limit=self.body.limit, flags=flags)))
            code, sponsored = sponsorship.create_entry_with_possible_sponsorship(
                ltx, header, self.tx, new_entry, src_e,
                src_id if header.ledgerVersion >= 14 else None)
            bad = self.sponsorship_error(code, C.CHANGE_TRUST_LOW_RESERVE)
            if bad is not None:
                return bad
            if sponsored:
                src.numSubEntries += 1
            elif not add_num_entries(header, src, 1):
                return self.result(C.CHANGE_TRUST_LOW_RESERVE)
            ltx.update(src_e)
            ltx.create(new_entry)
            return self.success()
        tl = existing.data.value
        if self.body.limit == 0:
            if tl.balance != 0:
                return self.result(C.CHANGE_TRUST_INVALID_LIMIT)
            buying, selling = utils.trustline_liabilities(tl)
            if buying or selling:
                return self.result(C.CHANGE_TRUST_CANNOT_DELETE)
            ltx.erase(key)
            if sponsorship.entry_sponsor(existing) is not None:
                sponsorship.release_entry_sponsorship(
                    ltx, header, existing, src_e)
                src.numSubEntries -= 1
            else:
                add_num_entries(header, src, -1)
            ltx.update(src_e)
            return self.success()
        buying, _ = utils.trustline_liabilities(tl)
        if self.body.limit < tl.balance + buying:
            return self.result(C.CHANGE_TRUST_INVALID_LIMIT)
        if not ltx.exists(utils.account_key(asset.value.issuer)):
            return self.result(C.CHANGE_TRUST_NO_ISSUER)
        tl.limit = self.body.limit
        existing.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(existing)
        return self.success()

    def _apply_pool_share(self, ltx):
        """CAP-38 pool-share trustline create/update/delete: requires
        trustlines to both non-native constituents, counts 2 subentries,
        and maintains the LiquidityPoolEntry's poolSharesTrustLineCount
        plus each constituent trustline's liquidityPoolUseCount
        (reference: ChangeTrustOpFrame::tryIncrementPoolUseCount)."""
        from .offer_exchange import pool_id_for
        C = self.C
        header = ltx.get_header()
        src_id = self.source_account_id()
        params = self.body.line.value.value
        pool_id = pool_id_for(params.assetA, params.assetB, params.fee)
        key = trustline_key(src_id, X.TrustLineAsset.liquidityPoolID(pool_id))
        existing = ltx.load(key)
        src_e = load_account(ltx, src_id)
        src = src_e.data.value
        pool_key = X.LedgerKey.liquidityPool(
            X.LedgerKeyLiquidityPool(liquidityPoolID=pool_id))

        if existing is None:
            if self.body.limit == 0:
                return self.result(C.CHANGE_TRUST_INVALID_LIMIT)
            # constituents: native needs nothing; credit assets need an
            # authorized trustline, whose pool-use count we bump
            for asset in (params.assetA, params.assetB):
                if asset.switch == X.AssetType.ASSET_TYPE_NATIVE \
                        or is_issuer(src_id, asset):
                    continue
                tl_e = utils.load_trustline(ltx, src_id, asset)
                if tl_e is None:
                    return self.result(C.CHANGE_TRUST_TRUST_LINE_MISSING)
                # CAP-38: maintain-liabilities suffices for pool membership
                if not utils.is_authorized_to_maintain_liabilities(
                        tl_e.data.value):
                    return self.result(C.CHANGE_TRUST_NOT_AUTH_MAINTAIN_LIABILITIES)
                self._bump_pool_use(tl_e, +1)
                ltx.update(tl_e)
            new_tl_entry = X.LedgerEntry(
                lastModifiedLedgerSeq=header.ledgerSeq,
                data=X.LedgerEntryData.trustLine(X.TrustLineEntry(
                    accountID=src_id,
                    asset=X.TrustLineAsset.liquidityPoolID(pool_id),
                    balance=0, limit=self.body.limit,
                    flags=X.TrustLineFlags.AUTHORIZED_FLAG)))
            code, sponsored = sponsorship.create_entry_with_possible_sponsorship(
                ltx, header, self.tx, new_tl_entry, src_e,
                src_id if header.ledgerVersion >= 18 else None)
            bad = self.sponsorship_error(code, C.CHANGE_TRUST_LOW_RESERVE)
            if bad is not None:
                return bad
            if sponsored:
                src.numSubEntries += 2
            elif not add_num_entries(header, src, 2):
                return self.result(C.CHANGE_TRUST_LOW_RESERVE)
            ltx.update(src_e)
            pe = ltx.load(pool_key)
            if pe is None:
                cp = X.LiquidityPoolEntryConstantProduct(
                    params=params, reserveA=0, reserveB=0,
                    totalPoolShares=0, poolSharesTrustLineCount=1)
                ltx.create(X.LedgerEntry(
                    lastModifiedLedgerSeq=header.ledgerSeq,
                    data=X.LedgerEntryData.liquidityPool(X.LiquidityPoolEntry(
                        liquidityPoolID=pool_id,
                        body=X.LiquidityPoolEntryBody.constantProduct(cp)))))
            else:
                pe.data.value.body.value.poolSharesTrustLineCount += 1
                ltx.update(pe)
            ltx.create(new_tl_entry)
            return self.success()

        tl = existing.data.value
        if self.body.limit == 0:
            if tl.balance != 0:
                return self.result(C.CHANGE_TRUST_INVALID_LIMIT)
            ltx.erase(key)
            if sponsorship.entry_sponsor(existing) is not None:
                sponsorship.release_entry_sponsorship(
                    ltx, header, existing, src_e)
                src.numSubEntries -= 2
            else:
                add_num_entries(header, src, -2)
            ltx.update(src_e)
            pe = ltx.load(pool_key)
            cp = pe.data.value.body.value
            cp.poolSharesTrustLineCount -= 1
            if cp.poolSharesTrustLineCount == 0:
                ltx.erase(pool_key)
            else:
                ltx.update(pe)
            for asset in (params.assetA, params.assetB):
                if asset.switch == X.AssetType.ASSET_TYPE_NATIVE \
                        or is_issuer(src_id, asset):
                    continue
                tl_e = utils.load_trustline(ltx, src_id, asset)
                if tl_e is not None:
                    self._bump_pool_use(tl_e, -1)
                    ltx.update(tl_e)
            return self.success()
        if self.body.limit < tl.balance:
            return self.result(C.CHANGE_TRUST_INVALID_LIMIT)
        tl.limit = self.body.limit
        existing.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(existing)
        return self.success()

    @staticmethod
    def _bump_pool_use(tl_entry, delta: int) -> None:
        """Adjust TrustLineEntry ext-v2 liquidityPoolUseCount."""
        tl = tl_entry.data.value
        if tl.ext.switch == 0:
            tl.ext = X.TrustLineEntryExt.v1(X.TrustLineEntryV1(
                liabilities=X.Liabilities(buying=0, selling=0)))
        v1 = tl.ext.value
        if v1.ext.switch != 2:
            v1.ext = X.TrustLineEntryV1Ext.v2(X.TrustLineEntryExtensionV2(
                liquidityPoolUseCount=0))
        v1.ext.value.liquidityPoolUseCount += delta


class AllowTrustOpFrame(OperationFrame):
    """Reference: src/transactions/AllowTrustOpFrame.cpp.  LOW threshold."""
    OP_TYPE = OT.ALLOW_TRUST
    RESULT_CLS = X.AllowTrustResult
    C = X.AllowTrustResultCode

    def threshold_level(self):
        return THRESHOLD_LOW

    def do_check_valid(self, ltx):
        if self.body.authorize > (X.TrustLineFlags.AUTHORIZED_FLAG
                                  | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG):
            return self.result(self.C.ALLOW_TRUST_MALFORMED)
        if (self.body.authorize & X.TrustLineFlags.AUTHORIZED_FLAG) and \
                (self.body.authorize & X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG):
            return self.result(self.C.ALLOW_TRUST_MALFORMED)
        code = self.body.asset
        probe = X.Asset(code.switch, X.AlphaNum4(
            assetCode=code.value, issuer=self.source_account_id())
            if code.switch == X.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4
            else X.AlphaNum12(assetCode=code.value,
                              issuer=self.source_account_id()))
        if not asset_valid(probe):
            return self.result(self.C.ALLOW_TRUST_MALFORMED)
        if self.body.trustor == self.source_account_id():
            return self.result(self.C.ALLOW_TRUST_SELF_NOT_ALLOWED)
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        src_id = self.source_account_id()
        src_e = load_account(ltx, src_id)
        src = src_e.data.value
        if not (src.flags & X.AccountFlags.AUTH_REQUIRED_FLAG) \
                and self.body.authorize != 0:
            pass  # issuing auth when not required is allowed (no-op flagging)
        if not (src.flags & X.AccountFlags.AUTH_REVOCABLE_FLAG) \
                and self.body.authorize != X.TrustLineFlags.AUTHORIZED_FLAG:
            return self.result(C.ALLOW_TRUST_CANT_REVOKE)
        code = self.body.asset
        if code.switch == X.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4:
            asset = X.Asset.alphaNum4(X.AlphaNum4(assetCode=code.value,
                                                  issuer=src_id))
        else:
            asset = X.Asset.alphaNum12(X.AlphaNum12(assetCode=code.value,
                                                    issuer=src_id))
        tl_e = load_trustline(ltx, self.body.trustor, asset)
        if tl_e is None:
            return self.result(C.ALLOW_TRUST_NO_TRUST_LINE)
        tl = tl_e.data.value
        auth_mask = (X.TrustLineFlags.AUTHORIZED_FLAG
                     | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG)
        tl.flags = (tl.flags & ~auth_mask) | self.body.authorize
        tl_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(tl_e)
        return self.success()


class AccountMergeOpFrame(OperationFrame):
    """Reference: src/transactions/MergeOpFrame.cpp.  HIGH threshold."""
    OP_TYPE = OT.ACCOUNT_MERGE
    RESULT_CLS = X.AccountMergeResult
    C = X.AccountMergeResultCode

    def threshold_level(self):
        return THRESHOLD_HIGH

    def do_check_valid(self, ltx):
        dest = X.muxed_to_account_id(self.op.body.value)
        if dest == self.source_account_id():
            return self.result(self.C.ACCOUNT_MERGE_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        src_id = self.source_account_id()
        dest_id = X.muxed_to_account_id(self.op.body.value)
        dest_e = load_account(ltx, dest_id)
        if dest_e is None:
            return self.result(C.ACCOUNT_MERGE_NO_ACCOUNT)
        src_e = load_account(ltx, src_id)
        src = src_e.data.value
        if src.flags & X.AccountFlags.AUTH_IMMUTABLE_FLAG:
            return self.result(C.ACCOUNT_MERGE_IMMUTABLE_SET)
        if header.ledgerVersion >= 14:
            # CAP-33 (reference: MergeOpFrame via loadSponsorship /
            # loadSponsorshipCounter): a party to an OPEN Begin/End
            # sandwich — sponsored account OR sponsor — cannot merge away
            # mid-tx; this is also what keeps the sandwich sponsor loadable
            # for the rest of the tx (see establish_sponsorship)
            ctx = getattr(self.tx, "_sponsorship_ctx", None) or {}
            src_x = src_id.to_xdr()
            if src_x in ctx or src_x in ctx.values():
                return self.result(C.ACCOUNT_MERGE_IS_SPONSOR)
        if src.numSubEntries != 0:
            return self.result(C.ACCOUNT_MERGE_HAS_SUB_ENTRIES)
        if utils.num_sponsoring(src) != 0:
            return self.result(C.ACCOUNT_MERGE_IS_SPONSOR)
        # seqnum too far: src seq >= max seq for current ledger
        if src.seqNum >= ((header.ledgerSeq + 1) << 32) - 1 \
                and src.seqNum == 2 ** 63 - 1:
            return self.result(C.ACCOUNT_MERGE_SEQNUM_TOO_FAR)
        balance = src.balance
        dest = dest_e.data.value
        if not add_balance(dest, balance):
            return self.result(C.ACCOUNT_MERGE_DEST_FULL)
        dest_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(dest_e)
        # a sponsored account entry releases its sponsor's 2 reserve units
        # when it leaves the ledger (removeEntryWithPossibleSponsorship);
        # the dying account's own numSponsored vanishes with it
        sponsorship.release_entry_sponsorship(ltx, header, src_e, None)
        ltx.erase(utils.account_key(src_id))
        return self.result(C.ACCOUNT_MERGE_SUCCESS, balance)


class InflationOpFrame(OperationFrame):
    """Reference: src/transactions/InflationOpFrame.cpp — inflation is
    disabled from protocol 12 (always NOT_TIME)."""
    OP_TYPE = OT.INFLATION
    RESULT_CLS = X.InflationResult
    C = X.InflationResultCode

    def do_apply(self, ltx):
        return self.result(self.C.INFLATION_NOT_TIME)


class CreateClaimableBalanceOpFrame(OperationFrame):
    """Reference: src/transactions/CreateClaimableBalanceOpFrame.cpp."""
    MIN_PROTOCOL_VERSION = 14
    OP_TYPE = OT.CREATE_CLAIMABLE_BALANCE
    RESULT_CLS = X.CreateClaimableBalanceResult
    C = X.CreateClaimableBalanceResultCode

    def do_check_valid(self, ltx):
        b = self.body
        if b.amount <= 0 or not asset_valid(b.asset) or not b.claimants:
            return self.result(self.C.CREATE_CLAIMABLE_BALANCE_MALFORMED)
        dests = set()
        for c in b.claimants:
            dests.add(c.value.destination.to_xdr())
            if not _predicate_valid(c.value.predicate):
                return self.result(self.C.CREATE_CLAIMABLE_BALANCE_MALFORMED)
        if len(dests) != len(b.claimants):
            return self.result(self.C.CREATE_CLAIMABLE_BALANCE_MALFORMED)
        return self.success()

    def balance_id(self, ltx: LedgerTxn) -> X.ClaimableBalanceID:
        """sha256(OperationID preimage) (reference: getBalanceID)."""
        pre = X.HashIDPreimage(
            X.EnvelopeType.ENVELOPE_TYPE_OP_ID,
            X.OperationIDId(sourceAccount=self.tx.source_account_id(),
                            seqNum=self.tx.seq_num, opNum=self.index))
        return X.ClaimableBalanceID.v0(sha256(pre.to_xdr()))

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        b = self.body
        src_id = self.source_account_id()
        # reserve for claimants is a sponsored reserve (reference:
        # CreateClaimableBalanceOpFrame — createEntryWithPossibleSponsorship;
        # the entry has no owner account, so only numSponsoring moves).
        # With an active sandwich for the source, the sandwich sponsor takes
        # it; otherwise the source sponsors its own creation.
        sponsor_id = sponsorship.active_sponsor(self.tx, src_id) or src_id
        if sponsor_id != src_id:
            # external sponsor: counters move on the sponsor inside the
            # helper (one unit per claimant; the entry is owner-less); the
            # source is loaded afterwards so its copy cannot clobber a
            # sponsor update
            code = sponsorship.establish_sponsorship(
                ltx, header, sponsor_id, None, len(b.claimants))
            bad = self.sponsorship_error(
                code, C.CREATE_CLAIMABLE_BALANCE_LOW_RESERVE)
            if bad is not None:
                return bad
        src_e = load_account(ltx, src_id)
        src = src_e.data.value
        if sponsor_id == src_id:
            if not utils.add_num_sponsoring(header, src, len(b.claimants)):
                return self.result(C.CREATE_CLAIMABLE_BALANCE_LOW_RESERVE)
        if b.asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            if not add_balance(src, -b.amount, header):
                return self.result(C.CREATE_CLAIMABLE_BALANCE_UNDERFUNDED)
        elif not is_issuer(src_id, b.asset):
            tl_e = load_trustline(ltx, src_id, b.asset)
            if tl_e is None:
                return self.result(C.CREATE_CLAIMABLE_BALANCE_NO_TRUST)
            tl = tl_e.data.value
            if not is_authorized(tl):
                return self.result(C.CREATE_CLAIMABLE_BALANCE_NOT_AUTHORIZED)
            if not add_trustline_balance(tl, -b.amount):
                return self.result(C.CREATE_CLAIMABLE_BALANCE_UNDERFUNDED)
            tl_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(tl_e)
        bid = self.balance_id(ltx)
        # clawback flag propagates from issuer trustline/source account
        flags = 0
        if b.asset.switch != X.AssetType.ASSET_TYPE_NATIVE \
                and not is_issuer(src_id, b.asset):
            tl_probe = load_trustline(ltx, src_id, b.asset)
            if tl_probe is not None and (
                    tl_probe.data.value.flags
                    & X.TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG):
                flags = X.ClaimableBalanceFlags.CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG
        entry = X.ClaimableBalanceEntry(
            balanceID=bid, claimants=list(b.claimants), asset=b.asset,
            amount=b.amount,
            ext=(X.ClaimableBalanceEntryExt.v1(
                    X.ClaimableBalanceEntryExtensionV1(flags=flags))
                 if flags else X.ClaimableBalanceEntryExt.v0()))
        ltx.update(src_e)
        ltx.create(X.LedgerEntry(
            lastModifiedLedgerSeq=header.ledgerSeq,
            data=X.LedgerEntryData.claimableBalance(entry),
            ext=X.LedgerEntryExt.v1(X.LedgerEntryExtensionV1(
                sponsoringID=sponsor_id,
                ext=X.LedgerEntryExtensionV1Ext.v0()))))
        return self.result(C.CREATE_CLAIMABLE_BALANCE_SUCCESS, bid)


def _predicate_valid(pred: X.ClaimPredicate, depth: int = 0) -> bool:
    if depth > 4:
        return False
    PT = X.ClaimPredicateType
    if pred.switch == PT.CLAIM_PREDICATE_AND or pred.switch == PT.CLAIM_PREDICATE_OR:
        if len(pred.value) != 2:
            return False
        return all(_predicate_valid(p, depth + 1) for p in pred.value)
    if pred.switch == PT.CLAIM_PREDICATE_NOT:
        return pred.value is not None and _predicate_valid(pred.value, depth + 1)
    if pred.switch == PT.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME:
        return pred.value >= 0
    if pred.switch == PT.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME:
        return pred.value >= 0
    return True


def predicate_satisfied(pred: X.ClaimPredicate, close_time: int,
                        created_time: int) -> bool:
    PT = X.ClaimPredicateType
    if pred.switch == PT.CLAIM_PREDICATE_UNCONDITIONAL:
        return True
    if pred.switch == PT.CLAIM_PREDICATE_AND:
        return all(predicate_satisfied(p, close_time, created_time)
                   for p in pred.value)
    if pred.switch == PT.CLAIM_PREDICATE_OR:
        return any(predicate_satisfied(p, close_time, created_time)
                   for p in pred.value)
    if pred.switch == PT.CLAIM_PREDICATE_NOT:
        return not predicate_satisfied(pred.value, close_time, created_time)
    if pred.switch == PT.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME:
        return close_time < pred.value
    if pred.switch == PT.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME:
        return close_time < created_time + pred.value
    return False


def _release_claimable_balance_reserve(ltx, cb_entry: X.LedgerEntry,
                                       header) -> None:
    """Refund the sponsor's numSponsoring when a claimable balance leaves
    the ledger (reference: removeEntryWithPossibleSponsorship)."""
    if cb_entry.ext.switch != 1 or cb_entry.ext.value.sponsoringID is None:
        return
    sp_e = load_account(ltx, cb_entry.ext.value.sponsoringID)
    if sp_e is None:
        return  # unreachable while merge rejects IS_SPONSOR; defensive
    released = utils.add_num_sponsoring(
        header, sp_e.data.value, -len(cb_entry.data.value.claimants))
    if not released:  # decrement below zero: counts were already corrupt
        raise RuntimeError("claimable balance sponsor count underflow")
    sp_e.lastModifiedLedgerSeq = header.ledgerSeq
    ltx.update(sp_e)


class ClaimClaimableBalanceOpFrame(OperationFrame):
    """Reference: src/transactions/ClaimClaimableBalanceOpFrame.cpp."""
    MIN_PROTOCOL_VERSION = 14
    OP_TYPE = OT.CLAIM_CLAIMABLE_BALANCE
    RESULT_CLS = X.ClaimClaimableBalanceResult
    C = X.ClaimClaimableBalanceResultCode

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        src_id = self.source_account_id()
        key = cb_key(self.body.balanceID)
        cb_e = ltx.load(key)
        if cb_e is None:
            return self.result(C.CLAIM_CLAIMABLE_BALANCE_DOES_NOT_EXIST)
        cb = cb_e.data.value
        claimant = next((c for c in cb.claimants
                         if c.value.destination == src_id), None)
        # creation time approximated by entry lastModified ledger's close —
        # we carry absolute predicates only against closeTime (relative
        # predicates resolved at create by the reference; simplification).
        if claimant is None or not predicate_satisfied(
                claimant.value.predicate, header.scpValue.closeTime, 0):
            return self.result(C.CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM)
        if cb.asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            acc_e = load_account(ltx, src_id)
            acc = acc_e.data.value
            if not add_balance(acc, cb.amount):
                return self.result(C.CLAIM_CLAIMABLE_BALANCE_LINE_FULL)
            acc_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(acc_e)
        elif not is_issuer(src_id, cb.asset):
            tl_e = load_trustline(ltx, src_id, cb.asset)
            if tl_e is None:
                return self.result(C.CLAIM_CLAIMABLE_BALANCE_NO_TRUST)
            tl = tl_e.data.value
            if not is_authorized(tl):
                return self.result(C.CLAIM_CLAIMABLE_BALANCE_NOT_AUTHORIZED)
            if not add_trustline_balance(tl, cb.amount):
                return self.result(C.CLAIM_CLAIMABLE_BALANCE_LINE_FULL)
            tl_e.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(tl_e)
        _release_claimable_balance_reserve(ltx, cb_e, header)
        ltx.erase(key)
        return self.success()


class ClawbackOpFrame(OperationFrame):
    """Reference: src/transactions/ClawbackOpFrame.cpp."""
    MIN_PROTOCOL_VERSION = 17
    OP_TYPE = OT.CLAWBACK
    RESULT_CLS = X.ClawbackResult
    C = X.ClawbackResultCode

    def do_check_valid(self, ltx):
        if self.body.amount <= 0 or not asset_valid(self.body.asset):
            return self.result(self.C.CLAWBACK_MALFORMED)
        if not is_issuer(self.source_account_id(), self.body.asset):
            return self.result(self.C.CLAWBACK_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        from_id = X.muxed_to_account_id(self.body.from_)
        tl_e = load_trustline(ltx, from_id, self.body.asset)
        if tl_e is None:
            return self.result(C.CLAWBACK_NO_TRUST)
        tl = tl_e.data.value
        if not (tl.flags & X.TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG):
            return self.result(C.CLAWBACK_NOT_CLAWBACK_ENABLED)
        if not add_trustline_balance(tl, -self.body.amount):
            return self.result(C.CLAWBACK_UNDERFUNDED)
        tl_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(tl_e)
        return self.success()


class ClawbackClaimableBalanceOpFrame(OperationFrame):
    """Reference: src/transactions/ClawbackClaimableBalanceOpFrame.cpp."""
    MIN_PROTOCOL_VERSION = 17
    OP_TYPE = OT.CLAWBACK_CLAIMABLE_BALANCE
    RESULT_CLS = X.ClawbackClaimableBalanceResult
    C = X.ClawbackClaimableBalanceResultCode

    def do_apply(self, ltx):
        C = self.C
        key = cb_key(self.body.balanceID)
        cb_e = ltx.load(key)
        if cb_e is None:
            return self.result(C.CLAWBACK_CLAIMABLE_BALANCE_DOES_NOT_EXIST)
        cb = cb_e.data.value
        if not is_issuer(self.source_account_id(), cb.asset):
            return self.result(C.CLAWBACK_CLAIMABLE_BALANCE_NOT_ISSUER)
        flags = cb.ext.value.flags if cb.ext.switch == 1 else 0
        if not (flags & X.ClaimableBalanceFlags.CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG):
            return self.result(C.CLAWBACK_CLAIMABLE_BALANCE_NOT_CLAWBACK_ENABLED)
        _release_claimable_balance_reserve(ltx, cb_e, ltx.get_header())
        ltx.erase(key)
        return self.success()


class SetTrustLineFlagsOpFrame(OperationFrame):
    """Reference: src/transactions/SetTrustLineFlagsOpFrame.cpp. LOW."""
    MIN_PROTOCOL_VERSION = 17
    OP_TYPE = OT.SET_TRUST_LINE_FLAGS
    RESULT_CLS = X.SetTrustLineFlagsResult
    C = X.SetTrustLineFlagsResultCode

    def threshold_level(self):
        return THRESHOLD_LOW

    def do_check_valid(self, ltx):
        b = self.body
        C = self.C
        if not asset_valid(b.asset) or b.asset.switch == X.AssetType.ASSET_TYPE_NATIVE:
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)
        if not is_issuer(self.source_account_id(), b.asset):
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)
        if b.trustor == self.source_account_id():
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)
        if b.setFlags & b.clearFlags:
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)
        auth_mask = (X.TrustLineFlags.AUTHORIZED_FLAG
                     | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG
                     | X.TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG)
        if (b.setFlags | b.clearFlags) & ~auth_mask:
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)
        if b.setFlags & X.TrustLineFlags.TRUSTLINE_CLAWBACK_ENABLED_FLAG:
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)  # can only clear
        if (b.setFlags & X.TrustLineFlags.AUTHORIZED_FLAG) and \
                (b.setFlags & X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG):
            return self.result(C.SET_TRUST_LINE_FLAGS_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        src_e = load_account(ltx, self.source_account_id())
        src = src_e.data.value
        b = self.body
        revoking = (b.clearFlags & (X.TrustLineFlags.AUTHORIZED_FLAG
                    | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG)) != 0
        if revoking and not (src.flags & X.AccountFlags.AUTH_REVOCABLE_FLAG):
            return self.result(C.SET_TRUST_LINE_FLAGS_CANT_REVOKE)
        tl_e = load_trustline(ltx, b.trustor, b.asset)
        if tl_e is None:
            return self.result(C.SET_TRUST_LINE_FLAGS_NO_TRUST_LINE)
        tl = tl_e.data.value
        new_flags = (tl.flags & ~b.clearFlags) | b.setFlags
        auth = new_flags & (X.TrustLineFlags.AUTHORIZED_FLAG
                            | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG)
        if auth == (X.TrustLineFlags.AUTHORIZED_FLAG
                    | X.TrustLineFlags.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG):
            return self.result(C.SET_TRUST_LINE_FLAGS_INVALID_STATE)
        tl.flags = new_flags
        tl_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(tl_e)
        return self.success()


class BeginSponsoringFutureReservesOpFrame(OperationFrame):
    """Reference: src/transactions/BeginSponsoringFutureReservesOpFrame.cpp.
    Opens a sandwich: until the sponsored account's
    EndSponsoringFutureReserves, every reserve created FOR that account
    (entries via create_entry_with_possible_sponsorship, signers via
    establish_signer_sponsorship) is sponsored by this op's source."""
    MIN_PROTOCOL_VERSION = 14
    OP_TYPE = OT.BEGIN_SPONSORING_FUTURE_RESERVES
    RESULT_CLS = X.BeginSponsoringFutureReservesResult
    C = X.BeginSponsoringFutureReservesResultCode

    def do_check_valid(self, ltx):
        if self.body.sponsoredID == self.source_account_id():
            return self.result(
                self.C.BEGIN_SPONSORING_FUTURE_RESERVES_MALFORMED)
        return self.success()

    def do_apply(self, ltx):
        C = self.C
        ctx = _sponsorship_ctx(self.tx)
        sponsored = self.body.sponsoredID.to_xdr()
        sponsor = self.source_account_id().to_xdr()
        if sponsored in ctx:
            return self.result(C.BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED)
        # RECURSIVE guards (reference: no sponsorship chains): the sponsor
        # must not itself be inside a sandwich, and the sponsored account
        # must not currently be sponsoring someone.  A sponsor MAY open
        # several concurrent sandwiches for different accounts.
        if sponsor in ctx:
            return self.result(C.BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE)
        for sponsor_of in ctx.values():
            if sponsor_of == sponsored:
                return self.result(C.BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE)
        ctx[sponsored] = sponsor
        return self.success()


class EndSponsoringFutureReservesOpFrame(OperationFrame):
    MIN_PROTOCOL_VERSION = 14
    OP_TYPE = OT.END_SPONSORING_FUTURE_RESERVES
    RESULT_CLS = X.EndSponsoringFutureReservesResult
    C = X.EndSponsoringFutureReservesResultCode

    def do_apply(self, ltx):
        ctx = _sponsorship_ctx(self.tx)
        me = self.source_account_id().to_xdr()
        if me not in ctx:
            return self.result(
                self.C.END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED)
        del ctx[me]
        return self.success()


class RevokeSponsorshipOpFrame(OperationFrame):
    """Reference: src/transactions/RevokeSponsorshipOpFrame.cpp —
    updateLedgerEntrySponsorship / updateSignerSponsorship.

    Semantics (CAP-33): the op source must be the entry's CURRENT sponsor
    (when sponsored) or its owner (when not).  The NEW sponsor is the
    active sandwich sponsor of the op source, if any:
      old=None, new=None  -> no-op SUCCESS
      old=None, new=S     -> establish (owner numSponsored+, S numSponsoring+)
      old=S1,  new=None   -> remove: reserve returns to the owner, which
                             must afford it (LOW_RESERVE); claimable
                             balances have no owner -> ONLY_TRANSFERABLE
      old=S1,  new=S2     -> transfer (S1 releases, S2 takes w/ checks)
    The canonical transfer recipe is therefore: S2 begins a sandwich FOR S1
    (the current sponsor), S1 runs RevokeSponsorship, S1 ends it."""
    MIN_PROTOCOL_VERSION = 14
    OP_TYPE = OT.REVOKE_SPONSORSHIP
    RESULT_CLS = X.RevokeSponsorshipResult
    C = X.RevokeSponsorshipResultCode

    _SPONSORABLE = (X.LedgerEntryType.ACCOUNT, X.LedgerEntryType.TRUSTLINE,
                    X.LedgerEntryType.OFFER, X.LedgerEntryType.DATA,
                    X.LedgerEntryType.CLAIMABLE_BALANCE)

    @staticmethod
    def _owner_of(key: X.LedgerKey):
        t = key.switch
        if t == X.LedgerEntryType.ACCOUNT:
            return key.value.accountID
        if t in (X.LedgerEntryType.TRUSTLINE, X.LedgerEntryType.DATA):
            return key.value.accountID
        if t == X.LedgerEntryType.OFFER:
            return key.value.sellerID
        return None  # claimable balance: owner-less reserve

    def do_apply(self, ltx):
        C = self.C
        header = ltx.get_header()
        src = self.source_account_id()
        new_sponsor = sponsorship.active_sponsor(self.tx, src)
        arm = self.op.body.value
        if arm.switch == X.RevokeSponsorshipType.REVOKE_SPONSORSHIP_LEDGER_ENTRY:
            key = arm.value
            if key.switch not in self._SPONSORABLE:
                return self.result(C.REVOKE_SPONSORSHIP_MALFORMED)
            entry = ltx.load(key)
            if entry is None:
                return self.result(C.REVOKE_SPONSORSHIP_DOES_NOT_EXIST)
            owner_id = self._owner_of(key)
            old_sponsor = sponsorship.entry_sponsor(entry)
            if new_sponsor is not None and new_sponsor == owner_id:
                # transferring to the owner == the owner reclaiming its own
                # reserve: a removal, never a self-sponsorship record
                new_sponsor = None
            if old_sponsor is not None:
                if src != old_sponsor:
                    return self.result(C.REVOKE_SPONSORSHIP_NOT_SPONSOR)
            elif owner_id is None or src != owner_id:
                return self.result(C.REVOKE_SPONSORSHIP_NOT_SPONSOR)
            if old_sponsor == new_sponsor or \
                    (old_sponsor is None and new_sponsor is None):
                return self.success()
            mult = sponsorship.compute_multiplier(entry)
            # the owner's account entry, when the owner is not the entry
            # itself (an ACCOUNT key's owner IS the entry)
            own_is_entry = key.switch == X.LedgerEntryType.ACCOUNT
            owner_e = entry if own_is_entry else (
                load_account(ltx, owner_id) if owner_id is not None else None)
            if old_sponsor is not None:
                if new_sponsor is None and owner_id is None:
                    return self.result(C.REVOKE_SPONSORSHIP_ONLY_TRANSFERABLE)
                if new_sponsor is None:
                    owner = owner_e.data.value
                    if not sponsorship.owner_can_afford(header, owner, mult):
                        return self.result(C.REVOKE_SPONSORSHIP_LOW_RESERVE)
                sponsorship.release_entry_sponsorship(
                    ltx, header, entry, owner_e)
                entry.ext = X.LedgerEntryExt.v0()
            if new_sponsor is not None:
                code = sponsorship.establish_entry_sponsorship(
                    ltx, header, entry, new_sponsor, owner_e)
                bad = self.sponsorship_error(
                    code, C.REVOKE_SPONSORSHIP_LOW_RESERVE)
                if bad is not None:
                    return bad
            entry.lastModifiedLedgerSeq = header.ledgerSeq
            ltx.update(entry)
            if owner_e is not None and not own_is_entry:
                owner_e.lastModifiedLedgerSeq = header.ledgerSeq
                ltx.update(owner_e)
            return self.success()

        # SIGNER arm
        acc_id = arm.value.accountID
        signer_key = arm.value.signerKey
        acc_e = load_account(ltx, acc_id)
        if acc_e is None:
            return self.result(C.REVOKE_SPONSORSHIP_DOES_NOT_EXIST)
        acc = acc_e.data.value
        idx = next((i for i, s in enumerate(acc.signers)
                    if s.key == signer_key), None)
        if idx is None:
            return self.result(C.REVOKE_SPONSORSHIP_DOES_NOT_EXIST)
        old_sponsor = sponsorship.signer_sponsor(acc, idx)
        if new_sponsor is not None and new_sponsor == acc_id:
            new_sponsor = None  # owner reclaiming its own reserve
        if old_sponsor is not None:
            if src != old_sponsor:
                return self.result(C.REVOKE_SPONSORSHIP_NOT_SPONSOR)
        elif src != acc_id:
            return self.result(C.REVOKE_SPONSORSHIP_NOT_SPONSOR)
        if old_sponsor == new_sponsor or \
                (old_sponsor is None and new_sponsor is None):
            return self.success()
        if old_sponsor is not None:
            if new_sponsor is None and not sponsorship.owner_can_afford(
                    header, acc, 1):
                return self.result(C.REVOKE_SPONSORSHIP_LOW_RESERVE)
            sponsorship.release_signer_sponsorship(
                ltx, header, old_sponsor, acc_e)
        if new_sponsor is not None:
            code = sponsorship.establish_signer_sponsorship(
                ltx, header, new_sponsor, acc_e)
            bad = self.sponsorship_error(
                code, C.REVOKE_SPONSORSHIP_LOW_RESERVE)
            if bad is not None:
                return bad
        ids = sponsorship._aligned_sponsoring_ids(acc)
        ids[idx] = new_sponsor
        utils._acc_ext_v2(acc).signerSponsoringIDs = ids
        acc_e.lastModifiedLedgerSeq = header.ledgerSeq
        ltx.update(acc_e)
        return self.success()


def _sponsorship_ctx(tx_frame) -> dict:
    ctx = getattr(tx_frame, "_sponsorship_ctx", None)
    if ctx is None:
        ctx = {}
        tx_frame._sponsorship_ctx = ctx
    return ctx


_OP_CLASSES = {
    OT.CREATE_ACCOUNT: CreateAccountOpFrame,
    OT.PAYMENT: PaymentOpFrame,
    OT.MANAGE_DATA: ManageDataOpFrame,
    OT.BUMP_SEQUENCE: BumpSequenceOpFrame,
    OT.SET_OPTIONS: SetOptionsOpFrame,
    OT.CHANGE_TRUST: ChangeTrustOpFrame,
    OT.ALLOW_TRUST: AllowTrustOpFrame,
    OT.ACCOUNT_MERGE: AccountMergeOpFrame,
    OT.INFLATION: InflationOpFrame,
    OT.CREATE_CLAIMABLE_BALANCE: CreateClaimableBalanceOpFrame,
    OT.CLAIM_CLAIMABLE_BALANCE: ClaimClaimableBalanceOpFrame,
    OT.CLAWBACK: ClawbackOpFrame,
    OT.CLAWBACK_CLAIMABLE_BALANCE: ClawbackClaimableBalanceOpFrame,
    OT.SET_TRUST_LINE_FLAGS: SetTrustLineFlagsOpFrame,
    OT.BEGIN_SPONSORING_FUTURE_RESERVES: BeginSponsoringFutureReservesOpFrame,
    OT.END_SPONSORING_FUTURE_RESERVES: EndSponsoringFutureReservesOpFrame,
    OT.REVOKE_SPONSORSHIP: RevokeSponsorshipOpFrame,
}


def register_op_class(op_type: OT, cls) -> None:
    """Extension point for op frames defined in other modules
    (offer_exchange.py registers the order-book ops)."""
    _OP_CLASSES[op_type] = cls


# Offer/path-payment/pool frames register themselves on import (bottom of
# module to avoid a circular import — offer_ops subclasses OperationFrame).
from . import offer_ops  # noqa: E402,F401
# Soroban frames likewise (soroban/ops.py subclasses OperationFrame and
# registers InvokeHostFunction / ExtendFootprintTTL / RestoreFootprint).
from ..soroban import ops as _soroban_ops  # noqa: E402,F401
