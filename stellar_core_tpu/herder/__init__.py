"""Herder layer: SCP↔ledger glue, tx queue, upgrades, quorum analysis.

Reference: src/herder/ (SURVEY.md §2.1).
"""

from .herder import (EXP_LEDGER_TIMESPAN_SECONDS, Herder, HerderState,
                     MAX_SLOTS_TO_REMEMBER)
from .pending_envelopes import (ENVELOPE_STATUS_DISCARDED,
                                ENVELOPE_STATUS_FETCHING,
                                ENVELOPE_STATUS_PROCESSED,
                                ENVELOPE_STATUS_READY, PendingEnvelopes)
from .quorum_intersection import (QuorumIntersectionChecker,
                                  QuorumIntersectionResult,
                                  check_intersection,
                                  intersection_critical_groups)
from .quorum_tracker import QuorumTracker
from .tx_queue import AddResult, TransactionQueue
from .upgrades import UpgradeParameters, Upgrades

__all__ = [
    "EXP_LEDGER_TIMESPAN_SECONDS", "Herder", "HerderState",
    "MAX_SLOTS_TO_REMEMBER", "ENVELOPE_STATUS_DISCARDED",
    "ENVELOPE_STATUS_FETCHING", "ENVELOPE_STATUS_PROCESSED",
    "ENVELOPE_STATUS_READY", "PendingEnvelopes",
    "QuorumIntersectionChecker", "QuorumIntersectionResult",
    "check_intersection", "intersection_critical_groups", "QuorumTracker",
    "AddResult", "TransactionQueue", "UpgradeParameters", "Upgrades",
]
