"""Upgrades — consensus-voted ledger parameter changes.

Reference: src/herder/Upgrades.{h,cpp} — createUpgradesFor, isValid,
applyTo, toString, removeUpgrades; UpgradeParameters (the node's desired
targets from config, with an expiration time).  Key design point preserved
(SURVEY.md §5.6): consensus-critical parameters (protocol version, base
fee, max tx set size, base reserve) change ONLY via SCP-voted upgrades
carried in StellarValue.upgrades, never via local config directly.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional

from .. import xdr as X
from ..util import logging as slog

log = slog.get("Herder")

UT = X.LedgerUpgradeType

# This build's max supported protocol (classic semantics; Soroban gap
# documented in SURVEY.md §2.4)
CURRENT_LEDGER_PROTOCOL_VERSION = 23


@dataclass
class UpgradeParameters:
    """The operator's desired upgrade targets (config / HTTP `/upgrades`).
    Reference: Upgrades::UpgradeParameters."""
    upgrade_time: int = 0                    # unix time the vote activates
    protocol_version: Optional[int] = None
    base_fee: Optional[int] = None
    max_tx_set_size: Optional[int] = None
    base_reserve: Optional[int] = None
    flags: Optional[int] = None


class Upgrades:
    def __init__(self, params: Optional[UpgradeParameters] = None):
        self.params = params or UpgradeParameters()

    def set_parameters(self, params: Optional[UpgradeParameters]) -> None:
        self.params = params or UpgradeParameters()

    def pending_json(self) -> dict:
        """The `/upgrades?mode=get` payload (reference:
        CommandHandler::upgrades get mode)."""
        p = self.params
        return {
            "upgradetime": p.upgrade_time,
            "protocolversion": p.protocol_version,
            "basefee": p.base_fee,
            "maxtxsetsize": p.max_tx_set_size,
            "basereserve": p.base_reserve,
            "flags": p.flags,
        }

    # ------------------------------------------------------------------
    def create_upgrades_for(self, header: X.LedgerHeader,
                            close_time: int) -> List[bytes]:
        """Upgrades to vote for in the next StellarValue (each serialized
        as an opaque UpgradeType blob).  Reference: Upgrades::createUpgradesFor."""
        p = self.params
        if close_time < p.upgrade_time:
            return []
        out: List[bytes] = []
        if p.protocol_version is not None \
                and p.protocol_version != header.ledgerVersion:
            out.append(X.LedgerUpgrade.newLedgerVersion(
                p.protocol_version).to_xdr())
        if p.base_fee is not None and p.base_fee != header.baseFee:
            out.append(X.LedgerUpgrade.newBaseFee(p.base_fee).to_xdr())
        if p.max_tx_set_size is not None \
                and p.max_tx_set_size != header.maxTxSetSize:
            out.append(X.LedgerUpgrade.newMaxTxSetSize(
                p.max_tx_set_size).to_xdr())
        if p.base_reserve is not None \
                and p.base_reserve != header.baseReserve:
            out.append(X.LedgerUpgrade.newBaseReserve(p.base_reserve).to_xdr())
        return out

    # ------------------------------------------------------------------
    def is_valid(self, upgrade_bytes: bytes, header: X.LedgerHeader,
                 nomination: bool, close_time: int = 0) -> bool:
        """Would we accept this upgrade in a value?  During nomination we
        only vote for upgrades we actively want; during the ballot protocol
        we accept any well-formed upgrade that doesn't regress the ledger.
        Reference: Upgrades::isValid / isValidForApply."""
        try:
            up = X.LedgerUpgrade.from_xdr(upgrade_bytes)
        except X.XdrError:
            return False
        if not self._valid_for_apply(up, header):
            return False
        if nomination:
            return self._wanted(up, header, close_time)
        return True

    @staticmethod
    def _valid_for_apply(up, header: X.LedgerHeader) -> bool:
        t = up.switch
        if t == UT.LEDGER_UPGRADE_VERSION:
            v = up.newLedgerVersion
            return (header.ledgerVersion < v
                    <= CURRENT_LEDGER_PROTOCOL_VERSION)
        if t == UT.LEDGER_UPGRADE_BASE_FEE:
            return up.newBaseFee > 0
        if t == UT.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return up.newMaxTxSetSize > 0
        if t == UT.LEDGER_UPGRADE_BASE_RESERVE:
            return up.newBaseReserve > 0
        return False  # flags/config upgrades not supported in this build

    def _wanted(self, up, header: X.LedgerHeader, close_time: int) -> bool:
        p = self.params
        if close_time and close_time < p.upgrade_time:
            return False
        t = up.switch
        if t == UT.LEDGER_UPGRADE_VERSION:
            return p.protocol_version == up.newLedgerVersion
        if t == UT.LEDGER_UPGRADE_BASE_FEE:
            return p.base_fee == up.newBaseFee
        if t == UT.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return p.max_tx_set_size == up.newMaxTxSetSize
        if t == UT.LEDGER_UPGRADE_BASE_RESERVE:
            return p.base_reserve == up.newBaseReserve
        return False

    # ------------------------------------------------------------------
    @staticmethod
    def apply_to(upgrade_bytes: bytes, header: X.LedgerHeader) -> None:
        """Mutate the in-flight ledger header per one voted upgrade.
        Reference: Upgrades::applyTo (the LedgerTxn header part; per-entry
        side effects like reserve-driven liability updates are out of this
        build's classic scope)."""
        up = X.LedgerUpgrade.from_xdr(upgrade_bytes)
        t = up.switch
        if t == UT.LEDGER_UPGRADE_VERSION:
            header.ledgerVersion = up.newLedgerVersion
        elif t == UT.LEDGER_UPGRADE_BASE_FEE:
            header.baseFee = up.newBaseFee
        elif t == UT.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            header.maxTxSetSize = up.newMaxTxSetSize
        elif t == UT.LEDGER_UPGRADE_BASE_RESERVE:
            header.baseReserve = up.newBaseReserve
        else:
            log.warning("ignoring unsupported upgrade type %s", t)

    @staticmethod
    def apply_to_checked(upgrade_bytes: bytes, header: X.LedgerHeader) -> bool:
        """applyTo with apply-time re-validation: malformed or
        invalid-for-apply upgrades are logged and skipped (never crash a
        ledger close in flight).  Reference: Upgrades::applyTo error
        handling in applyLedger."""
        try:
            up = X.LedgerUpgrade.from_xdr(upgrade_bytes)
        except Exception:
            log.error("skipping malformed upgrade in externalized value")
            return False
        if not Upgrades._valid_for_apply(up, header):
            log.error("skipping invalid-for-apply upgrade: %s",
                      Upgrades.describe(upgrade_bytes))
            return False
        Upgrades.apply_to(upgrade_bytes, header)
        return True

    @staticmethod
    def describe(upgrade_bytes: bytes) -> str:
        try:
            up = X.LedgerUpgrade.from_xdr(upgrade_bytes)
        except X.XdrError:
            return "<malformed>"
        t = up.switch
        if t == UT.LEDGER_UPGRADE_VERSION:
            return f"protocolversion={up.newLedgerVersion}"
        if t == UT.LEDGER_UPGRADE_BASE_FEE:
            return f"basefee={up.newBaseFee}"
        if t == UT.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
            return f"maxtxsetsize={up.newMaxTxSetSize}"
        if t == UT.LEDGER_UPGRADE_BASE_RESERVE:
            return f"basereserve={up.newBaseReserve}"
        return str(t)
